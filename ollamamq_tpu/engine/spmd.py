"""SPMD multi-host serving: one engine, many hosts.

The reference scales by adding independent HTTP backends; a TPU pod is a
single SPMD machine instead: every host runs the same program, params and
KV pools are sharded over a GLOBAL mesh (tensor axis spanning hosts'
chips), and each jitted step executes on all hosts with XLA collectives
over ICI/DCN doing the cross-chip movement.

Control plane: the primary host (process 0) owns the scheduler, HTTP
front, and all admission decisions. Before every device step it ships a
"step plan" — a fixed-shape header (opcode + static dims + routing
ordinals) plus the op payload (token ids, page tables, sampling params,
raw RNG key) — over the jax.distributed KV store as a monotonic key
stream (`_Wire`). Workers sit in `run_worker`, long-poll the stream, and
issue the SAME jit call with their local shards. Every value feeding the
computation travels on the wire, never recomputed locally, so all hosts
trace and execute identical steps. The control plane is deliberately
gRPC, not a device collective: broadcasts would share the cross-host
transport with model collectives (gloo pairs on CPU) and any reordering
between the two corrupts the transport; coordinator traffic cannot.

Opcode header (int32[5]: [op, a, b, model_ordinal, replica_ordinal]):
    OP_SHUTDOWN = 0              -> workers exit (no payload)
    OP_PREFILL  = 1, a=bucket, b=B
    OP_CHUNK    = 2, a=chunk_size
    OP_DECODE   = 3, a=k_steps
    OP_ENCODE   = 4, a=B, b=bucket (embedding batch forward, stateless)
    OP_PREFILL_SP = 5, a=T (sequence-parallel long-prompt prefill)
    OP_RELOAD   = 6              -> rebuild runtime [mi][ri] from pristine
                                    config (multi-host failure recovery)
    OP_LOAD     = 7, a=n_replicas; payload carries (name, ckpt) strings
                                    (runtime /api/pull on every host)
    OP_EVICT    = 8; payload carries name (runtime /api/delete)
    OP_EMBED    = 9, a=B, b=bucket (embed batch on a GENERATIVE runtime:
                                    causal forward + mean pool, stateless)
    OP_RAGGED   = 10, a=T_pad      (ragged mixed batch: prefill spans +
                                    decode rows in one flattened stream)
    OP_SPEC     = 11, a=T_pad, b=k_cap (ragged mixed batch carrying
                                    speculative verify spans: the RAGGED
                                    payload plus the per-row is_spec
                                    flag; k_cap sizes the multi-token
                                    output shape on every host)

Data parallelism under SPMD: dp replicas each live on a slice of the
mesh's data axis. make_mesh arranges the dp axis intra-host when
process_count > 1, so every slice spans every process and each replica's
jit is a valid multi-controller computation; the header's
replica_ordinal routes the worker's replay to the right replica.

Desync detection: after every replayed op, all hosts exchange a status
flag OUT-OF-BAND via the jax.distributed KV store (`status_sync`) — a
host-side barrier, deliberately NOT a device collective, so the report
can't deadlock behind the very computation whose failure it reports. A
worker whose replay failed has diverged KV state — serving on would emit
silently-wrong tokens on every later tp-sharded step — so the primary
fails the runtime LOUDLY and the recovery path broadcasts OP_RELOAD,
rebuilding it on all hosts from pristine config. The sync is one small
KV round-trip per dispatch (a fused k-step chunk, not a token);
OLLAMAMQ_SPMD_STATUS_EVERY=N rate-limits it to every Nth data op
(detection delayed ≤ N-1 dispatches) when even that is too much.

Failure-class caveat: clean recovery covers failures where both sides
ISSUED the step computation (device-side errors, post-dispatch state
bugs — the common class). A worker that fails BEFORE issuing the jit
(payload/shape protocol bug) leaves the primary's already-dispatched
computation waiting on collectives with a missing peer; detection is
still loud (the KV sync is out-of-band), the runtime is failed and
requests error, but the orphaned computation is abandoned, not
cancelled — on a real pod, prefer restarting the deployment after such
a protocol error.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.engine import (EncoderRuntime, ModelRuntime,
                                        PeerDeadError, WorkerDesyncError)

log = logging.getLogger("ollamamq.spmd")

OP_SHUTDOWN = 0
OP_PREFILL = 1
OP_CHUNK = 2
OP_DECODE = 3
OP_ENCODE = 4
OP_PREFILL_SP = 5
OP_RELOAD = 6
OP_LOAD = 7
OP_EVICT = 8
OP_EMBED = 9  # a=B, b=bucket: embed batch on a GENERATIVE runtime
OP_RAGGED = 10  # a=T_pad: ragged mixed batch (prefill spans + decode rows)
OP_SPEC = 11  # a=T_pad, b=k_cap: ragged mixed batch + speculative spans

KEY_SHAPE = (2,)  # raw uint32 threefry key data
NAME_LEN = 128  # utf-8 bytes, zero-padded, for OP_LOAD/OP_EVICT names
PATH_LEN = 256  # utf-8 bytes for checkpoint paths ("" = None)


def _status_every() -> int:
    try:
        # Clamped to bound the failure-detection delay (wire-key cleanup
        # no longer depends on this: the delete horizon tracks completed
        # barriers exactly, see _Wire).
        return min(256, max(1, int(
            os.environ.get("OLLAMAMQ_SPMD_STATUS_EVERY", "1"))))
    except ValueError:
        return 1


def _kv_client():
    from jax._src import distributed

    return distributed.global_state.client


def _status_timeout_ms() -> int:
    try:
        return int(
            float(os.environ.get("OLLAMAMQ_SPMD_STATUS_TIMEOUT", "900")) * 1000
        )
    except ValueError:
        return 900_000


def _hb_every() -> float:
    try:
        return float(os.environ.get("OLLAMAMQ_SPMD_HB_EVERY", "3"))
    except ValueError:
        return 3.0


def _hb_stale() -> float:
    try:
        return float(os.environ.get("OLLAMAMQ_SPMD_HB_STALE", "10"))
    except ValueError:
        return 10.0


class _HeartbeatMonitor:
    """Peer liveness from the KV store, clock-skew-free: a peer is stale
    when ITS heartbeat value has not changed for > _hb_stale() seconds of
    OUR monotonic clock (never compares cross-host timestamps). A peer
    that has never written a heartbeat is treated as alive — liveness is
    opt-in per host, so mixed/starting deployments can't false-positive."""

    def __init__(self):
        self._seen = {}  # pid -> (value, first observed at, our clock)

    def observe(self, pid: int, value: Optional[str], now: float) -> bool:
        """Record one reading; returns True if the peer is stale."""
        if value is None:
            return False
        prev = self._seen.get(pid)
        if prev is None or prev[0] != value:
            self._seen[pid] = (value, now)
            return False
        return (now - prev[1]) > _hb_stale()

    def stale_peers(self, pids) -> list:
        import time as _time

        client = _kv_client()
        now = _time.monotonic()
        out = []
        for pid in pids:
            try:
                v = client.key_value_try_get(f"ollamamq/hb/{pid}")
            except Exception:
                v = None  # never written -> alive
            if self.observe(pid, v, now):
                out.append(pid)
        return out


_hb_monitor = _HeartbeatMonitor()


def start_heartbeat() -> None:
    """Advertise this host's liveness (`ollamamq/hb/<pid>`, bumped every
    _hb_every() seconds) so peers stop waiting on us within ~_hb_stale()s
    of our death instead of the full status-sync timeout (VERDICT r3 weak
    #3: a crashed worker wedged the primary for 15 minutes; the reference
    detects a dead backend in 10s, dispatcher.rs:385)."""
    import threading
    import time as _time

    client = _kv_client()
    pid = jax.process_index()

    def run():
        import json as _json

        from ollamamq_tpu.engine.engine import per_chip_stats
        from ollamamq_tpu.telemetry.metrics import REGISTRY

        n = 0
        while True:
            try:
                client.key_value_set(f"ollamamq/hb/{pid}", str(n),
                                     allow_overwrite=True)
                # Piggyback per-chip HBM so the primary's telemetry can
                # show every host's chips (north star: per-chip HBM for
                # the whole pod, not device 0 of host 0).
                client.key_value_set(f"ollamamq/chips/{pid}",
                                     _json.dumps(per_chip_stats()),
                                     allow_overwrite=True)
                # ... and this host's full metrics snapshot: the primary's
                # /metrics merges peer counters/histograms so the pod
                # reads as ONE exposition (primary skips its own key).
                client.key_value_set(f"ollamamq/metrics/{pid}",
                                     REGISTRY.snapshot_json(),
                                     allow_overwrite=True)
            except Exception:
                pass  # coordinator gone: process is exiting anyway
            n += 1
            _time.sleep(_hb_every())

    threading.Thread(target=run, daemon=True, name="spmd-heartbeat").start()


def _is_deadline(e: Exception) -> bool:
    return "DEADLINE_EXCEEDED" in str(e) or "deadline" in str(e).lower()


def status_sync(ok: bool, seq: int) -> np.ndarray:
    """Exchange one ok/fail flag per process via the jax.distributed
    KV store; returns int32[nproc] (1 = that process's op failed). Runs
    entirely HOST-side: it must never be a device collective, because
    the failure being reported may be a computation one side issued and
    the other didn't — mixing the report into the device stream would
    deadlock behind that very computation. Every process calls this at
    the same point in the op stream (`seq` is the shared sync ordinal).

    The rendezvous is a POLLED barrier (everyone writes its flag, then
    reads everyone's) rather than wait_at_barrier: between short polls we
    check peer heartbeats, so a host that died — and therefore will never
    arrive — surfaces as PeerDeadError in ~_hb_stale()s instead of
    blocking serving for the full OLLAMAMQ_SPMD_STATUS_TIMEOUT (900s)."""
    import time as _time

    client = _kv_client()
    n = jax.process_count()
    pid = jax.process_index()
    client.key_value_set(f"ollamamq/st/{seq}/{pid}", "ok" if ok else "fail")
    deadline = _time.monotonic() + _status_timeout_ms() / 1e3
    flags = np.zeros(n, np.int32)
    for i in range(n):
        while True:
            try:
                v = client.blocking_key_value_get(
                    f"ollamamq/st/{seq}/{i}", 2_000)
                break
            except Exception as e:
                if not _is_deadline(e):
                    raise
                dead = _hb_monitor.stale_peers(
                    [p for p in range(n) if p != pid])
                if dead:
                    raise PeerDeadError(
                        f"host(s) {dead} heartbeat went stale at sync "
                        f"{seq}: presumed dead; failing in-flight work "
                        "loudly") from None
                if _time.monotonic() > deadline:
                    raise
        flags[i] = 0 if v == "ok" else 1
    # Everyone passed the PREVIOUS sync before writing this sync's key,
    # so our previous-sync key has been read by all — safe to clean up.
    if seq > 0:
        try:
            client.key_value_delete(f"ollamamq/st/{seq - 1}/{pid}")
        except Exception:
            pass
    return flags


def _encode_str(s: Optional[str], n: int) -> np.ndarray:
    raw = (s or "").encode("utf-8")
    if len(raw) > n:
        raise ValueError(f"string too long for SPMD wire field ({len(raw)} > {n})")
    out = np.zeros((n,), np.int32)
    out[: len(raw)] = np.frombuffer(raw, np.uint8)
    return out


def _decode_str(arr) -> str:
    b = bytes(int(x) for x in np.asarray(arr).tolist() if int(x) != 0)
    return b.decode("utf-8")


def payload_spec(op, a, b, S, MP, W):
    """[(shape, dtype), ...] for an opcode's broadcast payload — the ONE
    place the wire order lives. Senders cast their positional values to
    this spec; workers build a zeros template from it. Broadcast matches
    on tree structure + shape/dtype, so both sides must agree exactly.
    `W` is the repeat-penalty window (OP_CHUNK carries the first-chunk
    penalty-ring seed row, which on a prefix-cache hit holds the cached
    prefix's last W tokens — the tree itself is primary-only host state;
    only its effects travel)."""

    def samp(n):  # temp, top_k, top_p, repeat, presence, frequency, seed
        return [((n,), np.float32), ((n,), np.int32), ((n,), np.float32),
                ((n,), np.float32), ((n,), np.float32), ((n,), np.float32),
                ((n,), np.int32)]

    key = [(KEY_SHAPE, np.uint32)]
    if op == OP_PREFILL:
        bucket, B = a, b
        return [((B, bucket), np.int32), ((B,), np.int32), ((B,), np.int32),
                ((B, MP), np.int32)] + samp(B) + key
    if op == OP_CHUNK:
        # tokens, start, chunk_len, slot, is_final, is_first, seed_row, pt
        return [((1, a), np.int32), ((1,), np.int32), ((1,), np.int32),
                ((1,), np.int32), ((1,), np.int32), ((1,), np.int32),
                ((1, W), np.int32),
                ((1, MP), np.int32)] + samp(1) + key
    if op == OP_DECODE:
        return [((S,), np.int32), ((S,), np.int32), ((S,), np.int32),
                ((S, MP), np.int32)] + samp(S) + key
    if op == OP_PREFILL_SP:
        return [((1, a), np.int32), ((1,), np.int32), ((1,), np.int32),
                ((1, MP), np.int32)] + samp(1) + key
    if op == OP_RAGGED:
        T = a
        # tokens, tok_seq, tok_pos, write_slots; then per-sequence
        # q_start, q_len, kv_len, ring_len, is_first, append, slot_ids,
        # seed_rows, page tables, sampling, key.
        return ([((T,), np.int32)] * 4
                + [((S,), np.int32)] * 7
                + [((S, W), np.int32), ((S, MP), np.int32)]
                + samp(S) + key)
    if op == OP_SPEC:
        # The RAGGED payload plus the per-row is_spec flag (the eighth
        # [S] vector, after append / before slot_ids); k_cap rides the
        # header's b so every host compiles the same multi-token output
        # shape.
        T = a
        return ([((T,), np.int32)] * 4
                + [((S,), np.int32)] * 8
                + [((S, W), np.int32), ((S, MP), np.int32)]
                + samp(S) + key)
    if op in (OP_ENCODE, OP_EMBED):
        B, bucket = a, b
        return [((B, bucket), np.int32), ((B,), np.int32)]
    if op in (OP_RELOAD, OP_SHUTDOWN):
        return []
    if op == OP_LOAD:
        return [((NAME_LEN,), np.int32), ((PATH_LEN,), np.int32)]
    if op == OP_EVICT:
        return [((NAME_LEN,), np.int32)]
    raise ValueError(f"no payload spec for opcode {op}")


class _Wire:
    """Primary→worker op stream over the jax.distributed KV store.

    The op plan is CONTROL PLANE and deliberately travels over the
    coordinator's gRPC channel, not as a device collective: a broadcast
    jit shares the cross-host transport (gloo pairs on CPU) with model
    collectives, and any concurrency between the two — including the
    broadcast's own per-local-device reduction streams — interleaves ops
    differently per process and aborts the transport. gRPC keys have no
    ordering relationship with device collectives, so the control plane
    can never corrupt the data plane.

    Keys are `ollamamq/op/<seq>`: the primary writes them monotonically;
    each worker long-polls its own cursor. Cleanup horizon: workers
    process the stream serially and every completed status barrier sits
    at a deterministic position in it, so when a barrier completes on the
    primary, every worker has consumed ALL ops sent before it — keys
    below that barrier's send-seq are safe to delete. (A fixed seq-1024
    window was wrong with many runtime cadences: R cadences × ≤255 lag
    each could exceed it and delete a key a lagging worker still needed,
    wedging its _recv_op retry loop forever — ADVICE r3.)"""

    def __init__(self):
        self.seq = 0
        # All keys < consumed have been read by every worker (set at each
        # completed barrier); keys < deleted are already removed.
        self.consumed = 0
        self.deleted = 0


_wire = _Wire()

_HDR = 5 * 4  # int32[5] header bytes


def _pack_payload(cast) -> bytes:
    if not cast:
        return b""
    return b"".join(np.ascontiguousarray(v).tobytes() for v in cast)


def _unpack_payload(raw: bytes, spec):
    out = []
    off = 0
    for shape, dt in spec:
        nb = int(np.prod(shape)) * np.dtype(dt).itemsize
        out.append(np.frombuffer(raw[off:off + nb], dt).reshape(shape))
        off += nb
    return tuple(out)


def _send(op, a, b, index, replica, values, S, MP, W):
    spec = payload_spec(op, a, b, S, MP, W)
    assert len(values) == len(spec)
    cast = []
    for v, (shape, dt) in zip(values, spec):
        v = np.asarray(v, dt)
        # Shape drift would desync the wire decode on workers with an
        # opaque error; fail at the send site instead.
        assert v.shape == shape, (op, v.shape, shape)
        cast.append(v)
    header = np.asarray([op, a, b, index, replica], np.int32).tobytes()
    client = _kv_client()
    client.key_value_set_bytes(f"ollamamq/op/{_wire.seq}",
                               header + _pack_payload(cast))
    _wire.seq += 1
    # Reclaim keys every worker has provably consumed (barrier horizon).
    # Steady-state this is at most ops-per-barrier deletes per barrier.
    while _wire.deleted < _wire.consumed:
        try:
            client.key_value_delete(f"ollamamq/op/{_wire.deleted}")
        except Exception:
            pass
        _wire.deleted += 1


def _recv_op(seq: int, timeout_ms: int = 10_000):
    """Worker side: block for op `seq`; returns (header int32[5], raw
    payload bytes). Retries on poll timeout — an idle engine sends
    nothing for arbitrarily long — but a PRIMARY whose heartbeat went
    stale will never send again: exit loudly instead of idling forever."""
    client = _kv_client()
    while True:
        try:
            blob = client.blocking_key_value_get_bytes(
                f"ollamamq/op/{seq}", timeout_ms
            )
            break
        except Exception as e:
            if _is_deadline(e):
                if _hb_monitor.stale_peers([0]):
                    raise PeerDeadError(
                        "primary host heartbeat went stale; worker "
                        "exiting") from None
                continue
            raise
    header = np.frombuffer(blob[:_HDR], np.int32)
    return header, blob[_HDR:]


def broadcast_shutdown() -> None:
    """Release worker hosts. Sent exactly ONCE per deployment (the worker
    loop exits on the first shutdown header)."""
    if jax.process_count() > 1:
        _send(OP_SHUTDOWN, 0, 0, 0, 0, (), 0, 0, 0)


class _SyncBus:
    """Global barrier ordinal for status syncs. Sync points derive
    deterministically from the shared op stream, so every host executes
    the same syncs in the same order and `seq` stays aligned without any
    extra wire traffic; barrier ids are never reused."""

    def __init__(self):
        self.seq = 0

    def sync(self, ok: bool) -> np.ndarray:
        flags = status_sync(ok, self.seq)
        self.seq += 1
        # Barrier complete: on the primary, every op sent so far has been
        # consumed by every worker (workers hit this same barrier only
        # after serially processing all preceding ops) — advance the
        # wire-key cleanup horizon. On workers _wire.seq is 0 (no-op).
        _wire.consumed = _wire.seq
        return flags


_bus = _SyncBus()


class _OpCadence:
    """Per-RUNTIME data-op counter for the status-sync cadence. One
    instance lives on each SPMD runtime (primary) / worker replica, so a
    carried-forward off-cadence failure is always reported at a sync
    belonging to the SAME runtime — never attributed to whichever other
    runtime happened to dispatch next (that would reload the healthy one
    and leave the diverged one serving). Replays mirror dispatches
    per-runtime, so both sides' counts agree; a reload builds a fresh
    runtime and therefore a fresh zeroed cadence on every host."""

    def __init__(self):
        self.count = 0
        self._pending_fail = False  # off-cadence failure carried forward

    def after_op(self, ok: bool) -> Optional[np.ndarray]:
        self.count += 1
        # An off-cadence failure can't sync alone — the other hosts aren't
        # at a sync point. Carry it to this runtime's next scheduled sync
        # (detection delay ≤ every-1 of ITS ops). Default (every=1) syncs
        # every op.
        if self.count % _status_every() != 0:
            self._pending_fail = self._pending_fail or not ok
            return None
        flags = _bus.sync(ok and not self._pending_fail)
        self._pending_fail = False
        return flags


def _raise_on_worker_failure(flags: Optional[np.ndarray], name: str) -> None:
    if flags is not None and flags.any():
        bad = np.nonzero(flags)[0].tolist()
        # Typed so fail-only-this-batch handlers (prefill/embed) know to
        # re-raise: diverged device state must kill + reload the runtime.
        raise WorkerDesyncError(
            f"SPMD worker host(s) {bad} failed replaying a dispatch for "
            f"{name}; KV state diverged — failing runtime for reload"
        )


_OP_SITE = {OP_PREFILL: "prefill", OP_CHUNK: "chunk", OP_DECODE: "decode",
            OP_PREFILL_SP: "sp_prefill", OP_RAGGED: "ragged",
            OP_SPEC: "spec_verify", OP_EMBED: "embed", OP_ENCODE: "encode"}


def _mirrored_dispatch(rt, op, a, b, values, dispatch):
    """Ship the plan, run the local dispatch, then join this runtime's
    status sync. The status sync runs even when the local dispatch raised —
    skipping it would strand the other hosts at the barrier. Shared by the
    generative and encoder SPMD runtimes so the sync protocol can't drift
    between them."""
    if rt.fault_plan is not None:
        # Fault-injection seam, BEFORE the broadcast: an injected host
        # failure must fire while no worker has replayed anything, so the
        # containment/retry path sees a recoverable fault — a
        # post-broadcast failure is real KV divergence, which is the
        # desync path's job, not injection's.
        rt.fault_plan.check(_OP_SITE.get(op, "decode"))
    if rt.journal is not None:
        # Primary-host journaling of the broadcast plan: workers replay
        # this exact wire sequence, so a desync postmortem can line the
        # journal's wire_seq up against each host's replay position.
        rt.journal.record("broadcast", model=rt.name,
                          op=_OP_SITE.get(op, str(op)), wire_seq=_wire.seq)
    _send(op, a, b, rt.spmd_index, rt.spmd_replica, values,
          rt.ecfg.max_slots, rt.ecfg.max_pages_per_seq,
          rt.ecfg.repeat_last_n)
    ok = False
    try:
        out = dispatch()
        if _serialize_multihost():
            # Every output, not just the ones the caller materializes:
            # a trailing collective (e.g. a reshard on the KV-cache
            # output path that doesn't feed the sampled tokens) still
            # in flight when the next broadcast hits the shared gloo
            # context would interleave and abort the pair.
            jax.block_until_ready(out)
        ok = True
        return out
    finally:
        flags = rt._cadence.after_op(ok)
        if ok:
            _raise_on_worker_failure(flags, rt.name)


class SPMDModelRuntime(ModelRuntime):
    """ModelRuntime whose device dispatches are mirrored on every host.

    Single-process deployments behave exactly like ModelRuntime (the
    broadcast seam is skipped entirely).
    """

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._spmd = jax.process_count() > 1
        # Ordinals agreed with workers via the shared --models ordering
        # (and replica position within a ReplicaSet); carried in the opcode
        # header so multi-model / dp pods stay in step.
        self.spmd_index = 0
        self.spmd_replica = 0
        self._cadence = _OpCadence()

    def _mirrored(self, op, a, b, values, dispatch):
        return _mirrored_dispatch(self, op, a, b, values, dispatch)

    def _fault(self, site):
        # Multi-host: the check already ran pre-broadcast in
        # _mirrored_dispatch; firing again here would double-count the
        # plan's per-site call stream.
        if not self._spmd:
            super()._fault(site)

    def export_request(self, rid):
        # KV migration is a fleet-member feature; on a multi-host SPMD
        # runtime the pool gather/scatter would run primary-only and
        # desync worker replay state. Single-process behaves like
        # ModelRuntime (the fleet CLI already forbids --replicas+--spmd;
        # this guards the bare /admin/migrate surface too).
        if self._spmd:
            return None
        return super().export_request(rid)

    def import_request(self, blob, req):
        if self._spmd:
            return False
        return super().import_request(blob, req)

    def export_prefix(self, tokens):
        if self._spmd:
            return None
        return super().export_prefix(tokens)

    def import_prefix(self, blob):
        if self._spmd:
            return 0
        return super().import_prefix(blob)

    def _dispatch_prefill(self, bucket, B, tokens, lens, slot_ids, pt_rows,
                          temp, tk, tp, pen, pres, freq, seeds, key):
        if not self._spmd:
            return super()._dispatch_prefill(
                bucket, B, tokens, lens, slot_ids, pt_rows, temp, tk, tp,
                pen, pres, freq, seeds, key)
        return self._mirrored(
            OP_PREFILL, bucket, B,
            (tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen, pres,
             freq, seeds, key),
            lambda: super(SPMDModelRuntime, self)._dispatch_prefill(
                bucket, B, tokens, lens, slot_ids, pt_rows, temp, tk, tp,
                pen, pres, freq, seeds, key))

    def _dispatch_chunk(self, chunk, tokens, start, cl, slot_id, is_final,
                        is_first, seed_row, pt_row, temp, tk, tp, pen, pres,
                        freq, seeds, key):
        if not self._spmd:
            return super()._dispatch_chunk(
                chunk, tokens, start, cl, slot_id, is_final, is_first,
                seed_row, pt_row, temp, tk, tp, pen, pres, freq, seeds, key)
        return self._mirrored(
            OP_CHUNK, chunk, 0,
            (tokens, start, cl, slot_id, is_final, is_first, seed_row,
             pt_row, temp, tk, tp, pen, pres, freq, seeds, key),
            lambda: super(SPMDModelRuntime, self)._dispatch_chunk(
                chunk, tokens, start, cl, slot_id, is_final, is_first,
                seed_row, pt_row, temp, tk, tp, pen, pres, freq, seeds, key))

    def _dispatch_decode(self, k_steps, tokens, positions, active, pt, temp,
                         tk, tp, pen, pres, freq, seeds, key):
        if not self._spmd:
            return super()._dispatch_decode(
                k_steps, tokens, positions, active, pt, temp, tk, tp, pen,
                pres, freq, seeds, key)
        return self._mirrored(
            OP_DECODE, k_steps, 0,
            (tokens, positions, active, pt, temp, tk, tp, pen, pres, freq,
             seeds, key),
            lambda: super(SPMDModelRuntime, self)._dispatch_decode(
                k_steps, tokens, positions, active, pt, temp, tk, tp, pen,
                pres, freq, seeds, key))

    def _dispatch_prefill_sp(self, T, tokens, lens, slot_ids, pt_rows,
                             temp, tk, tp, pen, pres, freq, seeds, key):
        if not self._spmd:
            return super()._dispatch_prefill_sp(
                T, tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen,
                pres, freq, seeds, key)
        return self._mirrored(
            OP_PREFILL_SP, T, 0,
            (tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen, pres,
             freq, seeds, key),
            lambda: super(SPMDModelRuntime, self)._dispatch_prefill_sp(
                T, tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen,
                pres, freq, seeds, key))

    def _dispatch_ragged(self, T_pad, k_cap, tokens, tok_seq, tok_pos,
                         write_slots, q_start, q_len, kv_len, ring_len,
                         is_first, append, is_spec, seed_rows, slot_ids, pt,
                         temp, tk, tp, pen, pres, freq, seeds, key):
        if not self._spmd:
            return super()._dispatch_ragged(
                T_pad, k_cap, tokens, tok_seq, tok_pos, write_slots,
                q_start, q_len, kv_len, ring_len, is_first, append, is_spec,
                seed_rows, slot_ids, pt, temp, tk, tp, pen, pres, freq,
                seeds, key)
        # Plain mixed batches keep the OP_RAGGED wire shape; only
        # dispatches actually carrying verify spans pay for (and ship)
        # the is_spec vector + multi-token output (OP_SPEC, b=k_cap).
        if k_cap:
            op, payload = OP_SPEC, (
                tokens, tok_seq, tok_pos, write_slots, q_start, q_len,
                kv_len, ring_len, is_first, append, is_spec, slot_ids,
                seed_rows, pt, temp, tk, tp, pen, pres, freq, seeds, key)
        else:
            op, payload = OP_RAGGED, (
                tokens, tok_seq, tok_pos, write_slots, q_start, q_len,
                kv_len, ring_len, is_first, append, slot_ids, seed_rows,
                pt, temp, tk, tp, pen, pres, freq, seeds, key)
        return self._mirrored(
            op, T_pad, k_cap, payload,
            lambda: super(SPMDModelRuntime, self)._dispatch_ragged(
                T_pad, k_cap, tokens, tok_seq, tok_pos, write_slots,
                q_start, q_len, kv_len, ring_len, is_first, append, is_spec,
                seed_rows, slot_ids, pt, temp, tk, tp, pen, pres, freq,
                seeds, key))

    def _dispatch_embed(self, B, bucket, tokens, lens):
        if not self._spmd:
            return super()._dispatch_embed(B, bucket, tokens, lens)
        return self._mirrored(
            OP_EMBED, B, bucket, (tokens, lens),
            lambda: super(SPMDModelRuntime, self)._dispatch_embed(
                B, bucket, tokens, lens))


class SPMDEncoderRuntime(EncoderRuntime):
    """EncoderRuntime whose batch-encode dispatches are mirrored on every
    host (OP_ENCODE), so embedding models serve under --spmd too."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._spmd = jax.process_count() > 1
        self.spmd_index = 0
        self.spmd_replica = 0
        self._cadence = _OpCadence()

    def _dispatch_encode(self, B, bucket, tokens, lens):
        if not self._spmd:
            return super()._dispatch_encode(B, bucket, tokens, lens)
        return _mirrored_dispatch(
            self, OP_ENCODE, B, bucket, (tokens, lens),
            lambda: super(SPMDEncoderRuntime, self)._dispatch_encode(
                B, bucket, tokens, lens))


def _build_runtimes(name, ckpt, engine_cfg, mesh, dtype):
    """Worker-side replica list for one model: the SAME shared construction
    path the primary's load_model uses (engine.build_model_runtimes), with
    the SPMD runtime classes — every host must build byte-identical
    computations."""
    from ollamamq_tpu.config import get_model_config
    from ollamamq_tpu.engine.engine import build_model_runtimes

    cfg = get_model_config(name)
    if cfg is None:
        raise ValueError(f"model {name} not replayable under SPMD")
    return build_model_runtimes(name, cfg, engine_cfg, mesh, dtype, ckpt,
                                SPMDModelRuntime, SPMDEncoderRuntime)


class SPMDEngine:
    """Factory + lifecycle glue for the primary host: a TPUEngine whose
    runtimes broadcast their dispatches, whose model load/evict/reload
    control operations broadcast as opcodes serialized on the engine
    thread, and which releases workers on stop."""

    def __new__(cls, *args, **kw):
        from ollamamq_tpu.engine.engine import ReplicaSet, TPUEngine

        class _Engine(TPUEngine):
            runtime_class = SPMDModelRuntime
            encoder_runtime_class = SPMDEncoderRuntime

            def _renumber(self):
                """Re-derive (model ordinal, replica ordinal) for every
                runtime from the dict order — the same order the worker
                maintains its mirrored list in."""
                for mi, rt in enumerate(self.runtimes.values()):
                    reps = rt.replicas if isinstance(rt, ReplicaSet) else [rt]
                    for ri, rep in enumerate(reps):
                        rep.spmd_index = mi
                        rep.spmd_replica = ri

            def load_model(self, name, checkpoint_path=None):
                if name in self.runtimes:
                    return
                if self._running and jax.process_count() > 1:
                    from ollamamq_tpu.config import get_model_config

                    if get_model_config(name) is None:
                        # Validate BEFORE broadcasting: a post-broadcast
                        # failure would leave worker ordinal lists with an
                        # entry the primary never added.
                        raise KeyError(f"unknown model architecture: {name}")

                    # Runtime /api/pull: broadcast OP_LOAD from the engine
                    # thread (ordered with dispatches), load on every host.
                    def _do():
                        if name in self.runtimes:
                            # A concurrent pull of the same model won the
                            # race; broadcasting a second OP_LOAD would
                            # desync worker ordinals permanently.
                            return
                        n_reps = (self.ecfg.dp
                                  if not _is_encoder_name(name) else 1)
                        _send(OP_LOAD, n_reps, 0, len(self.runtimes), 0,
                              (_encode_str(name, NAME_LEN),
                               _encode_str(checkpoint_path, PATH_LEN)),
                              self.ecfg.max_slots,
                              self.ecfg.max_pages_per_seq,
                              self.ecfg.repeat_last_n)
                        ok = False
                        try:
                            super(_Engine, self).load_model(
                                name, checkpoint_path)
                            self._renumber()
                            ok = True
                        finally:
                            flags = _bus.sync(ok)
                            if ok and flags.any():
                                # Worker holds a None placeholder at this
                                # ordinal; first dispatch will fail loudly
                                # and the reload path rebuilds it.
                                raise RuntimeError(
                                    f"worker host(s) "
                                    f"{np.nonzero(flags)[0].tolist()} "
                                    f"failed loading {name}; serving "
                                    "deferred to reload recovery")

                    return self.call_on_loop(_do)
                super().load_model(name, checkpoint_path)
                self._renumber()

            def evict_model(self, name):
                if (name in self.runtimes and self._running
                        and jax.process_count() > 1):
                    def _do():
                        rt = self.runtimes.get(name)
                        if rt is None:
                            return False
                        if rt.has_work():
                            # Validate BEFORE broadcasting so the worker
                            # never evicts what the primary kept.
                            raise RuntimeError(
                                f"model {name} has in-flight work")
                        mi = list(self.runtimes).index(name)
                        _send(OP_EVICT, 0, 0, mi, 0,
                              (_encode_str(name, NAME_LEN),),
                              self.ecfg.max_slots,
                              self.ecfg.max_pages_per_seq,
                              self.ecfg.repeat_last_n)
                        ok = False
                        try:
                            out = super(_Engine, self).evict_model(name)
                            self._renumber()
                            ok = True
                            return out
                        finally:
                            flags = _bus.sync(ok)
                            if ok and flags.any():
                                # Worker refused the evict (its ordinal
                                # table already disagreed — a pre-existing
                                # protocol break, since workers defer
                                # deletion until the primary confirms).
                                log.critical(
                                    "worker host(s) %s refused evicting %s:"
                                    " ordinal tables diverged BEFORE this "
                                    "op; dispatches to those hosts may "
                                    "route to the wrong model — restart "
                                    "the deployment",
                                    np.nonzero(flags)[0].tolist(), name)

                    return self.call_on_loop(_do)
                out = super().evict_model(name)
                self._renumber()
                return out

            def _start_rebuild(self, rt):
                if jax.process_count() <= 1:
                    return super()._start_rebuild(rt)
                # Engine thread (via _try_recover ← _loop): broadcast the
                # reload and rebuild INLINE so the weight reload + KV alloc
                # happen at the same point of the op stream on every host.
                # Serving pauses for the reload; that is the cost of
                # lock-step recovery, and it is loud in the logs.
                log.warning("SPMD reload of %s (model %d replica %d) on "
                            "all hosts", rt.name, rt.spmd_index,
                            rt.spmd_replica)
                _send(OP_RELOAD, 0, 0, rt.spmd_index, rt.spmd_replica, (),
                      self.ecfg.max_slots, self.ecfg.max_pages_per_seq,
                      self.ecfg.repeat_last_n)
                ok = False
                try:
                    # Posts to _rebuilt on success; False = primary-side
                    # rebuild failure, reported truthfully at the sync.
                    ok = self._rebuild_runtime(rt)
                finally:
                    flags = _bus.sync(ok)
                    if ok and flags.any():
                        log.error(
                            "worker host(s) %s failed the reload of %s; "
                            "next dispatch will fail it again and retry",
                            np.nonzero(flags)[0].tolist(), rt.name)
                self._swap_rebuilt()

            def chip_stats(self):
                chips = super().chip_stats()
                if jax.process_count() > 1:
                    import json as _json

                    client = _kv_client()
                    me = jax.process_index()
                    for p in range(jax.process_count()):
                        if p == me:
                            continue
                        try:
                            v = client.key_value_try_get(
                                f"ollamamq/chips/{p}")
                            if v:
                                chips.extend(_json.loads(v))
                        except Exception:
                            pass  # host not publishing yet (or dead)
                    chips.sort(key=lambda c: (c.get("process", 0),
                                              c.get("id", 0)))
                return chips

            def stale_worker_hosts(self):
                """Worker hosts whose heartbeat value stopped advancing
                (same staleness rule the status sync uses — the shared
                module-level monitor keeps one view of peer liveness, so
                the watchdog and the sync can never disagree)."""
                if jax.process_count() <= 1:
                    return []
                me = jax.process_index()
                try:
                    return _hb_monitor.stale_peers(
                        [p for p in range(jax.process_count()) if p != me])
                except Exception:
                    return []  # coordinator unreachable: the sync path
                    #            will surface that loudly on its own

            def worker_metric_snapshots(self):
                if jax.process_count() <= 1:
                    return []
                import json as _json

                client = _kv_client()
                me = jax.process_index()
                out = []
                for p in range(jax.process_count()):
                    if p == me:
                        continue
                    try:
                        v = client.key_value_try_get(f"ollamamq/metrics/{p}")
                        if v:
                            out.append(_json.loads(v))
                    except Exception:
                        pass  # host not publishing yet (or dead)
                return out

            def stop(self):
                super().stop()
                broadcast_shutdown()  # exactly once, after dispatches ended

        eng = _Engine(*args, **kw)
        eng._renumber()
        if jax.process_count() > 1:
            start_heartbeat()
        return eng


def _is_encoder_name(name: str) -> bool:
    from ollamamq_tpu.config import get_model_config

    cfg = get_model_config(name)
    return bool(cfg is not None and cfg.is_encoder)


class _DeadReplica:
    """Placeholder for an ordinal slot whose runtime failed to build: keeps
    the slot's status-sync cadence alive (the primary's runtime still
    dispatches and syncs on ITS cadence until the reload lands) and makes
    any routed replay fail loudly."""

    def __init__(self, name: str):
        self.name = name
        self._cadence = _OpCadence()


def _slot(replica_lists, specs, mi, ri):
    """The holder at (mi, ri), growing the mirrored structure with dead
    replicas when the primary references an ordinal we never built (a
    protocol bug — kept loud but sync-aligned)."""
    while len(replica_lists) <= mi:
        replica_lists.append([])
        specs.append(("?", None))
    row = replica_lists[mi]
    while len(row) <= ri:
        row.append(_DeadReplica(specs[mi][0]))
    return row[ri]


def run_worker(
    models,
    engine_cfg: EngineConfig,
    mesh,
    dtype=jnp.bfloat16,
    max_steps: Optional[int] = None,
) -> int:
    """Worker-host loop (process_id != 0): replay the primary's dispatches.

    `models`: {name: checkpoint_path_or_None} in the SAME order as the
    primary's --models list — the opcode header routes by that ordinal
    (and by replica ordinal within a dp ReplicaSet). Returns the number of
    ops replayed. `max_steps` bounds the loop for tests; production
    workers run until OP_SHUTDOWN.

    A replay failure is answered over the KV-store status sync: the
    primary fails that runtime loudly and sends OP_RELOAD, which rebuilds
    the replica here from pristine config — no silently-diverged serving.
    """
    from ollamamq_tpu.config import get_model_config, validate_quant_config

    # Same quantization fail-fast the primary's CLI runs: both sides
    # build byte-identical computations, so a worker must reject an
    # unsupported --weights-dtype/--kv-dtype combination at startup too
    # (never mid-replay, where the primary would see a desync).
    err = validate_quant_config(
        engine_cfg.weights_dtype, engine_cfg.kv_dtype,
        pp=dict(mesh.shape).get("pipe", 1),
        sp=dict(mesh.shape).get("seq", 1),
        model_names=list(models))
    if err is not None:
        raise ValueError(err)

    start_heartbeat()
    replica_lists = []  # [model ordinal] -> [replica ordinal] -> runtime|None
    specs = []  # [model ordinal] -> (name, ckpt)
    for name, ckpt in models.items():
        replica_lists.append(_build_runtimes(name, ckpt, engine_cfg, mesh, dtype))
        specs.append((name, ckpt))
    steps = 0
    S = engine_cfg.max_slots
    MP = engine_cfg.max_pages_per_seq
    W = engine_cfg.repeat_last_n
    DATA_OPS = (OP_PREFILL, OP_CHUNK, OP_DECODE, OP_PREFILL_SP, OP_ENCODE,
                OP_EMBED, OP_RAGGED, OP_SPEC)

    wire_seq = 0
    while max_steps is None or steps < max_steps:
        header, raw = _recv_op(wire_seq)
        wire_seq += 1
        op, a, b, mi, ri = (int(x) for x in header)
        if op == OP_SHUTDOWN:
            break
        ok = True
        try:
            payload = _unpack_payload(raw, payload_spec(op, a, b, S, MP, W))
            if op in DATA_OPS:
                rt = _slot(replica_lists, specs, mi, ri)
                if isinstance(rt, _DeadReplica):
                    raise RuntimeError(
                        f"no live runtime at ordinal ({mi},{ri}) for op {op}")
                outs = _replay(rt, op, a, b, payload)
                if _serialize_multihost():
                    # Block on EVERY output (incl. the discarded sampled
                    # tokens): a trailing collective still in flight when
                    # the next broadcast-receive hits the shared gloo
                    # context would interleave and abort the pair.
                    jax.block_until_ready(outs)
            elif op == OP_RELOAD:
                name, ckpt = specs[mi]
                cfg = get_model_config(name)
                old = _slot(replica_lists, specs, mi, ri)
                sub_mesh = (old.mesh if not isinstance(old, _DeadReplica)
                            else _replica_mesh(mesh, engine_cfg, cfg, ri))
                cls = (SPMDEncoderRuntime if cfg.is_encoder
                       else SPMDModelRuntime)
                # Free old HBM before the reload; the dead placeholder holds
                # the slot (and a fresh cadence, mirroring the primary's
                # fresh runtime) if the rebuild below raises.
                replica_lists[mi][ri] = _DeadReplica(name)
                del old
                replica_lists[mi][ri] = cls(
                    name, cfg, engine_cfg, mesh=sub_mesh,
                    checkpoint_path=ckpt, dtype=dtype)
                log.warning("worker reloaded %s (model %d replica %d)",
                            name, mi, ri)
            elif op == OP_LOAD:
                name = _decode_str(payload[0])
                ckpt = _decode_str(payload[1]) or None
                specs.append((name, ckpt))
                try:
                    replica_lists.append(
                        _build_runtimes(name, ckpt, engine_cfg, mesh, dtype))
                except Exception:
                    # Keep ordinals aligned; OP_RELOAD rebuilds the holes.
                    replica_lists.append(
                        [_DeadReplica(name) for _ in range(max(1, a))])
                    raise
            elif op == OP_EVICT:
                name = _decode_str(payload[0])
                if mi >= len(specs) or specs[mi][0] != name:
                    raise RuntimeError(
                        f"evict ordinal {mi} names "
                        f"{specs[mi][0] if mi < len(specs) else '<none>'}, "
                        f"primary said {name}")
                # Deletion is DEFERRED to after the status sync: if the
                # primary's own evict fails post-broadcast it keeps its
                # runtime, and deleting ours here would desync every
                # ordinal > mi with no realignment path (ADVICE r3).
            else:
                log.error("unknown opcode %d; shutting down", op)
                break
        except Exception:
            ok = False
            log.exception("worker op failed (op=%d mi=%d ri=%d); reporting "
                          "desync", op, mi, ri)
        # Status sync: data ops ride the TARGET RUNTIME's cadence (matching
        # the primary's per-runtime cadence); control ops always sync (the
        # primary waits on the result).
        if op in DATA_OPS:
            _slot(replica_lists, specs, mi, ri)._cadence.after_op(ok)
        else:
            flags = _bus.sync(ok)
            if op == OP_LOAD and flags[0]:
                # Primary's own load failed AFTER broadcasting: it never
                # added the model, so drop our entry to realign ordinals.
                replica_lists.pop()
                specs.pop()
            elif op == OP_EVICT and ok:
                if flags[0]:
                    # Primary's evict failed post-broadcast: it kept the
                    # runtime, so we keep ours — ordinals stay aligned.
                    # (ok=True here, so `payload` decoded successfully.)
                    log.error("primary failed evicting %s; keeping our "
                              "replica to stay aligned",
                              _decode_str(payload[0]))
                else:
                    del replica_lists[mi]
                    del specs[mi]
        steps += 1
    return steps


def _replica_mesh(mesh, engine_cfg, cfg, ri):
    from ollamamq_tpu.parallel.mesh import replica_submesh

    if cfg.is_encoder or engine_cfg.dp <= 1 or mesh is None:
        return mesh
    # Same derivation the primary's build_model_runtimes uses — the
    # reloaded worker replica must land on the identical device set.
    return replica_submesh(mesh, ri)


def _serialize_multihost() -> bool:
    # Mirror of TPUEngine._serialize_multihost: CPU-gloo collectives from
    # two concurrently-executing computations interleave differently per
    # process and abort; force one cross-host computation at a time.
    return jax.process_count() > 1 and jax.default_backend() == "cpu"


def _replay(rt, op, a, b, payload):
    """Execute one data op against a worker replica, mirroring the
    primary's dispatch exactly (same jit, same inputs). Returns every
    device output of the replayed computation."""
    if op == OP_PREFILL:
        bucket, B = a, b
        (tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen, pres,
         freq, seeds, key_data) = payload
        key = jnp.asarray(key_data, jnp.uint32)
        toks, rt.kc, rt.vc, rt.recent = ModelRuntime._dispatch_prefill(
            rt, bucket, B, tokens, lens, slot_ids, pt_rows, temp,
            tk, tp, pen, pres, freq, seeds, key)
        return (toks, rt.kc, rt.vc, rt.recent)
    elif op == OP_CHUNK:
        chunk = a
        (tokens, start, cl, slot_id, is_final, is_first, seed_row, pt_row,
         temp, tk, tp, pen, pres, freq, seeds, key_data) = payload
        key = jnp.asarray(key_data, jnp.uint32)
        toks, rt.kc, rt.vc, rt.recent = ModelRuntime._dispatch_chunk(
            rt, chunk, tokens, start, cl, slot_id, is_final, is_first,
            seed_row, pt_row, temp, tk, tp, pen, pres, freq, seeds, key)
        return (toks, rt.kc, rt.vc, rt.recent)
    elif op == OP_DECODE:
        k_steps = a
        (tokens, positions, active, pt, temp, tk, tp, pen, pres,
         freq, seeds, key_data) = payload
        key = jnp.asarray(key_data, jnp.uint32)
        toks, rt.kc, rt.vc, rt.recent = ModelRuntime._dispatch_decode(
            rt, k_steps, tokens, positions, active, pt, temp, tk,
            tp, pen, pres, freq, seeds, key)
        return (toks, rt.kc, rt.vc, rt.recent)
    elif op == OP_PREFILL_SP:
        T = a
        (tokens, lens, slot_ids, pt_rows, temp, tk, tp, pen, pres,
         freq, seeds, key_data) = payload
        key = jnp.asarray(key_data, jnp.uint32)
        toks, rt.kc, rt.vc, rt.recent = ModelRuntime._dispatch_prefill_sp(
            rt, T, tokens, lens, slot_ids, pt_rows, temp, tk, tp,
            pen, pres, freq, seeds, key)
        return (toks, rt.kc, rt.vc, rt.recent)
    elif op == OP_RAGGED:
        T_pad = a
        (tokens, tok_seq, tok_pos, write_slots, q_start, q_len, kv_len,
         ring_len, is_first, append, slot_ids, seed_rows, pt, temp, tk,
         tp, pen, pres, freq, seeds, key_data) = payload
        key = jnp.asarray(key_data, jnp.uint32)
        # No verify spans on this wire shape: is_spec is identically
        # zero on every host (k_cap=0 compiles the 1-column output).
        is_spec = np.zeros_like(q_start)
        toks, n_emit, rt.kc, rt.vc, rt.recent = \
            ModelRuntime._dispatch_ragged(
                rt, T_pad, 0, tokens, tok_seq, tok_pos, write_slots,
                q_start, q_len, kv_len, ring_len, is_first, append,
                is_spec, seed_rows, slot_ids, pt, temp, tk, tp, pen,
                pres, freq, seeds, key)
        return (toks, n_emit, rt.kc, rt.vc, rt.recent)
    elif op == OP_SPEC:
        T_pad, k_cap = a, b
        (tokens, tok_seq, tok_pos, write_slots, q_start, q_len, kv_len,
         ring_len, is_first, append, is_spec, slot_ids, seed_rows, pt,
         temp, tk, tp, pen, pres, freq, seeds, key_data) = payload
        key = jnp.asarray(key_data, jnp.uint32)
        toks, n_emit, rt.kc, rt.vc, rt.recent = \
            ModelRuntime._dispatch_ragged(
                rt, T_pad, k_cap, tokens, tok_seq, tok_pos, write_slots,
                q_start, q_len, kv_len, ring_len, is_first, append,
                is_spec, seed_rows, slot_ids, pt, temp, tk, tp, pen,
                pres, freq, seeds, key)
        return (toks, n_emit, rt.kc, rt.vc, rt.recent)
    elif op == OP_ENCODE:
        B, bucket = a, b
        tokens, lens = payload
        return EncoderRuntime._dispatch_encode(rt, B, bucket, tokens, lens)
    elif op == OP_EMBED:
        B, bucket = a, b
        tokens, lens = payload
        return ModelRuntime._dispatch_embed(rt, B, bucket, tokens, lens)
    else:  # pragma: no cover — guarded by the caller's DATA_OPS check
        raise ValueError(f"not a data op: {op}")
