#!/usr/bin/env bash
# Hardware tuning sweep: run the moment the TPU tunnel answers
# (/tmp/tpu_probe_status.json reports "ok"). Each leg is a fresh process
# (page size / slots are runtime-construction knobs). Legs append to
# $OUT as JSON lines; the headline config is the best tok/s leg.
#
# Usage: scripts/bench_sweep.sh [OUT]
set -u
OUT="${1:-bench_sweep_results.jsonl}"
cd "$(dirname "$0")/.."

leg() {
  local name="$1"; shift
  echo "# leg: $name ($*)" >&2
  local t0=$(date +%s)
  local line rc
  line=$(python bench.py "$@" 2>/dev/null | tail -1; exit "${PIPESTATUS[0]}")
  rc=$?
  local t1=$(date +%s)
  if [ -n "$line" ]; then
    echo "{\"leg\": \"$name\", \"wall_s\": $((t1 - t0)), \"rc\": $rc, \"result\": $line}" >> "$OUT"
    echo "$line" >&2
  else
    echo "{\"leg\": \"$name\", \"wall_s\": $((t1 - t0)), \"rc\": $rc, \"result\": null}" >> "$OUT"
  fi
}

# 1. Current defaults (the shape BENCH_r* runs): chunk sweep inside one leg.
leg baseline           --slots 64  --page-size 32 --chunk 16 --sweep-chunks 8,32,64,128
# 2. Page-size neighbors (r3 said 32 > 16; check 64 too).
leg page16             --slots 64  --page-size 16 --chunk 16
leg page64             --slots 64  --page-size 64 --chunk 16
# 3. Batch scaling: decode is weight-streaming bound, so tok/s should rise
#    with slots until attention/page reads dominate.
leg slots96            --slots 96  --page-size 32 --chunk 16 --sweep-chunks 32,64
leg slots128           --slots 128 --page-size 32 --chunk 16 --sweep-chunks 32,64,128
# 4. Pallas A/B: same shape, kernel off (env prefix passes through).
OLLAMAMQ_NO_PALLAS=1 leg slots128_jnp --slots 128 --page-size 32 --chunk 16 --sweep-chunks 32
# 5. Full-sampler leg (Ollama defaults) on the larger batch.
leg slots128_sampled   --slots 128 --page-size 32 --chunk 16 --sweep-chunks 32 --sampled

echo "sweep done -> $OUT" >&2
