"""Force JAX onto the CPU platform with N virtual devices.

The deployment environment may export a TPU platform (e.g. JAX_PLATFORMS=axon
with a sitecustomize that registers a PJRT plugin in every process); tests and
the driver's multichip dry run must win over that without touching hardware.

This module must stay importable before jax is initialized — it imports jax
itself only inside force_cpu().
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def force_cpu(n_devices: int, check: bool = True) -> None:
    """Pin JAX to CPU with at least ``n_devices`` virtual devices.

    Call before any jax device/backend touch. Sets the env vars (honoring a
    pre-existing --xla_force_host_platform_device_count only if it is already
    large enough — a stale smaller value is replaced) and jax.config, which
    wins even when a sitecustomize pre-registered a TPU plugin.

    ``check=False`` skips the verifying jax.devices() call — required when
    jax.distributed.initialize() must still run before the first backend
    touch (multi-process CPU deployments).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"--{_FLAG}=(\d+)", flags)
    if m is None:
        flags = (flags + f" --{_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        flags = re.sub(rf"--{_FLAG}=\d+", f"--{_FLAG}={n_devices}", flags)
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    if not check:
        return
    # jax caches backends on first touch; if something initialized the real
    # TPU platform before us, the env/config changes above are silently
    # ignored — fail loudly instead of running "multi-chip CPU" work on it.
    plat = jax.devices()[0].platform
    if plat != "cpu":
        raise RuntimeError(
            f"force_cpu() called after jax initialized platform {plat!r}; "
            "call it before any jax device/backend touch"
        )
    n = len(jax.devices())
    if n < n_devices:
        raise RuntimeError(
            f"force_cpu({n_devices}) got only {n} CPU devices; XLA_FLAGS "
            f"({os.environ['XLA_FLAGS']!r}) was read before this call?"
        )
