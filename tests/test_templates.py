"""Chat-template family dispatch + rendering.

With inference in-tree, templating is ours (the reference forwarded chat
bodies to Ollama). The family heuristics misrouting a model silently
degrades every chat completion, so each family's dispatch is pinned here.
"""

from ollamamq_tpu.config import MODEL_CONFIGS
from ollamamq_tpu.server.templates import (
    chat_family,
    render_chat,
    template_owns_bos,
)

MSGS = [
    {"role": "system", "content": "be brief"},
    {"role": "user", "content": "hi"},
]


def test_family_dispatch():
    assert chat_family(MODEL_CONFIGS["llama3:8b"]) == "llama3"
    assert chat_family(MODEL_CONFIGS["llama3.2:1b"]) == "llama3"
    assert chat_family(MODEL_CONFIGS["qwen2.5:7b"]) == "chatml"
    # qwen3 has NO attention bias — the name, not the bias, must route it.
    assert chat_family(MODEL_CONFIGS["qwen3:8b"]) == "chatml"
    # mixtral's 32k vocab fails the llama3 size heuristic — name routes it.
    assert chat_family(MODEL_CONFIGS["mixtral:8x7b"]) == "mistral"
    assert chat_family(MODEL_CONFIGS["test-tiny"]) == "plain"
    assert chat_family(None) == "plain"


def test_llama3_render():
    out = render_chat(MSGS, MODEL_CONFIGS["llama3:8b"])
    assert out.startswith("<|begin_of_text|>")
    assert "<|start_header_id|>system<|end_header_id|>\n\nbe brief<|eot_id|>" in out
    assert out.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")
    assert template_owns_bos(MODEL_CONFIGS["llama3:8b"])


def test_chatml_render_qwen3():
    out = render_chat(MSGS, MODEL_CONFIGS["qwen3:8b"])
    assert out.startswith("<|im_start|>system\nbe brief<|im_end|>\n")
    assert out.endswith("<|im_start|>assistant\n")
    assert template_owns_bos(MODEL_CONFIGS["qwen3:8b"])


def test_mistral_render():
    cfg = MODEL_CONFIGS["mixtral:8x7b"]
    out = render_chat(MSGS, cfg)
    # System text folds into the first user turn.
    assert out == "[INST] be brief\n\nhi [/INST]"
    assert not template_owns_bos(cfg)  # tokenizer still prepends BOS
    # Multi-turn: assistant replies close with </s>.
    multi = MSGS + [{"role": "assistant", "content": "hello"},
                    {"role": "user", "content": "more"}]
    out2 = render_chat(multi, cfg)
    assert out2 == "[INST] be brief\n\nhi [/INST]hello</s>[INST] more [/INST]"
    # Two system messages both survive (append, not overwrite).
    two_sys = [{"role": "system", "content": "A"},
               {"role": "system", "content": "B"},
               {"role": "user", "content": "hi"}]
    assert render_chat(two_sys, cfg) == "[INST] A\n\nB\n\nhi [/INST]"


def test_openai_content_parts():
    msgs = [{"role": "user",
             "content": [{"type": "text", "text": "a"},
                         {"type": "text", "text": "b"}]}]
    assert "ab" in render_chat(msgs, None)
