"""Unified telemetry: metrics registry, request tracing, MFU accounting.

Dependency-free (stdlib only — no jax, no numpy): the same module serves
the engine hot path, the HTTP exposition layer, worker-host snapshot
publishing under SPMD, and the doc-consistency checker in CI.

  metrics.py      process-wide registry (counters / gauges / fixed-bucket
                  histograms) + Prometheus text exposition + mergeable
                  snapshots for multi-host aggregation
  schema.py       THE declaration site for every ollamamq_* metric —
                  imported by the engine/server for handles and by
                  scripts/check_metrics_docs.py for enumeration
  tracing.py      request-lifecycle span traces in a bounded ring buffer,
                  exported as Chrome trace-event JSON (/debug/trace)
  attribution.py  per-request latency attribution: phase timelines from
                  trace events (/debug/requests, /debug/requests/{id})
  slo.py          SLO objectives + multi-window burn-rate alerting + the
                  process-wide alert table (/health, TUI alerts panel)
  mfu.py          analytic FLOPs-per-token model + per-chip peak FLOPs
"""

from ollamamq_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                            MetricsRegistry, REGISTRY)
from ollamamq_tpu.telemetry.slo import Alert, AlertManager, SLOEngine
from ollamamq_tpu.telemetry.tracing import Trace, Tracer

__all__ = [
    "Alert", "AlertManager", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "REGISTRY", "SLOEngine", "Trace", "Tracer",
]
