"""Ring attention: causal attention with the sequence sharded over the
"seq" mesh axis — the framework's long-context / context-parallel prefill.

The reference has no long-context story at all (sequence length was
Ollama's problem — SURVEY.md §5); here it is first-class: a prompt longer
than one chip's HBM/FLOPs budget is split into contiguous chunks across
the "seq" axis, each device computes blockwise attention for its local
queries while K/V blocks rotate around the ring via `lax.ppermute` —
XLA lowers the rotation to ICI neighbor transfers, overlapping them with
the local block's compute. Online (flash-style) softmax accumulation keeps
the math exact vs. full attention.

Causality over the ring: at rotation step s, a device holding query chunk
`i` sees the K/V chunk originally at `(i - s) mod sp`:
  - earlier chunk  -> full attention
  - same chunk     -> causal mask within the block
  - later chunk    -> contributes nothing (masked out entirely)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ollamamq_tpu.ops.attention import repeat_kv
from ollamamq_tpu.parallel.mesh import AXIS_SEQ

NEG_INF = -1e30


def _ring_attention_local(q, k, v, seq_lens, *, axis: str):
    """Per-device body under shard_map.

    q, k, v: [B, C, H(k), hd] — this device's chunk (C = T / sp)
    seq_lens: [B] global valid lengths (replicated)
    """
    idx = jax.lax.axis_index(axis)
    sp = jax.lax.axis_size(axis)
    B, C, Hk, hd = k.shape
    H = q.shape[2]
    n_rep = H // Hk
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))

    qf = q.astype(jnp.float32)
    q_pos = idx * C + jnp.arange(C)  # [C] global positions of local queries

    acc = jnp.zeros((B, H, C, hd), jnp.float32)
    m_i = jnp.full((B, H, C, 1), NEG_INF, jnp.float32)
    l_i = jnp.zeros((B, H, C, 1), jnp.float32)

    def step(s, carry):
        acc, m_i, l_i, k_cur, v_cur = carry
        k_idx = (idx - s) % sp  # which chunk k_cur originally was
        k_pos = k_idx * C + jnp.arange(C)  # [C] global key positions

        kk = repeat_kv(k_cur, n_rep).astype(jnp.float32)
        vv = repeat_kv(v_cur, n_rep).astype(jnp.float32)
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kk) * scale  # [B,H,C,C]
        mask = (k_pos[None, :] <= q_pos[:, None])  # causal across chunks
        mask = mask[None, None] & (k_pos[None, None, None, :] < seq_lens[:, None, None, None])
        logits = jnp.where(mask, logits, NEG_INF)

        m_new = jnp.maximum(m_i, jnp.max(logits, axis=-1, keepdims=True))
        alpha = jnp.exp(m_i - m_new)
        p_ij = jnp.exp(logits - m_new)
        l_new = l_i * alpha + jnp.sum(p_ij, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("bhqk,bkhd->bhqd", p_ij, vv)

        # Rotate K/V around the ring: device d sends to d+1.
        perm = [(d, (d + 1) % sp) for d in range(sp)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return acc_new, m_new, l_new, k_nxt, v_nxt

    acc, m_i, l_i, _, _ = jax.lax.fori_loop(
        0, sp, step, (acc, m_i, l_i, k, v)
    )
    out = acc / jnp.maximum(l_i, 1e-20)  # [B,H,C,hd]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B,C,H,hd]


def ring_attention(
    q: jnp.ndarray,  # [B, T, H, hd] sharded on T over the "seq" axis
    k: jnp.ndarray,  # [B, T, Hk, hd]
    v: jnp.ndarray,
    seq_lens: jnp.ndarray,  # [B] replicated
    mesh: Mesh,
    axis: str = AXIS_SEQ,
) -> jnp.ndarray:
    """Causal ring attention over the mesh's sequence axis. Exact (up to
    f32 accumulation order) vs single-device causal attention."""
    body = functools.partial(_ring_attention_local, axis=axis)
    spec_qkv = P(None, axis, None, None)
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, P()),
        out_specs=spec_qkv,
        check_vma=False,
    )(q, k, v, seq_lens)
