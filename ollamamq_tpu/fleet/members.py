"""Fleet members: the engine replicas a FleetRouter places streams on.

Two shapes, one protocol:

  LocalMember  wraps an in-process engine (TPUEngine / FakeEngine /
               SPMDEngine) — the replica runs its own scheduler loop,
               KV pool, and health monitor inside this process. Replay
               is exact: a failed-over stream carries its generated
               token ids, incremental detokenizer, and penalty context
               (the PR-4 preemption/replay semantics lifted to fleet
               level), so greedy resumed streams are byte-identical.
  HttpMember   wraps a subprocess/remote engine speaking the existing
               HTTP API (the docker-compose "two engine services"
               shape). Health rides the member's /health JSON polled on
               a heartbeat; streams ride /api/generate NDJSON consumed
               by a reader thread; replay is text-level (prompt +
               already-emitted text, token budget shrunk by the emitted
               count) — exact for byte-level tokenizers, best-effort
               where detokenization is context-dependent.

The router is the ONLY consumer of an attempt's TokenStream: member-side
terminal items (including the CANCELLED ack of an eviction) are routing
signals, not client output — the router decides what the client stream
sees.
"""

from __future__ import annotations

import copy
import json
import logging
import threading
import time
import urllib.request
from typing import Optional

from ollamamq_tpu.engine.request import FinishReason, Request, StreamItem

log = logging.getLogger("ollamamq.fleet")

# Alerts that mean a replica cannot be trusted with new placements (the
# /health JSON "degraded" status alone must NOT eject: an SLO burning is
# pressure, not death — app.py /health makes the same distinction).
FATAL_ALERTS = frozenset({"device_offline", "engine_stall"})

_REASONS = {r.value: r for r in FinishReason}


class Attempt:
    """One member-side serving attempt of a client stream. `req` is the
    member-side Request whose TokenStream the router drains; the client
    never sees this object."""

    __slots__ = ("req", "member", "acked", "closed", "transport_dead",
                 "base_n", "n_items", "text_mode", "prior_text",
                 "text_parts", "thread", "resp", "embedding_val")

    def __init__(self, req: Request, member) -> None:
        self.req = req
        self.member = member
        self.acked = False           # member confirmed our eviction
        self.closed = False          # router asked this attempt to stop
        self.transport_dead = False  # HTTP stream died mid-flight
        self.base_n = 0              # tokens emitted by PRIOR attempts
        self.n_items = 0             # token items this attempt emitted
        self.text_mode = False       # replay state is text, not token ids
        self.prior_text = ""         # text emitted by prior attempts
        self.text_parts: list = []
        self.thread: Optional[threading.Thread] = None
        self.resp = None
        self.embedding_val = None

    def tokens_done(self) -> int:
        if self.text_mode:
            return self.base_n + self.n_items
        return len(self.req.generated_ids)

    def embedding(self):
        return self.embedding_val if self.text_mode else self.req.embedding

    def reader_dead(self) -> bool:
        return self.thread is not None and not self.thread.is_alive()

    def resume_state(self) -> dict:
        """Replay state for the NEXT attempt of this stream: everything a
        healthy replica needs to continue it seamlessly."""
        req = self.req
        if self.text_mode:
            return {"gen_ids": None,
                    "n_gen": self.base_n + self.n_items,
                    "text": self.prior_text + "".join(self.text_parts)}
        return {"gen_ids": list(req.generated_ids),
                "n_gen": len(req.generated_ids),
                "inc": req._inc_decode,
                "detok": req._detok_text,
                "emitted": req.emitted_len,
                # Full emitted text, for a cross-shape (local -> HTTP)
                # failover that can only replay in text space.
                "text": req._detok_text[:req.emitted_len]}


class _MemberBase:
    """State the router tracks per member regardless of shape."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.state = "healthy"       # healthy | ejected | draining
        self.backoff_s = 0.0         # set by the router at eject time
        self.next_probe_at = 0.0
        self.eject_count = 0
        self.drain_started_at = 0.0
        self.drain_deadline = 0.0
        self.forced_stale_until = 0.0  # fault site "replica", kind "slow"

    def force_stale(self, delay_s: float) -> None:
        self.forced_stale_until = time.monotonic() + float(delay_s)


class LocalMember(_MemberBase):
    """An in-process engine replica. The engine was constructed by the
    caller (cli/tests) and is started/stopped through this wrapper."""

    kind_label = "local"
    router_bounded = False  # the engine's own capacity gate bounds intake

    def __init__(self, name: str, engine) -> None:
        super().__init__(name)
        self.engine = engine

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.engine.start()

    def stop(self) -> None:
        self.engine.stop()

    def crash(self) -> None:
        """Abrupt loop death (fault injection / observed failure): the
        loop thread exits after its current iteration — deliberately NOT
        a clean stop(), which would join and tidy up the very state a
        real crash leaves behind."""
        self.engine._running = False
        self.engine.notify()

    def restart(self) -> None:
        """Hot restart after a crash or heal: the loop thread (and the
        member's health monitor) come back over the SAME runtimes —
        weights stay resident. The OLD loop thread must be fully dead
        first: it may still be inside a long iteration (a compile, a
        wedged dispatch), and starting a second loop would reset
        _running to True — the zombie then keeps looping, and two loops
        dispatching over the same donated KV buffers poison the runtime
        ("Array has been deleted"). Waits briefly for the first liveness
        tick so the caller's health evaluation sees a fresh heartbeat."""
        old = self.engine._thread
        if old is not None and old.is_alive():
            old.join(timeout=5.0)
            if old.is_alive():
                return  # still wedged: stay ejected, re-probe later
        self.engine._thread = None
        self.engine.start()
        deadline = time.monotonic() + 1.0
        while (time.monotonic() - self.engine.last_tick_at > 0.5
               and time.monotonic() < deadline):
            time.sleep(0.01)

    def hot_restart(self) -> None:
        """Drain-complete restart: clean stop (nothing in flight) then
        start — the rolling-restart primitive."""
        self.engine.stop()
        self.engine.start()

    # -- health ------------------------------------------------------------
    def alive(self) -> bool:
        eng = self.engine
        return bool(eng._running and eng._thread is not None
                    and eng._thread.is_alive())

    def heartbeat_age(self) -> float:
        now = time.monotonic()
        if now < self.forced_stale_until:
            return float("inf")
        return now - self.engine.last_tick_at

    def fatal_alerts(self) -> list:
        alerts = getattr(self.engine, "alerts", None)
        if alerts is None:
            return []
        return [a.name for a in alerts.active() if a.name in FATAL_ALERTS]

    def active_alerts(self) -> list:
        alerts = getattr(self.engine, "alerts", None)
        if alerts is None:
            return []
        return [(a.name, a.severity) for a in alerts.active()]

    # -- placement ---------------------------------------------------------
    def can_take(self, model: str, kind: str) -> bool:
        eng = self.engine
        rt = eng.resolve_runtime(model, kind=kind)
        if rt is None:
            return False
        probe = rt.replicas[0] if hasattr(rt, "replicas") else rt
        if kind not in getattr(probe, "SERVES", ("generate",)):
            return False
        return rt.has_capacity(kind)

    def affinity_pages(self, model: str, tokens) -> int:
        fn = getattr(self.engine, "prefix_match_pages", None)
        return fn(model, tokens) if fn is not None else 0

    # -- streams -----------------------------------------------------------
    def _tokenize(self, model: str, text: str):
        rt = self.engine.resolve_runtime(model)
        if rt is None:
            from ollamamq_tpu.engine.tokenizer import ByteTokenizer

            return ByteTokenizer().encode(text, add_bos=True)
        return rt.tokenizer.encode(text, add_bos=True)

    def begin(self, flight, resume: Optional[dict], on_item=None) -> Attempt:
        sampling = flight.sampling
        if resume and resume.get("gen_ids") is not None:
            # Token-space replay: prompt + every already-emitted token,
            # generation state carried over — the engine's own
            # preemption-replay convention (generated_ids pre-filled, so
            # LENGTH accounting and the fake engine's resume-awareness
            # both hold; the incremental detokenizer never re-sees the
            # replayed ids).
            gen = list(resume["gen_ids"])
            req = Request(0, flight.user, flight.model,
                          list(flight.prompt_tokens) + gen, sampling,
                          kind=flight.kind, raw_prompt=flight.raw_prompt)
            req.generated_ids = list(gen)
            req._replay_gen = len(gen)
            req._inc_decode = resume.get("inc")
            req._detok_text = resume.get("detok", "")
            req.emitted_len = resume.get("emitted", 0)
        elif resume:
            # Text-space replay (stream previously served over HTTP):
            # fold the emitted text into the prompt and shrink the budget.
            n_gen = int(resume.get("n_gen", 0))
            tokens = self._tokenize(
                flight.model, flight.raw_prompt + resume.get("text", ""))
            sampling = copy.copy(sampling)  # copy.copy skips __post_init__
            sampling.max_tokens = max(1, sampling.max_tokens - n_gen)
            req = Request(0, flight.user, flight.model, tokens, sampling,
                          kind=flight.kind, raw_prompt=flight.raw_prompt)
        else:
            req = Request(0, flight.user, flight.model,
                          list(flight.prompt_tokens), sampling,
                          kind=flight.kind, raw_prompt=flight.raw_prompt)
        # The client's deadline is absolute; the attempt must not get a
        # fresh budget just because it re-enqueued later.
        req.deadline = flight.req.deadline
        if on_item is not None:
            req.stream.on_item = on_item
        att = Attempt(req, self)
        if resume and resume.get("gen_ids") is None:
            att.text_mode = True
            att.base_n = int(resume.get("n_gen", 0))
            att.prior_text = resume.get("text", "")
        self.engine.inject_request(req, ip=flight.ip, family=flight.family)
        return att

    def cancel(self, att: Attempt) -> None:
        att.closed = True
        att.req.cancelled.set()
        try:
            self.engine.cancel(att.req.req_id)
        except Exception:  # noqa: BLE001 — a dead member must not block evac
            log.exception("cancel on member %s failed", self.name)


class HttpMember(_MemberBase):
    """A remote engine replica speaking the existing HTTP API. Health is
    the member's /health JSON polled on a heartbeat cadence; staleness =
    no successful poll recently."""

    kind_label = "http"
    router_bounded = True  # no capacity introspection over HTTP

    def __init__(self, name: str, url: str, timeout_s: float = 300.0,
                 poll_period_s: float = 1.0) -> None:
        super().__init__(name)
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s
        self.poll_period_s = poll_period_s
        self._forced_down = False
        self._last_ok = time.monotonic()
        self._status: dict = {}
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._poller is None:
            self._stop.clear()
            self._poller = threading.Thread(
                target=self._poll_loop, name=f"fleet-poll-{self.name}",
                daemon=True)
            self._poller.start()

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=2)
            self._poller = None

    def crash(self) -> None:
        # Fault injection can't kill a remote process; it marks the
        # member down so the router's eject/failover path still runs.
        self._forced_down = True

    def restart(self) -> None:
        self._forced_down = False

    def hot_restart(self) -> None:
        # The remote process restarts itself (rolling deploy); drain's
        # job here was only to quiesce placements first.
        self._forced_down = False

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_period_s):
            try:
                with urllib.request.urlopen(self.url + "/health",
                                            timeout=2.0) as resp:
                    self._status = json.loads(resp.read())
                self._last_ok = time.monotonic()
            except Exception:  # noqa: BLE001 — staleness IS the signal
                pass

    # -- health ------------------------------------------------------------
    def alive(self) -> bool:
        return not self._forced_down

    def heartbeat_age(self) -> float:
        now = time.monotonic()
        if now < self.forced_stale_until or self._forced_down:
            return float("inf")
        return now - self._last_ok

    def fatal_alerts(self) -> list:
        return [a.get("name") for a in self._status.get("alerts", ())
                if a.get("name") in FATAL_ALERTS]

    def active_alerts(self) -> list:
        return [(a.get("name"), a.get("severity"))
                for a in self._status.get("alerts", ())]

    # -- placement ---------------------------------------------------------
    def can_take(self, model: str, kind: str) -> bool:
        return True  # the router bounds in-flight per HTTP member

    def affinity_pages(self, model: str, tokens) -> int:
        return 0  # no cross-process radix probe; falls back to least-loaded

    # -- streams -----------------------------------------------------------
    def begin(self, flight, resume: Optional[dict], on_item=None) -> Attempt:
        n_prior = int(resume.get("n_gen", 0)) if resume else 0
        prior_text = resume.get("text", "") if resume else ""
        req = Request(0, flight.user, flight.model, [], flight.sampling,
                      kind=flight.kind,
                      raw_prompt=flight.raw_prompt + prior_text)
        if on_item is not None:
            req.stream.on_item = on_item
        att = Attempt(req, self)
        att.text_mode = True
        att.base_n = n_prior
        att.prior_text = prior_text
        att.thread = threading.Thread(
            target=self._reader, args=(att, flight, n_prior),
            name=f"fleet-{self.name}-r{flight.rid0}", daemon=True)
        att.thread.start()
        return att

    def _options(self, sampling, remaining: int) -> dict:
        opts = {
            "num_predict": remaining,
            "temperature": sampling.temperature,
            "top_k": sampling.top_k,
            "top_p": sampling.top_p,
            "repeat_penalty": sampling.repeat_penalty,
            "presence_penalty": sampling.presence_penalty,
            "frequency_penalty": sampling.frequency_penalty,
        }
        if sampling.stop:
            opts["stop"] = list(sampling.stop)
        if sampling.seed:
            opts["seed"] = sampling.seed
        return opts

    def _reader(self, att: Attempt, flight, n_prior: int) -> None:
        """(reader thread) Drive one streamed member request, pushing
        items into the attempt stream. A transport failure pushes
        NOTHING terminal: a dead connection is the failover trigger, not
        a client-visible error — the router notices transport_dead and
        re-dispatches the stream."""
        stream = att.req.stream
        try:
            if flight.kind == "embed":
                body = {"model": flight.model, "input": flight.raw_prompt}
                httpreq = urllib.request.Request(
                    self.url + "/api/embed",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json",
                             "X-User-ID": flight.user}, method="POST")
                with urllib.request.urlopen(httpreq,
                                            timeout=self.timeout_s) as resp:
                    out = json.loads(resp.read())
                vecs = out.get("embeddings") or []
                att.embedding_val = vecs[0] if vecs else []
                stream.push(StreamItem("done", finish_reason=FinishReason.STOP))
                return
            remaining = max(1, flight.sampling.max_tokens - n_prior)
            body = {"model": flight.model, "prompt": att.req.raw_prompt,
                    "stream": True,
                    "options": self._options(flight.sampling, remaining)}
            headers = {"Content-Type": "application/json",
                       "X-User-ID": flight.user}
            if flight.req.deadline is not None:
                left_ms = (flight.req.deadline - time.monotonic()) * 1e3
                headers["X-Deadline-Ms"] = str(max(1.0, left_ms))
            httpreq = urllib.request.Request(
                self.url + "/api/generate", data=json.dumps(body).encode(),
                headers=headers, method="POST")
            att.resp = urllib.request.urlopen(httpreq, timeout=self.timeout_s)
            for raw in att.resp:
                if att.closed:
                    return
                try:
                    obj = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if obj.get("error"):
                    reason = _REASONS.get(obj.get("done_reason", ""),
                                          FinishReason.ERROR)
                    stream.push(StreamItem("error", finish_reason=reason,
                                           error=str(obj["error"])))
                    return
                txt = obj.get("response", "")
                if txt:
                    att.n_items += 1
                    att.text_parts.append(txt)
                    stream.push(StreamItem("token", text=txt))
                if obj.get("done"):
                    reason = _REASONS.get(obj.get("done_reason", "stop"),
                                          FinishReason.STOP)
                    stream.push(StreamItem("done", finish_reason=reason))
                    return
            # Stream ended without a done line: the member died mid-write.
            att.transport_dead = True
        except Exception as e:  # noqa: BLE001
            if not att.closed:
                log.warning("member %s stream for req %s died: %s",
                            self.name, flight.rid0, e)
                att.transport_dead = True
        finally:
            resp = att.resp
            if resp is not None:
                try:
                    resp.close()
                except Exception:  # noqa: BLE001
                    pass

    def cancel(self, att: Attempt) -> None:
        att.closed = True
        resp = att.resp
        if resp is not None:
            try:
                resp.close()  # member sees the disconnect and cancels
            except Exception:  # noqa: BLE001
                pass
