"""HTTP API layer: dual Ollama (/api/*) + OpenAI (/v1/*) surface.

Route-for-route parity with the reference router (/root/reference/src/
main.rs:96-124 — 21 explicit routes + optional fallback, 1 GB body limit),
but handlers drive the in-tree TPU engine instead of proxying HTTP:

  - `X-User-ID` header keys the fair-share queue; missing => "anonymous"
    (dispatcher.rs:596-600).
  - blocked user/IP => 403 at ingress (dispatcher.rs:602-610).
  - streaming: NDJSON for /api/*, SSE for /v1/* — the wire formats Ollama
    and OpenAI clients expect; chunks carry tokens from the engine's
    TokenStream rather than relayed HTTP bytes.
  - client disconnect mid-stream cancels the request and frees its KV
    pages (dispatcher.rs:537-551 analogue).
  - request timeout (default 300 s, main.rs:31-32) cancels and errors.
"""

from __future__ import annotations

import asyncio
import functools
import json
import logging
import os
import time
import uuid


from aiohttp import web

from ollamamq_tpu import __version__
from ollamamq_tpu.config import get_model_config
from ollamamq_tpu.core.mqcore import BlockedError, Family
from ollamamq_tpu.engine.engine import QueueFullError
from ollamamq_tpu.engine.request import FinishReason, Request, StreamItem
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.server.registry import ModelRegistry
from ollamamq_tpu.telemetry import stepprof
from ollamamq_tpu.server.templates import render_chat, template_owns_bos

log = logging.getLogger("ollamamq.server")

# Multimodal contract: image payloads are accepted for wire-compat (the
# reference proxies them to vision-capable Ollama backends,
# test_dispatcher.sh:81-104) but no vision path exists here — responses
# carry this warning so the text-only answer is never silent (README
# "Route status"; VERDICT r3 missing #4).
_IMAGES_IGNORED = ("images ignored: this deployment has no vision model; "
                   "the response was generated from text inputs only")

MAX_BODY = 1024 * 1024 * 1024  # 1 GB, main.rs:127


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()) + ".000000000Z"


# Key substrings whose values never belong in a diagnostics bundle. The
# bundle is built to be pasted into tickets/chat — redact by KEY (the only
# reliable signal; value sniffing misses short secrets and false-positives
# on hashes).
_SECRET_KEY_MARKERS = ("token", "secret", "password", "passwd", "api_key",
                       "apikey", "credential", "auth", "cookie", "private")


def _redact(obj):
    """Recursively replace secret-shaped mapping values with a marker."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            kl = str(k).lower()
            if any(m in kl for m in _SECRET_KEY_MARKERS):
                out[k] = "[REDACTED]"
            else:
                out[k] = _redact(v)
        return out
    if isinstance(obj, (list, tuple)):
        return [_redact(v) for v in obj]
    return obj


def _ns(seconds: float) -> int:
    return int(seconds * 1e9)


class ApiError(web.HTTPException):
    def __init__(self, status: int, message: str, headers: dict = None):
        self.status_code = status
        super().__init__(
            text=json.dumps({"error": message}),
            content_type="application/json", headers=headers,
        )


class Server:
    def __init__(self, engine, timeout_s: float = 300.0, allow_all_routes: bool = False):
        self.engine = engine
        self.registry = ModelRegistry(engine)
        self.timeout_s = timeout_s
        self.allow_all_routes = allow_all_routes
        self.started_at = time.time()
        self._profiling = False
        # Router HA epoch fencing (member side): the highest
        # X-Router-Epoch this member has seen. Any call carrying a
        # HIGHER epoch adopts it (the new primary owns us even if its
        # explicit /admin/ha/register never arrived); a LOWER one is a
        # zombie ex-primary and gets fenced with 409. 0 = HA never seen,
        # header-less callers always pass. Persisted next to the WAL
        # when one exists so a member RESTART cannot regress the fence
        # and let the zombie back in; WAL-less members instead re-adopt
        # via the router heartbeat (it re-registers any member whose
        # /health reports a lower epoch within one poll).
        self._ha_epoch = 0
        self._epoch_path = None
        wal_dir = getattr(getattr(engine, "ecfg", None), "wal_dir", None)
        if wal_dir:
            self._epoch_path = os.path.join(wal_dir, "member_epoch.json")
            try:
                with open(self._epoch_path, encoding="utf-8") as f:
                    self._ha_epoch = max(0, int(json.load(f)["epoch"]))
            except (OSError, KeyError, TypeError, ValueError,
                    json.JSONDecodeError):
                pass

    # ------------------------------------------------------------------ app
    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=MAX_BODY)
        r = app.router
        r.add_route("GET", "/health", self.health)
        r.add_route("*", "/", self.root)
        r.add_route("*", "/api/generate", self.api_generate)
        r.add_route("*", "/api/chat", self.api_chat)
        r.add_route("*", "/api/embed", self.api_embed)
        r.add_route("*", "/api/embeddings", self.api_embeddings_legacy)
        r.add_route("*", "/api/tags", self.api_tags)
        r.add_route("*", "/api/show", self.api_show)
        r.add_route("*", "/api/create", self.api_create)
        r.add_route("*", "/api/copy", self.api_copy)
        r.add_route("*", "/api/delete", self.api_delete)
        r.add_route("*", "/api/pull", self.api_pull)
        r.add_route("*", "/api/push", self.api_push)
        r.add_route("*", "/api/blobs/{digest}", self.api_blobs)
        # Client-resumable streams (only with --wal-dir durability): a
        # disconnected client — including one cut off by a server crash
        # + restart — reattaches by the req_id its NDJSON frames carried
        # and receives the remainder byte- and token-identical.
        if getattr(self.engine, "durability", None) is not None:
            r.add_route("GET", "/api/stream/{req_id}", self.api_stream_resume)
        r.add_route("*", "/api/ps", self.api_ps)
        r.add_route("*", "/api/version", self.api_version)
        r.add_route("*", "/v1/chat/completions", self.v1_chat_completions)
        r.add_route("*", "/v1/completions", self.v1_completions)
        r.add_route("*", "/v1/embeddings", self.v1_embeddings)
        r.add_route("*", "/v1/models", self.v1_models)
        r.add_route("*", "/v1/models/{model}", self.v1_model)
        # TPU-era observability: Prometheus exposition, the legacy JSON
        # payload (TUI / scripts), Chrome trace-event request traces,
        # latency attribution, and the one-shot diagnostics bundle.
        r.add_route("GET", "/metrics", self.metrics)
        r.add_route("GET", "/metrics.json", self.metrics_json)
        # Metrics federation wire: the raw registry snapshot a fleet
        # router scrapes on its health heartbeat and re-exports with a
        # `replica` label (mergeable JSON, same shape as the SPMD
        # host-merge path).
        r.add_route("GET", "/metrics/snapshot", self.metrics_snapshot)
        r.add_route("GET", "/debug/trace", self.debug_trace)
        # Fleet-stitched single-stream trace: every process's spans for
        # the stream the client knows as {rid}, merged into one Chrome
        # trace-event timeline whose phase sum equals the client e2e.
        r.add_route("GET", "/debug/trace/{req_id}", self.debug_trace_one)
        r.add_route("GET", "/debug/journal", self.debug_journal)
        r.add_route("GET", "/debug/requests", self.debug_requests)
        r.add_route("GET", "/debug/requests/{req_id}", self.debug_request)
        r.add_route("GET", "/debug/bundle", self.debug_bundle)
        r.add_route("POST", "/debug/profile", self.debug_profile)
        r.add_route("GET", "/debug/stepprof", self.debug_stepprof)
        r.add_route("GET", "/debug/hbm", self.debug_hbm)
        r.add_route("GET", "/debug/prefix_cache", self.debug_prefix_cache)
        r.add_route("POST", "/debug/prefix_cache",
                    self.debug_prefix_cache_flush)
        # Fleet admin (only when the engine IS a fleet router): replica
        # states + zero-drop draining for rolling restarts.
        if hasattr(self.engine, "drain_replica"):
            r.add_route("GET", "/admin/fleet", self.admin_fleet)
            r.add_route("POST", "/admin/drain/{replica}", self.admin_drain)
            # Tiered fleet (--tiers): per-tier status + manual regroup.
            r.add_route("GET", "/admin/tiers", self.admin_tiers)
            r.add_route("POST", "/admin/retier/{replica}",
                        self.admin_retier)
            # Elastic fleet (--autoscale / --preemptible): spot-style
            # termination notice -> migrate-off-then-retire.
            r.add_route("POST", "/admin/preempt/{replica}",
                        self.admin_preempt)
            # Router HA (--ha): the warm standby tails this replication
            # stream. Registered on every router; the handler answers
            # 409 unless the engine is an HA primary RIGHT NOW (a
            # promoted standby starts serving it without a new app).
            r.add_route("GET", "/admin/ha/sync", self.admin_ha_sync)
        # KV migration wire (only when the engine IS an engine, not a
        # router): the fleet's HttpMember speaks these to ship a live
        # stream's pages + request state between member services.
        if hasattr(self.engine, "export_stream"):
            r.add_route("POST", "/admin/migrate/export",
                        self.admin_migrate_export)
            r.add_route("POST", "/admin/migrate/import",
                        self.admin_migrate_import)
            r.add_route("POST", "/admin/migrate/commit",
                        self.admin_migrate_commit)
            r.add_route("POST", "/admin/migrate/abort",
                        self.admin_migrate_abort)
            # Router HA: a (newly promoted) router claims this member
            # under its epoch; older epochs are fenced from here on.
            r.add_route("POST", "/admin/ha/register",
                        self.admin_ha_register)
        if self.allow_all_routes:
            r.add_route("*", "/{tail:.*}", self.fallback)
        return app

    # -------------------------------------------------------------- helpers
    def _ident(self, request: web.Request):
        """(user, ip) + ingress block check => 403 (dispatcher.rs:596-610)."""
        user = request.headers.get("X-User-ID", "anonymous") or "anonymous"
        ip = request.remote or ""
        core = self.engine.core
        if core.is_user_blocked(user):
            raise ApiError(403, f"user '{user}' is blocked")
        if ip and core.is_ip_blocked(ip):
            raise ApiError(403, f"ip '{ip}' is blocked")
        return user, ip

    def _fence(self, got: int, kind: str, path: str):
        """Reject a stale-epoch router call: journal it, count it, 409.
        The zombie gets told exactly why so its logs explain the fence."""
        from ollamamq_tpu.telemetry import schema as tm

        cur = self._ha_epoch
        journal = getattr(self.engine, "journal", None)
        if journal is not None:
            try:
                journal.record("epoch_fence", epoch=cur, stale_epoch=got,
                               path=path, caller=kind)
            except Exception:  # noqa: BLE001
                log.exception("epoch_fence journal failed")
        tm.HA_FENCED_CALLS_TOTAL.labels(kind=kind).inc()
        log.warning("fenced stale-epoch router call: epoch %d < %d (%s)",
                    got, cur, path)
        raise ApiError(
            409, f"stale router epoch {got} (current {cur}): this member "
                 "was taken over by a newer router")

    def _check_epoch(self, request: web.Request, kind: str) -> None:
        """Epoch fence on member-facing placement/migration calls. No
        X-Router-Epoch header (HA off, old routers) always passes; a
        higher epoch is adopted; a lower one is fenced."""
        hdr = request.headers.get("X-Router-Epoch")
        if hdr is None:
            return
        try:
            got = int(hdr)
        except ValueError:
            raise ApiError(400, "X-Router-Epoch must be an integer")
        if got >= self._ha_epoch:
            self._adopt_epoch(got)
            return
        self._fence(got, kind, request.path)

    def _adopt_epoch(self, epoch: int) -> None:
        """Adopt a (new) router epoch, durably when a WAL dir exists:
        write-new-then-rename + fsync, so a member restart revives at
        the fence it held — not at 0, where a zombie ex-primary's
        retried calls would pass again."""
        if epoch == self._ha_epoch:
            return
        self._ha_epoch = epoch
        if self._epoch_path is None:
            return
        tmp = self._epoch_path + ".new"
        try:
            os.makedirs(os.path.dirname(self._epoch_path), exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"epoch": int(epoch)}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._epoch_path)
        except OSError:
            log.exception("member epoch persist failed (epoch %d)", epoch)

    async def _body_json(self, request: web.Request) -> dict:
        if request.method in ("GET", "HEAD"):
            return {}
        try:
            raw = await request.read()
            if not raw:
                return {}
            body = json.loads(raw)
        except json.JSONDecodeError:
            raise ApiError(400, "invalid JSON body")
        if not isinstance(body, dict):
            raise ApiError(400, "request body must be a JSON object")
        return body

    def _resolve_model(self, name: str):
        if not name:
            raise ApiError(400, "missing 'model' field")
        entry = self.registry.resolve(name)
        if entry is None and get_model_config(name) is None:
            raise ApiError(404, f"model '{name}' not found")
        return entry  # may be None: known architecture, not registered

    @staticmethod
    def _trace_ctx(request: web.Request):
        """Propagated fleet trace context (`traceparent` header): the
        fleet router stamps it on member requests so every process's
        spans stitch under the client's stable rid; clients may supply
        their own. None (the default) mints a fresh root context."""
        from ollamamq_tpu.telemetry.tracing import (TRACEPARENT_HEADER,
                                                    valid_ctx)

        ctx = request.headers.get(TRACEPARENT_HEADER)
        return ctx if ctx and valid_ctx(ctx) else None

    def _enqueue(self, user, ip, model, family, prompt_tokens, sampling,
                 kind="generate", raw_prompt="",
                 context_ids=None, trace_ctx=None) -> Request:
        try:
            kw = {"kind": kind, "raw_prompt": raw_prompt}
            if context_ids:
                kw["context_ids"] = context_ids
            if trace_ctx:
                kw["trace_ctx"] = trace_ctx
            return self.engine.enqueue_request(
                user, ip, model, family, prompt_tokens, sampling, **kw,
            )
        except BlockedError as e:
            raise ApiError(403, str(e))
        except QueueFullError as e:
            # Bounded admission: per-user cap => 429 (this client should
            # back off), global cap => 503 (the service is saturated).
            # Retry-After derives from the observed completion rate, not
            # a magic constant.
            status = 429 if e.scope == "user_queue_full" else 503
            raise ApiError(status, str(e), headers={
                "Retry-After": str(max(1, int(round(e.retry_after_s))))})

    @staticmethod
    def _apply_deadline(request: web.Request, sampling) -> None:
        """X-Deadline-Ms header wins over the options/body deadline_ms
        field; junk values are a client error, not a silent ignore."""
        hdr = request.headers.get("X-Deadline-Ms")
        if hdr is None:
            return
        try:
            ms = float(hdr)
        except ValueError:
            raise ApiError(400, "X-Deadline-Ms must be a number "
                                "(milliseconds from arrival)")
        if ms <= 0:
            raise ApiError(400, "X-Deadline-Ms must be > 0")
        sampling.deadline_ms = ms

    def _tokenize(self, model: str, text: str, add_bos: bool = True):
        rt = self.engine.resolve_runtime(model)
        if rt is None:
            # Not loaded: byte-tokenize as a safe default; the request will
            # wait in queue until the model is pulled anyway.
            from ollamamq_tpu.engine.tokenizer import ByteTokenizer

            return ByteTokenizer().encode(text, add_bos=add_bos)
        return rt.tokenizer.encode(text, add_bos=add_bos)

    async def _collect(self, req: Request) -> list:
        """Await all stream items (non-streaming responses). A disconnect
        while waiting cancels the engine-side request."""
        items = []
        try:
            async for item in self._aiter(req):
                items.append(item)
        except asyncio.CancelledError:
            self.engine.cancel(req.req_id)
            raise
        return items

    async def _aiter(self, req: Request):
        """Async iterator over a request's TokenStream with timeout and
        engine wakeup wiring."""
        loop = asyncio.get_running_loop()
        event = asyncio.Event()
        req.stream.on_item = lambda: loop.call_soon_threadsafe(event.set)
        deadline = loop.time() + self.timeout_s
        try:
            while True:
                item = req.stream.get_nowait()
                if item is None:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        # Cancel ENGINE-side too, directly on the request:
                        # engine.cancel alone resolves through req_id,
                        # which a preemption/retry requeue may have just
                        # rotated — without the direct flag the slot and
                        # its KV pages stay held until the generation ends
                        # on its own.
                        req.cancelled.set()
                        self.engine.cancel(req.req_id)
                        yield StreamItem("error", error="request timeout")
                        return
                    try:
                        await asyncio.wait_for(event.wait(), timeout=min(remaining, 1.0))
                    except asyncio.TimeoutError:
                        pass
                    event.clear()
                    continue
                yield item
                if item.kind in ("done", "error"):
                    return
        finally:
            req.stream.on_item = None

    @staticmethod
    def _done_reason(item: StreamItem) -> str:
        if item.finish_reason == FinishReason.LENGTH:
            return "length"
        return "stop"

    @staticmethod
    def _error_reason(item: StreamItem) -> str:
        """done_reason for an error item: degradation terminals keep
        their DISTINCT reason (kv_exhausted / deadline) — a client must
        be able to tell honest resource exhaustion from a generic
        engine error."""
        if item.finish_reason is not None:
            return item.finish_reason.value
        return "error"

    @staticmethod
    def _error_status(item: StreamItem) -> int:
        """HTTP status for a non-streaming error item: an expired
        deadline is a timeout, not an internal error."""
        if item.finish_reason == FinishReason.DEADLINE:
            return 504
        return 500

    @staticmethod
    def _gen_stats(req: Request) -> dict:
        st = req.stats
        total = st.total_duration_s
        eval_dur = max(0.0, (st.finished_at or time.monotonic()) - (st.first_token_at or st.enqueued_at))
        prefill_dur = max(0.0, (st.first_token_at or st.enqueued_at) - st.enqueued_at)
        return {
            "total_duration": _ns(total),
            "load_duration": 0,
            "prompt_eval_count": st.prompt_tokens,
            "prompt_eval_duration": _ns(prefill_dur),
            "eval_count": st.completion_tokens,
            "eval_duration": _ns(eval_dur),
        }

    # ------------------------------------------------------------ liveness
    async def health(self, request: web.Request) -> web.Response:
        """Liveness + degradation. Always 200 (degraded != dead: an LB
        must not evict the only replica because an SLO is burning); the
        body carries "ok"/"degraded" plus every firing alert — SLO burn,
        watchdog stalls, device loss — from the shared alert table.
        Stays open to blocked users, like the reference's /health."""
        alerts = getattr(self.engine, "alerts", None)
        if alerts is None:
            return web.json_response({"status": "ok", "alerts": []})
        active = [a.to_dict() for a in alerts.active()]
        status = "degraded" if active else "ok"
        payload = {"status": status, "alerts": active}
        dur = getattr(self.engine, "durability", None)
        if dur is not None:
            # Readiness gating: while the WAL recovery pass is still
            # re-admitting, the process is up but not ready — an LB/
            # orchestrator keying on "ok" holds traffic until the
            # recovered streams are back in the queue.
            wal = dur.status()
            payload["wal"] = wal
            if wal.get("recovering"):
                payload["status"] = "recovering"
        # Router HA role block (both roles). A standby answers status
        # "standby" — NOT "degraded" — so the stock healthcheck (and an
        # operator's eyeball) reads an idle standby as healthy; during
        # promotion the status says so, and the promoting router's
        # Retry-After tells shed clients when to come back.
        hs_fn = getattr(self.engine, "ha_status", None)
        hs = hs_fn() if hs_fn is not None else None
        if hs is not None:
            payload["role"] = hs.get("role")
            payload["epoch"] = hs.get("epoch")
            payload["sync_lag_records"] = hs.get("sync_lag_records")
            if hs.get("role") in ("standby", "promoting"):
                payload["status"] = hs["role"]
        elif self._ha_epoch:
            # Member side: the adopted fencing epoch, so the router's
            # heartbeat can spot a restarted member that regressed below
            # the fleet epoch and re-register it (closing the zombie
            # window for WAL-less members).
            payload["epoch"] = self._ha_epoch
        return web.json_response(payload)

    async def root(self, request: web.Request) -> web.Response:
        # Ollama answers its root with this exact liveness string; clients
        # (and the reference's health fallback, dispatcher.rs:363-371) use it.
        # Block check applies: the reference routes "/" through its proxy
        # handler, so blocked users 403 everywhere except /health.
        self._ident(request)
        return web.Response(text="Ollama is running")

    async def metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition (format 0.0.4). Scrape-time-derived
        gauges (queue depth per user, per-chip HBM, uptime) refresh here;
        hot-path metrics are already up to date in the registry. The
        snapshot runs off the event loop — core.snapshot and chip_stats
        can block on FFI / device round-trips."""
        self._ident(request)
        text = await asyncio.get_running_loop().run_in_executor(
            None, self._render_prometheus)
        return web.Response(
            body=text.encode(),
            headers={"Content-Type":
                     "text/plain; version=0.0.4; charset=utf-8"})

    def _render_prometheus(self) -> str:
        from ollamamq_tpu.telemetry import REGISTRY
        from ollamamq_tpu.telemetry import schema as tm

        eng = self.engine
        tm.UPTIME_SECONDS.set(time.time() - eng.started_at)
        # Queue depth per user: rebuilt each scrape so departed users'
        # series don't linger.
        try:
            users = eng.core.snapshot().get("users", {})
            tm.QUEUE_DEPTH.clear()
            for user, row in users.items():
                tm.QUEUE_DEPTH.labels(user=user).set(row.get("queued", 0))
        except Exception:
            log.exception("queue-depth scrape failed")
        # Per-chip HBM: chips whose backend has no memory_stats are
        # OMITTED (n/a), never exported as a fake 0-byte reading.
        try:
            tm.HBM_USED_BYTES.clear()
            tm.HBM_TOTAL_BYTES.clear()
            for c in eng.chip_stats():
                if not c.get("memory_stats"):
                    continue
                lab = {"chip": str(c.get("id", 0)),
                       "host": str(c.get("process", 0))}
                tm.HBM_USED_BYTES.labels(**lab).set(c.get("hbm_used", 0))
                tm.HBM_TOTAL_BYTES.labels(**lab).set(c.get("hbm_total", 0))
        except Exception:
            log.exception("chip-stats scrape failed")
        # Active alerts: rebuilt each scrape so resolved alerts' series
        # disappear instead of lingering at 1.
        try:
            tm.SLO_ALERTS_FIRING.clear()
            alerts = getattr(eng, "alerts", None)
            if alerts is not None:
                for a in alerts.active():
                    tm.SLO_ALERTS_FIRING.labels(
                        alert=a.name, severity=a.severity).set(1)
        except Exception:
            log.exception("alert scrape failed")
        extra = []
        try:
            extra = eng.worker_metric_snapshots()
        except Exception:
            log.exception("worker metric snapshot fetch failed")
        # Metrics federation (fleet router): every HTTP member's scraped
        # snapshot re-exports with a `replica` label next to the
        # router's own series — one Prometheus scrape sees the fleet.
        federated = []
        fed_fn = getattr(eng, "member_metric_federation", None)
        if fed_fn is not None:
            try:
                federated = fed_fn()
            except Exception:
                log.exception("member metric federation failed")
        return REGISTRY.render(extra_snapshots=extra, federated=federated)

    async def metrics_snapshot(self, request: web.Request) -> web.Response:
        """Raw registry snapshot (mergeable JSON): the federation wire a
        fleet router scrapes on its member-health heartbeat."""
        self._ident(request)
        from ollamamq_tpu.telemetry import REGISTRY

        snap = await asyncio.get_running_loop().run_in_executor(
            None, REGISTRY.snapshot)
        return web.json_response(snap)

    async def metrics_json(self, request: web.Request) -> web.Response:
        """The pre-Prometheus ad-hoc JSON payload (runtimes/chips/queue);
        the TUI and ops scripts read this shape."""
        self._ident(request)
        return web.json_response(self.engine.stats())

    async def debug_trace(self, request: web.Request) -> web.Response:
        """Request-lifecycle traces as Chrome trace-event JSON: load in
        chrome://tracing or Perfetto to read a wedged/slow request off
        its span timeline. `?ctx=<traceparent>` instead returns this
        process's raw span export for that fleet trace context — the
        stitching wire a fleet router reads to merge member spans under
        the client's rid."""
        self._ident(request)
        tracer = getattr(self.engine, "tracer", None)
        if tracer is None:
            raise ApiError(501, "this engine does not trace requests")
        ctx = request.query.get("ctx")
        if ctx is not None:
            from ollamamq_tpu.telemetry.tracing import valid_ctx

            if not valid_ctx(ctx):
                raise ApiError(400, "'ctx' must be a traceparent-shaped "
                                    "trace context")
            spans = tracer.export_spans(tracer.find_ctx(ctx))
            return web.json_response({"ctx": ctx, "spans": spans})
        return web.json_response(tracer.export_chrome())

    async def debug_trace_one(self, request: web.Request) -> web.Response:
        """ONE stream's merged timeline, fleet-wide: the router's root
        spans plus every member process's spans for the same fleet
        context, stitched into a single Chrome trace-event JSON. The
        `stitched` block carries the attribution invariant upgraded to
        fleet level: phases_ms (handoffs included) sum to the
        client-observed end-to-end wall clock."""
        self._ident(request)
        from ollamamq_tpu.telemetry import tracing

        tracer = getattr(self.engine, "tracer", None)
        if tracer is None:
            raise ApiError(501, "this engine does not trace requests")
        try:
            rid = int(request.match_info["req_id"])
        except ValueError:
            raise ApiError(400, "request id must be an integer")
        spans_fn = getattr(self.engine, "fleet_trace_spans", None)
        loop = asyncio.get_running_loop()
        if spans_fn is not None:
            # Fleet router: member span fetches can ride real sockets —
            # off the event loop.
            spans = await loop.run_in_executor(None, spans_fn, rid)
            root_origin = tracer.origin
        else:
            tr = tracer.find(rid)
            spans = tracer.export_spans([tr]) if tr is not None else []
            root_origin = tracer.origin
        if not spans:
            raise ApiError(404, f"no trace for request {rid} (expired "
                                "from the ring, or never existed)")
        return web.json_response(
            tracing.merged_chrome(spans, root_origin=root_origin))

    async def debug_journal(self, request: web.Request) -> web.Response:
        """Flight-recorder ring tail: the engine's scheduler decision
        journal (telemetry/journal.py) with every record carrying the
        inputs that justified the decision. Filters: `?n=` (tail length,
        default 200), `?req_id=`, `?user=`, `?kind=` (one of the closed
        event vocabulary — unknown kinds are a client error, not an
        empty result)."""
        self._ident(request)
        journal = getattr(self.engine, "journal", None)
        if journal is None:
            raise ApiError(501, "this engine keeps no decision journal")
        from ollamamq_tpu.telemetry.journal import EVENTS

        q = request.query
        try:
            n = int(q.get("n", "200"))
        except ValueError:
            raise ApiError(400, "'n' must be an integer")
        req_id = None
        if q.get("req_id") is not None:
            try:
                req_id = int(q["req_id"])
            except ValueError:
                raise ApiError(400, "'req_id' must be an integer")
        kind = q.get("kind")
        if kind is not None and kind not in EVENTS:
            raise ApiError(400, f"unknown event kind '{kind}' "
                                f"(vocabulary: {', '.join(EVENTS)})")
        events = journal.tail(n=n, req_id=req_id, user=q.get("user"),
                              kind=kind)
        return web.json_response({**journal.snapshot(), "events": events})

    async def debug_requests(self, request: web.Request) -> web.Response:
        """Latency attribution index: every in-flight request (with its
        current phase and how long it has sat there) plus the most recent
        finished timelines. `?recent=N` bounds the finished list."""
        self._ident(request)
        tracer = getattr(self.engine, "tracer", None)
        if tracer is None:
            raise ApiError(501, "this engine does not trace requests")
        from ollamamq_tpu.telemetry import attribution

        try:
            recent = int(request.query.get("recent", "50"))
        except ValueError:
            raise ApiError(400, "'recent' must be an integer")
        return web.json_response(attribution.summarize(tracer, recent=recent))

    async def debug_request(self, request: web.Request) -> web.Response:
        """Full phase timeline for one request: per-phase milliseconds
        (summing to wall-clock e2e) plus the raw lifecycle events."""
        self._ident(request)
        tracer = getattr(self.engine, "tracer", None)
        if tracer is None:
            raise ApiError(501, "this engine does not trace requests")
        try:
            rid = int(request.match_info["req_id"])
        except ValueError:
            raise ApiError(400, "request id must be an integer")
        journal = getattr(self.engine, "journal", None)
        tr = tracer.find(rid)
        if tr is None:
            # WAL-recovered stream, queried by its PRE-CRASH id: the
            # tracer restarted empty, but the recovery pass journaled
            # the old->new aliasing (recover_replay.wal_rid). Answer
            # with the cross-link instead of a dead end — the post-crash
            # timeline is one click away.
            alias = self._recovered_as(journal, rid)
            if alias is not None:
                return web.json_response({
                    "req_id": rid, "state": "recovered",
                    "recovered_as": alias,
                    "timeline": f"/debug/requests/{alias}",
                    "note": ("this id predates a restart; the WAL "
                             "recovery pass re-admitted the stream "
                             f"as request {alias}")})
            raise ApiError(404, f"no trace for request {rid} (expired from "
                                "the ring, or never existed)")
        from ollamamq_tpu.telemetry import attribution

        out = attribution.timeline(tr)
        if journal is not None:
            # The request's slice of the decision journal: WHY it was
            # admitted/batched/preempted/shed, alongside WHERE its time
            # went (the phase timeline above).
            out["journal"] = journal.tail(n=100, req_id=rid)
            # WAL cross-links, both directions: a recovered stream's new
            # timeline names its pre-crash id (wal_rid), and a pre-crash
            # id still in the ring names where it resumed.
            for rec in journal.tail(None, kind="recover_replay"):
                if rec.get("req_id") == rid \
                        and rec.get("wal_rid") is not None:
                    out["wal_rid"] = rec["wal_rid"]
                    out["pre_crash_timeline"] = \
                        f"/debug/requests/{rec['wal_rid']}"
                elif rec.get("wal_rid") == rid:
                    out["recovered_as"] = rec.get("req_id")
        return web.json_response(out)

    @staticmethod
    def _recovered_as(journal, rid: int):
        """The post-recovery id a WAL'd pre-crash `rid` was re-admitted
        under, off the journal's recover_replay records (None = no such
        recovery in the ring)."""
        if journal is None:
            return None
        for rec in journal.tail(None, kind="recover_replay"):
            if rec.get("wal_rid") == rid:
                return rec.get("req_id")
        return None

    async def debug_bundle(self, request: web.Request) -> web.Response:
        """One-shot diagnostics bundle: config, metrics, request
        timelines, prefix-cache stats, SLO state, and the alert table in
        a single JSON document — what an operator attaches to an incident
        before restarting anything. Secret-shaped values are redacted."""
        self._ident(request)
        bundle = await asyncio.get_running_loop().run_in_executor(
            None, self._build_bundle)
        return web.json_response(bundle)

    def _build_bundle(self) -> dict:
        import dataclasses
        import os

        eng = self.engine
        bundle: dict = {
            "generated_at": _now_iso(),
            "version": __version__,
            "uptime_s": round(time.time() - eng.started_at, 1),
        }

        def section(name, fn):
            # Every section is error-contained: a diagnostics endpoint
            # that throws while the engine is sick is worse than useless.
            try:
                bundle[name] = fn()
            except Exception as e:  # noqa: BLE001
                bundle[name] = {"error": f"{type(e).__name__}: {e}"}

        section("config", lambda: _redact(dataclasses.asdict(eng.ecfg)))
        if hasattr(eng, "fleet_status"):
            section("fleet", eng.fleet_status)
        if hasattr(eng, "member_bundles"):
            # Fleet roll-up: each member's own bundle (HTTP members are
            # fetched whole; local members read in-process), redacted
            # like every other section and error-contained PER member.
            section("members", lambda: _redact(eng.member_bundles()))
        section("env", lambda: _redact({
            k: v for k, v in os.environ.items()
            if k.startswith(("OLLAMAMQ_", "JAX_", "TPU_"))}))
        section("models", eng.loaded_models)
        section("stats", eng.stats)
        section("health", lambda: (eng.health.status() if eng.health
                                   else None))
        section("alerts", lambda: eng.alerts.to_dict())
        section("slo", lambda: eng.slo.summary())
        section("metrics", self._render_prometheus)
        # Engine performance plane: step-phase/compile summary + the
        # HBM timeline tail — the dispatch-level accounting an incident
        # bundle needs next to the request timelines.
        section("stepprof", lambda: stepprof.PROFILER.snapshot(64))
        section("hbm", lambda: stepprof.PROFILER.hbm_tail(64))
        if getattr(eng, "tracer", None) is not None:
            from ollamamq_tpu.telemetry import attribution

            section("requests",
                    lambda: attribution.summarize(eng.tracer, recent=50))
        pc = getattr(eng, "prefix_cache_stats", None)
        if pc is not None:
            section("prefix_cache", pc)
        journal = getattr(eng, "journal", None)
        if journal is not None:
            # Redacted flight-recorder tail: the last scheduler decisions
            # before the incident, pasted into the ticket alongside the
            # metrics and timelines they explain.
            section("journal", lambda: _redact(
                {**journal.snapshot(), "events": journal.tail(n=200)}))
        return bundle

    async def debug_prefix_cache(self, request: web.Request) -> web.Response:
        """Prefix-cache stats per model: hit/miss/eviction counters,
        tokens saved, cached/evictable/pinned page counts (replicas
        summed). `enabled: false` when no runtime caches."""
        self._ident(request)
        fn = getattr(self.engine, "prefix_cache_stats", None)
        if fn is None:
            raise ApiError(501, "this engine has no prefix cache")
        stats = await asyncio.get_running_loop().run_in_executor(None, fn)
        return web.json_response(stats)

    async def debug_prefix_cache_flush(self, request: web.Request) -> web.Response:
        """Evict every unreferenced cached page (pinned prefixes of live
        requests survive). Runs on the engine thread — the tree and the
        page allocator are engine-loop state."""
        self._ident(request)
        fn = getattr(self.engine, "prefix_cache_flush", None)
        if fn is None:
            raise ApiError(501, "this engine has no prefix cache")
        try:
            freed = await asyncio.get_running_loop().run_in_executor(None, fn)
        except Exception as e:
            raise ApiError(500, f"prefix-cache flush failed: {e}")
        return web.json_response({"status": "success", "freed_pages": freed})

    # --------------------------------------------------------- fleet admin
    async def admin_fleet(self, request: web.Request) -> web.Response:
        """Fleet status: per-replica state (healthy/ejected/draining),
        heartbeat age, in-flight streams, firing alerts, plus placement
        policy and failover counts."""
        self._ident(request)
        return web.json_response(self.engine.fleet_status())

    async def admin_drain(self, request: web.Request) -> web.Response:
        """Quiesce one replica: no new placements, in-flight streams run
        to completion (stragglers past the drain timeout fail over),
        then hot-restart and rejoin — a rolling restart drops nothing.
        Poll GET /admin/fleet until the replica is healthy again."""
        self._ident(request)
        name = request.match_info["replica"]
        body = await self._body_json(request)
        timeout_s = None
        if "timeout_s" in body:
            try:
                timeout_s = float(body["timeout_s"])
            except (TypeError, ValueError):
                raise ApiError(400, "'timeout_s' must be a number")
            if timeout_s <= 0:
                raise ApiError(400, "'timeout_s' must be > 0")
        try:
            out = self.engine.drain_replica(name, timeout_s=timeout_s)
        except KeyError as e:
            raise ApiError(404, str(e.args[0]) if e.args else str(e))
        except RuntimeError as e:
            raise ApiError(409, str(e))
        return web.json_response({"status": "success", **out})

    async def admin_tiers(self, request: web.Request) -> web.Response:
        """Tiered-fleet status: per-tier membership and states, TTFT
        burn rates and overflow state, the balancer's class-mix EMA,
        and overflow/regroup counters. 404 on an untiered fleet."""
        self._ident(request)
        tiers = getattr(self.engine, "tiers", None)
        if tiers is None:
            raise ApiError(404, "fleet is untiered (--tiers not set)")
        return web.json_response(tiers.status())

    async def admin_retier(self, request: web.Request) -> web.Response:
        """Manually move one replica to the other tier: drain, migrate
        its live streams off, hot-restart at the target tier's TP width
        (or re-label an HTTP member), rejoin. Body: {"tier":
        "interactive"|"bulk", "timeout_s": N?}. Poll GET /admin/tiers
        until the regroup commits (tier_regroup done in the journal)."""
        self._ident(request)
        name = request.match_info["replica"]
        body = await self._body_json(request)
        tier = body.get("tier")
        if not isinstance(tier, str) or not tier:
            raise ApiError(400, "'tier' must name the target tier")
        timeout_s = None
        if "timeout_s" in body:
            try:
                timeout_s = float(body["timeout_s"])
            except (TypeError, ValueError):
                raise ApiError(400, "'timeout_s' must be a number")
            if timeout_s <= 0:
                raise ApiError(400, "'timeout_s' must be > 0")
        try:
            out = self.engine.retier_replica(name, tier,
                                             timeout_s=timeout_s,
                                             why="admin")
        except AttributeError:
            raise ApiError(404, "fleet is untiered (--tiers not set)")
        except KeyError as e:
            raise ApiError(404, str(e.args[0]) if e.args else str(e))
        except ValueError as e:
            raise ApiError(400, str(e))
        except RuntimeError as e:
            raise ApiError(409, str(e))
        return web.json_response({"status": "success", **out})

    async def admin_preempt(self, request: web.Request) -> web.Response:
        """Serve one preemptible replica a termination notice (the spot-
        reclamation path): its live streams migrate off within the
        notice window, then it retires from the fleet — zero dropped
        streams. Body: {"notice_s": N?} (default: the drain timeout).
        Poll GET /admin/fleet until the replica leaves the roster
        (scale_down done in the journal)."""
        self._ident(request)
        name = request.match_info["replica"]
        body = await self._body_json(request)
        notice_s = None
        if "notice_s" in body:
            try:
                notice_s = float(body["notice_s"])
            except (TypeError, ValueError):
                raise ApiError(400, "'notice_s' must be a number")
            if notice_s <= 0:
                raise ApiError(400, "'notice_s' must be > 0")
        try:
            out = self.engine.preempt_replica(name, notice_s=notice_s)
        except KeyError as e:
            raise ApiError(404, str(e.args[0]) if e.args else str(e))
        except ValueError as e:
            raise ApiError(400, str(e))
        except RuntimeError as e:
            raise ApiError(409, str(e))
        return web.json_response({"status": "success", **out})

    # ---------------------------------------------------- router HA wire
    async def admin_ha_sync(self, request: web.Request) -> web.Response:
        """The warm standby's replication poll: `?seq=N` acks everything
        through N and fetches what follows (records, or a whole-file WAL
        snapshot on cold start / ring overrun) plus the shadow-state
        blob. 409 unless this router is an HA primary right now — a
        standby polled by mistake must not serve an empty stream as
        truth."""
        self._ident(request)
        ha = getattr(self.engine, "ha", None)
        if ha is None or not hasattr(ha, "sync_batch"):
            raise ApiError(409, "not an HA primary (no replication "
                                "stream here)")
        try:
            seq = int(request.query.get("seq", "0"))
        except ValueError:
            raise ApiError(400, "'seq' must be an integer")
        # snap=1: the standby's one-time initial-snapshot request (sent
        # until its first snapshot lands). confirm=1: the caught-up
        # handover ack — the only poll that releases a SIGTERM wait.
        want_snapshot = request.query.get("snap") == "1"
        confirm = request.query.get("confirm") == "1"
        # Off the event loop: a cold catch-up reads the whole WAL file.
        resp = await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(ha.sync_batch, seq,
                                    want_snapshot=want_snapshot,
                                    confirm_handover=confirm))
        return web.json_response(resp)

    async def admin_ha_register(self, request: web.Request) -> web.Response:
        """A router (usually a freshly promoted standby) claims this
        member under its epoch. Equal-or-higher adopts; lower is the
        zombie ex-primary and fences (409 + journal + metric)."""
        self._ident(request)
        body = await self._body_json(request)
        try:
            epoch = int(body["epoch"])
        except (KeyError, TypeError, ValueError):
            raise ApiError(400, "'epoch' must be an integer")
        if epoch < self._ha_epoch:
            self._fence(epoch, "register", request.path)
        self._adopt_epoch(epoch)
        return web.json_response({"ok": True, "epoch": epoch})

    # ------------------------------------------------- KV migration wire
    def _migrate_rid(self, body: dict) -> int:
        try:
            return int(body["req_id"])
        except (KeyError, TypeError, ValueError):
            raise ApiError(400, "'req_id' must be an integer")

    async def admin_migrate_export(self, request: web.Request) -> web.Response:
        """Phase 1 of the two-phase handoff, source side: snapshot +
        PARK one live stream's decode slot (pages, decode cursor,
        penalty ring, request state) and ship it as a binary blob. The
        source keeps the parked state until /admin/migrate/commit (the
        target acked) or /admin/migrate/abort (fall back to recompute)
        resolves it. 409 when the request holds no migratable state."""
        self._ident(request)
        self._check_epoch(request, "migrate")
        body = await self._body_json(request)
        rid = self._migrate_rid(body)
        try:
            budget = min(60.0, max(0.1, float(body.get("timeout_s", 10.0))))
        except (TypeError, ValueError):
            raise ApiError(400, "'timeout_s' must be a number")
        deadline = time.monotonic() + budget
        blob = await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.engine.export_stream(rid, deadline))
        if blob is None:
            raise ApiError(
                409, f"request {rid} holds no migratable decode state")
        from ollamamq_tpu.engine.kv_cache import pack_migration_blob

        return web.Response(body=pack_migration_blob(blob),
                            content_type="application/octet-stream")

    async def admin_migrate_import(self, request: web.Request):
        """Target side: install a shipped stream straight into a decode
        slot and STREAM its continuation as /api/generate NDJSON. The
        2xx status line is the import ack the source's commit waits on —
        it is only sent after the slot is installed; a 409 means nothing
        landed and the caller must fall back to recompute."""
        user, ip = self._ident(request)
        self._check_epoch(request, "migrate")
        from ollamamq_tpu.engine.engine import MigrationError
        from ollamamq_tpu.engine.kv_cache import unpack_migration_blob

        raw = await request.read()
        try:
            blob = unpack_migration_blob(raw)
        except ValueError as e:
            raise ApiError(400, f"bad migration blob: {e}")
        deadline = None
        hdr = request.headers.get("X-Deadline-Ms")
        if hdr is not None:
            try:
                deadline = time.monotonic() + max(1.0, float(hdr)) / 1e3
            except ValueError:
                raise ApiError(400, "X-Deadline-Ms must be a number")
        trace_ctx = self._trace_ctx(request)
        try:
            req = await asyncio.get_running_loop().run_in_executor(
                None, lambda: self.engine.import_stream(
                    blob, ip=ip, deadline=deadline, trace_ctx=trace_ctx))
        except MigrationError as e:
            raise ApiError(409, f"migration import failed: {e}")
        model = req.model or (blob.get("request") or {}).get("model", "")
        return await self._ollama_stream(request, model, req, chat=False)

    async def admin_migrate_commit(self, request: web.Request) -> web.Response:
        return await self._migrate_resolve(request, commit=True)

    async def admin_migrate_abort(self, request: web.Request) -> web.Response:
        return await self._migrate_resolve(request, commit=False)

    async def _migrate_resolve(self, request: web.Request,
                               commit: bool) -> web.Response:
        """Phase 2: release the parked source state (commit and abort
        free identically; abort journals why and signals the recompute
        fallback). 404 when no export is parked under that id."""
        self._ident(request)
        self._check_epoch(request, "migrate")
        body = await self._body_json(request)
        rid = self._migrate_rid(body)
        why = str(body.get("why") or "transfer_failed")
        ok = await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.engine.resolve_export(
                rid, commit=commit, why=why))
        if not ok:
            raise ApiError(404, f"no parked migration export for "
                                f"request {rid}")
        return web.json_response({"status": "success", "req_id": rid})

    async def debug_profile(self, request: web.Request) -> web.Response:
        """Capture a jax.profiler trace of the live engine for N seconds
        (the tracing/profiling subsystem the reference lacks entirely).
        View with TensorBoard / xprof.

        The output directory is operator-controlled (OLLAMAMQ_PROFILE_DIR
        env, never the request body), duration is clamped to [0.1, 30] s,
        and only one trace runs at a time.
        """
        import os

        self._ident(request)
        body = await self._body_json(request)
        try:
            seconds = max(0.1, min(float(body.get("seconds", 3.0)), 30.0))
        except (TypeError, ValueError):
            raise ApiError(400, "'seconds' must be a number")
        out_dir = os.environ.get("OLLAMAMQ_PROFILE_DIR", "/tmp/ollamamq-profile")
        if self._profiling:
            raise ApiError(409, "a profile capture is already running")
        self._profiling = True

        def run_trace():
            import jax

            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(seconds)
            finally:
                # stop_trace must run even if the sleep is interrupted:
                # a started-but-never-stopped jax profiler refuses every
                # later start_trace, wedging the endpoint permanently.
                jax.profiler.stop_trace()

        t_start = time.time()
        try:
            await asyncio.get_running_loop().run_in_executor(None, run_trace)
        except Exception as e:
            # A failed capture answers 500 and — via the finally below —
            # clears the capture-running flag, so the NEXT capture gets a
            # fresh try instead of 409 forever.
            raise ApiError(500, f"profile capture failed: {e}")
        finally:
            self._profiling = False
        return web.json_response({
            "status": "success", "trace_dir": out_dir, "seconds": seconds,
            # The capture window's step accounting rides along: the
            # stepprof ring slice taken while the device trace ran, so
            # a trace and its per-phase step samples land together and
            # a TensorBoard timeline can be read against the engine's
            # own host_prep/dispatch/collect/detok attribution.
            "stepprof": stepprof.PROFILER.window(t_start, time.time()),
        })

    async def debug_stepprof(self, request: web.Request) -> web.Response:
        """Engine performance plane: the always-on step profiler's
        bounded ring (telemetry/stepprof.py) — per-mode/per-phase
        p50/p99, the per-shape (mode, T_pad, k_cap) latency table, the
        compile-event ledger, and the profiler's own overhead meter.
        `?n=` bounds the recent-samples/compile-events tails
        (default 128)."""
        self._ident(request)
        try:
            n = int(request.query.get("n", "128"))
        except ValueError:
            raise ApiError(400, "'n' must be an integer")
        return web.json_response(stepprof.PROFILER.snapshot(max(1, n)))

    async def debug_hbm(self, request: web.Request) -> web.Response:
        """Allocator/HBM timeline: the sampled ring of per-runtime page-
        pool state (free/used/cached/pool) and weight/KV byte footprints
        over time — how headroom trends under load, and what an OOM
        postmortem reads back. `?n=` bounds the tail."""
        self._ident(request)
        try:
            n = int(request.query.get("n", "0"))
        except ValueError:
            raise ApiError(400, "'n' must be an integer")
        eng = self.engine
        return web.json_response({
            "period_s": getattr(eng, "HBM_SAMPLE_PERIOD_S", None),
            "timeline": stepprof.PROFILER.hbm_tail(n if n > 0 else None),
        })

    # ------------------------------------------------------------- /api/*
    async def api_generate(self, request: web.Request) -> web.StreamResponse:
        user, ip = self._ident(request)
        # A fenced ex-primary must not place work here (member side).
        self._check_epoch(request, "placement")
        body = await self._body_json(request)
        model = body.get("model", "")
        self._resolve_model(model)
        prompt = body.get("prompt", "")
        stream = body.get("stream", True)
        sampling = SamplingParams.from_ollama_options(
            body.get("options"), self.engine.ecfg.max_new_tokens
        )
        self._apply_deadline(request, sampling)
        # `images` accepted for wire-compat (multimodal payloads flow
        # through the queue like test_dispatcher.sh's 5% image traffic);
        # no vision path exists, so the response SAYS so (a `warnings`
        # field) instead of silently answering from text alone.
        tokens = self._tokenize(model, prompt)
        # Ollama's `context` field: token ids from a prior turn (or the
        # fleet router's token-space failover replay). The engine
        # re-prefills prompt + exact ids and continues the stream from
        # there — num_predict still budgets NEW tokens only.
        context = body.get("context") or []
        if context and not (isinstance(context, list)
                            and all(isinstance(t, int)
                                    and not isinstance(t, bool)
                                    for t in context)):
            raise ApiError(400, "'context' must be a list of token ids")
        req = self._enqueue(user, ip, model, Family.OLLAMA, tokens, sampling,
                            raw_prompt=prompt,
                            context_ids=context or None,
                            trace_ctx=self._trace_ctx(request))
        if body.get("images"):
            req.images_ignored = True

        if not stream:
            items = await self._collect(req)
            return self._ollama_final_response(request, model, req, items, chat=False)
        return await self._ollama_stream(request, model, req, chat=False)

    async def api_chat(self, request: web.Request) -> web.StreamResponse:
        user, ip = self._ident(request)
        body = await self._body_json(request)
        model = body.get("model", "")
        entry = self._resolve_model(model)
        messages = body.get("messages", [])
        stream = body.get("stream", True)
        sampling = SamplingParams.from_ollama_options(
            body.get("options"), self.engine.ecfg.max_new_tokens
        )
        self._apply_deadline(request, sampling)
        chat_cfg = entry.config if entry else get_model_config(model)
        prompt = render_chat(messages, chat_cfg)
        # Templates that emit their own BOS (or define none) must not get a
        # second one from the tokenizer; plain-fallback models still do.
        tokens = self._tokenize(model, prompt,
                                add_bos=not template_owns_bos(chat_cfg))
        req = self._enqueue(user, ip, model, Family.OLLAMA, tokens, sampling,
                            raw_prompt=prompt,
                            trace_ctx=self._trace_ctx(request))
        if any(isinstance(m, dict) and m.get("images") for m in messages):
            req.images_ignored = True

        if not stream:
            items = await self._collect(req)
            return self._ollama_final_response(request, model, req, items, chat=True)
        return await self._ollama_stream(request, model, req, chat=True)

    def _ollama_final_response(self, request, model, req, items, chat: bool):
        err = next((i for i in items if i.kind == "error"), None)
        if err is not None:
            raise ApiError(self._error_status(err),
                           f"engine error: {err.error}")
        text = "".join(i.text for i in items if i.kind == "token")
        done = items[-1]
        payload = {
            "model": model,
            "created_at": _now_iso(),
            "done": True,
            "done_reason": self._done_reason(done),
            **self._gen_stats(req),
        }
        if getattr(req, "images_ignored", False):
            payload["warnings"] = [_IMAGES_IGNORED]
        if chat:
            payload["message"] = {"role": "assistant", "content": text}
        else:
            payload["response"] = text
        return web.json_response(payload)

    async def _ollama_stream(self, request, model, req, chat: bool):
        resp = web.StreamResponse()
        resp.content_type = "application/x-ndjson"
        await resp.prepare(request)

        # Every frame carries the engine-side request id and the sampled
        # token ids its text covers (held-back tokens' ids ride the next
        # written frame, so the id stream is complete): the fleet router
        # reads these to resume a failed-over stream in TOKEN space —
        # verified token-identical — and to key /admin/migrate exports.
        pending_ids: list = []

        def chunk(text):
            p = {"model": model, "created_at": _now_iso(), "done": False,
                 "req_id": req.req_id}
            if pending_ids:
                p["token_ids"] = pending_ids[:]
                pending_ids.clear()
            if chat:
                p["message"] = {"role": "assistant", "content": text}
            else:
                p["response"] = text
            return (json.dumps(p) + "\n").encode()

        try:
            async for item in self._aiter(req):
                if item.kind == "token":
                    if item.token_id >= 0:
                        pending_ids.append(item.token_id)
                    if item.text:
                        await resp.write(chunk(item.text))
                elif item.kind == "error":
                    await resp.write((json.dumps(
                        {"model": model, "created_at": _now_iso(),
                         "done": True, "req_id": req.req_id,
                         "done_reason": self._error_reason(item),
                         "error": item.error}) + "\n").encode())
                    break
                elif item.kind == "done":
                    p = {"model": model, "created_at": _now_iso(), "done": True,
                         "done_reason": self._done_reason(item),
                         "req_id": req.req_id,
                         **self._gen_stats(req)}
                    if pending_ids:
                        p["token_ids"] = pending_ids[:]
                        pending_ids.clear()
                    if getattr(req, "images_ignored", False):
                        p["warnings"] = [_IMAGES_IGNORED]
                    if chat:
                        p["message"] = {"role": "assistant", "content": ""}
                    else:
                        p["response"] = ""
                    await resp.write((json.dumps(p) + "\n").encode())
                    break
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away mid-stream: cancel + reclaim (dropped count).
            self.engine.cancel(req.req_id)
            raise
        await resp.write_eof()
        return resp

    # ------------------------------------------------- resumable streams
    async def api_stream_resume(self, request: web.Request):
        """Reattach to a stream by the `req_id` its NDJSON frames
        carried: replay every frame from token index `?from=N` (default
        0) out of the durability registry's frame log, then follow live
        until the stream's terminal. Works across a server restart —
        the WAL recovery pass re-admits unfinished streams under their
        ORIGINAL ids — and the replayed remainder is byte- and
        token-identical to what an uninterrupted run would have sent.
        This is an observer: disconnecting from it never cancels the
        underlying request."""
        self._ident(request)
        dur = self.engine.durability  # route only exists when attached
        try:
            rid = int(request.match_info["req_id"])
        except ValueError:
            raise ApiError(400, "request id must be an integer")
        try:
            from_n = int(request.query.get("from", "0"))
        except ValueError:
            raise ApiError(400, "'from' must be an integer token index")
        if from_n < 0:
            raise ApiError(400, "'from' must be >= 0")
        entry = dur.registry.find(rid)
        if entry is None:
            raise ApiError(404, f"no resumable stream for request {rid} "
                                "(unknown id, or expired from the "
                                "stream archive)")
        model = ""
        resp = web.StreamResponse()
        resp.content_type = "application/x-ndjson"
        await resp.prepare(request)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.timeout_s
        sent = 0          # frames consumed from the entry
        tokens_seen = 0   # id-carrying frames passed (the ?from cursor)
        try:
            while True:
                frames, terminal = entry.snapshot(sent)
                for tid, text in frames:
                    sent += 1
                    if tokens_seen < from_n:
                        # Still inside the prefix the client already
                        # has: count and skip.
                        if tid >= 0:
                            tokens_seen += 1
                        continue
                    if tid >= 0:
                        tokens_seen += 1
                    p = {"model": model, "created_at": _now_iso(),
                         "done": False, "req_id": entry.rid,
                         "response": text}
                    if tid >= 0:
                        p["token_ids"] = [tid]
                    await resp.write((json.dumps(p) + "\n").encode())
                # A set terminal is final: the registry rejects frame
                # appends after it, and the snapshot is atomic — every
                # frame has been sent by the time we get here.
                if terminal is not None:
                    reason = terminal.get("reason", "stop")
                    p = {"model": model, "created_at": _now_iso(),
                         "done": True, "req_id": entry.rid,
                         "done_reason": reason, "response": ""}
                    if terminal.get("error"):
                        p["error"] = terminal["error"]
                    await resp.write((json.dumps(p) + "\n").encode())
                    break
                if loop.time() > deadline:
                    await resp.write((json.dumps(
                        {"model": model, "created_at": _now_iso(),
                         "done": True, "req_id": entry.rid,
                         "done_reason": "error",
                         "error": "resume timeout"}) + "\n").encode())
                    break
                await asyncio.sleep(0.02)
        except (ConnectionResetError, asyncio.CancelledError):
            # Resume reader gone: the underlying stream keeps running
            # (it can be resumed again); nothing to cancel.
            raise
        await resp.write_eof()
        return resp

    # ------------------------------------------------------------ embeddings
    async def api_embed(self, request: web.Request) -> web.Response:
        user, ip = self._ident(request)
        self._check_epoch(request, "placement")
        body = await self._body_json(request)
        model = body.get("model", "")
        entry = self._resolve_model(model)
        inputs = body.get("input", "")
        single = isinstance(inputs, str)
        texts = [inputs] if single else list(inputs)
        vectors, counts = await self._embed_batch(user, ip, model, texts, entry)
        return web.json_response({
            "model": model,
            "embeddings": vectors,
            "total_duration": 0,
            "load_duration": 0,
            "prompt_eval_count": sum(counts),
        })

    async def api_embeddings_legacy(self, request: web.Request) -> web.Response:
        user, ip = self._ident(request)
        body = await self._body_json(request)
        model = body.get("model", "")
        entry = self._resolve_model(model)
        prompt = body.get("prompt", "")
        vectors, _ = await self._embed_batch(user, ip, model, [prompt], entry)
        return web.json_response({"embedding": vectors[0] if vectors else []})

    async def _embed_batch(self, user, ip, model, texts, entry):
        """Returns (vectors, per-input token counts). `entry` is the
        caller's _resolve_model result. Generative models embed too —
        causal forward + masked mean pool (ModelRuntime.step_embed), the
        same semantics the reference's Ollama backends give /api/embed on
        e.g. llama3; encoder models use their bidirectional path. Unknown
        models still 400 here rather than queueing into a resolve error."""
        cfg = entry.config if entry else get_model_config(model)
        if cfg is None:
            raise ApiError(400, f"model '{model}' is not an embedding model")
        reqs, counts = [], []
        for t in texts:
            tokens = self._tokenize(model, t)
            counts.append(len(tokens))
            req = self._enqueue(user, ip, model, Family.OLLAMA, tokens,
                                SamplingParams(), kind="embed", raw_prompt=t)
            reqs.append(req)
        out = []
        for req in reqs:
            items = await self._collect(req)
            err = next((i for i in items if i.kind == "error"), None)
            if err is not None:
                raise ApiError(500, f"engine error: {err.error}")
            out.append(req.embedding or [])
        return out, counts

    # --------------------------------------------------------- registry api
    async def api_tags(self, request: web.Request) -> web.Response:
        self._ident(request)
        return web.json_response(self.registry.tags_payload())

    async def api_ps(self, request: web.Request) -> web.Response:
        self._ident(request)
        return web.json_response(self.registry.ps_payload())

    async def api_show(self, request: web.Request) -> web.Response:
        self._ident(request)
        body = await self._body_json(request)
        name = body.get("model") or body.get("name") or ""
        payload = self.registry.show_payload(name)
        if payload is None:
            raise ApiError(404, f"model '{name}' not found")
        return web.json_response(payload)

    async def api_pull(self, request: web.Request) -> web.StreamResponse:
        self._ident(request)
        body = await self._body_json(request)
        name = body.get("model") or body.get("name") or ""
        stream = body.get("stream", True)
        if get_model_config(name) is None:
            raise ApiError(404, f"model '{name}' not found in the registry")

        loop = asyncio.get_running_loop()

        async def do_pull():
            await loop.run_in_executor(None, self.registry.pull, name)

        if not stream:
            try:
                await do_pull()
            except NotImplementedError as e:
                # Deliberate deployment-mode gate (e.g. runtime pull under
                # --spmd), not a load failure.
                raise ApiError(501, str(e))
            except Exception as e:
                raise ApiError(500, f"failed to load {name}: {e}")
            return web.json_response({"status": "success"})
        resp = web.StreamResponse()
        resp.content_type = "application/x-ndjson"
        await resp.prepare(request)
        await resp.write((json.dumps({"status": "pulling manifest"}) + "\n").encode())
        await resp.write((json.dumps(
            {"status": f"loading {name} into HBM"}) + "\n").encode())
        try:
            await do_pull()
        except Exception as e:
            # The 200 status is already on the wire; signal failure in-band
            # the way Ollama does (an "error" line instead of "success").
            await resp.write((json.dumps({"error": f"failed to load {name}: {e}"}) + "\n").encode())
            await resp.write_eof()
            return resp
        await resp.write((json.dumps({"status": "success"}) + "\n").encode())
        await resp.write_eof()
        return resp

    async def api_delete(self, request: web.Request) -> web.Response:
        self._ident(request)
        body = await self._body_json(request)
        name = body.get("model") or body.get("name") or ""
        try:
            ok = await asyncio.get_running_loop().run_in_executor(
                None, self.registry.delete, name
            )
        except RuntimeError as e:  # model busy (in-flight work)
            raise ApiError(409, str(e))
        if not ok:
            raise ApiError(404, f"model '{name}' not found")
        return web.json_response({"status": "success"})

    async def api_copy(self, request: web.Request) -> web.Response:
        self._ident(request)
        body = await self._body_json(request)
        src = body.get("source", "")
        dst = body.get("destination", "")
        if not src or not dst:
            raise ApiError(400, "source and destination required")
        if not self.registry.copy(src, dst):
            raise ApiError(404, f"model '{src}' not found")
        return web.json_response({"status": "success"})

    async def api_create(self, request: web.Request) -> web.Response:
        self._ident(request)
        raise ApiError(
            501, "model creation from Modelfiles is not supported; "
                 "register checkpoints via --checkpoints at startup"
        )

    async def api_push(self, request: web.Request) -> web.Response:
        self._ident(request)
        raise ApiError(501, "pushing models to a remote registry is not supported")

    async def api_blobs(self, request: web.Request) -> web.Response:
        self._ident(request)
        raise ApiError(501, "blob upload is not supported on the TPU registry")

    async def api_version(self, request: web.Request) -> web.Response:
        self._ident(request)
        return web.json_response({"version": __version__})

    # --------------------------------------------------------------- /v1/*
    async def v1_chat_completions(self, request: web.Request) -> web.StreamResponse:
        user, ip = self._ident(request)
        body = await self._body_json(request)
        model = body.get("model", "")
        entry = self._resolve_model(model)
        messages = body.get("messages", [])
        stream = body.get("stream", False)
        sampling = SamplingParams.from_openai(body, self.engine.ecfg.max_new_tokens)
        self._apply_deadline(request, sampling)
        chat_cfg = entry.config if entry else get_model_config(model)
        prompt = render_chat(messages, chat_cfg)
        # Templates that emit their own BOS (or define none) must not get a
        # second one from the tokenizer; plain-fallback models still do.
        tokens = self._tokenize(model, prompt,
                                add_bos=not template_owns_bos(chat_cfg))
        req = self._enqueue(user, ip, model, Family.OPENAI, tokens, sampling,
                            raw_prompt=prompt,
                            trace_ctx=self._trace_ctx(request))
        if any(isinstance(p, dict) and p.get("type") == "image_url"
               for m in messages if isinstance(m, dict)
               for p in (m.get("content") if isinstance(m.get("content"),
                                                        list) else [])):
            req.images_ignored = True
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        if stream:
            return await self._openai_stream(request, model, req, rid, chat=True)
        items = await self._collect(req)
        return self._openai_final(model, req, items, rid, chat=True)

    async def v1_completions(self, request: web.Request) -> web.StreamResponse:
        user, ip = self._ident(request)
        body = await self._body_json(request)
        model = body.get("model", "")
        self._resolve_model(model)
        prompt = body.get("prompt", "")
        prompts = prompt if isinstance(prompt, list) else [prompt]
        if not prompts:
            prompts = [""]
        stream = body.get("stream", False)
        sampling = SamplingParams.from_openai(body, self.engine.ecfg.max_new_tokens)
        self._apply_deadline(request, sampling)
        rid = f"cmpl-{uuid.uuid4().hex[:24]}"
        if stream:
            if len(prompts) > 1:
                raise ApiError(400, "streaming with multiple prompts is not supported")
            tokens = self._tokenize(model, prompts[0])
            req = self._enqueue(user, ip, model, Family.OPENAI, tokens, sampling,
                                raw_prompt=prompts[0])
            return await self._openai_stream(request, model, req, rid, chat=False)
        # One choice per prompt (OpenAI list-prompt semantics).
        reqs = [
            self._enqueue(user, ip, model, Family.OPENAI,
                          self._tokenize(model, p), sampling, raw_prompt=p)
            for p in prompts
        ]
        choices, usage_p, usage_c = [], 0, 0
        for i, req in enumerate(reqs):
            items = await self._collect(req)
            err = next((it for it in items if it.kind == "error"), None)
            if err is not None:
                raise ApiError(self._error_status(err),
                               f"engine error: {err.error}")
            text = "".join(it.text for it in items if it.kind == "token")
            choices.append({"index": i, "text": text,
                            "finish_reason": self._done_reason(items[-1])})
            usage_p += req.stats.prompt_tokens
            usage_c += req.stats.completion_tokens
        return web.json_response({
            "id": rid, "object": "text_completion", "created": int(time.time()),
            "model": model, "choices": choices,
            "usage": {"prompt_tokens": usage_p, "completion_tokens": usage_c,
                      "total_tokens": usage_p + usage_c},
        })

    def _openai_final(self, model, req, items, rid, chat: bool):
        err = next((i for i in items if i.kind == "error"), None)
        if err is not None:
            raise ApiError(self._error_status(err),
                           f"engine error: {err.error}")
        text = "".join(i.text for i in items if i.kind == "token")
        done = items[-1]
        choice = {"index": 0, "finish_reason": self._done_reason(done)}
        if chat:
            choice["message"] = {"role": "assistant", "content": text}
        else:
            choice["text"] = text
        out = {
            "id": rid,
            "object": "chat.completion" if chat else "text_completion",
            "created": int(time.time()),
            "model": model,
            "choices": [choice],
            "usage": {
                "prompt_tokens": req.stats.prompt_tokens,
                "completion_tokens": req.stats.completion_tokens,
                "total_tokens": req.stats.prompt_tokens + req.stats.completion_tokens,
            },
        }
        if getattr(req, "images_ignored", False):
            out["warnings"] = [_IMAGES_IGNORED]
        return web.json_response(out)

    async def _openai_stream(self, request, model, req, rid, chat: bool):
        resp = web.StreamResponse()
        resp.content_type = "text/event-stream"
        resp.headers["Cache-Control"] = "no-cache"
        await resp.prepare(request)
        obj = "chat.completion.chunk" if chat else "text_completion"

        def sse(choice):
            return (
                "data: "
                + json.dumps({
                    "id": rid, "object": obj, "created": int(time.time()),
                    "model": model, "choices": [choice],
                })
                + "\n\n"
            ).encode()

        first = True
        try:
            async for item in self._aiter(req):
                if item.kind == "token" and item.text:
                    if chat:
                        delta = {"content": item.text}
                        if first:
                            delta["role"] = "assistant"
                            first = False
                        await resp.write(sse({"index": 0, "delta": delta,
                                              "finish_reason": None}))
                    else:
                        await resp.write(sse({"index": 0, "text": item.text,
                                              "finish_reason": None}))
                elif item.kind == "error":
                    await resp.write(
                        ("data: " + json.dumps(
                            {"error": item.error,
                             "reason": self._error_reason(item)}) +
                         "\n\n").encode()
                    )
                    break
                elif item.kind == "done":
                    fin = {"index": 0, "finish_reason": self._done_reason(item)}
                    if chat:
                        fin["delta"] = {}
                    else:
                        fin["text"] = ""
                    if getattr(req, "images_ignored", False):
                        await resp.write(
                            ("data: " + json.dumps(
                                {"id": rid, "object": obj,
                                 "created": int(time.time()),
                                 "model": model, "choices": [],
                                 "warnings": [_IMAGES_IGNORED]}) +
                             "\n\n").encode())
                    await resp.write(sse(fin))
                    await resp.write(b"data: [DONE]\n\n")
                    break
        except (ConnectionResetError, asyncio.CancelledError):
            self.engine.cancel(req.req_id)
            raise
        await resp.write_eof()
        return resp

    async def v1_embeddings(self, request: web.Request) -> web.Response:
        user, ip = self._ident(request)
        body = await self._body_json(request)
        model = body.get("model", "")
        entry = self._resolve_model(model)
        inputs = body.get("input", "")
        texts = [inputs] if isinstance(inputs, str) else list(inputs)
        vectors, counts = await self._embed_batch(user, ip, model, texts, entry)
        return web.json_response({
            "object": "list",
            "data": [
                {"object": "embedding", "embedding": v, "index": i}
                for i, v in enumerate(vectors)
            ],
            "model": model,
            "usage": {"prompt_tokens": sum(counts),
                      "total_tokens": sum(counts)},
        })

    async def v1_models(self, request: web.Request) -> web.Response:
        self._ident(request)
        return web.json_response(self.registry.openai_models_payload())

    async def v1_model(self, request: web.Request) -> web.Response:
        self._ident(request)
        name = request.match_info["model"]
        entry = self.registry.resolve(name)
        if entry is None:
            raise ApiError(404, f"model '{name}' not found")
        return web.json_response({
            "id": entry.name, "object": "model",
            "created": int(entry.registered_at), "owned_by": "ollamamq-tpu",
        })

    async def fallback(self, request: web.Request) -> web.Response:
        self._ident(request)
        raise ApiError(
            501,
            f"route {request.path} has no TPU-native handler "
            "(--allow-all-routes only exposes the fallback, there is no "
            "backend to proxy to)",
        )
