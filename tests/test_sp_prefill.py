"""Sequence-parallel prefill in the SERVING path (VERDICT r1 item 5):
an sp=2 engine routes long prompts through forward_prefill_sp (ring
attention over the mesh seq axis, K/V scattered into pages) and produces
the same tokens as the sp=1 chunked-prefill engine."""

import time

import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.engine import TPUEngine
from ollamamq_tpu.engine.request import Request
from ollamamq_tpu.ops.sampling import SamplingParams


def cfg(sp):
    return EngineConfig(
        model="test-tiny", max_slots=2, num_pages=128, page_size=8,
        max_pages_per_seq=32, prefill_buckets=(16, 32, 64),
        max_new_tokens=8, decode_steps_per_iter=2, sp=sp,
    )


def collect(req, timeout=120):
    deadline = time.monotonic() + timeout
    items = []
    while time.monotonic() < deadline:
        item = req.stream.get(timeout=0.2)
        if item is None:
            continue
        items.append(item)
        if item.kind in ("done", "error"):
            return items
    raise TimeoutError(f"request {req.req_id} did not finish")


def run_long_prompt(eng, user):
    rt = next(iter(r for r in eng._step_targets()))
    tok = rt.tokenizer
    prompt = "long prompt " * 12  # 145 chars -> ~146 tokens > largest bucket 64
    rid = eng.core.enqueue(user, "", "test-tiny")
    req = Request(rid, user, "test-tiny", tok.encode(prompt),
                  SamplingParams(max_tokens=6))
    eng.submit(req)
    items = collect(req)
    assert items[-1].kind == "done", items[-1]
    return req.generated_ids


@pytest.mark.parametrize("sp", [2])
def test_sp_prefill_matches_chunked(sp):
    eng_sp = TPUEngine(cfg(sp), blocklist_path=None)
    eng_ref = TPUEngine(cfg(1), blocklist_path=None)
    eng_sp.start()
    eng_ref.start()
    try:
        rt_sp = eng_sp.runtimes["test-tiny"]
        assert rt_sp._sp, "sp engine did not enable sequence-parallel prefill"
        ids_sp = run_long_prompt(eng_sp, "sp-user")
        assert ("sp", 192) in rt_sp._prefill_jits or any(
            k[0] == "sp" for k in rt_sp._prefill_jits if isinstance(k, tuple)
        ), f"SP prefill jit never built: {list(rt_sp._prefill_jits)}"
        ids_ref = run_long_prompt(eng_ref, "ref-user")
        assert ids_sp == ids_ref, f"{ids_sp} != {ids_ref}"
    finally:
        eng_sp.stop()
        eng_ref.stop()


def test_sp_decode_continues_after_sp_prefill():
    """After an SP prefill, decode reads the scattered K/V pages: the
    continuation must depend on the actual prompt (two different long
    prompts diverge)."""
    eng = TPUEngine(cfg(2), blocklist_path=None)
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        tok = rt.tokenizer
        outs = []
        for i, text in enumerate(("alpha " * 30, "omega " * 30)):
            rid = eng.core.enqueue(f"u{i}", "", "test-tiny")
            req = Request(rid, f"u{i}", "test-tiny", tok.encode(text),
                          SamplingParams(max_tokens=6))
            eng.submit(req)
            items = collect(req)
            assert items[-1].kind == "done"
            outs.append(req.generated_ids)
        assert outs[0] != outs[1], "decode ignored the prefilled context"
    finally:
        eng.stop()
