"""Int8 quantization primitives: weights and paged KV cache.

Two quantized containers, both plain NamedTuples (JAX treats them as
pytrees, so they flow through jit/scan/donation/sharding unchanged):

  QuantTensor — a weight matrix as (q: int8, s: f32 per-channel scales).
    Symmetric per-channel quantization: q = round(w / s), s chosen per
    OUTPUT channel so each channel's max magnitude maps to 127. Layer
    matmul weights quantize along their LAST axis (the output features of
    "btd,de->bte"-shaped einsums); embed/lm_head quantize along axis 0
    (per vocab row — the output channel of the logits einsum AND the
    gathered row of the embedding lookup, so one scale vector serves
    both uses).

  QuantKV — one KV slot pool as (q: int8 [..., S, Hk, hd],
    s: f32 [..., S, Hk]). Scales are per token-slot per kv-head, stored
    page-aligned alongside the pool (slot index == page * page_size +
    offset), so the allocator/prefix-tree/preemption/rollback machinery
    is untouched: pages just shrink ~2x and their scale rows travel with
    the same page ids. Per-slot (not per-page-amax) scales keep writes
    exact and incremental — a decode step writes one token's row without
    requantizing the rest of the page.

The dequant-fused entry points keep quantized data in its narrow dtype
until inside the consuming op: `qeinsum` casts int8 weights to the
activation dtype inside the contraction (HBM streams int8 bytes; the
MXU accumulates in bf16/f32 as usual), and `kv_gather` dequantizes
gathered page rows straight to f32 for the softmax path.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp

# Epsilon floor for scales: an all-zero channel/row must not divide by 0.
_EPS = 1e-8


class QuantTensor(NamedTuple):
    """Per-channel symmetric int8 weight: w ≈ q * s (s broadcast along
    the quantized axis)."""

    q: Any  # int8 payload, original weight shape
    s: Any  # f32 scales, shaped to broadcast against q

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        return self.q.nbytes + self.s.nbytes


class QuantKV(NamedTuple):
    """One quantized KV slot pool: q int8 [..., S, Hk, hd] plus
    page-aligned per-slot per-head scales s f32 [..., S, Hk]."""

    q: Any
    s: Any

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        return self.q.nbytes + self.s.nbytes


# -- weights ---------------------------------------------------------------
def quantize_tensor(w, axis: int = -1) -> QuantTensor:
    """Per-channel symmetric int8 quantization of `w` along `axis` (the
    channel axis KEEPS its extent in s; every other axis of s matches w,
    reduced away). s keeps a broadcastable singleton where the reduced
    axes were NOT — concretely: s = amax(|w|, all axes except `axis`)?
    No: per-channel means ONE scale per slice along `axis`... the
    convention here is one scale per index of `axis`, shared by the
    whole slice — but layer stacks carry a leading L that must stay
    per-layer. So the reduction is over every axis EXCEPT leading
    "batch-like" axes and `axis` itself: for a [L, d, e] stack with
    axis=-1 the scales are [L, e]; for [V, D] with axis=0 they are [V].
    """
    wf = jnp.asarray(w, jnp.float32)
    nd = wf.ndim
    axis = axis % nd
    if axis == nd - 1:
        # [..., d, e] -> reduce d: scales [..., e] (per trailing channel,
        # per leading layer).
        amax = jnp.max(jnp.abs(wf), axis=-2)
        s = jnp.maximum(amax, _EPS) / 127.0
        q = jnp.clip(jnp.round(wf / s[..., None, :]), -127, 127)
        return QuantTensor(q.astype(jnp.int8), s.astype(jnp.float32))
    if axis == 0:
        # [V, ...] -> reduce everything else: scales [V] (per row).
        amax = jnp.max(jnp.abs(wf), axis=tuple(range(1, nd)))
        s = jnp.maximum(amax, _EPS) / 127.0
        sb = s.reshape((-1,) + (1,) * (nd - 1))
        q = jnp.clip(jnp.round(wf / sb), -127, 127)
        return QuantTensor(q.astype(jnp.int8), s.astype(jnp.float32))
    raise ValueError(f"unsupported quantization axis {axis} for ndim {nd}")


def dequantize_tensor(t: QuantTensor, axis: int = -1, dtype=jnp.float32):
    """Inverse of quantize_tensor (tests/roundtrip bounds)."""
    qf = t.q.astype(jnp.float32)
    nd = qf.ndim
    axis = axis % nd
    if axis == nd - 1:
        return (qf * t.s[..., None, :]).astype(dtype)
    sb = t.s.reshape((-1,) + (1,) * (nd - 1))
    return (qf * sb).astype(dtype)


def qeinsum(spec: str, x, w):
    """Dequant-fused einsum over a last-axis-quantized weight: the int8
    payload is cast to the activation dtype INSIDE the contraction (XLA
    fuses the convert, so HBM streams half the bytes of bf16) and the
    f32 per-channel scale lands on the output's trailing channel axis.
    Raw arrays pass straight through — every matmul call site uses this
    one entry point, so quantized params flow through the forwards with
    no shape changes."""
    if isinstance(w, QuantTensor):
        y = jnp.einsum(spec, x, w.q.astype(x.dtype))
        return (y * w.s).astype(x.dtype)
    return jnp.einsum(spec, x, w)


def embed_lookup(embed, tokens, dtype):
    """Embedding-row gather with optional row-quantized table: gathered
    int8 rows dequantize by their row scale. `dtype` names the activation
    dtype (the caller's norm weights carry it — norms stay unquantized)."""
    if isinstance(embed, QuantTensor):
        rows = embed.q[tokens].astype(dtype)
        return (rows * embed.s[tokens][..., None]).astype(dtype)
    return embed[tokens].astype(dtype)


def logits_head(x, head):
    """lm_head/tied-embed logits einsum ("btd,vd->btv") in f32, with the
    row-quantized head dequant-fused: per-vocab-row scales multiply the
    logit columns."""
    if isinstance(head, QuantTensor):
        y = jnp.einsum("btd,vd->btv", x.astype(jnp.float32),
                       head.q.astype(jnp.float32))
        return y * head.s
    return jnp.einsum("btd,vd->btv", x.astype(jnp.float32),
                      head.astype(jnp.float32))


# -- KV cache --------------------------------------------------------------
def kv_quantize(vals):
    """Quantize K/V rows [..., Hk, hd] -> (int8 rows, f32 [..., Hk]
    scales): symmetric amax over head_dim per token per head."""
    vf = jnp.asarray(vals, jnp.float32)
    amax = jnp.max(jnp.abs(vf), axis=-1)
    s = jnp.maximum(amax, _EPS) / 127.0
    q = jnp.clip(jnp.round(vf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s.astype(jnp.float32)


def kv_write(cache, slots, vals):
    """Scatter-write K/V rows into the slot pool, quantizing on the fly
    when the pool is int8. `slots` indexes the pool's slot axis; `vals`
    is [..., Hk, hd] matching the indexed shape. Returns the updated
    pool (same container type — QuantKV scatters payload AND scales)."""
    if isinstance(cache, QuantKV):
        q, s = kv_quantize(vals)
        return QuantKV(cache.q.at[slots].set(q), cache.s.at[slots].set(s))
    return cache.at[slots].set(vals)


def kv_gather(cache, slots):
    """Gather K/V rows from the slot pool, dequantizing int8 pools to
    f32 (the softmax path consumes f32 regardless of pool dtype)."""
    if isinstance(cache, QuantKV):
        return cache.q[slots].astype(jnp.float32) * cache.s[slots][..., None]
    return cache[slots]
