"""SPMD failure recovery and dp replica serving across hosts.

Two 2-process CPU deployments:

1. Worker desync: a worker-side replay failure must surface LOUDLY on the
   primary (the in-flight request errors), then the reload opcode rebuilds
   the runtime on every host and serving resumes — no silently-diverged
   tokens (VERDICT r2 "what's weak" #2). Also exercises runtime model
   load (OP_LOAD → /api/pull under --spmd) after the recovery.

2. dp=2 replica serving under --spmd: make_mesh arranges the dp axis
   intra-host so each replica's submesh spans both processes; the wire
   header's replica ordinal routes worker replays (VERDICT r2 missing #3).
"""

import json
import os
import subprocess
import sys

import pytest

from testutil import free_port

_DESYNC_SCRIPT = r"""
import json, os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local device per process
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
assert jax.device_count() == 2

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.parallel.mesh import make_mesh
import jax.numpy as jnp

mesh = make_mesh(dp=1, sp=1, tp=2)
ecfg = EngineConfig(model="test-tiny", max_slots=2, num_pages=32, page_size=8,
                    max_pages_per_seq=8, prefill_buckets=(16,),
                    decode_steps_per_iter=2)
MODELS = {"test-tiny": None}

if pid == 0:
    import time
    from ollamamq_tpu.engine.spmd import SPMDEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = SPMDEngine(ecfg, models=MODELS, blocklist_path=None,
                     mesh=mesh, dtype=jnp.float32)
    eng.recover_interval = 0.5
    eng.start()

    def wait(req, budget=300):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.5)
            if item and item.kind in ("done", "error"):
                return item
        return None

    tok = eng.runtimes["test-tiny"].tokenizer
    req1 = eng.enqueue_request("u", "", "test-tiny",
                               prompt_tokens=tok.encode("first request"),
                               sampling=SamplingParams(max_tokens=4))
    item1 = wait(req1)
    loud = bool(item1 and item1.kind == "error")

    # Wait for the reload to swap a fresh runtime in.
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        rt = eng.runtimes["test-tiny"]
        if not getattr(rt, "_failed", False):
            break
        time.sleep(0.2)
    recovered = not getattr(eng.runtimes["test-tiny"], "_failed", True)

    req2 = eng.enqueue_request("u", "", "test-tiny",
                               prompt_tokens=tok.encode("first request"),
                               sampling=SamplingParams(max_tokens=4))
    item2 = wait(req2)

    # Runtime model load across hosts (OP_LOAD == /api/pull under --spmd).
    eng.load_model("test-tiny-embed")
    etok = eng.runtimes["test-tiny-embed"].tokenizer
    ereq = eng.enqueue_request("u", "", "test-tiny-embed",
                               prompt_tokens=etok.encode("embed me"),
                               sampling=SamplingParams(), kind="embed")
    eitem = wait(ereq)
    eng.stop()
    print("RESULT " + json.dumps({
        "loud": loud,
        "recovered": recovered,
        "tokens2": req2.generated_ids,
        "done2": bool(item2 and item2.kind == "done"),
        "embed_ok": bool(eitem and eitem.kind == "done"),
        "embed_dim": len(ereq.embedding or []),
    }), flush=True)
else:
    from ollamamq_tpu.engine import spmd

    orig = spmd._replay
    state = {"tripped": False}

    def sabotage(rt, op, a, b, payload):
        # Fail AFTER the dispatch is issued (device-side error class: both
        # hosts ran the computation, but this worker's post-step state
        # update is lost) — the class the reload path recovers cleanly.
        out = orig(rt, op, a, b, payload)
        if op == spmd.OP_DECODE and not state["tripped"]:
            state["tripped"] = True
            rt.recent = rt.recent * 0  # diverged state a real bug would leave
            raise RuntimeError("injected worker decode failure")
        return out

    spmd._replay = sabotage
    steps = spmd.run_worker(MODELS, ecfg, mesh, dtype=jnp.float32)
    print("RESULT " + json.dumps(
        {"steps": steps, "tripped": state["tripped"]}), flush=True)
"""

_DP_SCRIPT = r"""
import json, os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
assert jax.device_count() == 4

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.parallel.mesh import make_mesh
import jax.numpy as jnp

mesh = make_mesh(dp=2, sp=1, tp=2)
# Every dp slice must span both processes (the intra-host arrangement).
for r in range(2):
    procs = {d.process_index for d in mesh.devices[r].flat}
    assert procs == {0, 1}, procs

ecfg = EngineConfig(model="test-tiny", max_slots=2, num_pages=32, page_size=8,
                    max_pages_per_seq=8, prefill_buckets=(16,),
                    decode_steps_per_iter=2, dp=2, tp=2)
MODELS = {"test-tiny": None}

if pid == 0:
    import time
    from ollamamq_tpu.engine.spmd import SPMDEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = SPMDEngine(ecfg, models=MODELS, blocklist_path=None,
                     mesh=mesh, dtype=jnp.float32)
    rt = eng.runtimes["test-tiny"]
    n_replicas = len(rt.replicas)
    eng.start()

    def wait(req, budget=300):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.5)
            if item and item.kind in ("done", "error"):
                return item
        return None

    tok = rt.tokenizer
    prompt = tok.encode("replica parity")
    reqs = [eng.enqueue_request(f"user{i}", "", "test-tiny",
                                prompt_tokens=list(prompt),
                                sampling=SamplingParams(max_tokens=5))
            for i in range(2)]
    items = [wait(r) for r in reqs]
    served = {id(rep): rep.tokens_generated for rep in rt.replicas}
    eng.stop()
    print("RESULT " + json.dumps({
        "n_replicas": n_replicas,
        "done": [bool(i and i.kind == "done") for i in items],
        "tokens": [r.generated_ids for r in reqs],
        "both_replicas_served": all(v > 0 for v in served.values()),
    }), flush=True)
else:
    from ollamamq_tpu.engine import spmd

    steps = spmd.run_worker(MODELS, ecfg, mesh, dtype=jnp.float32)
    print("RESULT " + json.dumps({"steps": steps}), flush=True)
"""



def _launch(script_text, tmp_path, timeout=540):
    port = free_port()
    script = tmp_path / "spmd_child.py"
    script.write_text(script_text)
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("SPMD processes hung")
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
        outs.append(out)
    return [
        json.loads([l for l in o.splitlines() if l.startswith("RESULT ")][0][7:])
        for o in outs
    ]


def test_spmd_worker_desync_fails_loud_then_reloads(tmp_path):
    primary, worker = _launch(_DESYNC_SCRIPT, tmp_path)
    assert worker["tripped"], "sabotage never fired"
    # The poisoned step must error the request — not serve diverged tokens.
    assert primary["loud"], "worker desync was silent"
    # The reload opcode rebuilt the runtime on every host and serving resumed.
    assert primary["recovered"]
    assert primary["done2"] and len(primary["tokens2"]) >= 1
    # Runtime /api/pull after recovery (OP_LOAD) served an embedding.
    assert primary["embed_ok"] and primary["embed_dim"] > 0


def test_spmd_dp_replicas_across_hosts(tmp_path):
    primary, worker = _launch(_DP_SCRIPT, tmp_path)
    assert primary["n_replicas"] == 2
    assert primary["done"] == [True, True]
    # Greedy decode of the same prompt on either replica must agree exactly
    # (replicas share seed/weights), proving replica-ordinal routing kept
    # worker KV state in step on both submeshes.
    assert primary["tokens"][0] == primary["tokens"][1]
    assert primary["both_replicas_served"]
    assert worker["steps"] >= 4
