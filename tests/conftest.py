"""Test config: force JAX onto CPU with 8 virtual devices BEFORE jax import,
so mesh/sharding logic is exercised without a TPU (SURVEY.md §4)."""

from ollamamq_tpu.platform_force import force_cpu

force_cpu(8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_cfg():
    from ollamamq_tpu.config import MODEL_CONFIGS

    return MODEL_CONFIGS["test-tiny"]


@pytest.fixture(scope="session")
def tiny_params(tiny_cfg):
    import jax
    import jax.numpy as jnp
    from ollamamq_tpu.models import llama

    return llama.init_params(tiny_cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
