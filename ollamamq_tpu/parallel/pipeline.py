"""Pipeline parallelism: layers sharded over the mesh "pipe" axis.

The reference scales by adding whole HTTP backends (one full model copy
each — /root/reference/src/dispatcher.rs:434-482); it has no way to serve
a model LARGER than one backend's memory. Pipeline parallelism is that
missing axis: the stacked layer parameters [L, ...] (already the repo's
scan-over-layers layout, models/llama.py) shard their leading L dim over
the "pipe" mesh axis, so each chip group holds only L/P layers' weights
and L/P layers' KV pages — the per-chip HBM footprint drops by P.

TPU-native schedule (not a translation of GPU send/recv pipelines):
  - One `jax.shard_map` over the whole mesh; each pipe stage runs the
    SAME traced program (SPMD), scanning its local layer stack.
  - GPipe-style microbatching: the batch splits into M microbatches; at
    schedule step t, stage p works on microbatch (t - p). Activations
    hand off between stages via a single `lax.ppermute` per step — XLA
    lowers it to an ICI neighbor copy that overlaps the next stage's
    compute. M + P - 1 steps drain the pipeline.
  - Bubble steps (t - p outside [0, M)) compute on garbage and write
    their K/V to the allocator's trash page (slot 0 — engine/kv_cache.py
    TRASH_PAGE), keeping every step fully static-shaped: no cond, no
    dynamic shapes, one compiled program.
  - Composes with tensor parallelism INSIDE each stage: head/FFN dims
    stay sharded over "tensor" and the row-parallel matmuls (wo, w_down)
    reduce via `lax.psum` — identity when tp == 1, Megatron-style TP
    when tp > 1 (works with replicated-group KV too, since the shards'
    local shapes carry the already-rewritten head counts). Embedding and
    lm_head stay vocab-sharded over "tensor" via masked local lookup +
    psum.

All three serving forwards share one stage body (`_tp_layer`) and one
schedule loop (`_pipeline_schedule`); they differ only in the attention
call and the per-microbatch operands. Numerics match the single-device
forwards exactly (same per-layer math, same f32 softmax); only the
schedule is distributed — pinned by tests/test_pipeline.py against
forward_prefill / forward_prefill_chunk / forward_decode.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ollamamq_tpu.config import ModelConfig
from ollamamq_tpu.models.llama import rmsnorm
from ollamamq_tpu.ops.attention import (
    causal_attention,
    flat_slot_indices,
    paged_chunk_attention_blockwise,
    paged_decode_attention_any,
)
from ollamamq_tpu.ops.rope import apply_rope
from ollamamq_tpu.parallel.mesh import AXIS_PIPE, AXIS_TENSOR
from ollamamq_tpu.parallel.sharding import pipeline_param_specs

KV_SPEC = P(AXIS_PIPE, None, AXIS_TENSOR, None)


def n_microbatches(batch: int, pipe: int, requested: Optional[int] = None) -> int:
    """Microbatch count: the largest divisor of `batch` that is <= the
    requested count (default: the stage count, which keeps every stage
    busy in steady state with the fewest handoffs)."""
    m = min(requested or pipe, batch)
    while batch % m:
        m -= 1
    return max(m, 1)


# ---------------------------------------------------------------------------
# Per-stage layer math (tensor-parallel inside the stage).
#
# Mirrors models/llama.py's layer bodies, except the head / FFN dims are
# tensor-LOCAL shards and the row-parallel outputs (wo, w_down) reduce
# with an explicit psum — under shard_map the collective XLA would
# otherwise infer from shardings must be written out.
# ---------------------------------------------------------------------------


def _tp_qkv(cfg: ModelConfig, lp: dict, h: jnp.ndarray):
    B, T, _ = h.shape
    hd = cfg.head_dim
    q = jnp.einsum("btd,de->bte", h, lp["wq"])
    k = jnp.einsum("btd,de->bte", h, lp["wk"])
    v = jnp.einsum("btd,de->bte", h, lp["wv"])
    if "bq" in lp:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(B, T, q.shape[-1] // hd, hd)
    k = k.reshape(B, T, k.shape[-1] // hd, hd)
    v = v.reshape(B, T, v.shape[-1] // hd, hd)
    if cfg.qk_norm:
        q = rmsnorm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rmsnorm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _tp_mlp(lp: dict, h: jnp.ndarray) -> jnp.ndarray:
    gate = jnp.einsum("btd,df->btf", h, lp["w_gate"])
    up = jnp.einsum("btd,df->btf", h, lp["w_up"])
    down = jnp.einsum("btf,fd->btd", jax.nn.silu(gate) * up, lp["w_down"])
    return lax.psum(down, AXIS_TENSOR)


def _tp_layer(cfg, lp, x, positions, kcl, vcl, attn_and_cache):
    """One transformer layer on this stage — the SINGLE definition of the
    stage layer math (prefill, chunk, and decode inject only the
    attention/KV-write schedule via `attn_and_cache`).

    x: [mb, T, D]; kcl/vcl: ONE local layer's [S, Hk_loc, hd] cache.
    attn_and_cache(q, k, v, kcl, vcl) -> (attn [mb, T, H_loc*hd], kcl, vcl)
    writes the new K/V wherever its schedule wants them, then attends.
    """
    B, T, _ = x.shape
    h = rmsnorm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q, k, v = _tp_qkv(cfg, lp, h)
    q = apply_rope(q, positions, cfg.rope_theta)
    k = apply_rope(k, positions, cfg.rope_theta)
    attn, kcl, vcl = attn_and_cache(q, k, v, kcl, vcl)
    delta = jnp.einsum("bte,ed->btd", attn.reshape(B, T, -1), lp["wo"])
    x = x + lax.psum(delta, AXIS_TENSOR)
    h2 = rmsnorm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    return x + _tp_mlp(lp, h2), kcl, vcl


def _stage(cfg, layers, x, positions, kc, vc, attn_and_cache):
    """Scan this stage's local layer stack over one microbatch."""

    def body(carry, per_layer):
        x = carry
        lp, kcl, vcl = per_layer
        x, kcl, vcl = _tp_layer(cfg, lp, x, positions, kcl, vcl,
                                attn_and_cache)
        return x, (kcl, vcl)

    x, (kc, vc) = lax.scan(body, x, (layers, kc, vc))
    return x, kc, vc


# ---------------------------------------------------------------------------
# Vocab-sharded embedding / logits under shard_map.
# ---------------------------------------------------------------------------


def _embed_lookup(embed_local: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Gather from a vocab-sharded embedding: each tensor shard looks up
    the ids it owns, everything else contributes zero, psum combines."""
    ti = lax.axis_index(AXIS_TENSOR)
    v_loc = embed_local.shape[0]
    loc = tokens - ti * v_loc
    ok = (loc >= 0) & (loc < v_loc)
    x = embed_local[jnp.clip(loc, 0, v_loc - 1)]
    x = jnp.where(ok[..., None], x, jnp.zeros((), embed_local.dtype))
    return lax.psum(x, AXIS_TENSOR)


def _final_logits(params: dict, cfg: ModelConfig, x_last: jnp.ndarray) -> jnp.ndarray:
    """x_last: [B, D] last-position hiddens (zero on every stage but the
    last). Returns replicated [B, V]: psum over pipe folds the stages
    (zeros elsewhere), all_gather over tensor stitches the vocab shards."""
    xf = rmsnorm(x_last, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head", params["embed"])
    logits = jnp.einsum(
        "bd,vd->bv", xf.astype(jnp.float32), head.astype(jnp.float32)
    )
    logits = lax.psum(logits, AXIS_PIPE)
    return lax.all_gather(logits, AXIS_TENSOR, axis=1, tiled=True)


# ---------------------------------------------------------------------------
# The GPipe schedule, shared by all three forwards.
# ---------------------------------------------------------------------------


def _pipeline_schedule(pipe, M, x_all, kc, vc, run_stage):
    """Drive M microbatches through `pipe` stages (M + pipe - 1 steps).

    x_all: [M, mb, T, D] stage-0 inputs (embedded microbatches).
    run_stage(m, valid, inp, kc, vc) -> (h_out [mb, T, D], kc, vc,
    x_last [mb, D]) runs THIS stage's layers on microbatch m (`valid`
    False on bubble steps — the callback must redirect its KV writes to
    the trash page then). Returns (out_x [M, mb, D] last-stage results,
    kc, vc).
    """
    p = lax.axis_index(AXIS_PIPE)
    M_, mb = x_all.shape[0], x_all.shape[1]
    out_x = jnp.zeros((M_, mb, x_all.shape[-1]), x_all.dtype)
    h0 = jnp.zeros(x_all.shape[1:], x_all.dtype)

    def step(t, carry):
        h_state, kc, vc, out_x = carry
        m = jnp.clip(t - p, 0, M - 1)
        valid = (t >= p) & (t - p < M)
        inp = jnp.where(
            p == 0,
            lax.dynamic_index_in_dim(x_all, m, 0, keepdims=False),
            h_state,
        )
        h_out, kc, vc, x_last = run_stage(m, valid, inp, kc, vc)
        prev = lax.dynamic_index_in_dim(out_x, m, 0, keepdims=False)
        row = jnp.where(valid & (p == pipe - 1), x_last, prev)
        out_x = lax.dynamic_update_index_in_dim(out_x, row, m, 0)
        perm = [(d, (d + 1) % pipe) for d in range(pipe)]
        h_nxt = lax.ppermute(h_out, AXIS_PIPE, perm)
        return h_nxt, kc, vc, out_x

    _, kc, vc, out_x = lax.fori_loop(0, M + pipe - 1, step, (h0, kc, vc, out_x))
    return out_x, kc, vc


def _pick(stack, m):
    return lax.dynamic_index_in_dim(stack, m, 0, keepdims=False)


def _last_valid(h_out, lens):
    """[mb, T, D] -> [mb, D] at each row's last valid position."""
    last = jnp.clip(lens - 1, 0, h_out.shape[1] - 1)
    return jnp.take_along_axis(h_out, last[:, None, None], axis=1)[:, 0]


# ---------------------------------------------------------------------------
# Pipelined forwards (drop-in signatures vs the llama.py single-mesh ones).
# ---------------------------------------------------------------------------


def pp_forward_prefill(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, T] right-padded
    seq_lens: jnp.ndarray,  # [B]
    k_cache: jnp.ndarray,  # [L, S, Hk, hd], L sharded over "pipe"
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    page_size: int,
    mesh: Mesh,
    n_micro: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipelined prefill; returns (last_logits [B, V], k_cache', v_cache').
    Exact vs forward_prefill — schedule-only difference."""
    B, T = tokens.shape
    pipe = mesh.shape[AXIS_PIPE]
    M = n_microbatches(B, pipe, n_micro)
    mb = B // M

    def body(params, tokens, seq_lens, kc, vc, pt):
        x_all = _embed_lookup(params["embed"], tokens).reshape(M, mb, T, -1)
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (mb, T))
        pos_b = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
        slots_all = flat_slot_indices(pt, pos_b, page_size).reshape(M, mb, T)
        lens_all = seq_lens.reshape(M, mb)

        def run_stage(m, valid, inp, kc, vc):
            lens = _pick(lens_all, m)
            slots = jnp.where(valid, _pick(slots_all, m), 0)  # bubbles->trash

            def attn_and_cache(q, k, v, kcl, vcl):
                kcl = kcl.at[slots].set(k)
                vcl = vcl.at[slots].set(v)
                return causal_attention(q, k, v, lens), kcl, vcl

            h_out, kc, vc = _stage(cfg, params["layers"], inp, positions,
                                   kc, vc, attn_and_cache)
            return h_out, kc, vc, _last_valid(h_out, lens)

        out_x, kc, vc = _pipeline_schedule(pipe, M, x_all, kc, vc, run_stage)
        return _final_logits(params, cfg, out_x.reshape(B, -1)), kc, vc

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pipeline_param_specs(params), P(), P(), KV_SPEC, KV_SPEC, P()),
        out_specs=(P(), KV_SPEC, KV_SPEC),
        check_vma=False,
    )(params, tokens, seq_lens, k_cache, v_cache, page_table)


def pp_forward_prefill_chunk(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B, C] one chunk of the prompt, right-padded
    start: jnp.ndarray,  # [B] global position of the chunk's first token
    chunk_lens: jnp.ndarray,  # [B] valid tokens in this chunk
    k_cache: jnp.ndarray,  # [L, S, Hk, hd], L sharded over "pipe"
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages] — covers prefix AND chunk
    page_size: int,
    mesh: Mesh,
    n_micro: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipelined chunked prefill (long prompts beyond the largest bucket);
    chaining chunks reproduces pp_forward_prefill exactly. Returns
    (last-valid-position logits [B, V], caches')."""
    B, C = tokens.shape
    pipe = mesh.shape[AXIS_PIPE]
    M = n_microbatches(B, pipe, n_micro)
    mb = B // M

    def body(params, tokens, start, chunk_lens, kc, vc, pt):
        x_all = _embed_lookup(params["embed"], tokens).reshape(M, mb, C, -1)
        pos_b = start[:, None] + jnp.broadcast_to(
            jnp.arange(C, dtype=jnp.int32), (B, C)
        )
        slots_all = flat_slot_indices(pt, pos_b, page_size).reshape(M, mb, C)
        pos_all = pos_b.reshape(M, mb, C)
        start_all = start.reshape(M, mb)
        clen_all = chunk_lens.reshape(M, mb)
        pt_all = pt.reshape(M, mb, -1)

        def run_stage(m, valid, inp, kc, vc):
            st, cl = _pick(start_all, m), _pick(clen_all, m)
            ptm = _pick(pt_all, m)
            slots = jnp.where(valid, _pick(slots_all, m), 0)  # bubbles->trash

            def attn_and_cache(q, k, v, kcl, vcl):
                kcl = kcl.at[slots].set(k)
                vcl = vcl.at[slots].set(v)
                # Blockwise online-softmax walk over the already-written
                # prefix + this chunk (mirrors forward_prefill_chunk).
                attn = paged_chunk_attention_blockwise(
                    q, kcl, vcl, ptm, st, cl, page_size
                )
                return attn, kcl, vcl

            h_out, kc, vc = _stage(cfg, params["layers"], inp,
                                   _pick(pos_all, m), kc, vc, attn_and_cache)
            return h_out, kc, vc, _last_valid(h_out, cl)

        out_x, kc, vc = _pipeline_schedule(pipe, M, x_all, kc, vc, run_stage)
        return _final_logits(params, cfg, out_x.reshape(B, -1)), kc, vc

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pipeline_param_specs(params), P(), P(), P(), KV_SPEC,
                  KV_SPEC, P()),
        out_specs=(P(), KV_SPEC, KV_SPEC),
        check_vma=False,
    )(params, tokens, start, chunk_lens, k_cache, v_cache, page_table)


def pp_forward_decode(
    params: dict,
    cfg: ModelConfig,
    tokens: jnp.ndarray,  # [B] last generated token per slot
    positions: jnp.ndarray,  # [B]
    k_cache: jnp.ndarray,  # [L, S, Hk, hd], L sharded over "pipe"
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, max_pages]
    page_size: int,
    mesh: Mesh,
    n_micro: Optional[int] = None,
    attn_impl: str = "jnp",  # "jnp" reference | "pallas" ragged TPU kernel
    interpret: bool = False,  # pallas interpret mode (CPU tests)
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pipelined single decode step; returns (logits [B, V], caches').

    The ragged Pallas kernel runs per-device inside the shard_map stage
    (each stage's pallas_call sees its local layer-slice caches), same
    AOT-probe fallback discipline as the single-mesh path."""
    B = tokens.shape[0]
    pipe = mesh.shape[AXIS_PIPE]
    M = n_microbatches(B, pipe, n_micro)
    mb = B // M

    def body(params, tokens, positions, kc, vc, pt):
        x_all = _embed_lookup(params["embed"], tokens).reshape(M, mb, 1, -1)
        ws_all = flat_slot_indices(pt, positions[:, None], page_size)[:, 0]
        ws_all = ws_all.reshape(M, mb)
        pos_all = positions.reshape(M, mb)
        pt_all = pt.reshape(M, mb, -1)

        def run_stage(m, valid, inp, kc, vc):
            pos = _pick(pos_all, m)
            ptm = _pick(pt_all, m)
            ws = jnp.where(valid, _pick(ws_all, m), 0)  # bubbles->trash

            def attn_and_cache(q, k, v, kcl, vcl):
                kcl = kcl.at[ws].set(k[:, 0])
                vcl = vcl.at[ws].set(v[:, 0])
                attn = paged_decode_attention_any(
                    attn_impl, q[:, 0], kcl, vcl, ptm, pos + 1, page_size,
                    interpret=interpret,
                )
                return attn[:, None], kcl, vcl  # [mb, 1, H_loc, hd]

            h_out, kc, vc = _stage(cfg, params["layers"], inp, pos[:, None],
                                   kc, vc, attn_and_cache)
            return h_out, kc, vc, h_out[:, 0]

        out_x, kc, vc = _pipeline_schedule(pipe, M, x_all, kc, vc, run_stage)
        return _final_logits(params, cfg, out_x.reshape(B, -1)), kc, vc

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(pipeline_param_specs(params), P(), P(), KV_SPEC, KV_SPEC, P()),
        out_specs=(P(), KV_SPEC, KV_SPEC),
        check_vma=False,
    )(params, tokens, positions, k_cache, v_cache, page_table)
