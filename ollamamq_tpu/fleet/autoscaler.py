"""Elastic fleet: an SLO-burn-driven autoscaler with preemptible
members and scale-to-zero.

UELLM's framing (PAPERS.md): SLO-aware deployment holds latency targets
at measurably lower resource cost — which is also the precondition for
the spot-style preemptible capacity real TPU fleets run on. The fleet
already has everything elasticity needs: per-tier SLO burn rates
(tiering.py / slo.py), live-stream migration, drain/retier machinery,
and a WAL that makes any member's death survivable. This module closes
the loop from observed load to fleet size:

  Control loop   a per-tier scaler (one group = one tier; the whole
                 fleet when untiered) watches sustained SLO burn +
                 queue backlog each router tick and decides scale-up /
                 scale-down ONE member at a time, with the TierBalancer
                 hysteresis discipline: a cooldown after every event,
                 and the burn/idle signal must be SUSTAINED (windows
                 derived from --scale-cooldown-s) — an oscillating load
                 must produce ZERO scale events. Scale-down is always
                 drain -> migrate-off -> retire (router.retire_replica),
                 never a kill.

  Provisioner    MemberProvisioner is the seam between the decision
                 loop and capacity. SubprocessProvisioner (the first
                 real implementation, the crash_restart bench's
                 subprocess harness) spawns `python -m ollamamq_tpu.cli`
                 engine servers on free ports and retires them with
                 SIGTERM; LocalProvisioner builds in-process engine
                 replicas from the CLI's engine factory (tests, and
                 real-TPU fleets that share local chips). A cloud
                 provisioner (TPU VM create/delete through a cloud API)
                 implements the same three methods — provision /
                 retire / describe — and plugs in here unchanged; it is
                 deliberately NOT shipped: this repo has no cloud
                 credentials to test it against. Provisioned members
                 join through the existing probe/rejoin path and
                 inherit tier + scheduler + model config from the
                 member config the provisioner closed over.

  Preemptible    members flagged `preemptible` accept a termination
                 notice (POST /admin/preempt/{replica}, or the fault
                 plan's "preempt" site) that triggers migrate-off-then-
                 retire within the notice window instead of failover —
                 spot reclamation costs zero dropped streams.

  Scale-to-zero  the bulk tier may scale to zero members overnight:
                 queued bulk work PARKS at the router (the tier-
                 isolation path holds it; tiering.py's scaled_to_zero
                 set stops the empty-tier cross-tier fallback), and the
                 parked backlog is the pending-work signal that wakes
                 the tier — a wake bypasses cooldown AND sustain,
                 because parked streams must never wait out a timer
                 that exists to stop flapping. The interactive tier
                 (and an untiered fleet) keeps the --min-replicas
                 floor.

Every decision lands in the journal (scale_up / scale_down /
preempt_notice — paired by tools/journal.py's multi-spill checker),
metrics (ollamamq_fleet_scale_events_total / _member_hours_total /
_preemptions_total), and the TUI fleet chip.
"""

from __future__ import annotations

import logging
import os
import queue
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry.slo import DEFAULT_WINDOWS, Objective

log = logging.getLogger("ollamamq.autoscaler")

# Decision cadence: signals are cheap (a pending-dict scan + cached burn
# reads) but there is no reason to re-decide faster than the probe loop.
TICK_PERIOD_S = 0.25

# Untiered fleets get their own TTFT objective at this threshold when
# the operator configured no --slo-ttft-ms (tiering.py's interactive
# default).
FLEET_TTFT_MS = 500.0

# Cold spawn estimate (seconds) before the first observed spawn: what a
# scaled-to-zero tier's Retry-After accounts for. Observed spawn
# durations fold in with this EMA weight.
SPAWN_EST_S = 5.0
SPAWN_EST_ALPHA = 0.5

# Scale-down low-water fraction: a group may shrink only when its load
# fits in HALF the remaining members' slots (plus zero backlog and no
# burn) — the surviving members must absorb the retiree with headroom,
# not at 100% occupancy.
IDLE_LOAD_FRACTION = 0.5


class MemberProvisioner:
    """The seam between the scale decision and actual capacity.

    provision(name, tier=None, tp=None) -> an UNSTARTED member object
        (fleet/members.py shape) named `name`; may block for seconds
        (it runs on the scaler's spawn thread, never the router loop).
        Raise on failure — the scaler journals scale_up aborted.
    retire(member) -> tear down what provision built (kill the
        subprocess, delete the VM); called after the member's drain
        emptied and it left the roster. Must not raise.
    describe() -> one-line provenance string for status surfaces.

    A cloud provisioner (TPU VM create/delete) implements exactly this
    interface; see the module docstring for why none ships here.
    """

    def provision(self, name: str, tier: Optional[str] = None,
                  tp: Optional[int] = None):
        raise NotImplementedError

    def retire(self, member) -> None:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class LocalProvisioner(MemberProvisioner):
    """In-process members from an engine factory (the CLI's closure:
    same models, scheduler, fairness as the seed members). The cheap
    path for tests and for real-TPU fleets whose replicas share the
    local chips."""

    def __init__(self, engine_factory):
        self.engine_factory = engine_factory

    def provision(self, name: str, tier: Optional[str] = None,
                  tp: Optional[int] = None):
        from ollamamq_tpu.fleet.members import LocalMember

        engine = self.engine_factory(tp)
        return LocalMember(name, engine, engine_factory=self.engine_factory)

    def retire(self, member) -> None:
        try:
            member.stop()
        except Exception:  # noqa: BLE001
            log.exception("stopping retired member %s failed", member.name)

    def describe(self) -> str:
        return "local (in-process engine factory)"


class SubprocessProvisioner(MemberProvisioner):
    """Subprocess HttpMember engines — the crash_restart bench's
    harness as a provisioner: spawn `python -m ollamamq_tpu.cli` on a
    free port, wait for /health, hand the router an HttpMember; retire
    is SIGTERM (the member server drains + flushes before exit).

    `member_argv` carries everything after the port (--fake-engine,
    --models, --scheduler, --max-slots, ... — the member_cfg the
    provisioned member inherits); `env` overlays os.environ."""

    # Router-level configuration that must NOT leak into a provisioned
    # member's environment: the member is a plain single-engine server,
    # and inheriting these turns it into a second router (TIERS without
    # a fleet fail-fasts the child; REPLICAS forks a nested fleet; a
    # shared WAL_DIR / JOURNAL_FILE has two processes appending to one
    # durability log). The in-process path strips the same fields from
    # member_cfg; this is the subprocess analog.
    ROUTER_ONLY_ENV = frozenset({
        "TIERS", "AUTOSCALE", "MIN_REPLICAS", "MAX_REPLICAS",
        "SCALE_COOLDOWN_S", "PREEMPTIBLE", "REPLICAS", "REPLICA_URLS",
        "PLACEMENT", "WAL_DIR", "JOURNAL_FILE", "BLOCKLIST", "PORT",
    })

    def __init__(self, member_argv: List[str],
                 env: Optional[dict] = None,
                 log_dir: Optional[str] = None,
                 health_timeout_s: float = 60.0):
        self.member_argv = list(member_argv)
        self.env = dict(env or {})
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="ollamamq-scale-")
        self.health_timeout_s = float(health_timeout_s)
        self._procs: Dict[str, tuple] = {}  # name -> (proc, log handle)

    def child_env(self) -> dict:
        env = {k: v for k, v in os.environ.items()
               if k not in self.ROUTER_ONLY_ENV}
        env.update(self.env)
        return env

    @staticmethod
    def _free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def _wait_health(self, url: str, deadline: float) -> None:
        import json
        import urllib.request

        last = "no response"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(f"{url}/health",
                                            timeout=2.0) as resp:
                    body = json.loads(resp.read().decode())
                if body.get("state") != "recovering":
                    return
                last = "recovering"
            except Exception as e:  # noqa: BLE001
                last = str(e)
            time.sleep(0.1)
        raise RuntimeError(f"member at {url} never became healthy "
                           f"({last})")

    def provision(self, name: str, tier: Optional[str] = None,
                  tp: Optional[int] = None):
        from ollamamq_tpu.fleet.members import HttpMember

        port = self._free_port()
        argv = [sys.executable, "-m", "ollamamq_tpu.cli",
                "--no-tui", "--host", "127.0.0.1", "--port", str(port)]
        argv += self.member_argv
        if tp is not None and tp > 0:
            argv += ["--tp", str(tp)]
        logf = open(os.path.join(self.log_dir, f"{name}.log"), "ab")
        proc = subprocess.Popen(argv, env=self.child_env(),
                                stdout=logf, stderr=subprocess.STDOUT)
        url = f"http://127.0.0.1:{port}"
        try:
            self._wait_health(
                url, time.monotonic() + self.health_timeout_s)
        except Exception:
            proc.terminate()
            try:
                proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001
                proc.kill()
            logf.close()
            raise
        member = HttpMember(name, url)
        self._procs[name] = (proc, logf)
        return member

    def retire(self, member) -> None:
        try:
            member.stop()
        except Exception:  # noqa: BLE001
            pass
        proc, logf = self._procs.pop(member.name, (None, None))
        if proc is None:
            return
        proc.terminate()  # SIGTERM: the member drains + flushes first
        try:
            proc.wait(timeout=10.0)
        except Exception:  # noqa: BLE001
            proc.kill()
        if logf is not None:
            logf.close()

    def shutdown(self) -> None:
        """Kill any members still alive (router stop / test teardown)."""
        for name in list(self._procs):
            proc, logf = self._procs.pop(name)
            proc.terminate()
            try:
                proc.wait(timeout=5.0)
            except Exception:  # noqa: BLE001
                proc.kill()
            logf.close()

    def describe(self) -> str:
        return "subprocess (HttpMember engine servers)"


class AutoscalerManager:
    """The control loop. Owned by FleetRouter (constructed under
    --autoscale); tick() runs on the router loop thread right after the
    TierBalancer's. Provisioning runs on a spawn thread — the router
    loop must keep serving while a member boots — and the booted member
    joins on the next tick."""

    def __init__(self, router, provisioner: MemberProvisioner,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 sustain_s: Optional[float] = None,
                 idle_sustain_s: Optional[float] = None,
                 backlog_high: Optional[int] = None,
                 scale_to_zero: bool = True,
                 provision_preemptible: bool = False,
                 windows: Tuple[tuple, ...] = DEFAULT_WINDOWS,
                 tick_period_s: float = TICK_PERIOD_S):
        ecfg = router.ecfg
        self.router = router
        self.journal = router.journal
        self.provisioner = provisioner
        self.min_replicas = int(
            getattr(ecfg, "min_replicas", 1)
            if min_replicas is None else min_replicas)
        self.max_replicas = int(
            getattr(ecfg, "max_replicas", 4)
            if max_replicas is None else max_replicas)
        self.cooldown_s = float(
            getattr(ecfg, "scale_cooldown_s", 30.0)
            if cooldown_s is None else cooldown_s)
        # Hysteresis windows derive from the one operator knob unless a
        # test overrides them: pressure must hold a third of a cooldown
        # before a scale-up; idleness must hold a FULL cooldown before a
        # scale-down (shrinking too eagerly costs a spawn to undo).
        self.sustain_s = (max(0.5, self.cooldown_s / 3.0)
                          if sustain_s is None else float(sustain_s))
        self.idle_sustain_s = (self.cooldown_s if idle_sustain_s is None
                               else float(idle_sustain_s))
        self.backlog_high = int(
            max(1, getattr(ecfg, "max_slots", 8))
            if backlog_high is None else backlog_high)
        self.scale_to_zero = bool(scale_to_zero)
        self.provision_preemptible = bool(provision_preemptible)
        self.windows = windows
        self.tick_period_s = float(tick_period_s)
        # Untiered fleets carry their own TTFT objective (tiered ones
        # read the TierManager's per-tier burn).
        self.objective: Optional[Objective] = None
        if router.tiers is None:
            ttft = getattr(ecfg, "slo_ttft_ms", None) or FLEET_TTFT_MS
            horizon = max((w[1] for w in windows), default=3600.0)
            self.objective = Objective(
                "autoscale_fleet", ttft,
                getattr(ecfg, "slo_target", 0.99) or 0.99,
                horizon_s=horizon)
        # Control-loop state.
        self._last_tick = 0.0
        self._hot_since: Dict[Optional[str], float] = {}
        self._idle_since: Dict[Optional[str], float] = {}
        self.last_event_at = 0.0
        self.scale_times: deque = deque(maxlen=128)
        self.scale_counts: Dict[str, int] = {}
        self.spawn_est_s = SPAWN_EST_S
        self._spawn: Optional[dict] = None  # {"name","tier","t0","why"}
        self._spawn_done: "queue.Queue" = queue.Queue()
        self._next_id = 0
        # Member-hours ledger (the metric is cumulative; the float here
        # backs the bench/status readout).
        self.member_seconds = 0.0
        self._hours_at = time.monotonic()

    # ------------------------------------------------------------- signals
    def record_ttft(self, ttft_ms: float) -> None:
        """Router first-token hook for UNTIERED fleets (tiered ones
        feed TierManager.record_ttft, which this scaler reads)."""
        if self.objective is not None:
            self.objective.record(ttft_ms)

    def _groups(self) -> List[Optional[str]]:
        if self.router.tiers is not None:
            return ["interactive", "bulk"]
        return [None]

    def _floor(self, group: Optional[str]) -> int:
        if group == "bulk" and self.scale_to_zero:
            return 0
        return self.min_replicas

    def _members_of(self, group: Optional[str]) -> List[object]:
        return [m for m in self.router.members
                if group is None or getattr(m, "tier", None) == group]

    def _burn_state(self, group: Optional[str]) -> Tuple[bool, float]:
        if self.router.tiers is not None:
            return self.router.tiers.overflow_state(group)
        obj = self.objective
        now = time.monotonic()
        active, burn = False, 0.0
        for _label, long_w, short_w, factor, _sev in self.windows:
            burn_long = obj.burn_rate(long_w, now=now)
            burn_short = obj.burn_rate(short_w, now=now)
            if burn_long > factor and burn_short > factor:
                active, burn = True, max(burn, burn_long)
        return active, burn

    def _backlog(self, group: Optional[str]) -> int:
        """Queued streams waiting at the router for this group — parked
        work on a scaled-to-zero tier shows up here (the wake signal)."""
        router = self.router
        with router._pending_lock:
            flights = list(router.pending.values())
        if group is None or router.tiers is None:
            return len(flights)
        tiers = router.tiers
        n = 0
        for f in flights:
            t = getattr(f, "tier", None)
            if t is None:
                try:
                    t = tiers.tier_of_class(
                        tiers.class_of(f.user, f.req.deadline))
                except Exception:  # noqa: BLE001
                    t = "bulk"
            if t == group:
                n += 1
        return n

    def _inflight(self, group: Optional[str]) -> int:
        mems = set(id(m) for m in self._members_of(group))
        return sum(1 for f in self.router.flights
                   if not f.done and f.member is not None
                   and id(f.member) in mems)

    def _slot_cap(self, group: Optional[str]) -> int:
        caps = [self.router._slot_cap(m) for m in self._members_of(group)]
        return max(caps) if caps else int(
            getattr(self.router.ecfg, "max_slots", 8) or 8)

    # ---------------------------------------------------------------- tick
    def tick(self) -> None:
        now = time.monotonic()
        self._accrue_member_hours(now)
        self._reap_spawn(now)
        if now - self._last_tick < self.tick_period_s:
            return
        self._last_tick = now
        # One scale operation in flight fleet-wide: a pending spawn, or
        # any member mid-retire/mid-regroup, parks the decision loop.
        busy = self._spawn is not None or any(
            getattr(m, "retiring", False) or m.retier_to is not None
            for m in self.router.members)
        for group in self._groups():
            self._evaluate(group, now, busy)

    def _accrue_member_hours(self, now: float) -> None:
        dt = now - self._hours_at
        if dt <= 0:
            return
        self._hours_at = now
        n = sum(1 for m in self.router.members if m.state != "ejected")
        if n:
            self.member_seconds += dt * n
            tm.FLEET_MEMBER_HOURS_TOTAL.inc(dt * n / 3600.0)

    def _evaluate(self, group: Optional[str], now: float,
                  busy: bool) -> None:
        mems = self._members_of(group)
        healthy = [m for m in mems
                   if m.state == "healthy"
                   and not getattr(m, "retiring", False)]
        n = len(mems)
        fleet = len(self.router.members)
        backlog = self._backlog(group)
        firing, burn = self._burn_state(group)
        inflight = self._inflight(group)
        cap = self._slot_cap(group)
        # --- wake: a scaled-to-zero group with parked work bypasses
        # every hysteresis timer — capacity now, debate later.
        if (not healthy and backlog > 0 and not busy
                and fleet < self.max_replicas):
            self._launch_scale_up(group, "wake", burn, backlog)
            return
        # --- scale-up pressure: sustained burn, or a backlog more than
        # one member's worth of slots deep.
        hot = (firing or backlog > self.backlog_high) and n > 0
        if hot:
            self._idle_since.pop(group, None)
            since = self._hot_since.setdefault(group, now)
            if (not busy and fleet < self.max_replicas
                    and now - since >= self.sustain_s
                    and now - self.last_event_at >= self.cooldown_s):
                why = "burn" if firing else "backlog"
                self._hot_since.pop(group, None)
                self._launch_scale_up(group, why, burn, backlog)
            return
        self._hot_since.pop(group, None)
        # --- scale-down: no burn, no backlog, and the group's load fits
        # comfortably in one fewer member — sustained a full cooldown.
        floor = self._floor(group)
        idle = (n > floor and backlog == 0 and not firing
                and inflight <= (n - 1) * cap * IDLE_LOAD_FRACTION)
        if not idle:
            self._idle_since.pop(group, None)
            return
        since = self._idle_since.setdefault(group, now)
        if (busy or now - since < self.idle_sustain_s
                or now - self.last_event_at < self.cooldown_s):
            return
        victim = self._pick_victim(group)
        if victim is None:
            return
        self._idle_since.pop(group, None)
        try:
            self.router.retire_replica(victim.name, why="idle",
                                       burn=round(burn, 2),
                                       queued=backlog)
        except (KeyError, ValueError, RuntimeError) as e:
            log.warning("scale-down of %s skipped: %s", victim.name, e)

    def _pick_victim(self, group: Optional[str]):
        """Least-loaded healthy member of the group, preferring ones
        this scaler provisioned (operator-defined seed members retire
        last), then preemptible ones (spot capacity is the cheapest to
        give back)."""
        cands = [m for m in self._members_of(group)
                 if m.state == "healthy"
                 and not getattr(m, "retiring", False)
                 and m.retier_to is None]
        if not cands:
            return None
        for pool in (
                [m for m in cands
                 if getattr(m, "provisioned_by", None) is not None],
                [m for m in cands if getattr(m, "preemptible", False)],
                cands):
            if pool:
                return min(pool, key=self.router._load_of)
        return None

    # ------------------------------------------------------------ scale-up
    def _next_name(self) -> str:
        taken = {m.name for m in self.router.members}
        while True:
            name = f"a{self._next_id}"
            self._next_id += 1
            if name not in taken:
                return name

    def _launch_scale_up(self, group: Optional[str], why: str,
                         burn: float, backlog: int) -> None:
        name = self._next_name()
        self.journal.record(
            "scale_up", replica=name, phase="start",
            tier=group, why=why,
            burn=round(burn, 2) if burn else None,
            queued=backlog, fleet=len(self.router.members))
        log.warning("scaler growing tier %s: provisioning %s (%s, "
                    "%d queued)", group or "fleet", name, why, backlog)
        self._spawn = {"name": name, "tier": group,
                       "t0": time.monotonic(), "why": why}
        tp = (self.router.tiers.widths.get(group)
              if self.router.tiers is not None else None)
        threading.Thread(target=self._spawn_worker,
                         args=(name, group, tp),
                         name=f"scale-up-{name}", daemon=True).start()

    def _spawn_worker(self, name: str, tier: Optional[str],
                      tp: Optional[int]) -> None:
        try:
            member = self.provisioner.provision(name, tier=tier, tp=tp)
        except Exception as e:  # noqa: BLE001
            log.exception("provisioning member %s failed", name)
            self._spawn_done.put(("error", name, str(e)))
        else:
            self._spawn_done.put(("ok", name, member))
        self.router.notify()

    def _reap_spawn(self, now: float) -> None:
        try:
            status, name, payload = self._spawn_done.get_nowait()
        except queue.Empty:
            return
        spawn = self._spawn or {}
        self._spawn = None
        tier = spawn.get("tier")
        spawn_s = now - spawn.get("t0", now)
        if status != "ok":
            self.journal.record(
                "scale_up", replica=name, phase="aborted", tier=tier,
                why=str(payload)[:120], fleet=len(self.router.members))
            self.note_scale_event("up", "aborted")
            log.error("scale-up of %s ABORTED: %s", name, payload)
            return
        member = payload
        member.provisioned_by = self.provisioner
        member.preemptible = self.provision_preemptible
        try:
            member.start()
        except Exception as e:  # noqa: BLE001
            log.exception("starting provisioned member %s failed", name)
            self.provisioner.retire(member)
            self.journal.record(
                "scale_up", replica=name, phase="aborted", tier=tier,
                why=f"start_failed: {e}"[:120],
                fleet=len(self.router.members))
            self.note_scale_event("up", "aborted")
            return
        self.spawn_est_s = (SPAWN_EST_ALPHA * spawn_s
                            + (1.0 - SPAWN_EST_ALPHA) * self.spawn_est_s)
        router = self.router
        router.members.append(member)
        if router.tiers is not None and tier is not None:
            router.tiers.note_member_added(member, tier)  # clears park
        self.journal.record(
            "scale_up", replica=name, phase="done", tier=tier,
            why=spawn.get("why"), spawn_ms=round(spawn_s * 1e3, 1),
            fleet=len(router.members))
        self.journal.record("replica_join", replica=name, why="scale_up")
        self.note_scale_event("up", "done")
        log.warning("member %s joined tier %s in %.1fs; fleet -> %d",
                    name, tier or "fleet", spawn_s, len(router.members))
        router._update_gauges()
        router.notify()

    # --------------------------------------------------------- bookkeeping
    def note_scale_event(self, direction: str, outcome: str) -> None:
        """Every completed/aborted scale event: metrics, the rate window
        the scale_storm watchdog reads, and the cooldown clock (aborted
        events cool down too — retrying a failing spawn in a tight loop
        IS flapping)."""
        tm.FLEET_SCALE_EVENTS_TOTAL.labels(direction=direction,
                                           outcome=outcome).inc()
        key = f"{direction}_{outcome}"
        self.scale_counts[key] = self.scale_counts.get(key, 0) + 1
        self.scale_times.append(time.monotonic())
        self.last_event_at = time.monotonic()

    def scale_rate_per_min(self, window_s: float = 60.0) -> float:
        """Scale events per minute over the trailing window — the
        health watchdog's scale_storm signal."""
        cutoff = time.monotonic() - window_s
        n = sum(1 for t in self.scale_times if t >= cutoff)
        return n * 60.0 / window_s

    def wake_wait_s(self) -> float:
        """Estimated seconds until a scaled-to-zero tier serves again:
        0 when nothing is parked at zero; otherwise the spawn estimate
        (minus elapsed spawn time when a wake is already in flight) —
        what retry_after_s adds to a 503 so clients don't hammer a
        Retry-After computed from the completion rate of members that
        don't exist."""
        tiers = self.router.tiers
        if tiers is None or not tiers.scaled_to_zero:
            return 0.0
        if self._spawn is not None:
            return max(0.0, self.spawn_est_s
                       - (time.monotonic() - self._spawn["t0"]))
        return self.spawn_est_s + self.tick_period_s

    def member_hours(self) -> float:
        self._accrue_member_hours(time.monotonic())
        return self.member_seconds / 3600.0

    def brief(self) -> dict:
        """TUI fleet chip payload: `fleet N (+P preemptible)`."""
        members = self.router.members
        return {
            "n": len(members),
            "preemptible": sum(1 for m in members
                               if getattr(m, "preemptible", False)),
            "min": self.min_replicas,
            "max": self.max_replicas,
        }

    def status(self) -> dict:
        tiers = self.router.tiers
        return {
            "enabled": True,
            "provisioner": self.provisioner.describe(),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "cooldown_s": self.cooldown_s,
            "sustain_s": self.sustain_s,
            "idle_sustain_s": self.idle_sustain_s,
            "fleet": len(self.router.members),
            "preemptible": [m.name for m in self.router.members
                            if getattr(m, "preemptible", False)],
            "spawn_in_flight": (self._spawn or {}).get("name"),
            "spawn_est_s": round(self.spawn_est_s, 2),
            "scaled_to_zero": (sorted(tiers.scaled_to_zero)
                               if tiers is not None else []),
            "scale_events": dict(self.scale_counts),
            "scale_rate_per_min": round(self.scale_rate_per_min(), 2),
            "member_hours": round(self.member_hours(), 4),
        }
