"""Shared test helpers (pytest puts this directory on sys.path)."""

import time


def collect(req, timeout=120):
    """Drain a request's stream until its terminal item (done/error)."""
    deadline = time.monotonic() + timeout
    items = []
    while time.monotonic() < deadline:
        item = req.stream.get(timeout=0.2)
        if item is None:
            continue
        items.append(item)
        if item.kind in ("done", "error"):
            return items
    raise TimeoutError(f"request {req.req_id} did not finish; got {items}")


def free_port() -> int:
    """An OS-assigned free TCP port (close-then-rebind race is acceptable
    for the jax.distributed coordinator in these short-lived tests)."""
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_two_process(script_text, tmp_path, timeout=540):
    """Launch a 2-process jax.distributed child script (argv: pid, port)
    and return the parsed RESULT json of each process. THE harness for
    every cross-host SPMD test — write-script/Popen/kill-on-timeout/parse
    lives here once."""
    import json
    import os
    import subprocess
    import sys

    import pytest

    script = tmp_path / "spmd_child.py"
    script.write_text(script_text)
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    port = free_port()
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("SPMD processes hung")
        assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
        outs.append(out)
    return [
        json.loads([l for l in o.splitlines()
                    if l.startswith("RESULT ")][0][7:])
        for o in outs
    ]


def single_device_greedy_tokens(model, prompt, max_tokens=6, **ecfg_kw):
    """Generated ids from a plain single-device engine — the numeric
    reference every cross-host parallelism test compares against."""
    import time

    import jax.numpy as jnp

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.ops.sampling import SamplingParams

    defaults = dict(model=model, max_slots=2, num_pages=32, page_size=8,
                    max_pages_per_seq=8, prefill_buckets=(16,),
                    decode_steps_per_iter=2)
    defaults.update(ecfg_kw)
    eng = TPUEngine(EngineConfig(**defaults), models={model: None},
                    blocklist_path=None, dtype=jnp.float32)
    eng.start()
    try:
        tok = eng.runtimes[model].tokenizer
        rid = eng.core.enqueue("u", "127.0.0.1", model)
        req = Request(rid, "u", model, tok.encode(prompt),
                      SamplingParams(max_tokens=max_tokens))
        eng.submit(req)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.5)
            if item and item.kind in ("done", "error"):
                break
    finally:
        eng.stop()
    return req.generated_ids
