"""Round-3 correctness edges (VERDICT r2 "what's weak" #5-#7 + ADVICE):

- resolve_runtime kind filter: generative requests never land on an
  EncoderRuntime via the empty-model fallback (they would "finish" with
  an embedding and zero tokens).
- ReplicaSet.submit returns work to the queue instead of parking on a
  full replica (wait-in-queue semantics, dispatcher.rs:467-473).
- EncoderRuntime compiles a B=1 variant so a lone embedding request
  doesn't pay the 8x padded batch.
- seed=0 is a VALID seed (OpenAI clients pass it expecting determinism),
  distinct from seed-absent.
"""

import time
from types import SimpleNamespace

import jax.numpy as jnp
import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.engine import ReplicaSet, TPUEngine
from ollamamq_tpu.engine.request import Request
from ollamamq_tpu.ops.sampling import SamplingParams


@pytest.fixture(scope="module")
def encoder_only_engine():
    eng = TPUEngine(
        EngineConfig(model="test-tiny-embed", max_slots=2, num_pages=32,
                     page_size=8, max_pages_per_seq=8,
                     prefill_buckets=(16,), decode_steps_per_iter=2),
        models={"test-tiny-embed": None},
        blocklist_path=None, dtype=jnp.float32,
    )
    eng.start()
    yield eng
    eng.stop()


def _wait(req, budget=60):
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        item = req.stream.get(timeout=0.5)
        if item and item.kind in ("done", "error"):
            return item
    return None


def test_generative_request_never_lands_on_encoder(encoder_only_engine):
    eng = encoder_only_engine
    # Empty model name, generate kind: the fallback must NOT pick the
    # encoder — with no generative runtime loaded the request errors.
    req = eng.enqueue_request("edgeA", "", "", prompt_tokens=[1, 2, 3],
                              sampling=SamplingParams(max_tokens=4))
    item = _wait(req)
    assert item is not None and item.kind == "error"
    assert "model not loaded" in (item.error or "")
    assert req.generated_ids == [] and req.embedding is None


def test_embed_request_resolves_encoder_via_fallback(encoder_only_engine):
    eng = encoder_only_engine
    tok = eng.runtimes["test-tiny-embed"].tokenizer
    req = eng.enqueue_request("edgeB", "", "", kind="embed",
                              prompt_tokens=tok.encode("hello"),
                              sampling=SamplingParams())
    item = _wait(req)
    assert item is not None and item.kind == "done"
    assert req.embedding and len(req.embedding) > 0


def test_encoder_compiles_b1_for_single_request(encoder_only_engine):
    eng = encoder_only_engine
    rt = eng.runtimes["test-tiny-embed"]
    tok = rt.tokenizer
    req = eng.enqueue_request("edgeC", "", "test-tiny-embed", kind="embed",
                              prompt_tokens=tok.encode("one"),
                              sampling=SamplingParams())
    assert _wait(req).kind == "done"
    assert any(batch == 1 for batch, _bucket in rt._jits), rt._jits.keys()
    assert not any(batch == 8 for batch, _bucket in rt._jits)


class _StubReplica:
    def __init__(self, capacity, load, failed=False):
        self.name = "stub"
        self.cfg = None
        self.ecfg = None
        self._capacity = capacity
        self._load_n = load
        self._failed = failed
        self.pending_prefill = []
        self.chunking = []
        self.submitted = []

    def has_capacity(self, kind=None):
        return self._capacity

    def active_count(self):
        return self._load_n

    def submit(self, req):
        self.submitted.append(req)
        return True


def test_replicaset_submit_refuses_when_full():
    rs = ReplicaSet([_StubReplica(False, 1), _StubReplica(False, 0)])
    assert rs.submit(SimpleNamespace(kind="generate")) is False
    assert all(not r.submitted for r in rs.replicas)


def test_replicaset_force_submit_picks_least_loaded_live():
    a, b, c = (_StubReplica(False, 3), _StubReplica(False, 1, failed=True),
               _StubReplica(False, 2))
    rs = ReplicaSet([a, b, c])
    rs.force_submit(object())
    # b is failed; c is the least-loaded live replica.
    assert c.submitted and not a.submitted and not b.submitted


def test_place_requeues_when_replica_capacity_races_away():
    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=2, num_pages=32,
                     page_size=8, max_pages_per_seq=8,
                     prefill_buckets=(16,), decode_steps_per_iter=2),
        models={"test-tiny": None},
        blocklist_path=None, dtype=jnp.float32,
    )
    # No engine loop: drive _place directly with a runtime that refuses.
    rt = eng.runtimes["test-tiny"]
    orig_submit = rt.submit
    rt.submit = lambda req: False
    try:
        req = eng.enqueue_request("edgeD", "", "test-tiny",
                                  prompt_tokens=[1, 2],
                                  sampling=SamplingParams(max_tokens=2))
        popped = eng.core.next(eligible_models=["test-tiny"])
        assert popped is not None and popped[0] == req.req_id
        placed = eng._place(req, "edgeD", "test-tiny")
        assert placed is False
        # Back in the native queue under a fresh id, still registered.
        snap = eng.core.snapshot()
        assert snap["users"]["edgeD"]["queued"] == 1
        assert req.req_id in eng.pending
        assert req.req_id != popped[0]
        # Per-user FIFO survives the race: a request B enqueued BEFORE the
        # race resolves must not overtake A — the requeue goes to the
        # FRONT of the user's queue (VERDICT r3 weak #4).
        req_b = eng.enqueue_request("edgeD", "", "test-tiny",
                                    prompt_tokens=[3, 4],
                                    sampling=SamplingParams(max_tokens=2))
        nxt = eng.core.next(eligible_models=["test-tiny"])
        assert nxt is not None and nxt[0] == req.req_id  # A first
        nxt2 = eng.core.next(eligible_models=["test-tiny"])
        assert nxt2 is not None and nxt2[0] == req_b.req_id
    finally:
        rt.submit = orig_submit


def test_prefill_drain_bounded_per_tick():
    """An arrival storm must not starve decode: _loop_once admits at most
    prefill_batches_per_tick batched prefills before dispatching decode
    (VERDICT r3 weak #5)."""
    # The per-tick batched-prefill budget belongs to the pipeline-path
    # loop branch (pp > 1 runtimes; the ragged path admits into spans and
    # dispatches exactly once per tick by construction). The bucketed
    # oracle flag is gone, so force the runtime onto that branch the way
    # a pp runtime lands there: ragged=False.
    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=2, num_pages=32,
                     page_size=8, max_pages_per_seq=8,
                     prefill_buckets=(16,), decode_steps_per_iter=2,
                     prefill_batches_per_tick=2),
        models={"test-tiny": None},
        blocklist_path=None, dtype=jnp.float32,
    )
    rt = eng.runtimes["test-tiny"]
    rt.ragged = False  # drive the pipeline-path loop branch
    calls = []
    rt.step_prefill = lambda core: (calls.append(1), True)[1]
    # A real queued request (sweep_blocked walks held requests); the stub
    # step_prefill never pops it, so pending_prefill stays non-empty.
    rt.pending_prefill.append(
        Request(1, "edgeF", "test-tiny", [1, 2],
                SamplingParams(max_tokens=2)))
    eng._loop_once()
    assert len(calls) == 2
    calls.clear()
    eng.ecfg.prefill_batches_per_tick = 1
    eng._loop_once()
    assert len(calls) == 1


def test_seed_zero_is_reproducible_and_distinct_from_absent():
    assert SamplingParams().seed == 0  # absent => engine stream
    assert SamplingParams(seed=None).seed == 0
    s0 = SamplingParams(seed=0)
    assert s0.seed > 0  # explicit 0 => a real, deterministic seed
    assert SamplingParams(seed=0).seed == s0.seed
    assert SamplingParams(seed=0).seed != SamplingParams(seed=1).seed
    # Ollama / OpenAI parsers preserve the distinction.
    assert SamplingParams.from_ollama_options({"seed": 0}, 16).seed == s0.seed
    assert SamplingParams.from_ollama_options({}, 16).seed == 0
    assert SamplingParams.from_openai({"seed": 0}, 16).seed == s0.seed
    assert SamplingParams.from_openai({}, 16).seed == 0


def test_call_on_loop_drained_on_stop():
    """stop() must fail pending engine-thread calls instead of leaving
    their waiters blocked until the call_on_loop timeout."""
    import threading

    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=2, num_pages=32,
                     page_size=8, max_pages_per_seq=8,
                     prefill_buckets=(16,), decode_steps_per_iter=2),
        models={"test-tiny": None},
        blocklist_path=None, dtype=jnp.float32,
    )
    eng.start()
    ran = threading.Event()
    results = {}

    def waiter():
        try:
            results["ret"] = eng.call_on_loop(lambda: "ok", timeout=30)
        except RuntimeError as e:
            results["err"] = str(e)
        ran.set()

    # A call queued while running executes on the loop.
    t = threading.Thread(target=waiter)
    t.start()
    assert ran.wait(20) and results.get("ret") == "ok"

    # A call stranded by a racing stop() is failed, not abandoned: simulate
    # the race by enqueueing directly (as call_on_loop does after its
    # _running check) and then stopping.
    ev = threading.Event()
    box = {}
    eng._engine_calls.append((lambda: "late", ev, box))
    eng.stop()
    assert ev.wait(10)
    # Either the loop ran it just before exiting, or stop() failed it.
    assert box.get("ret") == "late" or "stopped" in str(box.get("err"))


def test_named_model_kind_mismatch_errors(encoder_only_engine):
    eng = encoder_only_engine
    # generate on a NAMED encoder model: permanent mismatch, loud error.
    req = eng.enqueue_request("edgeE", "", "test-tiny-embed",
                              prompt_tokens=[1, 2, 3],
                              sampling=SamplingParams(max_tokens=4))
    item = _wait(req)
    assert item is not None and item.kind == "error"
    assert "embedding-only" in (item.error or "")


def test_multihost_dp_mesh_arrangement_validates():
    """dp slices must span every process; make_mesh enforces/arranges it
    (simulated process layout — single-process here exercises only the
    arithmetic via the internal arrangement path)."""
    import numpy as np

    from ollamamq_tpu.parallel import mesh as M

    # Simulate 2 processes x 4 local devices over the 8 virtual devices.
    class _FakeProc:
        def __init__(self, n):
            self.n = n

        def __call__(self):
            return self.n

    orig = M.jax.process_count
    M.jax.process_count = _FakeProc(2)
    try:
        m = M.make_mesh(dp=2, sp=1, tp=4)
        # Each dp slice takes 2 devices from EACH simulated process half.
        ids = np.vectorize(lambda d: d.id)(m.devices)
        for r in range(2):
            slice_ids = set(ids[r].ravel().tolist())
            assert slice_ids & {0, 1, 2, 3} and slice_ids & {4, 5, 6, 7}
        # dp that can't give every process a chip per replica: loud error.
        import pytest as _pytest

        with _pytest.raises(ValueError, match="per-process"):
            M.make_mesh(dp=8, sp=1, tp=1)
    finally:
        M.jax.process_count = orig
