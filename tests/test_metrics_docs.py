"""CI wiring for scripts/check_metrics_docs.py: the registry's metric
surface and README.md's Observability table must not drift. Runs in
tier-1 (non-slow, no jax/engine needed by the script)."""

import importlib.util
import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "scripts", "check_metrics_docs.py")


def _load():
    spec = importlib.util.spec_from_file_location("check_metrics_docs",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_readme_documents_every_registered_metric():
    mod = _load()
    assert mod.main(["check_metrics_docs.py"]) == 0


def test_checker_catches_missing_and_ghost_names(tmp_path):
    mod = _load()
    # Missing: a README without any metric names.
    bare = tmp_path / "README_bare.md"
    bare.write_text("# no metrics documented here\n")
    assert mod.main(["check_metrics_docs.py", str(bare)]) == 1
    # Ghost: documents a metric the registry never registered.
    with open(os.path.join(_REPO, "README.md"), encoding="utf-8") as f:
        full = f.read()
    ghost = tmp_path / "README_ghost.md"
    ghost.write_text(full + "\n| `ollamamq_definitely_not_real` | gauge |\n")
    assert mod.main(["check_metrics_docs.py", str(ghost)]) == 1


def test_checker_pins_journal_event_table(tmp_path):
    """Satellite: every decision-journal event kind the engine can record
    (telemetry/journal.py EVENTS) must appear in the README flight-
    recorder table (marker-scoped), and the table must not document
    kinds the journal no longer emits — the checker exits non-zero on
    any drift, and this test gates it in tier-1."""
    mod = _load()
    with open(os.path.join(_REPO, "README.md"), encoding="utf-8") as f:
        full = f.read()
    assert "| `preempt` |" in full, "journal table row shape changed"
    # A documented event row removed => missing-event failure.
    missing = tmp_path / "README_noevent.md"
    missing.write_text(full.replace("| `preempt` |", "| preempt-less |", 1))
    assert mod.main(["check_metrics_docs.py", str(missing)]) == 1
    # A ghost kind inside the markers => ghost-event failure.
    ghost = tmp_path / "README_ghostevent.md"
    ghost.write_text(full.replace(
        mod.JOURNAL_END,
        "| `notarealevent` | bogus |\n" + mod.JOURNAL_END, 1))
    assert mod.main(["check_metrics_docs.py", str(ghost)]) == 1
    # Markers stripped => every kind reads as undocumented.
    bare = tmp_path / "README_nojournalmarkers.md"
    bare.write_text(full.replace(mod.JOURNAL_BEGIN, "").replace(
        mod.JOURNAL_END, ""))
    assert mod.main(["check_metrics_docs.py", str(bare)]) == 1


def test_checker_pins_attribution_phase_table(tmp_path):
    """Satellite: every phase the attribution layer can emit must appear
    in the README phase table (marker-scoped), and the table must not
    document phases that no longer exist."""
    mod = _load()
    with open(os.path.join(_REPO, "README.md"), encoding="utf-8") as f:
        full = f.read()
    # A documented phase row removed => missing-phase failure.
    assert "| `queue` |" in full, "phase table row shape changed"
    missing = tmp_path / "README_nophase.md"
    missing.write_text(full.replace("| `queue` |", "| queue-less |", 1))
    assert mod.main(["check_metrics_docs.py", str(missing)]) == 1
    # A ghost phase inside the markers => ghost-phase failure.
    ghost = tmp_path / "README_ghostphase.md"
    ghost.write_text(full.replace(
        mod.PHASES_END, "| `notarealphase` | bogus |\n" + mod.PHASES_END, 1))
    assert mod.main(["check_metrics_docs.py", str(ghost)]) == 1
    # Markers stripped entirely => every phase reads as undocumented.
    bare = tmp_path / "README_nomarkers.md"
    bare.write_text(full.replace(mod.PHASES_BEGIN, "").replace(
        mod.PHASES_END, ""))
    assert mod.main(["check_metrics_docs.py", str(bare)]) == 1


def test_checker_pins_stepprof_phase_table(tmp_path):
    """Satellite (PR 20): the step profiler's closed dispatch-phase
    vocabulary (telemetry/stepprof.py PHASES — the `phase` label of
    `ollamamq_step_phase_ms`) is pinned to the README engine-
    performance-plane table, same marker pattern as the others."""
    mod = _load()
    from ollamamq_tpu.telemetry.stepprof import PHASES

    with open(os.path.join(_REPO, "README.md"), encoding="utf-8") as f:
        full = f.read()
    assert "| `host_prep` |" in full, "stepprof table row shape changed"
    assert set(PHASES) == {"host_prep", "dispatch", "collect", "detok"}
    # A documented phase row removed => missing-phase failure.
    missing = tmp_path / "README_nostepphase.md"
    missing.write_text(full.replace("| `host_prep` |", "| prep-less |", 1))
    assert mod.main(["check_metrics_docs.py", str(missing)]) == 1
    # A ghost phase inside the markers => ghost-phase failure.
    ghost = tmp_path / "README_ghoststepphase.md"
    ghost.write_text(full.replace(
        mod.STEPPROF_END,
        "| `notastepphase` | bogus |\n" + mod.STEPPROF_END, 1))
    assert mod.main(["check_metrics_docs.py", str(ghost)]) == 1
    # Markers stripped entirely => every phase reads as undocumented.
    bare = tmp_path / "README_nostepmarkers.md"
    bare.write_text(full.replace(mod.STEPPROF_BEGIN, "").replace(
        mod.STEPPROF_END, ""))
    assert mod.main(["check_metrics_docs.py", str(bare)]) == 1
