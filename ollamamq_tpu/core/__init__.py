from ollamamq_tpu.core.mqcore import MQCore, Family, Fairness
