"""Request-lifecycle tracing: span events per request, bounded ring.

Every request the engine accepts carries a Trace; the engine drops span
events at each lifecycle boundary (enqueue -> admit -> place -> prefill
[per chunk] -> first_token -> decode [sampled] -> stop/cancelled/error).
Consecutive events define contiguous phase spans — gapless by
construction — so a wedged or slow request reads straight off the
timeline in chrome://tracing / Perfetto via GET /debug/trace.

Finished traces live in a bounded ring (oldest evicted); in-flight
traces are exported too — those are exactly the ones an operator
debugging a wedge needs to see.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

from ollamamq_tpu.telemetry import attribution
from ollamamq_tpu.telemetry import schema as tm

# Per-trace event cap: a 100k-token generation must not grow its trace
# unboundedly. Terminal events always land (the chain must end).
MAX_EVENTS = 256
# Sample cadence for decode-progress events after the first token.
DECODE_EVENT_EVERY = 16


class Trace:
    __slots__ = ("req_id", "user", "model", "kind", "events", "dropped",
                 "finished", "outcome", "_tracer")

    def __init__(self, tracer: "Tracer", req_id: int, user: str, model: str,
                 kind: str):
        self._tracer = tracer
        self.req_id = req_id
        self.user = user
        self.model = model
        self.kind = kind
        self.events: List[tuple] = []  # (name, t_monotonic, args|None)
        self.dropped = 0
        self.finished = False
        self.outcome: Optional[str] = None

    def event(self, name: str, _force: bool = False, **args) -> None:
        if self.finished:
            return
        if len(self.events) >= MAX_EVENTS and not _force:
            self.dropped += 1
            return
        self.events.append((name, time.monotonic(), args or None))

    def finish(self, outcome: str) -> None:
        """Terminal event + hand the trace to the ring. Idempotent — the
        cancel and finish paths can race to it."""
        if self.finished:
            return
        self.event(outcome, _force=True)
        self.finished = True
        self.outcome = outcome
        self._tracer._finished(self, outcome)


class Tracer:
    """Owner of the live-trace table and the finished-trace ring."""

    def __init__(self, capacity: int = 512):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=max(1, capacity))
        self._live: Dict[int, Trace] = {}
        self.epoch = time.monotonic()
        # Monotonic finish instants of recent requests: the observed
        # completion rate behind load-shedding Retry-After estimates.
        self.finish_times: collections.deque = collections.deque(maxlen=256)

    def begin(self, req_id: int, user: str, model: str,
              kind: str = "generate") -> Trace:
        tr = Trace(self, req_id, user, model, kind)
        tr.event("enqueue")
        with self._lock:
            self._live[id(tr)] = tr
        tm.REQUESTS_INFLIGHT.inc()
        return tr

    def _finished(self, tr: Trace, outcome: str) -> None:
        with self._lock:
            self._live.pop(id(tr), None)
            self._ring.append(tr)
            self.finish_times.append(time.monotonic())
        tm.REQUESTS_INFLIGHT.dec()
        tm.REQUESTS_TOTAL.labels(model=tr.model or "?", outcome=outcome).inc()
        # Latency attribution: fold the finished timeline's per-phase
        # totals into ollamamq_request_phase_ms.
        attribution.observe_phases(tr.model, list(tr.events))

    def traces(self) -> List[Trace]:
        with self._lock:
            return list(self._ring) + list(self._live.values())

    def find(self, req_id: int) -> Optional[Trace]:
        """Latest trace for a request id: the in-flight table first, then
        the finished ring newest-first (ids can recur across requeues —
        the newest holder is the one an operator is asking about)."""
        with self._lock:
            for tr in self._live.values():
                if tr.req_id == req_id:
                    return tr
            for tr in reversed(self._ring):
                if tr.req_id == req_id:
                    return tr
        return None

    def export_chrome(self) -> dict:
        """Chrome trace-event JSON (the chrome://tracing 'JSON Array
        Format' wrapped in an object): consecutive events of a request
        become complete ("X") spans named after the phase they open; the
        terminal event is an instant ("i") mark. tid = req_id, so each
        request renders as its own row."""
        events: List[dict] = []
        for tr in self.traces():
            evs = list(tr.events)  # engine thread may still append; copy
            tid = tr.req_id
            events.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"req {tr.req_id} {tr.user} "
                                 f"{tr.model or '?'} [{tr.kind}]"},
            })
            for i, (name, t, args) in enumerate(evs):
                ts = (t - self.epoch) * 1e6  # Chrome wants microseconds
                ev = {"name": name, "pid": 1, "tid": tid, "ts": ts,
                      "cat": tr.kind}
                if args:
                    ev["args"] = args
                if i + 1 < len(evs):
                    ev["ph"] = "X"
                    ev["dur"] = (evs[i + 1][1] - t) * 1e6
                else:
                    ev["ph"] = "i"
                    ev["s"] = "t"
                events.append(ev)
            if tr.dropped:
                events.append({
                    "name": f"{tr.dropped} events dropped", "ph": "i",
                    "s": "t", "pid": 1, "tid": tid,
                    "ts": (evs[-1][1] - self.epoch) * 1e6 if evs else 0,
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}
