"""ByteTokenizer: roundtrip and incremental streaming decode semantics."""

from ollamamq_tpu.engine.tokenizer import ByteTokenizer


def test_roundtrip():
    tok = ByteTokenizer()
    s = "héllo wörld ☃"
    assert tok.decode(tok.encode(s, add_bos=False)) == s


def test_incremental_holds_multibyte_tail():
    tok = ByteTokenizer()
    step = tok.make_incremental_decoder()
    ids = tok.encode("☃", add_bos=False)  # 3-byte UTF-8 snowman
    assert step(ids[0]) == ""
    assert step(ids[1]) == ""
    assert step(ids[2]) == "☃"


def test_incremental_invalid_byte_does_not_wedge():
    """A bare continuation byte can never complete a sequence; it must
    surface as U+FFFD instead of silencing the rest of the stream."""
    tok = ByteTokenizer()
    step = tok.make_incremental_decoder()
    assert step(0x80 + 3) == "�"  # invalid head byte
    # Stream recovers: subsequent ASCII flows through immediately.
    assert step(ord("a") + 3) == "a"


def test_incremental_out_of_range_ids_silent():
    tok = ByteTokenizer()
    step = tok.make_incremental_decoder()
    assert step(0) == "" and step(1) == "" and step(2) == ""
    assert step(300) == ""  # beyond byte vocab (random-weight models)
    assert step(ord("x") + 3) == "x"
