"""Model correctness: prefill/decode equivalence, paged KV, encoder pooling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig, get_model_config
from ollamamq_tpu.engine import kv_cache as kvc
from ollamamq_tpu.models import llama

PAGE_SIZE = 8
MAX_PAGES = 8


def _fresh_cache(cfg, num_pages=32):
    shape = (cfg.num_layers, num_pages * PAGE_SIZE, cfg.num_kv_heads, cfg.head_dim)
    return jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32)


def _page_table(alloc, rows):
    return jnp.asarray(
        np.stack([kvc.make_page_table_row(r, MAX_PAGES) for r in rows])
    )


def test_smart_model_match():
    assert get_model_config("llama3:8b").name == "llama3:8b"
    assert get_model_config("LLAMA3:8B").name == "llama3:8b"
    assert get_model_config("llama3.2").name in ("llama3.2:1b", "llama3.2:3b")
    assert get_model_config("qwen2.5:latest") is not None
    assert get_model_config("nope-model") is None


def test_page_allocator():
    a = kvc.PageAllocator(num_pages=8, page_size=4, max_pages_per_seq=4)
    assert a.free_pages == 7  # page 0 reserved
    p = a.alloc(9)  # 3 pages
    assert len(p) == 3 and kvc.TRASH_PAGE not in p
    assert a.extend(p, 16)  # 4 pages
    assert len(p) == 4
    assert not a.extend(p, 17)  # cap hit
    a.free(p)
    assert a.free_pages == 7 and p == []


def test_prefill_decode_equivalence(tiny_cfg, tiny_params):
    """Greedy decode via paged cache must match teacher-forced prefill logits."""
    cfg, params = tiny_cfg, tiny_params
    key = jax.random.PRNGKey(42)
    prompt = jax.random.randint(key, (1, 5), 0, cfg.vocab_size, dtype=jnp.int32)
    alloc = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
    pages = alloc.alloc(5)
    pt = _page_table(alloc, [pages])

    kc, vc = _fresh_cache(cfg)
    logits, kc, vc = llama.forward_prefill(
        params, cfg, prompt, jnp.array([5]), kc, vc, pt, PAGE_SIZE
    )
    toks = [int(jnp.argmax(logits[0]))]

    # Decode 6 more tokens through the paged cache.
    for i in range(6):
        pos = 5 + i
        alloc.extend(pages, pos + 1)
        pt = _page_table(alloc, [pages])
        logits_d, kc, vc = llama.forward_decode(
            params, cfg, jnp.array([toks[-1]], jnp.int32), jnp.array([pos], jnp.int32),
            kc, vc, pt, PAGE_SIZE,
        )
        # Reference: full prefill over the entire prefix with a fresh cache.
        full = jnp.concatenate([prompt[0], jnp.array(toks, jnp.int32)])[None, :]
        kc2, vc2 = _fresh_cache(cfg)
        a2 = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
        pt2 = _page_table(a2, [a2.alloc(full.shape[1])])
        logits_ref, _, _ = llama.forward_prefill(
            params, cfg, full, jnp.array([full.shape[1]]), kc2, vc2, pt2, PAGE_SIZE
        )
        np.testing.assert_allclose(
            np.asarray(logits_d[0]), np.asarray(logits_ref[0]), rtol=2e-4, atol=2e-4
        )
        toks.append(int(jnp.argmax(logits_d[0])))


def test_prefill_padding_invariance(tiny_cfg, tiny_params):
    """Padded prompt gives same last-token logits as exact-length prompt."""
    cfg, params = tiny_cfg, tiny_params
    prompt = jnp.arange(1, 6, dtype=jnp.int32)[None, :]  # len 5
    padded = jnp.pad(prompt, ((0, 0), (0, 11)))  # len 16

    out = []
    for toks in (prompt, padded):
        kc, vc = _fresh_cache(cfg)
        a = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
        pt = _page_table(a, [a.alloc(5)])
        logits, _, _ = llama.forward_prefill(
            params, cfg, toks, jnp.array([5]), kc, vc, pt, PAGE_SIZE
        )
        out.append(np.asarray(logits))
    np.testing.assert_allclose(out[0], out[1], rtol=1e-4, atol=1e-4)


def test_batched_decode_independence(tiny_cfg, tiny_params):
    """Sequences in one decode batch don't contaminate each other."""
    cfg, params = tiny_cfg, tiny_params
    p1 = jnp.array([[1, 2, 3, 4, 5]], jnp.int32)
    p2 = jnp.array([[9, 8, 7]], jnp.int32)

    # Solo run of p1.
    kc, vc = _fresh_cache(cfg)
    a = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
    pg1 = a.alloc(5)
    pt = _page_table(a, [pg1])
    lg_solo, kc, vc = llama.forward_prefill(params, cfg, p1, jnp.array([5]), kc, vc, pt, PAGE_SIZE)
    t1 = int(jnp.argmax(lg_solo[0]))
    a.extend(pg1, 6)
    lg_solo_d, _, _ = llama.forward_decode(
        params, cfg, jnp.array([t1], jnp.int32), jnp.array([5], jnp.int32),
        kc, vc, _page_table(a, [pg1]), PAGE_SIZE,
    )

    # Batched: p1 and p2 share the pool.
    kc, vc = _fresh_cache(cfg)
    a = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
    pg1, pg2 = a.alloc(5), a.alloc(3)
    pad2 = jnp.pad(p2, ((0, 0), (0, 2)))
    lg1, kc, vc = llama.forward_prefill(params, cfg, p1, jnp.array([5]), kc, vc, _page_table(a, [pg1]), PAGE_SIZE)
    lg2, kc, vc = llama.forward_prefill(params, cfg, pad2, jnp.array([3]), kc, vc, _page_table(a, [pg2]), PAGE_SIZE)
    bt1 = int(jnp.argmax(lg1[0]))
    a.extend(pg1, 6)
    a.extend(pg2, 4)
    pt = _page_table(a, [pg1, pg2])
    lg_b, _, _ = llama.forward_decode(
        params, cfg,
        jnp.array([bt1, int(jnp.argmax(lg2[0]))], jnp.int32),
        jnp.array([5, 3], jnp.int32),
        kc, vc, pt, PAGE_SIZE,
    )
    assert bt1 == t1
    np.testing.assert_allclose(
        np.asarray(lg_b[0]), np.asarray(lg_solo_d[0]), rtol=2e-4, atol=2e-4
    )


def test_qwen_bias_config():
    cfg = MODEL_CONFIGS["test-tiny-qwen"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    assert "bq" in params["layers"]
    kc, vc = _fresh_cache(cfg)
    a = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
    pt = _page_table(a, [a.alloc(4)])
    logits, _, _ = llama.forward_prefill(
        params, cfg, jnp.array([[1, 2, 3, 4]], jnp.int32), jnp.array([4]), kc, vc, pt, PAGE_SIZE
    )
    assert logits.shape == (1, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_qwen3_qk_norm():
    cfg = MODEL_CONFIGS["test-tiny-qwen3"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    assert "q_norm" in params["layers"] and "bq" not in params["layers"]
    kc, vc = _fresh_cache(cfg)
    a = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
    pt = _page_table(a, [a.alloc(4)])
    toks = jnp.array([[1, 2, 3, 4]], jnp.int32)
    logits, _, _ = llama.forward_prefill(
        params, cfg, toks, jnp.array([4]), kc, vc, pt, PAGE_SIZE
    )
    assert bool(jnp.all(jnp.isfinite(logits)))
    # The norm is actually in the path: scaling its weight changes logits.
    bent = dict(params, layers=dict(params["layers"]))
    bent["layers"]["q_norm"] = params["layers"]["q_norm"] * 3.0
    kc2, vc2 = _fresh_cache(cfg)
    logits2, _, _ = llama.forward_prefill(
        bent, cfg, toks, jnp.array([4]), kc2, vc2, pt, PAGE_SIZE
    )
    assert not np.allclose(np.asarray(logits), np.asarray(logits2))


def test_encoder_embeddings():
    cfg = MODEL_CONFIGS["test-tiny-embed"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jnp.array([[1, 2, 3, 0, 0], [4, 5, 6, 7, 8]], jnp.int32)
    emb = llama.forward_encoder(params, cfg, toks, jnp.array([3, 5]))
    assert emb.shape == (2, cfg.hidden_size)
    norms = jnp.linalg.norm(emb, axis=-1)
    np.testing.assert_allclose(np.asarray(norms), 1.0, rtol=1e-5)
    # Padding invariance: same tokens, different pad width => same embedding.
    emb2 = llama.forward_encoder(
        params, cfg, jnp.array([[1, 2, 3]], jnp.int32), jnp.array([3])
    )
    np.testing.assert_allclose(np.asarray(emb[0]), np.asarray(emb2[0]), rtol=1e-4, atol=1e-5)


def test_forward_embed_generative():
    """Embeddings from a CAUSAL model: normalized, padding-invariant."""
    cfg = MODEL_CONFIGS["test-tiny"]
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jnp.array([[1, 2, 3, 0, 0], [4, 5, 6, 7, 8]], jnp.int32)
    emb = llama.forward_embed(params, cfg, toks, jnp.array([3, 5]))
    assert emb.shape == (2, cfg.hidden_size)
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(emb, axis=-1)), 1.0, rtol=1e-5)
    emb2 = llama.forward_embed(
        params, cfg, jnp.array([[1, 2, 3]], jnp.int32), jnp.array([3])
    )
    np.testing.assert_allclose(
        np.asarray(emb[0]), np.asarray(emb2[0]), rtol=1e-4, atol=1e-5)


def test_chunked_prefill_equivalence(tiny_cfg, tiny_params):
    """Chaining forward_prefill_chunk chunks == one-shot forward_prefill."""
    cfg, params = tiny_cfg, tiny_params
    T, C = 24, 8  # 3 chunks
    toks = jax.random.randint(jax.random.PRNGKey(7), (1, T), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    a = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
    pages = a.alloc(T)
    pt = _page_table(a, [pages])

    kc, vc = _fresh_cache(cfg)
    ref_logits, ref_kc, ref_vc = llama.forward_prefill(
        params, cfg, toks, jnp.array([T]), kc, vc, pt, PAGE_SIZE
    )

    kc2, vc2 = _fresh_cache(cfg)
    for start in range(0, T, C):
        chunk = toks[:, start:start + C]
        logits, kc2, vc2 = llama.forward_prefill_chunk(
            params, cfg, chunk, jnp.array([start]), jnp.array([C]),
            kc2, vc2, pt, PAGE_SIZE,
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(kc2), np.asarray(ref_kc), rtol=1e-5, atol=1e-5
    )


def test_chunked_prefill_ragged_last_chunk(tiny_cfg, tiny_params):
    """Last chunk shorter than the chunk bucket (padding masked)."""
    cfg, params = tiny_cfg, tiny_params
    T, C = 19, 8  # chunks of 8, 8, 3
    toks = jax.random.randint(jax.random.PRNGKey(8), (1, T), 0, cfg.vocab_size,
                              dtype=jnp.int32)
    a = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
    pt = _page_table(a, [a.alloc(T)])

    kc, vc = _fresh_cache(cfg)
    ref_logits, _, _ = llama.forward_prefill(
        params, cfg, toks, jnp.array([T]), kc, vc, pt, PAGE_SIZE
    )
    kc2, vc2 = _fresh_cache(cfg)
    for start in range(0, T, C):
        piece = np.zeros((1, C), np.int32)
        cl = min(C, T - start)
        piece[0, :cl] = np.asarray(toks[0, start:start + cl])
        logits, kc2, vc2 = llama.forward_prefill_chunk(
            params, cfg, jnp.asarray(piece), jnp.array([start]), jnp.array([cl]),
            kc2, vc2, pt, PAGE_SIZE,
        )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )


def test_blockwise_chunk_attention_matches_full_gather():
    """paged_chunk_attention_blockwise (dynamic block walk, online softmax)
    == paged_chunk_attention (full padded gather) on ragged paged batches."""
    from ollamamq_tpu.ops.attention import (
        paged_chunk_attention,
        paged_chunk_attention_blockwise,
    )

    rng = np.random.default_rng(3)
    B, C, H, Hk, hd, ps, MP = 3, 8, 4, 2, 16, 4, 12
    S = 64 * ps
    q = jnp.asarray(rng.normal(size=(B, C, H, hd)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(S, Hk, hd)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(S, Hk, hd)), jnp.float32)
    # Distinct pages per sequence; tables longer than any sequence needs.
    pt = jnp.asarray(
        rng.permutation(64 - 1)[: B * MP].reshape(B, MP) + 1, jnp.int32
    )
    # Third sequence's context reaches the LAST page (end=48 == MP*ps), so
    # the final partial block is exercised when block_pages doesn't divide MP.
    start = jnp.asarray([0, 9, 44], jnp.int32)
    chunk_lens = jnp.asarray([8, 5, 4], jnp.int32)  # ragged
    ref = paged_chunk_attention(q, kc, vc, pt, start, chunk_lens, ps)
    # block_pages=5 does NOT divide MP=12: the final partial block must not
    # relabel or double-count pages (clamped-slice regression).
    for bp in (2, 5):
        blk = paged_chunk_attention_blockwise(
            q, kc, vc, pt, start, chunk_lens, ps, block_pages=bp
        )
        for b in range(B):
            n = int(chunk_lens[b])
            np.testing.assert_allclose(
                np.asarray(blk[b, :n]), np.asarray(ref[b, :n]),
                rtol=2e-5, atol=2e-5, err_msg=f"block_pages={bp} seq {b}",
            )


def test_apply_penalties_math():
    from ollamamq_tpu.ops.sampling import apply_penalties

    logits = jnp.array([[2.0, -2.0, 1.0, -1.0]])
    recent = jnp.array([[1, 1, 0, -1]], jnp.int32)  # id1 twice, id0 once
    one = jnp.array([1.0])
    zero = jnp.array([0.0])
    # repeat only: matches apply_repeat_penalty semantics
    out = np.asarray(apply_penalties(logits, recent, jnp.array([2.0]), zero, zero))
    np.testing.assert_allclose(out, [[1.0, -4.0, 1.0, -1.0]])
    # presence: flat -0.5 on seen ids regardless of count
    out = np.asarray(apply_penalties(logits, recent, one, jnp.array([0.5]), zero))
    np.testing.assert_allclose(out, [[1.5, -2.5, 1.0, -1.0]])
    # frequency: -0.5 per occurrence (id1 seen twice)
    out = np.asarray(apply_penalties(logits, recent, one, zero, jnp.array([0.5])))
    np.testing.assert_allclose(out, [[1.5, -3.0, 1.0, -1.0]])
    # all off => identity
    out = np.asarray(apply_penalties(logits, recent, one, zero, zero))
    np.testing.assert_allclose(out, np.asarray(logits))


def test_per_row_keys_seed_isolation():
    """Seeded rows depend only on (seed, position); unseeded rows follow the
    engine stream key."""
    from ollamamq_tpu.ops.sampling import per_row_keys

    seeds = jnp.array([7, 0], jnp.int32)
    pos = jnp.array([5, 5], jnp.int32)
    k1 = per_row_keys(jax.random.PRNGKey(1), seeds, pos)
    k2 = per_row_keys(jax.random.PRNGKey(2), seeds, pos)
    assert np.array_equal(k1[0], k2[0])  # seeded: engine key irrelevant
    assert not np.array_equal(k1[1], k2[1])  # unseeded: engine key matters
    k3 = per_row_keys(jax.random.PRNGKey(1), seeds, jnp.array([6, 5], jnp.int32))
    assert not np.array_equal(k1[0], k3[0])  # position advances the stream


def test_apply_repeat_penalty_math():
    from ollamamq_tpu.ops.sampling import apply_repeat_penalty

    logits = jnp.array([[2.0, -2.0, 1.0, -1.0]])
    seen = jnp.array([[1, 1, 0, 0]], jnp.int8)
    pen = jnp.array([2.0])
    out = np.asarray(apply_repeat_penalty(logits, seen, pen))
    np.testing.assert_allclose(out, [[1.0, -4.0, 1.0, -1.0]])
    # penalty 1.0 => identity
    out2 = np.asarray(apply_repeat_penalty(logits, seen, jnp.array([1.0])))
    np.testing.assert_allclose(out2, np.asarray(logits))
