"""Telemetry subsystem: histogram/bucket math, Prometheus exposition
format (HELP/TYPE lines, label escaping), trace ring-buffer eviction,
and end-to-end — a FakeEngine request produces a valid exposition with
the headline metrics AND a complete, monotonic, gapless span chain on
/debug/trace. Also pins the two observability satellites: a failed
/debug/profile capture must not wedge the endpoint at 409, and
per_chip_stats must tag backends without memory_stats instead of
reporting fake zeros."""

import asyncio
import json
import re
import tempfile
import unittest.mock

from aiohttp.test_utils import TestClient, TestServer

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                            MetricsRegistry,
                                            escape_label_value)
from ollamamq_tpu.telemetry.tracing import Tracer
from ollamamq_tpu.telemetry import mfu as mfu_model


# ---------------------------------------------------------------- registry
def test_counter_and_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "help", labels=("model",))
    c.labels(model="a").inc()
    c.labels(model="a").inc(2)
    c.labels(model="b").inc()
    assert c.labels(model="a").value == 3
    assert c.labels(model="b").value == 1
    g = reg.gauge("t_gauge", "help")
    g.set(5)
    g.inc()
    g.dec(3)
    assert g.value == 3
    # Counters refuse to go down; labels must match the declaration.
    try:
        c.labels(model="a").inc(-1)
        assert False, "negative counter inc must raise"
    except ValueError:
        pass
    try:
        c.labels(nope="a")
        assert False, "wrong label name must raise"
    except ValueError:
        pass


def test_registry_idempotent_and_type_conflict():
    reg = MetricsRegistry()
    a = reg.counter("t_x", "h")
    assert reg.counter("t_x", "h") is a  # same name => same object
    try:
        reg.gauge("t_x", "h")
        assert False, "type flip must raise"
    except ValueError:
        pass


def test_histogram_bucket_boundaries():
    """Prometheus le is INCLUSIVE: observe(boundary) lands in that bucket;
    anything past the last bound lands in +Inf."""
    reg = MetricsRegistry()
    h = reg.histogram("t_h", "h", buckets=(1.0, 5.0, 10.0))
    for v in (0.5, 1.0, 1.0001, 5.0, 9.99, 10.0, 11.0, 1e9):
        h.observe(v)
    child = h.labels()
    # buckets: <=1: {0.5, 1.0}; <=5: {1.0001, 5.0}; <=10: {9.99, 10.0};
    # +Inf: {11.0, 1e9}
    assert child.counts == [2, 2, 2, 2]
    assert child.count == 8
    assert abs(child.sum - (0.5 + 1.0 + 1.0001 + 5.0 + 9.99 + 10.0 + 11.0 + 1e9)) < 1e-3


def test_histogram_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("t_q", "h", buckets=(10.0, 20.0, 40.0))
    assert h.quantile(0.5) == 0.0  # empty
    for _ in range(10):
        h.observe(5.0)   # bucket (0, 10]
    for _ in range(10):
        h.observe(15.0)  # bucket (10, 20]
    # p50 = rank 10 => exactly fills the first bucket => its upper bound.
    assert abs(h.quantile(0.5) - 10.0) < 1e-9
    # p75 = rank 15 => midway through the second bucket (10..20).
    assert abs(h.quantile(0.75) - 15.0) < 1e-9
    # p100 clamps to the last bound touched.
    assert h.quantile(1.0) <= 40.0
    # all mass in +Inf clamps to the last finite bound.
    h2 = reg.histogram("t_q2", "h", buckets=(10.0,))
    h2.observe(100.0)
    assert h2.quantile(0.5) == 10.0


def test_set_buckets_resets():
    reg = MetricsRegistry()
    h = reg.histogram("t_rebucket", "h", buckets=(1.0, 2.0))
    h.observe(1.5)
    h.set_buckets((5.0, 50.0, 500.0))
    child = h.labels()
    assert child.count == 0 and child.counts == [0, 0, 0, 0]  # 3 + +Inf
    h.observe(7.0)
    assert child.counts == [0, 1, 0, 0]


def test_label_escaping():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"


def parse_prom(text):
    """Minimal exposition parser: returns (help, type, samples) maps and
    asserts every line is well-formed."""
    helps, types, samples = {}, {}, {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name, h = line[7:].split(" ", 1)
            helps[name] = h
        elif line.startswith("# TYPE "):
            name, t = line[7:].split(" ", 1)
            types[name] = t
        else:
            m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (.+)$", line)
            assert m, f"malformed exposition line: {line!r}"
            val = m.group(3)
            assert val == "+Inf" or val == "NaN" or float(val) is not None
            samples[m.group(1) + (m.group(2) or "")] = val
    return helps, types, samples


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "requests served", labels=("model",))
    c.labels(model='we"ird\\mo\ndel').inc(3)
    h = reg.histogram("t_lat_ms", "latency", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(100.0)
    g = reg.gauge("t_up", "uptime")
    g.set(1.5)
    text = reg.render()
    helps, types, samples = parse_prom(text)
    assert types == {"t_total": "counter", "t_lat_ms": "histogram",
                     "t_up": "gauge"}
    assert helps["t_total"] == "requests served"
    # Label escaping on the wire.
    assert samples['t_total{model="we\\"ird\\\\mo\\ndel"}'] == "3"
    # Histogram: cumulative buckets + +Inf + sum/count.
    assert samples['t_lat_ms_bucket{le="1"}'] == "1"
    assert samples['t_lat_ms_bucket{le="10"}'] == "1"
    assert samples['t_lat_ms_bucket{le="+Inf"}'] == "2"
    assert samples["t_lat_ms_count"] == "2"
    assert float(samples["t_lat_ms_sum"]) == 100.5
    assert samples["t_up"] == "1.5"


def test_snapshot_merge_sums_counters_and_histograms():
    """The SPMD host-merge path: peer snapshots sum into counters and
    histograms; gauges union with local winning."""
    a, b = MetricsRegistry(), MetricsRegistry()
    for reg, n in ((a, 2), (b, 5)):
        c = reg.counter("t_tok_total", "h", labels=("model",))
        c.labels(model="m").inc(n)
        h = reg.histogram("t_ms", "h", buckets=(1.0, 10.0))
        h.observe(n)
        g = reg.gauge("t_g", "h", labels=("chip",))
        g.labels(chip=str(n)).set(n)
    text = a.render(extra_snapshots=[b.snapshot()])
    _, _, samples = parse_prom(text)
    assert samples['t_tok_total{model="m"}'] == "7"
    assert samples['t_ms_bucket{le="+Inf"}'] == "2"
    assert float(samples["t_ms_sum"]) == 7.0
    # disjoint gauge series union:
    assert samples['t_g{chip="2"}'] == "2" and samples['t_g{chip="5"}'] == "5"


# ----------------------------------------------------------------- tracing
def test_trace_ring_eviction():
    tr = Tracer(capacity=4)
    for i in range(10):
        t = tr.begin(i, "u", "m")
        t.finish("stop")
    kept = tr.traces()
    assert len(kept) == 4
    assert [t.req_id for t in kept] == [6, 7, 8, 9]  # oldest evicted
    # Finish is idempotent: a cancel/finish race can't double-insert.
    kept[0].finish("stop")
    assert len(tr.traces()) == 4


def test_trace_event_cap_keeps_terminal():
    tr = Tracer(capacity=4)
    t = tr.begin(1, "u", "m")
    for i in range(1000):
        t.event("decode", tokens=i)
    t.finish("stop")
    assert len(t.events) <= 257  # cap + forced terminal
    assert t.events[-1][0] == "stop"
    assert t.dropped > 0


def test_chrome_export_spans_contiguous():
    tr = Tracer(capacity=8)
    t = tr.begin(7, "alice", "test-tiny")
    for name in ("admit", "place", "prefill", "first_token"):
        t.event(name)
    t.finish("stop")
    out = tr.export_chrome()
    evs = [e for e in out["traceEvents"]
           if e.get("tid") == 7 and e.get("ph") in ("X", "i")]
    names = [e["name"] for e in evs]
    assert names == ["enqueue", "admit", "place", "prefill", "first_token",
                     "stop"]
    # Gapless: each X span ends exactly where the next event begins.
    for cur, nxt in zip(evs, evs[1:]):
        assert cur["ph"] == "X"
        assert abs((cur["ts"] + cur["dur"]) - nxt["ts"]) < 1e-6
        assert nxt["ts"] >= cur["ts"]  # monotonic
    assert evs[-1]["ph"] == "i"


# --------------------------------------------------------------------- mfu
def test_mfu_model():
    from ollamamq_tpu.config import MODEL_CONFIGS

    cfg = MODEL_CONFIGS["test-tiny"]
    base = mfu_model.flops_per_token(cfg)
    assert base == 2.0 * mfu_model.active_param_count(cfg)
    with_ctx = mfu_model.flops_per_token(cfg, context_len=128)
    assert with_ctx == base + 4.0 * cfg.num_layers * 128 * cfg.q_dim
    # MoE counts routed-active experts only.
    moe = MODEL_CONFIGS["test-tiny-moe"]
    assert mfu_model.active_param_count(moe) < moe.param_count()
    # Unknown accelerator => 0, never invented.
    assert mfu_model.mfu(cfg, 100, 1.0, None) == 0.0
    # Known peak: achieved/peak.
    got = mfu_model.mfu(cfg, tokens=10, seconds=1.0, peak_per_chip=base * 100,
                        n_chips=1)
    assert abs(got - 0.1) < 1e-9
    assert mfu_model.peak_flops_per_chip("TPU v5 lite") == 394e12
    assert mfu_model.peak_flops_per_chip("weird-npu") is None
    with unittest.mock.patch.dict("os.environ",
                                  {"OLLAMAMQ_PEAK_FLOPS": "1e12"}):
        assert mfu_model.peak_flops_per_chip("weird-npu") == 1e12


# ------------------------------------------------------------- chip stats
def test_per_chip_stats_tags_missing_memory_stats():
    """CPU backends report memory_stats=False so consumers render n/a
    instead of a fake 0-byte HBM reading."""
    from ollamamq_tpu.engine.engine import per_chip_stats

    rows = per_chip_stats()
    assert rows, "expected the 8 virtual CPU devices"
    for row in rows:
        assert "memory_stats" in row
        if not row["memory_stats"]:
            assert row["hbm_used"] == 0 and row["hbm_total"] == 0


# --------------------------------------------------------------- e2e HTTP
def _serve(fn):
    """Async harness: fresh FakeEngine + server (test_api.py idiom)."""
    async def main():
        with tempfile.TemporaryDirectory() as tmp:
            from ollamamq_tpu.engine.fake import FakeEngine
            from ollamamq_tpu.server.app import Server

            eng = FakeEngine(
                EngineConfig(model="test-tiny", max_slots=8),
                models={"test-tiny": None, "test-tiny-embed": None},
                blocklist_path=f"{tmp}/blocked_items.json",
            )
            eng.start()
            server = Server(eng, timeout_s=30)
            cl = TestClient(TestServer(server.build_app()))
            cl.engine = eng
            await cl.start_server()
            try:
                await fn(cl)
            finally:
                await cl.close()
                eng.stop()

    asyncio.run(main())


def test_e2e_prometheus_exposition():
    """GET /metrics is valid Prometheus text carrying the acceptance
    metrics with real values after one request."""
    async def run(cl):
        r = await cl.post("/api/generate", json={
            "model": "test-tiny", "prompt": "hello", "stream": False,
            "options": {"num_predict": 4},
        }, headers={"X-User-ID": "alice"})
        assert r.status == 200
        r = await cl.get("/metrics")
        assert r.status == 200
        assert "text/plain" in r.headers["Content-Type"]
        assert "version=0.0.4" in r.headers["Content-Type"]
        helps, types, samples = parse_prom(await r.text())
        for name, typ in (
            ("ollamamq_ttft_ms", "histogram"),
            ("ollamamq_tpot_ms", "histogram"),
            ("ollamamq_queue_depth", "gauge"),
            ("ollamamq_batch_occupancy", "gauge"),
            ("ollamamq_mfu", "gauge"),
            ("ollamamq_requests_total", "counter"),
            ("ollamamq_tokens_generated_total", "counter"),
            ("ollamamq_uptime_seconds", "gauge"),
        ):
            assert types.get(name) == typ, f"{name} missing or wrong type"
            assert name in helps
        # Value lines, not just declarations:
        assert 'ollamamq_queue_depth{user="alice"}' in samples
        assert 'ollamamq_batch_occupancy{model="test-tiny"}' in samples
        assert 'ollamamq_mfu{model="test-tiny"}' in samples
        # The request actually landed in the histograms/counters.
        assert int(samples[
            'ollamamq_ttft_ms_bucket{model="test-tiny",le="+Inf"}']) >= 1
        assert float(samples[
            'ollamamq_tokens_generated_total{model="test-tiny"}']) >= 4

    _serve(run)


def test_e2e_metrics_json_still_serves_legacy_payload():
    async def run(cl):
        r = await cl.get("/metrics.json")
        assert r.status == 200
        body = await r.json()
        assert "runtimes" in body and "queue" in body
        assert all("mfu" in rt for rt in body["runtimes"])

    _serve(run)


def test_e2e_fake_engine_trace_chain():
    """A FakeEngine request's /debug/trace spans cover enqueue->complete
    with monotonic timestamps and no gaps."""
    async def run(cl):
        r = await cl.post("/api/generate", json={
            "model": "test-tiny", "prompt": "hello", "stream": False,
            "options": {"num_predict": 3},
        }, headers={"X-User-ID": "bob"})
        assert r.status == 200
        r = await cl.get("/debug/trace")
        assert r.status == 200
        out = await r.json()
        assert "traceEvents" in out
        # Find bob's generate request row.
        metas = [e for e in out["traceEvents"] if e.get("ph") == "M"
                 and "bob" in e.get("args", {}).get("name", "")]
        assert metas, "traced request missing from export"
        tid = metas[0]["tid"]
        evs = [e for e in out["traceEvents"]
               if e.get("tid") == tid and e.get("ph") in ("X", "i")]
        names = [e["name"] for e in evs]
        assert names[0] == "enqueue"
        assert names[-1] in ("stop", "length")
        for must in ("admit", "place", "prefill", "first_token"):
            assert must in names, f"span chain missing {must}: {names}"
        prev_end = None
        for e in evs:
            assert e["ts"] >= (prev_end if prev_end is not None else e["ts"])
            if e["ph"] == "X":
                assert e["dur"] >= 0
                if prev_end is not None:
                    assert abs(e["ts"] - prev_end) < 1e-6, "gap in span chain"
                prev_end = e["ts"] + e["dur"]
        # JSON round-trips (chrome://tracing loads it).
        json.dumps(out)

    _serve(run)


def test_debug_profile_failure_does_not_wedge():
    """Satellite: a capture that throws must clear the running flag — the
    next POST gets a fresh 500/success, never a permanent 409."""
    async def run(cl):
        import jax

        with unittest.mock.patch.object(
                jax.profiler, "start_trace",
                side_effect=RuntimeError("disk full")):
            r1 = await cl.post("/debug/profile", json={"seconds": 0.1})
            assert r1.status == 500
            assert "profile capture failed" in (await r1.json())["error"]
            r2 = await cl.post("/debug/profile", json={"seconds": 0.1})
            assert r2.status == 500, "second capture wedged at 409"

    _serve(run)


def test_bench_cpu_fallback_argv():
    """bench.py's wedged-tunnel fallback re-execs itself on the CPU
    platform with a smoke workload (tagged platform=cpu by the caller)."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_under_test", path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    argv = bench._fallback_argv("llama3.2:1b")
    assert "--cpu" in argv
    assert "llama3.2:1b" in argv
    assert argv[1].endswith("bench.py")
    # Recursion guard: with the env marker set, no fallback is attempted.
    with unittest.mock.patch.dict(
            "os.environ", {"OLLAMAMQ_BENCH_NO_FALLBACK": "1"}):
        assert bench._cpu_fallback("llama3.2:1b", "test") is False


def test_trace_ring_flag_bounds_engine_ring():
    from ollamamq_tpu.engine.fake import FakeEngine

    eng = FakeEngine(EngineConfig(model="test-tiny", trace_ring=3),
                     models={"test-tiny": None})
    eng.start()
    try:
        reqs = [eng.enqueue_request("u", "", "test-tiny",
                                    prompt_tokens=[1, 2]) for _ in range(8)]
        for req in reqs:
            items = []
            while not items or items[-1].kind not in ("done", "error"):
                item = req.stream.get(timeout=5)
                assert item is not None, "request never finished"
                items.append(item)
        finished = [t for t in eng.tracer.traces() if t.finished]
        assert len(finished) == 3
    finally:
        eng.stop()
