"""Int8 quantization: weights + KV pages (PR 8 tentpole).

Pinned here:
  - per-channel weight quantize/dequantize roundtrip error bounds (last
    axis for layer matmuls, row axis for embed/lm_head);
  - per-page KV write/gather roundtrip error bounds (per-slot per-head
    scales stored page-aligned alongside the pool);
  - the quality guardrail: greedy token-match-rate + max-logit-error of
    the int8 tree vs its bf16 source on real-shaped weights (GQA,
    head_dim 64), enforced in tier-1 and published as
    `ollamamq_quant_logit_err`;
  - quantized Pallas kernels (ragged + decode) match the jnp quantized
    reference in interpret mode;
  - engine integration: quantized pools shrink kv_bytes ~2x, spec-on
    stays byte-identical to spec-off on an int8 runtime, and a
    randomized preemption/rollback/prefix-sharing fuzz preserves
    free+used+cached == pool with shrunken pages (journal invariants
    clean);
  - the density regression gate: at EQUAL HBM an int8 pool holds
    2*hd/(hd+4) more pages and preempts no more than the bf16 pool on
    the same arrival trace;
  - fail-fast: invalid --weights-dtype/--kv-dtype combinations error at
    CLI/config/runtime-build time, never at first dispatch.
"""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.config import (MODEL_CONFIGS, EngineConfig, ModelConfig,
                                 validate_quant_config)
from ollamamq_tpu.core import MQCore
from ollamamq_tpu.engine import kv_cache as kvc
from ollamamq_tpu.engine.engine import ModelRuntime
from ollamamq_tpu.engine.request import Request
from ollamamq_tpu.models import weights
from ollamamq_tpu.ops.quant import (QuantKV, QuantTensor, dequantize_tensor,
                                    kv_gather, kv_quantize, kv_write,
                                    quantize_tensor)
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry.journal import Journal, check_invariants

_IDS = itertools.count(1)

# Real-shaped guardrail config: llama-family GQA geometry (head_dim 64,
# grouped KV heads, SwiGLU) at a layer/width CI can afford.
GUARD_SHAPE = ModelConfig(
    name="guard-shape", vocab_size=4096, hidden_size=256,
    intermediate_size=512, num_layers=4, num_heads=4, num_kv_heads=2,
    head_dim=64, rope_theta=500_000.0, max_seq_len=512,
    tie_embeddings=True,
)


# ---------------------------------------------------------------- roundtrips
def test_weight_roundtrip_per_channel_bounds():
    """Symmetric per-channel int8: every element's roundtrip error is
    bounded by half its channel's scale (the quantization step)."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(3, 32, 48)).astype(np.float32) * 2.5)
    t = quantize_tensor(w, axis=-1)
    assert t.q.dtype == jnp.int8 and t.s.dtype == jnp.float32
    assert t.s.shape == (3, 48)
    back = np.asarray(dequantize_tensor(t, axis=-1))
    err = np.abs(back - np.asarray(w))
    per_channel_bound = np.asarray(t.s)[:, None, :] * 0.5 + 1e-6
    assert (err <= per_channel_bound).all()


def test_embed_roundtrip_per_row_bounds():
    rng = np.random.default_rng(1)
    e = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    t = quantize_tensor(e, axis=0)
    assert t.s.shape == (64,)
    back = np.asarray(dequantize_tensor(t, axis=0))
    err = np.abs(back - np.asarray(e))
    assert (err <= np.asarray(t.s)[:, None] * 0.5 + 1e-6).all()


def test_kv_roundtrip_per_page_bounds():
    """KV rows quantize per (slot, head): the roundtrip error of every
    element is bounded by half that row's scale, and scales sit
    page-aligned (slot index == page * page_size + offset) so a page's
    scale rows travel with its page id."""
    rng = np.random.default_rng(2)
    S, Hk, hd = 64, 2, 16
    pool = QuantKV(jnp.zeros((S, Hk, hd), jnp.int8),
                   jnp.ones((S, Hk), jnp.float32))
    vals = jnp.asarray(rng.normal(size=(24, Hk, hd)).astype(np.float32) * 3)
    slots = jnp.asarray(rng.choice(S, size=24, replace=False))
    pool = kv_write(pool, slots, vals)
    got = np.asarray(kv_gather(pool, slots))
    scales = np.asarray(pool.s)[np.asarray(slots)]  # [24, Hk]
    err = np.abs(got - np.asarray(vals))
    assert (err <= scales[..., None] * 0.5 + 1e-6).all()
    # kv_quantize is the same math the in-jit writer runs.
    q, s = kv_quantize(vals)
    assert q.dtype == jnp.int8 and s.shape == (24, Hk)


# ----------------------------------------------------------------- guardrail
def test_quant_guardrail_real_shaped():
    """The tier-1 quality gate the ISSUE names: on real-shaped weights
    (GQA, head_dim 64) the int8 tree must track bf16 greedy decisions
    and keep the worst logit error bounded relative to the logit spread."""
    out = weights.quant_guardrail(GUARD_SHAPE, seed=3, dtype=jnp.bfloat16,
                                  prompt_len=16, steps=16)
    assert out["token_match_rate"] >= 0.85, out
    assert out["rel_logit_err"] <= 0.5, out
    from ollamamq_tpu.telemetry import schema as tm

    assert tm.QUANT_LOGIT_ERR.labels(
        model=GUARD_SHAPE.name).value == pytest.approx(out["max_logit_err"])


def test_quant_guardrail_tiny_smoke():
    """test-tiny's near-tied random logits are the worst case for greedy
    agreement — the bound is loose, but a quantization bug (wrong scale
    axis, off-by-one clip) craters it to ~chance."""
    out = weights.quant_guardrail(MODEL_CONFIGS["test-tiny"], seed=1,
                                  dtype=jnp.float32, prompt_len=8, steps=8)
    assert out["token_match_rate"] >= 0.5, out
    assert out["max_logit_err"] <= 1.0, out


def test_quantize_params_rejects_moe():
    with pytest.raises(ValueError):
        weights.load_params(MODEL_CONFIGS["test-tiny-moe"], None,
                            weights_dtype="int8")


# ------------------------------------------------- quantized pallas kernels
def _mixed_stream(rng, S=160, Hk=2, hd=16, H=4, ps=8, MP=8):
    kraw = jnp.asarray(rng.normal(size=(S, Hk, hd)).astype(np.float32))
    vraw = jnp.asarray(rng.normal(size=(S, Hk, hd)).astype(np.float32))
    kq, ks = kv_quantize(kraw)
    vq, vs = kv_quantize(vraw)
    pt = np.zeros((3, MP), np.int32)
    pt[0, :4] = [1, 2, 3, 4]
    pt[1, :2] = [5, 6]
    pt[2, :3] = [7, 8, 9]
    spans = [(0, 10, 26), (10, 1, 11), (11, 5, 17)]  # (q_start, q_len, kv)
    tok_seq, tok_pos = [], []
    for s, (qs, ql, kv) in enumerate(spans):
        for j in range(ql):
            tok_seq.append(s)
            tok_pos.append(kv - ql + j)
    return (QuantKV(kq, ks), QuantKV(vq, vs), jnp.asarray(pt),
            jnp.asarray([s[0] for s in spans], jnp.int32),
            jnp.asarray([s[1] for s in spans], jnp.int32),
            jnp.asarray([s[2] for s in spans], jnp.int32),
            jnp.asarray(tok_seq, jnp.int32), jnp.asarray(tok_pos, jnp.int32),
            ps, H, hd)


def test_pallas_ragged_quantized_matches_jnp_interpret():
    from ollamamq_tpu.ops.attention import ragged_paged_attention_blockwise
    from ollamamq_tpu.ops.pallas.ragged_attention import (
        ragged_paged_attention_pallas)

    rng = np.random.default_rng(4)
    (kc, vc, pt, q_start, q_len, kv_len, tok_seq, tok_pos,
     ps, H, hd) = _mixed_stream(rng)
    q = jnp.asarray(rng.normal(size=(16, H, hd)).astype(np.float32))
    ref = ragged_paged_attention_blockwise(q, kc, vc, pt, tok_seq, tok_pos,
                                           kv_len, ps)
    out = ragged_paged_attention_pallas(q, kc.q, vc.q, pt, q_start, q_len,
                                        kv_len, ps, interpret=True,
                                        k_scale=kc.s, v_scale=vc.s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_pallas_decode_quantized_matches_jnp_interpret():
    from ollamamq_tpu.ops.attention import paged_decode_attention
    from ollamamq_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas)

    rng = np.random.default_rng(5)
    (kc, vc, pt, _qs, _ql, kv_len, _ts, _tp, ps, H, hd) = _mixed_stream(rng)
    q = jnp.asarray(rng.normal(size=(3, H, hd)).astype(np.float32))
    ref = paged_decode_attention(q, kc, vc, pt, kv_len, ps)
    out = paged_decode_attention_pallas(q, kc.q, vc.q, pt, kv_len, ps,
                                        interpret=True,
                                        k_scale=kc.s, v_scale=vc.s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


# --------------------------------------------------------- engine integration
def make_rt(**kw):
    defaults = dict(
        model="test-tiny", max_slots=4, num_pages=96, page_size=8,
        max_pages_per_seq=16, prefill_buckets=(16, 64), max_new_tokens=8,
        decode_steps_per_iter=2, max_batch_tokens=48, token_granule=8,
    )
    defaults.update(kw)
    rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"],
                      EngineConfig(**defaults), dtype=jnp.float32)
    rt.tokenizer.eos_id = -1
    return rt


def run_all(rt, prompts, max_tokens=6, max_ticks=800):
    core = MQCore(None)
    reqs = []
    for p in prompts:
        req = Request(next(_IDS), f"u{len(reqs) % 3}", "test-tiny", list(p),
                      SamplingParams(max_tokens=max_tokens))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        reqs.append(req)
    for _ in range(max_ticks):
        if all(r.stats.finished_at for r in reqs):
            break
        ran = rt.step_ragged(core)
        if not ran and any(s is not None for s in rt.slot_req):
            rt.step_decode(core, k_steps=1)
    assert all(r.stats.finished_at for r in reqs), "requests wedged"
    return [list(r.generated_ids) for r in reqs]


def test_quantized_runtime_serves_and_shrinks_kv():
    rng = np.random.default_rng(6)
    prompts = [rng.integers(3, 500, size=n).tolist() for n in (20, 7, 35)]
    bf = make_rt()
    q8 = make_rt(kv_dtype="int8", weights_dtype="int8")
    out = run_all(q8, prompts)
    assert all(len(o) == 6 for o in out)
    # int8 pool: 1 payload byte + 4/hd scale bytes per element vs 4 (f32
    # test dtype) — and the weight tree shrinks too.
    assert q8.kv_bytes < 0.40 * bf.kv_bytes
    assert q8.param_bytes < 0.45 * bf.param_bytes
    from ollamamq_tpu.telemetry import schema as tm

    assert tm.HBM_KV_BYTES.labels(model="test-tiny").value == q8.kv_bytes
    assert tm.HBM_WEIGHT_BYTES.labels(model="test-tiny").value == \
        q8.param_bytes


def _copy_map(rt):
    """Zero the residual output projections (test_spec_decoding's trick):
    the next token becomes a pure function of the last, greedy
    generation cycles, and n-gram lookup drafts actually verify. On a
    quantized runtime the projections are QuantTensors — zero both the
    payload and the scales."""
    import jax

    for key in ("wo", "w_down"):
        rt.params["layers"][key] = jax.tree_util.tree_map(
            jnp.zeros_like, rt.params["layers"][key])
    return rt


def test_spec_byte_identical_on_quantized_runtime():
    """Speculative verify on an int8 runtime is still greedy-exact
    AGAINST ITSELF: drafts verify with the same quantized forward and
    the same quantized KV writes the 1-token path would make, so
    spec-on streams match spec-off byte-for-byte."""
    rng = np.random.default_rng(7)
    cyc = rng.integers(3, 400, size=5).tolist()
    prompts = [(cyc * 10)[:40], (cyc * 5)[:24]]
    # Long enough for the copy-map's generation cycle to establish and
    # the lookup to start drafting (the repetitive regime).
    base = run_all(_copy_map(make_rt(kv_dtype="int8",
                                     weights_dtype="int8")),
                   prompts, max_tokens=40)
    rt = _copy_map(make_rt(kv_dtype="int8", weights_dtype="int8",
                           spec=True, spec_k=3, spec_min_accept=0.0))
    spec = run_all(rt, prompts, max_tokens=40)
    assert spec == base
    assert rt.spec_proposed > 0  # speculation actually exercised
    assert rt.spec_accepted > 0  # ...and drafts verified on the int8 path
    assert rt.kv_dtype == "int8"


def test_page_conservation_fuzz_quantized():
    """Randomized preemption + speculative rollback + prefix-cache
    sharing on shrunken int8 pages: free + used + cached == pool holds
    through every tick, and the journal invariant sweep stays clean."""
    rng = np.random.default_rng(8)
    rt = make_rt(kv_dtype="int8", num_pages=24, prefix_cache=True,
                 spec=True, spec_k=3, spec_min_accept=0.0, preempt_max=2)
    journal = Journal(capacity=65536)
    rt.journal = journal
    core = MQCore(None)

    def requeue(req):
        rt.pending_prefill.appendleft(req)
        return True

    rt.on_preempt = requeue
    shared = rng.integers(3, 400, size=16).tolist()
    reqs, issued = [], 0
    guard = 0
    while True:
        while issued < 18 and len(rt.pending_prefill) < 5:
            tail = rng.integers(3, 400, size=int(rng.integers(2, 30)))
            prompt = (shared + tail.tolist() if rng.random() < 0.5
                      else tail.tolist())
            req = Request(next(_IDS), f"q{issued % 4}", "test-tiny", prompt,
                          SamplingParams(max_tokens=6))
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            rt.pending_prefill.append(req)
            reqs.append(req)
            issued += 1
        ran = rt.step_ragged(core)
        if not ran and any(s is not None for s in rt.slot_req):
            rt.step_decode(core, k_steps=1)
        a = rt.alloc
        assert a.free_pages + a.used_pages + a.cached_pages \
            == a.num_pages - 1, "page conservation broken"
        if issued >= 18 and all(r.stats.finished_at for r in reqs):
            break
        guard += 1
        assert guard < 8000, "fuzz wedged"
    assert not check_invariants(journal.tail(None))


def test_density_gate_equal_hbm():
    """The CI density regression gate: at the SAME HBM byte budget the
    int8 pool holds 2*hd/(hd+4) more pages (1.6x at test-tiny's hd=16;
    1.88-1.94x at real models' hd=64/128) and, driven with the same
    arrival trace, preempts no more than the bf16 pool — and finishes
    every request."""
    cfg = MODEL_CONFIGS["test-tiny"]
    ps = 8
    pages_bf16 = 12
    budget = pages_bf16 * kvc.kv_page_bytes(cfg, ps, kv_dtype="bfloat16")
    pages_int8 = budget // kvc.kv_page_bytes(cfg, ps, kv_dtype="int8")
    expected = 2 * cfg.head_dim / (cfg.head_dim + 4)
    assert pages_int8 / pages_bf16 >= 0.9 * expected

    def run_leg(kv_dtype, pages):
        rt = make_rt(kv_dtype=kv_dtype, num_pages=pages + 1,
                     max_pages_per_seq=8, preempt_max=2)
        journal = Journal(capacity=65536)
        rt.journal = journal

        def requeue(req):
            rt.pending_prefill.appendleft(req)
            return True

        rt.on_preempt = requeue
        trace = np.random.default_rng(99)
        prompts = [trace.integers(3, 400, size=20).tolist()
                   for _ in range(10)]
        run_all(rt, prompts, max_tokens=8)
        assert not check_invariants(journal.tail(None))
        return rt.preempt_count

    preempt_bf16 = run_leg("bfloat16", pages_bf16)
    preempt_int8 = run_leg("int8", pages_int8)
    assert preempt_int8 <= preempt_bf16
    assert preempt_bf16 > 0, "trace never hit the bf16 pool ceiling"


# ------------------------------------------------------------------ fail fast
def test_validate_quant_config_combinations():
    ok = validate_quant_config("bfloat16", "bfloat16")
    assert ok is None
    assert validate_quant_config("int8", "int8") is None
    assert "fp8" in validate_quant_config("fp8", "bfloat16")
    assert "--kv-dtype" in validate_quant_config("bfloat16", "fp8")
    assert "pp" in validate_quant_config("bfloat16", "int8", pp=2)
    assert "sequence-parallel" in validate_quant_config(
        "bfloat16", "int8", sp=2)
    assert "MoE" in validate_quant_config(
        "int8", "bfloat16", model_names=["mixtral:8x7b"])
    # int8 weights with pp are fine only when KV stays bf16 and the
    # model is dense — the validator must not over-reject.
    assert validate_quant_config("int8", "bfloat16", pp=2) is None


def test_cli_fails_fast_on_invalid_combinations():
    from ollamamq_tpu.cli import main

    # MoE model with int8 weights: rejected before any engine work.
    assert main(["--no-tui", "--models", "mixtral:8x7b",
                 "--weights-dtype", "int8"]) == 2
    # int8 KV on a pipeline mesh: the pp path reads bf16 pages.
    assert main(["--no-tui", "--models", "test-tiny",
                 "--kv-dtype", "int8", "--pp", "2"]) == 2
    # int8 KV on a sequence-parallel mesh.
    assert main(["--no-tui", "--models", "test-tiny",
                 "--kv-dtype", "int8", "--sp", "2"]) == 2


def test_cli_rejects_removed_bucketed_oracle():
    """--attention is gone with the bucketed path: argparse must reject
    it loudly instead of silently serving ragged."""
    from ollamamq_tpu.cli import build_parser

    with pytest.raises(SystemExit):
        build_parser().parse_args(["--attention", "bucketed"])


def test_runtime_build_fails_fast():
    with pytest.raises(ValueError):
        make_rt(kv_dtype="fp8")
    with pytest.raises(ValueError):
        ModelRuntime("test-tiny-moe", MODEL_CONFIGS["test-tiny-moe"],
                     EngineConfig(model="test-tiny-moe", max_slots=2,
                                  num_pages=16, page_size=8,
                                  max_pages_per_seq=8,
                                  weights_dtype="int8"),
                     dtype=jnp.float32)


def test_quant_tensor_is_a_pytree():
    """QuantTensor/QuantKV must flow through tree_map/scan/donation: the
    flatten must yield exactly (q, s) and rebuild the same type."""
    import jax

    t = quantize_tensor(jnp.ones((2, 4, 4)), axis=-1)
    leaves, treedef = jax.tree_util.tree_flatten(t)
    assert len(leaves) == 2
    back = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(back, QuantTensor)
    doubled = jax.tree_util.tree_map(lambda x: x * 2, t)
    assert isinstance(doubled, QuantTensor)
