"""Shared test helpers (pytest puts this directory on sys.path)."""

import time


def collect(req, timeout=120):
    """Drain a request's stream until its terminal item (done/error)."""
    deadline = time.monotonic() + timeout
    items = []
    while time.monotonic() < deadline:
        item = req.stream.get(timeout=0.2)
        if item is None:
            continue
        items.append(item)
        if item.kind in ("done", "error"):
            return items
    raise TimeoutError(f"request {req.req_id} did not finish; got {items}")


def free_port() -> int:
    """An OS-assigned free TCP port (close-then-rebind race is acceptable
    for the jax.distributed coordinator in these short-lived tests)."""
    import socket

    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port
