"""Token sampling under jit: greedy, temperature, top-k, top-p.

All branches are trace-friendly (no data-dependent Python control flow):
the sampling mode is encoded in per-sequence parameter vectors so one
compiled decode step serves heterogeneous per-request options — requests
with different temperatures share a batch, unlike the reference which
forwards options opaquely to Ollama.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class SamplingParams:
    """Host-side per-request sampling options (Ollama/OpenAI option names)."""

    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0
    repeat_penalty: float = 1.0  # 1.0 => off (Ollama's default is 1.1)
    presence_penalty: float = 0.0  # additive, OpenAI semantics (0 => off)
    frequency_penalty: float = 0.0  # additive per occurrence (0 => off)
    # None => unseeded. Any provided integer — INCLUDING 0, which OpenAI
    # clients pass expecting reproducibility — maps to a seeded stream.
    seed: "int | None" = None  # stored as int32 > 0 after __post_init__
    max_tokens: int = 256
    stop: tuple = ()
    # Per-request deadline budget in ms from enqueue (0 = none). Not a
    # sampling knob, but it rides the options/body like one (and the
    # X-Deadline-Ms header overrides it): expired requests are dropped
    # at admission / before prefill instead of burning TPU time.
    deadline_ms: float = 0.0

    def __post_init__(self):
        # Non-positive / junk deadlines mean "no deadline".
        try:
            self.deadline_ms = max(0.0, float(self.deadline_ms or 0.0))
        except (TypeError, ValueError):
            self.deadline_ms = 0.0
        # Seeds ride int32 device arrays; an out-of-range value would raise
        # OverflowError in the engine thread (numpy 2 rejects lossy int32
        # assignment) and fail every in-flight request on the runtime. Fold
        # arbitrary client seeds (OpenAI seeds are commonly 64-bit) into
        # [1, 2^31-1] deterministically; seed=0 is a VALID seed (folds to
        # 1), distinct from absent (None -> 0 = engine-stream sampling).
        self.seed = 0 if self.seed is None else (
            int(self.seed) % 0x7FFFFFFE) + 1

    @classmethod
    def from_ollama_options(cls, options: dict, max_tokens_default: int) -> "SamplingParams":
        options = options or {}
        return cls(
            temperature=float(options.get("temperature", 0.8) or 0.0),
            top_k=int(options.get("top_k", 0) or 0),
            top_p=float(options.get("top_p", 1.0) or 1.0),
            repeat_penalty=float(options.get("repeat_penalty", 1.1) or 1.0),
            presence_penalty=float(options.get("presence_penalty", 0.0) or 0.0),
            frequency_penalty=float(options.get("frequency_penalty", 0.0) or 0.0),
            seed=options.get("seed"),  # absent/null => None => unseeded
            max_tokens=int(options.get("num_predict", max_tokens_default) or max_tokens_default),
            stop=tuple(options.get("stop", []) or []),
            deadline_ms=options.get("deadline_ms", 0.0),
        )

    @classmethod
    def from_openai(cls, body: dict, max_tokens_default: int) -> "SamplingParams":
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        return cls(
            temperature=float(body.get("temperature", 1.0) or 0.0),
            top_k=0,
            top_p=float(body.get("top_p", 1.0) or 1.0),
            # Not an OpenAI field, but accepted for parity with clients that
            # pass Ollama-style options through the /v1 surface.
            repeat_penalty=float(body.get("repeat_penalty", 1.0) or 1.0),
            presence_penalty=float(body.get("presence_penalty", 0.0) or 0.0),
            frequency_penalty=float(body.get("frequency_penalty", 0.0) or 0.0),
            seed=body.get("seed"),  # absent/null => None => unseeded
            max_tokens=int(
                body.get("max_tokens") or body.get("max_completion_tokens") or max_tokens_default
            ),
            stop=tuple(stop),
            # Not an OpenAI field either; same pass-through rationale.
            deadline_ms=body.get("deadline_ms", 0.0),
        )


def recent_token_mask(recent: jnp.ndarray, vocab: int) -> jnp.ndarray:
    """[B, W] ring of recent token ids (-1 = empty) -> [B, V] int8 mask."""
    B, _ = recent.shape
    valid = (recent >= 0).astype(jnp.int8)
    mask = jnp.zeros((B, vocab), jnp.int8)
    return mask.at[jnp.arange(B)[:, None], jnp.clip(recent, 0)].max(valid)


def recent_token_counts(recent: jnp.ndarray, vocab: int) -> jnp.ndarray:
    """[B, W] ring of recent token ids (-1 = empty) -> [B, V] int32 counts."""
    B, _ = recent.shape
    valid = (recent >= 0).astype(jnp.int32)
    counts = jnp.zeros((B, vocab), jnp.int32)
    return counts.at[jnp.arange(B)[:, None], jnp.clip(recent, 0)].add(valid)


def apply_repeat_penalty(
    logits: jnp.ndarray,  # [B, V] float32
    recent: jnp.ndarray,  # [B, W] int32 — last-W context token ids (-1 pad)
    penalty: jnp.ndarray,  # [B] float (1.0 = off)
) -> jnp.ndarray:
    """llama.cpp-style repetition penalty over the recent-token window
    (repeat_last_n semantics): for tokens in the window, positive logits
    divide by the penalty and negative logits multiply by it."""
    mask = recent_token_mask(recent, logits.shape[1])
    p = penalty[:, None]
    penalized = jnp.where(logits > 0, logits / p, logits * p)
    return jnp.where((mask > 0) & (p != 1.0), penalized, logits)


def apply_penalties(
    logits: jnp.ndarray,  # [B, V] float32
    recent: jnp.ndarray,  # [B, W] int32 — last-W context token ids (-1 pad)
    repeat: jnp.ndarray,  # [B] multiplicative, llama.cpp semantics (1.0 = off)
    presence: jnp.ndarray,  # [B] additive once per seen token (0.0 = off)
    frequency: jnp.ndarray,  # [B] additive per occurrence (0.0 = off)
) -> jnp.ndarray:
    """Repetition control over the recent-token window: llama.cpp-style
    multiplicative repeat_penalty plus OpenAI-style additive presence /
    frequency penalties (counts come from the same window)."""
    counts = recent_token_counts(recent, logits.shape[1])
    seen = counts > 0
    p = repeat[:, None]
    penalized = jnp.where(logits > 0, logits / p, logits * p)
    out = jnp.where(seen & (p != 1.0), penalized, logits)
    out = out - frequency[:, None] * counts.astype(logits.dtype)
    return out - presence[:, None] * seen.astype(logits.dtype)


# Candidate pool for top-k / top-p thresholds. A full [B, V] sort per
# decode step is the single most expensive op in the sampler (V is 128K
# for Llama-3); lax.top_k over a fixed pool is ~an order of magnitude
# cheaper. Requests asking top_k > MAX_TOPK are clamped, and a nucleus
# wider than MAX_TOPK candidates degrades to top-MAX_TOPK — same spirit
# as llama.cpp's default top_k=40 pre-filter that the reference inherits
# via Ollama. Probabilities use the FULL softmax normalizer (logsumexp
# over all logits), so within the pool the nucleus cutoff is exact.
MAX_TOPK = 256


def _masked_scaled_logits(
    logits: jnp.ndarray,  # [B, V] float32
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B]
    need_mask: bool = True,
):
    """(masked scaled logits, greedy argmax) shared by both samplers.
    `need_mask` is a trace-time flag: when the host knows no row in the
    batch uses top-k/top-p, the threshold computation is skipped."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]
    if not need_mask:
        return scaled, greedy

    K = min(MAX_TOPK, V)
    vals, _ = jax.lax.top_k(scaled, K)  # [B, K] descending

    # top-k mask: keep the k largest (k==0 -> keep all; k > K clamps).
    k_idx = jnp.clip(top_k - 1, 0, K - 1)
    kth = jnp.take_along_axis(vals, k_idx[:, None], axis=-1)  # [B,1]
    topk_mask = jnp.where((top_k > 0)[:, None], scaled >= kth, True)

    # top-p (nucleus) mask: exact probabilities for the pool via the full
    # normalizer; cutoff at the last token whose cumulative mass (before
    # itself) is below top_p.
    log_z = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
    probs = jnp.exp(vals - log_z)  # [B, K]
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_count = jnp.sum(cum - probs < top_p[:, None], axis=-1)  # >=1
    cut_idx = jnp.clip(cutoff_count - 1, 0, K - 1)
    p_kth = jnp.take_along_axis(vals, cut_idx[:, None], axis=-1)
    topp_mask = jnp.where((top_p < 1.0)[:, None], scaled >= p_kth, True)

    return jnp.where(topk_mask & topp_mask, scaled, -jnp.inf), greedy


def sampling_flags(temp, top_k, top_p, repeat, presence, frequency):
    """(need_penalties, need_mask, need_sample) from HOST-side parameter
    arrays. These are trace-time specialization flags: the engine keys its
    compiled step variants on them, so an all-greedy batch (the common
    /api/generate default) runs argmax only — no [B, V] scatter-counts, no
    top-k scan, no categorical draw. Each flag covers the whole batch;
    mixed batches take the general path for everyone."""
    return (
        bool(np.any(np.asarray(repeat) != 1.0)
             or np.any(np.asarray(presence) != 0.0)
             or np.any(np.asarray(frequency) != 0.0)),
        bool(np.any(np.asarray(top_k) > 0)
             or np.any(np.asarray(top_p) < 1.0)),
        bool(np.any(np.asarray(temp) > 0)),
    )


def maybe_apply_penalties(logits, recent, repeat, presence, frequency,
                          need_penalties: bool = True):
    """apply_penalties, skipped entirely at trace time when the host knows
    every row is neutral (repeat==1, presence==frequency==0)."""
    if not need_penalties:
        return logits
    return apply_penalties(logits, recent, repeat, presence, frequency)


def accept_prefix(
    draft: jnp.ndarray,  # [B, K] int32 proposed draft tokens
    greedy: jnp.ndarray,  # [B, K] int32 model argmax at each draft's position
    draft_len: jnp.ndarray,  # [B] int32 valid drafts per row (0 = none)
) -> jnp.ndarray:
    """[B] number of leading draft tokens the model verified.

    Greedy speculative verification: draft j is accepted iff every draft
    before it was accepted AND the model's argmax at its position equals
    it — the longest matching prefix, computed as the sum of a running
    product over the match mask (the first mismatch zeroes everything
    after it). Positions at or past draft_len never count, so k=0 rows
    answer 0. Exact: accepting this prefix and then taking the model's
    own next token reproduces the non-speculative greedy stream
    byte-for-byte."""
    K = draft.shape[1]
    if K == 0:
        return jnp.zeros(draft.shape[0], jnp.int32)
    valid = jnp.arange(K)[None, :] < draft_len[:, None]
    match = ((draft == greedy) & valid).astype(jnp.int32)
    return jnp.sum(jnp.cumprod(match, axis=1), axis=1).astype(jnp.int32)


def per_row_keys(
    key: jax.Array,  # engine-stream key for this dispatch
    seeds: jnp.ndarray,  # [B] int32; >0 = request-provided seed
    positions: jnp.ndarray,  # [B] int32 absolute position being sampled
) -> jnp.ndarray:
    """[B, 2] uint32 sampling keys. Seeded rows derive their key purely from
    (seed, position) — replaying the request reproduces the exact stream no
    matter what else shares the batch; unseeded rows draw from the engine
    stream, decorrelated per row."""
    n = seeds.shape[0]
    unseeded = jax.random.split(key, n)
    seeded = jax.vmap(jax.random.fold_in)(
        jax.vmap(jax.random.PRNGKey)(seeds), positions.astype(jnp.uint32)
    )
    return jnp.where((seeds > 0)[:, None], seeded, unseeded)


def sample_tokens_rowwise(
    logits: jnp.ndarray,  # [B, V] float32
    row_keys: jnp.ndarray,  # [B, 2] uint32 (per_row_keys)
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B]
    need_mask: bool = True,
    need_sample: bool = True,
) -> jnp.ndarray:
    """sample_tokens with an independent key per row (per-request seeds)."""
    masked, greedy = _masked_scaled_logits(logits, temperature, top_k, top_p,
                                           need_mask)
    if not need_sample:
        return greedy.astype(jnp.int32)
    sampled = jax.vmap(lambda k, l: jax.random.categorical(k, l))(row_keys, masked)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
