"""Router HA (fleet/ha.py): warm-standby replication over
/admin/ha/sync, epoch-fenced takeover, and zero-drop promotion.

The contract under test: a standby tails the primary's WAL records and
journal decision events into shadow state; when the primary dies (or
hands over on SIGTERM) the standby bumps a monotonic epoch, re-registers
every member under it, re-admits the unfinished WAL streams through the
existing recovery path, and serves GET /api/stream/{rid}?from=N
byte-identical across the router swap — while members 409 every call
the revived zombie primary makes at its stale epoch (fenced, never
split-brained).
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from ollamamq_tpu.config import EngineConfig, validate_ha
from ollamamq_tpu.durability.wal import load_wal_records
from ollamamq_tpu.engine import health as health_mod
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.health import HealthMonitor
from ollamamq_tpu.fleet import FleetRouter, LocalMember
from ollamamq_tpu.fleet.ha import HAStandby, load_ha_state
from ollamamq_tpu.fleet.members import HttpMember
from ollamamq_tpu.server.app import Server
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry.slo import AlertManager
from ollamamq_tpu.testing.faults import FaultPlan
from ollamamq_tpu.tools.journal import (check_epoch_monotonicity,
                                        check_files,
                                        check_takeover_pairing)
from testutil import collect, free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = dict(model="test-tiny", max_slots=4, num_pages=64, page_size=8,
            max_pages_per_seq=8, prefill_buckets=(16, 32),
            decode_steps_per_iter=2)

FAST = dict(probe_period_s=0.05, eject_heartbeat_s=5.0,
            reprobe_backoff_s=0.1, evac_grace_s=1.0)


# ------------------------------------------------------- CLI fail-fast units
def test_validate_ha_fail_fast():
    """Every malformed --ha/--standby-of combination is rejected BEFORE
    any device work, with an error naming the offending flag."""
    # HA off entirely: nothing to validate.
    assert validate_ha(False, None, 3.0, None, None) is None
    # Valid shapes.
    assert validate_ha(True, None, 3.0, "/w", None) is None
    assert validate_ha(False, "http://p:1", 3.0, "/w", "http://m:2") is None
    # A process is the primary or the standby, never both.
    assert "mutually exclusive" in validate_ha(
        True, "http://p:1", 3.0, "/w", "http://m:2")
    assert "--takeover-grace-s" in validate_ha(True, None, 0.0, "/w", None)
    assert "--takeover-grace-s" in validate_ha(
        False, "http://p:1", -1.0, "/w", "http://m:2")
    # The replicated WAL is what a takeover recovers from.
    assert "--wal-dir" in validate_ha(True, None, 3.0, None, None)
    assert "--wal-dir" in validate_ha(
        False, "http://p:1", 3.0, None, "http://m:2")
    # The standby tails a URL and promotes over the SAME member fleet.
    assert "http(s)" in validate_ha(False, "ftp://p:1", 3.0, "/w", "u")
    assert "--replica-urls" in validate_ha(
        False, "http://p:1", 3.0, "/w", None)


def test_cli_rejects_bad_ha_args_exit_2(tmp_path):
    """`--ha --standby-of` together (and --ha without a WAL) kill the
    process with exit 2 at argument time — not at the first heartbeat."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    base = [sys.executable, "-m", "ollamamq_tpu.cli", "--fake-engine",
            "--no-tui", "--models", "test-tiny",
            "--blocklist", str(tmp_path / "bl.json")]
    both = subprocess.run(
        base + ["--ha", "--standby-of", "http://127.0.0.1:1",
                "--wal-dir", str(tmp_path / "w")],
        env=env, capture_output=True, timeout=120)
    assert both.returncode == 2, both.stderr
    no_wal = subprocess.run(base + ["--ha"], env=env,
                            capture_output=True, timeout=120)
    assert no_wal.returncode == 2, no_wal.stderr


# ------------------------------------------------------- journal audit units
def _tk(phase, seq, **kw):
    return dict(kind="router_takeover", phase=phase, seq=seq,
                why="primary_dead", **kw)


def test_takeover_pairing_audit():
    ok = [_tk("begin", 1), _tk("done", 2, epoch=2, from_epoch=1)]
    assert check_takeover_pairing(ok) == []
    # Aborted promotions resolve the pairing too.
    assert check_takeover_pairing(
        [_tk("begin", 1), _tk("aborted", 2)]) == []
    # A begin with no resolution = promotion crashed mid-ladder.
    bad = check_takeover_pairing([_tk("begin", 5)])
    assert len(bad) == 1 and "UNRESOLVED" in bad[0] and "seq 5" in bad[0]
    # Takeovers are serial: begin while another begin is open is a bug.
    twice = check_takeover_pairing([_tk("begin", 1), _tk("begin", 2),
                                    _tk("done", 3, epoch=2)])
    assert any("never resolved" in v for v in twice)
    # Ring tails: a done with no begin in the window is tolerated.
    assert check_takeover_pairing([_tk("done", 9, epoch=3)]) == []


def test_epoch_monotonicity_audit():
    clean = [
        _tk("done", 1, epoch=2, from_epoch=1),
        _tk("done", 2, epoch=3, from_epoch=2),
        dict(kind="epoch_fence", seq=3, epoch=3, stale_epoch=1,
             path="/api/generate", caller="placement"),
    ]
    assert check_epoch_monotonicity(clean) == []
    # A takeover that did not advance the epoch cannot fence anybody.
    bad = check_epoch_monotonicity([_tk("done", 1, epoch=1, from_epoch=1)])
    assert any("did not advance" in v for v in bad)
    # Successive takeovers must strictly increase.
    bad = check_epoch_monotonicity([_tk("done", 1, epoch=3, from_epoch=2),
                                    _tk("done", 2, epoch=3, from_epoch=2)])
    assert any("strictly monotonic" in v for v in bad)
    # A member may only fence STRICTLY older epochs.
    bad = check_epoch_monotonicity([
        dict(kind="epoch_fence", seq=1, epoch=2, stale_epoch=2,
             path="/api/generate", caller="placement")])
    assert any("strictly older" in v for v in bad)
    # A done without an epoch is unverifiable — flagged, not skipped.
    bad = check_epoch_monotonicity([_tk("done", 1)])
    assert any("no epoch" in v for v in bad)


def _spill(path, records, meta=None):
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(
            {"journal_meta": dict({"version": 1}, **(meta or {}))}) + "\n")
        for r in records:
            f.write(json.dumps(r) + "\n")
    return str(path)


def test_check_files_cross_spill_duplicate_epoch(tmp_path):
    """The same epoch completed by TWO spills is split brain; the
    standby's primary-journal replica (journal_meta replica_of) is a
    byte copy and must NOT trip the duplicate check."""
    a = _spill(tmp_path / "a.jsonl",
               [_tk("begin", 1), _tk("done", 2, epoch=2, from_epoch=1)])
    b = _spill(tmp_path / "b.jsonl",
               [_tk("begin", 1), _tk("done", 2, epoch=2, from_epoch=1)])
    bad, _ = check_files([a, b])
    assert any("taken over TWICE" in v for v in bad)
    # Same duplicate in a replica spill: excluded by design.
    rep = _spill(tmp_path / "replica.jsonl",
                 [_tk("begin", 1), _tk("done", 2, epoch=2, from_epoch=1)],
                 meta={"replica_of": "http://primary:11434"})
    bad, _ = check_files([a, rep])
    assert not any("TWICE" in v for v in bad)
    # Distinct epochs across spills (a takeover chain): clean.
    c = _spill(tmp_path / "c.jsonl",
               [_tk("begin", 1), _tk("done", 2, epoch=3, from_epoch=2)])
    bad, _ = check_files([a, c])
    assert bad == []


# ------------------------------------------------------------- watchdog rules
class _HsEngine:
    """Health-monitor stub: just an alert table + an ha_status dict."""

    def __init__(self, hs):
        self.alerts = AlertManager()
        self._hs = hs

    def ha_status(self):
        return self._hs


def _names(am):
    return [a.name for a in am.active()]


def test_watchdog_standby_lag_fire_and_resolve(monkeypatch):
    monkeypatch.setattr(health_mod, "STANDBY_LAG_ALERT_RECORDS", 10)
    eng = _HsEngine({"role": "primary", "epoch": 1,
                     "sync_lag_records": 50, "standby_connected": True})
    mon = HealthMonitor(eng)
    mon._check_ha()
    assert "standby_lag" in _names(eng.alerts)
    # Catch-up resolves the alert.
    eng._hs = {"role": "primary", "epoch": 1, "sync_lag_records": 0,
               "standby_connected": True}
    mon._check_ha()
    assert "standby_lag" not in _names(eng.alerts)
    # A standby that stops polling fires even at lag 0.
    eng._hs = {"role": "primary", "epoch": 1, "sync_lag_records": 0,
               "standby_connected": False}
    mon._check_ha()
    assert "standby_lag" in _names(eng.alerts)
    # lag None = no standby has EVER polled: a config choice, no alert.
    eng2 = _HsEngine({"role": "primary", "epoch": 1,
                      "sync_lag_records": None})
    HealthMonitor(eng2)._check_ha()
    assert _names(eng2.alerts) == []


def test_watchdog_takeover_stuck_fire_and_resolve(monkeypatch):
    monkeypatch.setattr(health_mod, "TAKEOVER_STUCK_S", 1.0)
    eng = _HsEngine({"role": "promoting", "epoch": 2,
                     "sync_lag_records": 0, "promote_elapsed_s": 5.0})
    mon = HealthMonitor(eng)
    mon._check_ha()
    assert "takeover_stuck" in _names(eng.alerts)
    # Promotion lands → primary role → resolved.
    eng._hs = {"role": "primary", "epoch": 2, "sync_lag_records": None}
    mon._check_ha()
    assert "takeover_stuck" not in _names(eng.alerts)


# ------------------------------------------------- in-process primary side
def _ha_router(tmp_path, n=2):
    ecfg = EngineConfig(ha=True, wal_dir=str(tmp_path / "wal"),
                        wal_fsync_ms=2.0, **TINY)
    member_cfg = dataclasses.replace(ecfg, ha=False, wal_dir=None,
                                     max_queued=0, max_queued_per_user=0)
    members = [
        LocalMember(f"r{i}", FakeEngine(member_cfg, blocklist_path=None,
                                        token_latency_s=0.0))
        for i in range(n)
    ]
    router = FleetRouter(members, ecfg, blocklist_path=None, **FAST)
    router.start()
    return router


def test_coordinator_cold_snapshot_then_tail(tmp_path):
    """The replication stream's two regimes: a from-seq-0 poll ships a
    WAL snapshot (begin() compaction bypasses the mirror, so cold
    catch-up can never be record-by-record) plus the shadow placement
    state; subsequent polls tail sequence-numbered records, and the
    poll's seq doubles as the ack that drives the lag gauge."""
    router = _ha_router(tmp_path)
    try:
        ha = router.ha
        assert ha is not None and router.epoch == 1
        # Epoch persisted for crash-surviving fencing.
        assert load_ha_state(str(tmp_path / "wal"))["epoch"] == 1
        # Members registered under the epoch at start().
        assert all(m.router_epoch == 1 for m in router.members)

        req = router.enqueue_request(
            "u", "1.2.3.4", "test-tiny", prompt_tokens=[1, 2, 3],
            sampling=SamplingParams(max_tokens=4))
        items = collect(req)
        assert items[-1].kind == "done"

        resp = ha.sync_batch(0)
        assert resp["role"] == "primary" and resp["epoch"] == 1
        assert resp["records"] == []          # cold poll = snapshot
        snap = resp["snapshot"]
        assert any('"admit"' in ln or '"kind": "admit"' in ln
                   for ln in snap) or len(snap) >= 1
        names = [m["name"] for m in resp["state"]["members"]]
        assert names == ["r0", "r1"]
        head = resp["head"]
        assert resp["snapshot_head"] == head

        # Caught-up poll: no snapshot, no records, lag 0.
        resp2 = ha.sync_batch(head)
        assert "snapshot" not in resp2 and resp2["records"] == []
        st = ha.status()
        assert st["role"] == "primary" and st["sync_lag_records"] == 0
        assert st["standby_connected"]

        # New traffic tails as records, every seq above the ack.
        req2 = router.enqueue_request(
            "u", "1.2.3.4", "test-tiny", prompt_tokens=[4, 5],
            sampling=SamplingParams(max_tokens=3))
        collect(req2)
        resp3 = ha.sync_batch(head)
        kinds = {r["kind"] for r in resp3["records"]}
        assert resp3["records"] and kinds <= {"wal", "journal"}
        assert "wal" in kinds
        assert all(r["seq"] > head for r in resp3["records"])
        assert resp3["head"] >= max(r["seq"] for r in resp3["records"])
    finally:
        router.stop()


def test_standby_router_fault_site(tmp_path):
    """testing/faults.py "router" site drives the standby's poll loop:
    an injected fault marks the round failed (feeding the takeover
    grace clock) without touching the real primary."""
    ecfg = EngineConfig(wal_dir=str(tmp_path / "wal"), wal_fsync_ms=2.0,
                        **TINY)
    member_cfg = dataclasses.replace(ecfg, wal_dir=None)
    router = FleetRouter(
        [LocalMember("r0", FakeEngine(member_cfg, blocklist_path=None,
                                      token_latency_s=0.0))],
        ecfg, blocklist_path=None, **FAST)
    try:
        plan = FaultPlan([{"site": "router", "kind": "exception",
                           "at": [1]}], seed=3)
        sb = HAStandby(router, "http://127.0.0.1:1",
                       fault_plan=plan)
        assert sb._fault_round() is True          # injected: round fails
        assert sb.last_error == "injected router fault"
        assert sb._fault_round() is False         # one-shot rule spent
        # Pre-promotion ETA hint: at least the grace, never sub-second.
        eta = sb.promote_eta_s()
        assert eta is not None and eta >= 1.0
    finally:
        router.stop()


def _standby_router(tmp_path, grace=3.0):
    """Unstarted standby-side router + HAStandby pair (no sockets)."""
    ecfg = EngineConfig(wal_dir=str(tmp_path / "wal-s"), wal_fsync_ms=2.0,
                        takeover_grace_s=grace, **TINY)
    member_cfg = dataclasses.replace(ecfg, wal_dir=None)
    router = FleetRouter(
        [LocalMember("r0", FakeEngine(member_cfg, blocklist_path=None,
                                      token_latency_s=0.0))],
        ecfg, blocklist_path=None, **FAST)
    return router, HAStandby(router, "http://127.0.0.1:1")


def _alert_names(router):
    return [a.name for a in router.alerts.active()]


def test_sync_initial_snapshot_is_explicit_not_a_storm(tmp_path):
    """An idle primary (head 0 — e.g. freshly promoted, no traffic yet)
    must NOT re-ship + re-fsync the whole WAL replica on every cold
    poll: the standby asks for its one-time initial snapshot with
    snap=1, and plain from-seq-0 polls tail (empty) records."""
    router = _ha_router(tmp_path)
    try:
        ha = router.ha
        # Simulate the freshly-promoted idle case: nothing mirrored.
        with ha._lock:
            ha._ring.clear()
            ha.head = 0
        r1 = ha.sync_batch(0)
        assert "snapshot" not in r1 and r1["records"] == []
        # The explicit one-time request gets the whole file.
        r2 = ha.sync_batch(0, want_snapshot=True)
        assert r2.get("snapshot") is not None
        # Synced: back to (empty) record tailing, no re-snapshot.
        r3 = ha.sync_batch(r2["snapshot_head"])
        assert "snapshot" not in r3 and r3["records"] == []
        # With records past seq 0, a cold poll still snapshots (WAL
        # compaction lines bypass the mirror).
        req = router.enqueue_request(
            "u", "1.2.3.4", "test-tiny", prompt_tokens=[1, 2],
            sampling=SamplingParams(max_tokens=2))
        collect(req)
        r4 = ha.sync_batch(0)
        assert r4.get("snapshot") is not None
    finally:
        router.stop()


def test_handover_released_only_by_confirm_poll(tmp_path):
    """A routine poll at lag 0 must NOT release the primary's SIGTERM
    wait: at the instant SIGTERM lands, the standby's next routine poll
    already carries from_seq == head, and releasing on it would let the
    primary exit before the standby even learned of the handover. Only
    the explicit caught-up confirm poll releases."""
    router = _ha_router(tmp_path)
    try:
        ha = router.ha
        with ha._lock:
            ha.handover = True
            ha._handover_target = ha.head
            ha._handover_acked.clear()
        # Routine caught-up poll: advertises the handover, releases
        # nothing.
        resp = ha.sync_batch(ha.head)
        assert resp["handover"] is True
        assert not ha._handover_acked.is_set()
        # A confirm poll BELOW the target releases nothing either.
        if ha.head > 0:
            ha.sync_batch(ha.head - 1, confirm_handover=True)
            assert not ha._handover_acked.is_set()
        # The caught-up confirm poll is the release.
        ha.sync_batch(ha.head, confirm_handover=True)
        assert ha._handover_acked.is_set()
    finally:
        router.stop()


def test_handover_catchup_drains_backlog_before_promote(tmp_path):
    """The zero-drop handover contract: the standby applies EVERYTHING
    up to the primary's head — multi-batch backlog included — and only
    a caught-up poll carries confirm=1 (the ack that releases the
    primary's SIGTERM wait). A confirm poll's records are never
    discarded."""
    router, sb = _standby_router(tmp_path)
    try:
        sb._open_replicas()
        sb.synced = True

        def wal(seq):
            return {"seq": seq, "kind": "wal",
                    "rec": {"k": "admit", "rid": seq, "user": "u",
                            "model": "test-tiny", "kind": "generate",
                            "prompt": [1], "sampling": {}}}

        responses = [
            {"handover": True, "epoch": 1, "head": 4,
             "records": [wal(1), wal(2)], "state": {}},
            {"handover": True, "epoch": 1, "head": 4,
             "records": [wal(3), wal(4)], "state": {}},
        ]
        polls = []

        def poll(confirm=False):
            polls.append((sb.applied, confirm))
            if responses:
                return responses.pop(0)
            return {"handover": True, "epoch": 1, "head": 4,
                    "records": [], "state": {}}

        sb._poll = poll
        assert sb._handover_catchup() is True
        assert sb.applied == 4 and sb.head == 4
        # The releasing ack carried the full head AND the confirm flag;
        # the mid-backlog poll (applied 2 < head 4) confirmed nothing.
        assert polls[-1] == (4, True)
        assert (2, False) in polls
        # Both batches landed in the replica WAL (nothing discarded).
        prev, torn = load_wal_records(sb._wal_path)
        assert torn == 0 and sorted(prev) == [1, 2, 3, 4]
    finally:
        sb._close_replicas()
        router.stop()


def test_handover_withdrawn_or_dead_primary_aborts_catchup(tmp_path):
    """Catch-up must NOT confirm a handover the primary withdrew (its
    wait timed out; it is draining itself — promoting would fence a
    live, draining router), nor spin forever against a dead one."""
    router, sb = _standby_router(tmp_path)
    try:
        sb._open_replicas()
        sb.synced = True
        sb._poll = lambda confirm=False: {
            "handover": False, "epoch": 1, "head": 0,
            "records": [], "state": {}}
        assert sb._handover_catchup() is False

        def boom(confirm=False):
            raise OSError("connection refused")

        sb._poll = boom
        assert sb._handover_catchup() is False
        assert sb.role == "standby" and not sb.promoted.is_set()
    finally:
        sb._close_replicas()
        router.stop()


def test_never_synced_standby_refuses_promotion(tmp_path):
    """A standby that has NEVER completed a first sync (booted before
    the primary, wrong URL, partitioned) must not promote after the
    grace: it would fence a possibly-healthy primary out of its own
    fleet and serve an empty replica. It alerts and keeps polling."""
    router, sb = _standby_router(tmp_path, grace=0.3)
    try:
        sb.start()  # primary URL is unreachable: every poll fails
        time.sleep(1.2)  # several grace windows elapse
        assert sb.role == "standby" and not sb.promoted.is_set()
        assert not sb.synced
        assert "standby_never_synced" in _alert_names(router)
        assert not [r for r in router.journal.tail(None)
                    if r.get("kind") == "router_takeover"]
        sb.stop()
        # The first snapshot resolves the alert (and arms promotion).
        sb._apply_snapshot({"snapshot": [], "snapshot_head": 0})
        assert sb.synced
        assert "standby_never_synced" not in _alert_names(router)
    finally:
        sb.stop()
        router.stop()


def test_aborted_promotion_bumps_epoch_and_retries_clean(tmp_path):
    """An aborted promotion already re-registered the members at the
    new epoch: the abort journals that fact (+ alert), and the RETRY
    claims a strictly higher epoch over an idempotently-restartable
    router — monotonicity holds across the abort."""
    router, sb = _standby_router(tmp_path)
    try:
        sb._open_replicas()
        sb.synced = True
        real_start = router.start
        calls = {"n": 0}

        def flaky_start():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("recovery wedged")
            real_start()

        router.start = flaky_start
        assert sb.promote(why="primary_dead") is False
        assert sb.role == "standby" and not sb.promoted.is_set()
        assert sb.epoch_seen == 2  # claimed-but-unserved epoch adopted
        assert not router.accepting
        assert "takeover_aborted" in _alert_names(router)
        aborted = [r for r in router.journal.tail(None)
                   if r.get("kind") == "router_takeover"
                   and r.get("phase") == "aborted"]
        assert aborted and aborted[-1]["members_claimed"] == 1
        assert aborted[-1]["epoch"] == 2

        assert sb.promote(why="primary_dead") is True
        assert sb.role == "primary" and router.epoch == 3
        assert "takeover_aborted" not in _alert_names(router)
        recs = [r for r in router.journal.tail(None)
                if r.get("kind") == "router_takeover"]
        assert check_takeover_pairing(recs) == []
        assert check_epoch_monotonicity(recs) == []
        assert [r for r in recs if r.get("phase") == "done"][-1][
            "epoch"] == 3
    finally:
        router.stop()


def test_router_start_partial_failure_is_retryable(tmp_path):
    """A start() that raises partway (e.g. recovery wedged) must leave
    the router restartable — the HA promotion retry path depends on
    it — without double-starting members."""
    ecfg = EngineConfig(wal_dir=str(tmp_path / "wal"), wal_fsync_ms=2.0,
                        **TINY)
    member_cfg = dataclasses.replace(ecfg, wal_dir=None)
    router = FleetRouter(
        [LocalMember("r0", FakeEngine(member_cfg, blocklist_path=None,
                                      token_latency_s=0.0))],
        ecfg, blocklist_path=None, **FAST)
    real_dur_start = router.durability.start
    calls = {"n": 0}

    def flaky(engine):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("boom")
        real_dur_start(engine)

    router.durability.start = flaky
    try:
        with pytest.raises(RuntimeError):
            router.start()
        assert router._running is False
        router.start()  # retry actually re-runs the ladder
        assert router._running is True and calls["n"] == 2
        req = router.enqueue_request(
            "u", "1.2.3.4", "test-tiny", prompt_tokens=[1],
            sampling=SamplingParams(max_tokens=2))
        assert collect(req)[-1].kind == "done"
    finally:
        router.stop()


def test_member_epoch_persists_across_restart(tmp_path):
    """The member-side fence must survive a member restart: with a WAL
    dir the adopted epoch persists (member_epoch.json), so a fresh
    process revives AT the fence instead of at 0 — where the zombie
    ex-primary's retried calls would pass again."""
    ecfg = EngineConfig(wal_dir=str(tmp_path / "mw"), **TINY)
    eng = FakeEngine(ecfg, blocklist_path=None, token_latency_s=0.0)
    srv = Server(eng)
    assert srv._ha_epoch == 0
    srv._adopt_epoch(3)
    assert json.load(open(os.path.join(
        str(tmp_path / "mw"), "member_epoch.json")))["epoch"] == 3
    # "Restart": a fresh Server over the same state dir holds the fence.
    srv2 = Server(eng)
    assert srv2._ha_epoch == 3
    # WAL-less member: memory-only, as before (heartbeat repair covers
    # it — see test_http_member_heartbeat_repairs_regressed_epoch).
    eng2 = FakeEngine(dataclasses.replace(ecfg, wal_dir=None),
                      blocklist_path=None, token_latency_s=0.0)
    srv3 = Server(eng2)
    srv3._adopt_epoch(5)
    assert Server(eng2)._ha_epoch == 0


def test_http_member_heartbeat_repairs_regressed_epoch():
    """The router heartbeat re-registers a member whose /health reports
    an epoch below the fleet's (a restarted WAL-less member) — closing
    the window where the zombie's calls would pass its reset fence."""
    m = HttpMember("m0", "http://127.0.0.1:1")
    calls = []
    m.register = lambda e: calls.append(e) or True
    m._status = {"status": "ok"}  # no epoch reported
    m._repair_epoch()
    assert calls == []            # HA off: nothing to repair
    m.router_epoch = 2
    m._repair_epoch()
    assert calls == [2]           # regressed (0 < 2): re-register
    m._status = {"status": "ok", "epoch": 2}
    m._repair_epoch()
    assert calls == [2]           # caught up: no churn
    m._status = {"status": "ok", "epoch": 3}
    m._repair_epoch()
    assert calls == [2]           # a newer router owns it: leave it
    m._status = {"status": "ok", "epoch": 0}
    m.fenced = True
    m._repair_epoch()
    assert calls == [2]           # fenced members are not ours to claim


# ---------------------------------------------------- subprocess e2e helpers
def _spawn(tmp_path, argv, log_name):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FAKE_TOKEN_LATENCY_S"] = "0.05"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    logf = open(str(tmp_path / log_name), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ollamamq_tpu.cli", "--fake-engine",
         "--no-tui", "--models", "test-tiny",
         "--blocklist", str(tmp_path / "bl.json"), *argv],
        stdout=logf, stderr=subprocess.STDOUT, env=env)
    proc._logf = logf
    return proc


def _health(port, timeout=2.0):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/health",
                                timeout=timeout) as r:
        return json.loads(r.read())


def _wait_health(port, budget=90.0, ok=None):
    if ok is None:
        ok = lambda b: b.get("status") != "recovering"  # noqa: E731
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        try:
            body = _health(port)
            if ok(body):
                return body
        except Exception:  # noqa: BLE001
            pass
        time.sleep(0.1)
    raise TimeoutError(f"server :{port} never reached the wanted state")


def _read_ndjson(resp):
    rid, text, ids, done = None, "", [], None
    for raw in resp:
        obj = json.loads(raw)
        if obj.get("req_id") is not None:
            rid = int(obj["req_id"])
        ids.extend(int(t) for t in obj.get("token_ids") or ())
        text += obj.get("response", "")
        if obj.get("done"):
            done = obj.get("done_reason")
            break
    return rid, text, ids, done


def _gen_request(port, num_predict, user="ha"):
    body = json.dumps({"model": "test-tiny", "prompt": "x",
                       "stream": True,
                       "options": {"num_predict": num_predict}}).encode()
    return urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{port}/api/generate", data=body,
        headers={"Content-Type": "application/json", "X-User-ID": user}),
        timeout=120)


def _fenced_total(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        text = r.read().decode()
    total = 0.0
    for line in text.splitlines():
        if line.startswith("ollamamq_ha_fenced_calls_total") \
                and " " in line:
            total += float(line.rsplit(" ", 1)[1])
    return total


# --------------------------------------------------------- subprocess e2e
def test_ha_kill9_promotion_and_zombie_fence_e2e(tmp_path):
    """THE headline e2e over real sockets: cold standby catch-up, the
    standby shedding (503 + Retry-After) while the primary serves,
    kill -9 of the primary mid-decode, promotion with a byte- AND
    token-identical resumed stream, and the revived zombie primary
    fenced by the members (zero stale-epoch placements accepted)."""
    ports = {k: free_port() for k in ("a", "b", "primary", "standby")}
    urls = (f"http://127.0.0.1:{ports['a']},"
            f"http://127.0.0.1:{ports['b']}")
    wal_p, wal_s = str(tmp_path / "wal-p"), str(tmp_path / "wal-s")
    procs = [
        _spawn(tmp_path, ["--port", str(ports["a"]), "--journal-file",
                          str(tmp_path / "ma.jsonl")], "ma.log"),
        _spawn(tmp_path, ["--port", str(ports["b"]), "--journal-file",
                          str(tmp_path / "mb.jsonl")], "mb.log"),
    ]

    def primary_argv(tag=""):
        return ["--port", str(ports["primary"]), "--replicas", "0",
                "--replica-urls", urls, "--ha",
                "--takeover-grace-s", "1.0", "--wal-dir", wal_p,
                "--wal-fsync-ms", "2", "--journal-file",
                str(tmp_path / f"primary{tag}.jsonl")]

    try:
        _wait_health(ports["a"])
        _wait_health(ports["b"])
        procs.append(_spawn(tmp_path, primary_argv(), "primary.log"))
        _wait_health(ports["primary"])

        # WAL has real traffic BEFORE the standby exists: catch-up must
        # go through the snapshot path, not record tailing.
        _rid, text0, ids0, done0 = _read_ndjson(
            _gen_request(ports["primary"], 6))
        assert done0 == "length" and len(ids0) == 6

        procs.append(_spawn(
            tmp_path,
            ["--port", str(ports["standby"]), "--replicas", "0",
             "--replica-urls", urls,
             "--standby-of", f"http://127.0.0.1:{ports['primary']}",
             "--takeover-grace-s", "1.0", "--wal-dir", wal_s,
             "--wal-fsync-ms", "2", "--journal-file",
             str(tmp_path / "standby.jsonl")], "standby.log"))
        standby = procs[-1]
        sb = _wait_health(
            ports["standby"],
            ok=lambda b: b.get("role") == "standby"
            and b.get("sync_lag_records") == 0)
        assert sb["status"] == "standby" and sb["epoch"] == 1
        # The snapshot really landed: the WAL replica holds the
        # pre-standby stream, finished.
        entries, _ = load_wal_records(os.path.join(wal_s, "wal.jsonl"))
        assert entries and all(e["finished"] is not None
                               for e in entries.values())
        # Primary-side view of the same link (the ack for a snapshot
        # rides the standby's NEXT poll, so converge rather than race).
        ph = _wait_health(ports["primary"], budget=30.0,
                          ok=lambda b: b.get("role") == "primary"
                          and b.get("sync_lag_records") == 0)
        assert ph["epoch"] == 1

        # A standby never serves: explicit shed with a takeover ETA.
        with pytest.raises(urllib.error.HTTPError) as e:
            _gen_request(ports["standby"], 2)
        assert e.value.code in (429, 503)
        assert e.value.headers.get("Retry-After") is not None

        # Mid-decode kill -9 of the primary.
        resp = _gen_request(ports["primary"], 12)
        rid, text, ids = None, "", []
        for raw in resp:
            obj = json.loads(raw)
            rid = obj.get("req_id", rid)
            ids.extend(int(t) for t in obj.get("token_ids") or ())
            text += obj.get("response", "")
            if len(ids) >= 5:
                break
        primary = procs[2]
        primary.kill()
        primary.wait(timeout=30)
        try:
            resp.close()
        except Exception:  # noqa: BLE001
            pass

        sb = _wait_health(
            ports["standby"], budget=60.0,
            ok=lambda b: b.get("role") == "primary"
            and b.get("status") != "recovering")
        assert sb["epoch"] == 2
        # Resume against the PROMOTED STANDBY: byte- and token-exact.
        _r, rtext, rids, done = _read_ndjson(urllib.request.urlopen(
            f"http://127.0.0.1:{ports['standby']}"
            f"/api/stream/{rid}?from={len(ids)}", timeout=120))
        assert done == "length"
        assert text + rtext == "".join(f"word{i} " for i in range(12))
        assert ids + rids == list(range(1, 13))

        # Revive the zombie on its old WAL dir: register + recovery
        # placements all carry the stale epoch — fenced, bounded (the
        # fence is terminal member-side, not a failover retry).
        procs.append(_spawn(tmp_path, primary_argv("-zombie"),
                            "zombie.log"))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if _fenced_total(ports["a"]) + _fenced_total(ports["b"]) >= 1:
                break
            time.sleep(0.2)
        fenced = _fenced_total(ports["a"]) + _fenced_total(ports["b"])
        assert fenced >= 1, "members never fenced the zombie"
        # The promoted router still owns the fleet.
        _r, ptext, pids, pdone = _read_ndjson(
            _gen_request(ports["standby"], 4))
        assert pdone == "length" and len(pids) == 4

        # Takeover pairing + epoch audit across the run's spills (the
        # zombie's spill is not part of the surviving run).
        standby.send_signal(signal.SIGTERM)
        standby.wait(timeout=60)
        spills = [p for p in
                  (str(tmp_path / "primary.jsonl"),
                   str(tmp_path / "standby.jsonl"),
                   os.path.join(wal_s, "primary-journal.jsonl"),
                   str(tmp_path / "ma.jsonl"),
                   str(tmp_path / "mb.jsonl"))
                  if os.path.exists(p)]
        assert len(spills) >= 4
        bad, total = check_files(spills)
        assert bad == [] and total > 0
        # The done record carries the measured promotion cost.
        with open(str(tmp_path / "standby.jsonl")) as f:
            recs = [json.loads(ln) for ln in f if '"kind"' in ln]
        done_recs = [r for r in recs if r.get("kind") == "router_takeover"
                     and r.get("phase") == "done"]
        assert done_recs and done_recs[-1]["epoch"] == 2
        assert done_recs[-1]["why"] == "primary_dead"
        assert done_recs[-1].get("takeover_ms") is not None
    finally:
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
            p._logf.close()


def test_ha_sigterm_handover_e2e(tmp_path):
    """Graceful SIGTERM on an HA primary HANDS OVER instead of
    draining: the primary waits for the standby's ack at its head seq,
    exits 0, and the standby promotes with why="handover" — zero
    client-visible downtime beyond the promotion window."""
    ports = {k: free_port() for k in ("a", "primary", "standby")}
    url = f"http://127.0.0.1:{ports['a']}"
    procs = [
        _spawn(tmp_path, ["--port", str(ports["a"]), "--journal-file",
                          str(tmp_path / "ma.jsonl")], "ma.log"),
    ]
    try:
        _wait_health(ports["a"])
        primary = _spawn(
            tmp_path,
            ["--port", str(ports["primary"]), "--replicas", "0",
             "--replica-urls", url, "--ha", "--takeover-grace-s", "1.0",
             "--wal-dir", str(tmp_path / "wal-p"), "--wal-fsync-ms", "2",
             "--journal-file", str(tmp_path / "primary.jsonl")],
            "primary.log")
        procs.append(primary)
        _wait_health(ports["primary"])
        procs.append(_spawn(
            tmp_path,
            ["--port", str(ports["standby"]), "--replicas", "0",
             "--replica-urls", url,
             "--standby-of", f"http://127.0.0.1:{ports['primary']}",
             "--takeover-grace-s", "1.0",
             "--wal-dir", str(tmp_path / "wal-s"), "--wal-fsync-ms", "2",
             "--journal-file", str(tmp_path / "standby.jsonl")],
            "standby.log"))
        _wait_health(ports["standby"],
                     ok=lambda b: b.get("role") == "standby"
                     and b.get("sync_lag_records") == 0)

        primary.send_signal(signal.SIGTERM)
        assert primary.wait(timeout=60) == 0
        _wait_health(ports["standby"], budget=60.0,
                     ok=lambda b: b.get("role") == "primary"
                     and b.get("status") != "recovering")

        # The handover is journaled as a takeover with why="handover".
        deadline = time.monotonic() + 30
        why = None
        while time.monotonic() < deadline and why != "handover":
            with open(str(tmp_path / "standby.jsonl")) as f:
                for ln in f:
                    if '"router_takeover"' in ln:
                        r = json.loads(ln)
                        if r.get("phase") == "done":
                            why = r.get("why")
            time.sleep(0.2)
        assert why == "handover"
        # The promoted router serves.
        _r, text, ids, done = _read_ndjson(
            _gen_request(ports["standby"], 5))
        assert done == "length" and len(ids) == 5
        assert text == "".join(f"word{i} " for i in range(5))
    finally:
        for p in procs:
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
            p._logf.close()
