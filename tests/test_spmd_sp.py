"""SPMD sequence-parallel prefill: 2 CPU processes, mesh seq axis spanning
both — a long prompt takes the OP_PREFILL_SP broadcast path and the
generated tokens equal a single-process run."""

import json
import os
import socket
import subprocess
import sys

import pytest

_SCRIPT = r"""
import json, os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local device per process
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
assert jax.device_count() == 2

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.parallel.mesh import make_mesh
import jax.numpy as jnp

mesh = make_mesh(dp=1, sp=2, tp=1)
ecfg = EngineConfig(model="test-tiny", max_slots=2, num_pages=64, page_size=8,
                    max_pages_per_seq=16, prefill_buckets=(16,),
                    decode_steps_per_iter=2, sp=2)

if pid == 0:
    from ollamamq_tpu.engine.spmd import SPMDEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = SPMDEngine(ecfg, models={"test-tiny": None}, blocklist_path=None,
                     mesh=mesh, dtype=jnp.float32)
    eng.start()
    rt = eng.runtimes["test-tiny"]
    assert rt._sp, "seq axis not detected"
    tok = rt.tokenizer
    prompt = tok.encode("sequence parallel spmd " * 3)  # ~70 > bucket 16
    req = eng.enqueue_request("u", "", "test-tiny", prompt_tokens=prompt,
                              sampling=SamplingParams(max_tokens=5))
    import time
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        item = req.stream.get(timeout=0.5)
        if item and item.kind in ("done", "error"):
            break
    used_sp = any(isinstance(k, tuple) and k[0] == "sp"
                  for k in rt._prefill_jits)
    eng.stop()
    print("RESULT " + json.dumps({"tokens": req.generated_ids,
                                  "used_sp": used_sp}), flush=True)
else:
    from ollamamq_tpu.engine.spmd import run_worker

    steps = run_worker({"test-tiny": None}, ecfg, mesh, dtype=jnp.float32)
    print("RESULT " + json.dumps({"steps": steps}), flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_spmd_sp_prefill_two_processes(tmp_path):
    port = _free_port()
    script = tmp_path / "spmd_sp_child.py"
    script.write_text(_SCRIPT)
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("SPMD SP processes hung")
        assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
        outs.append(out)

    primary = json.loads(
        [l for l in outs[0].splitlines() if l.startswith("RESULT ")][0][7:]
    )
    worker = json.loads(
        [l for l in outs[1].splitlines() if l.startswith("RESULT ")][0][7:]
    )
    assert primary["used_sp"], "long prompt did not take the SP path"
    assert worker["steps"] >= 2  # sp prefill + decode dispatches
    assert len(primary["tokens"]) >= 1

    # Single-process reference (same seed/config) must match exactly.
    import time

    import jax.numpy as jnp

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=2, num_pages=64,
                     page_size=8, max_pages_per_seq=16, prefill_buckets=(16,),
                     decode_steps_per_iter=2),
        models={"test-tiny": None}, blocklist_path=None, dtype=jnp.float32,
    )
    eng.start()
    try:
        tok = eng.runtimes["test-tiny"].tokenizer
        req = eng.enqueue_request(
            "u", "", "test-tiny",
            prompt_tokens=tok.encode("sequence parallel spmd " * 3),
            sampling=SamplingParams(max_tokens=5))
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.5)
            if item and item.kind in ("done", "error"):
                break
        assert req.generated_ids == primary["tokens"]
    finally:
        eng.stop()
