"""CLI entrypoint: `python -m ollamamq_tpu.cli`.

Flag parity with the reference CLI (/root/reference/src/main.rs:19-41),
re-targeted at TPU: `--backend-urls` becomes `--models` (the pool being
scheduled is model runtimes on TPU chips, not HTTP backends). Logging
mirrors main.rs:62-87: file appender when the TUI owns the terminal,
stdout otherwise, level from OLLAMAMQ_LOG (the RUST_LOG analogue).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ollamamq-tpu",
        description="TPU-native LLM serving with per-user fair-share queuing",
    )
    p.add_argument("--port", type=int, default=int(os.environ.get("PORT", 11434)),
                   help="HTTP port (default 11434)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--models", default=os.environ.get("MODELS", "llama3:8b"),
                   help="comma-separated model names to load at startup "
                        "(replaces the reference's --backend-urls)")
    p.add_argument("--checkpoints", default=os.environ.get("CHECKPOINTS", ""),
                   help="comma-separated name=path checkpoint mappings; "
                        "models without one use random weights")
    p.add_argument("--timeout", type=float,
                   default=float(os.environ.get("TIMEOUT", 300)),
                   help="per-request timeout seconds (default 300)")
    p.add_argument("--no-tui", action="store_true",
                   help="disable the admin TUI")
    p.add_argument("--allow-all-routes", action="store_true",
                   help="expose the fallback route for unhandled paths")
    p.add_argument("--fake-engine", action="store_true",
                   help="serve deterministic fake tokens (no TPU; for tests)")
    p.add_argument("--blocklist", default="blocked_items.json",
                   help="blocklist persistence path")
    # Engine shape.
    p.add_argument("--max-slots", type=int, default=64,
                   help="decode batch slots (max concurrent generations)")
    # page-size 32 measured faster than 16 on v5e (r3: 1762 vs ~1600
    # tok/s/chip); num-pages halved alongside so the default KV pool stays
    # 32768 slots — same HBM footprint as the old 2048 x 16.
    p.add_argument("--num-pages", type=int, default=1024)
    p.add_argument("--page-size", type=int, default=32)
    p.add_argument("--max-pages-per-seq", type=int, default=256)
    p.add_argument("--max-new-tokens", type=int, default=256)
    p.add_argument("--decode-steps", type=int, default=8,
                   help="decode steps fused per dispatch when idle")
    p.add_argument("--weights-dtype", choices=("bfloat16", "int8"),
                   default="bfloat16",
                   help="weight storage dtype: 'int8' quantizes at load "
                        "time (per-channel symmetric, fp32 scales, "
                        "dequant fused into the matmuls) — roughly "
                        "halves weight HBM and the bytes every weight-"
                        "streaming-bound dispatch reads")
    p.add_argument("--kv-dtype", choices=("bfloat16", "int8"),
                   default="bfloat16",
                   help="KV page dtype: 'int8' shrinks every page ~2x "
                        "(per-page-row fp32 scales stored alongside the "
                        "pool), so ~2x concurrent requests fit the same "
                        "HBM; invalid combinations (MoE weights, "
                        "--pp/--sp KV) fail at startup")
    p.add_argument("--max-batch-tokens", type=int, default=512,
                   help="token budget of one ragged dispatch (decode rows "
                        "+ prefill-span tokens); clamped up so a full "
                        "decode batch always fits")
    p.add_argument("--token-granule", type=int, default=16,
                   help="ragged streams pad their TOTAL token count to "
                        "this granule (the only padding the ragged path "
                        "pays; one compile per padded total)")
    p.add_argument("--spec", action="store_true", default=False,
                   help="speculative multi-token decoding on the ragged "
                        "path: n-gram prompt-lookup drafts (up to "
                        "--spec-k per greedy decode slot) verified in "
                        "one ragged dispatch; accepted drafts emit "
                        "together, rejected drafts' KV pages roll back. "
                        "Greedy streams stay byte-identical to --no-spec")
    p.add_argument("--no-spec", dest="spec", action="store_false",
                   help="disable speculative decoding (the default)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="max draft tokens proposed per decode slot per "
                        "dispatch")
    p.add_argument("--spec-min-accept", type=float, default=0.1,
                   help="per-user auto-throttle: once a user's observed "
                        "draft accept rate falls below this (after a "
                        "warmup sample), speculation is disabled for "
                        "that user — wasted verify FLOPs must pay for "
                        "themselves; 0 never throttles")
    p.add_argument("--scheduler",
                   default=os.environ.get("SCHEDULER", "fcfs"),
                   help="scheduling policy: 'fcfs' (default; FIFO within "
                        "fair share, bit-identical to the pre-policy "
                        "engine), 'srpt' (shortest-predicted-remaining-"
                        "first off an online output-length predictor, "
                        "with anti-starvation aging), or 'edf' "
                        "(earliest-deadline-first; srpt order for "
                        "deadline-less requests). Policies reorder only "
                        "within what fair-share already allows; promote "
                        "a candidate with `python -m "
                        "ollamamq_tpu.tools.journal simulate TRACE "
                        "--scheduler srpt` counterfactual replay")
    p.add_argument("--prefix-cache", action="store_true",
                   help="automatic prefix caching: share finished prompts' "
                        "KV pages (page-granular radix tree) across "
                        "requests; prefills only the uncached tail")
    p.add_argument("--prefix-cache-min-pages", type=int, default=1,
                   help="minimum matched full pages before a cached "
                        "prefix is reused (smaller hits prefill normally)")
    # Mesh.
    p.add_argument("--dp", type=int, default=1, help="data-parallel axis size")
    p.add_argument("--sp", type=int, default=1, help="sequence-parallel axis size")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel axis size (-1 = all devices)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages (layers split across "
                        "chip groups; for models beyond one group's HBM)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel axis size (MoE models)")
    p.add_argument("--pp-microbatches", type=int, default=0,
                   help="GPipe microbatches per pp dispatch (0 = one per "
                        "stage; sweep on hardware — prefill wants more, "
                        "weight-bound decode may want fewer)")
    # Fleet router: dispatcher-over-engines.
    p.add_argument("--replicas", type=int,
                   default=int(os.environ.get("REPLICAS", 1)),
                   help="in-process engine replicas behind the fleet "
                        "router (1 = single engine, no router): health-"
                        "driven ejection with backoff re-probe, mid-"
                        "stream failover replaying prompt + emitted "
                        "tokens, POST /admin/drain/{replica} zero-drop "
                        "rolling restarts")
    p.add_argument("--replica-urls",
                   default=os.environ.get("REPLICA_URLS", ""),
                   help="comma-separated base URLs of subprocess/remote "
                        "engines speaking the existing HTTP API, joined "
                        "to the fleet as members (the docker-compose "
                        "'router + engine services' shape); combines "
                        "with --replicas local members")
    p.add_argument("--placement", choices=("affinity", "least_loaded"),
                   default=os.environ.get("PLACEMENT", "affinity"),
                   help="fleet placement policy: 'affinity' routes to "
                        "the replica whose prefix-cache radix tree "
                        "already holds the prompt's prefix, falling "
                        "back to least-loaded (with round-robin tie "
                        "rotation); 'least_loaded' skips the probe")
    p.add_argument("--drain-timeout-s", type=float,
                   default=float(os.environ.get("DRAIN_TIMEOUT_S", 30.0)),
                   help="drain budget: in-flight streams get this long "
                        "to finish on a draining replica before the "
                        "stragglers fail over (still zero dropped "
                        "streams)")
    p.add_argument("--no-migrate", action="store_true",
                   default=os.environ.get("MIGRATE", "").lower()
                   in ("0", "false", "no"),
                   help="disable KV page migration: failover and drain "
                        "fall back to recompute replay (prompt + every "
                        "emitted token) instead of shipping KV pages + "
                        "request state to a healthy member, and affinity "
                        "misses stop shipping cached prefixes")
    p.add_argument("--migrate-timeout-s", type=float,
                   default=float(os.environ.get("MIGRATE_TIMEOUT_S", 10.0)),
                   help="per-transfer migration budget: a transfer "
                        "(export + ship + import ack) past this aborts "
                        "and the stream falls back to recompute replay")
    p.add_argument("--tiers", default=os.environ.get("TIERS", ""),
                   help="SLO-aware replica tiers for the fleet router "
                        "(needs --replicas/--replica-urls): "
                        "'interactive=r0;bulk=r1,r2' maps members to "
                        "tiers by name (or tpN for every member at that "
                        "TP width; an @tpN suffix on the tier declares "
                        "the width a retiered member restarts at). "
                        "VIP/boost users and deadlined requests place "
                        "on the interactive tier, everything else on "
                        "bulk; cross-tier placement only under "
                        "journaled SLO burn-rate overflow or an empty "
                        "tier, and a TierBalancer retiers members "
                        "(drain -> migrate -> restart -> rejoin) as the "
                        "class mix shifts. Unknown tier names or a tier "
                        "with no members fail startup")
    # Elastic fleet (fleet/autoscaler.py): SLO-burn-driven sizing.
    p.add_argument("--autoscale", action="store_true",
                   default=os.environ.get("AUTOSCALE", "").lower()
                   in ("1", "true", "yes"),
                   help="elastic fleet sizing: a per-tier control loop "
                        "watches sustained SLO burn + queue backlog and "
                        "scales the fleet one member at a time "
                        "(provisioned members join via the normal probe "
                        "path; scale-down is always drain -> migrate -> "
                        "retire, never a kill). The bulk tier may scale "
                        "to zero overnight — its queued work parks at "
                        "the router and wakes the tier. Implies a fleet "
                        "even with --replicas 1")
    p.add_argument("--min-replicas", type=int,
                   default=int(os.environ.get("MIN_REPLICAS", 1)),
                   help="scale-down floor for the interactive tier (and "
                        "for an untiered elastic fleet); the bulk tier's "
                        "floor is 0 (scale-to-zero)")
    p.add_argument("--max-replicas", type=int,
                   default=int(os.environ.get("MAX_REPLICAS", 4)),
                   help="fleet-wide scale-up ceiling")
    p.add_argument("--scale-cooldown-s", type=float,
                   default=float(os.environ.get("SCALE_COOLDOWN_S", 30.0)),
                   help="anti-flap cooldown between scale events; the "
                        "burn/idle sustain windows derive from it "
                        "(pressure must hold cooldown/3 before a scale-"
                        "up, idleness a full cooldown before a scale-"
                        "down). Waking a scaled-to-zero tier bypasses it")
    p.add_argument("--preemptible",
                   default=os.environ.get("PREEMPTIBLE", ""),
                   help="comma-separated member names (r0, h1, ...) that "
                        "accept a spot-style termination notice (POST "
                        "/admin/preempt/{replica} or the fault plan's "
                        "'preempt' site): live streams migrate off "
                        "within the notice window, then the member "
                        "retires — zero dropped streams")
    p.add_argument("--router-overhead-budget-ms", type=float,
                   default=float(os.environ.get(
                       "ROUTER_OVERHEAD_BUDGET_MS", 50.0)),
                   help="bound on the router's own placement-decision "
                        "cost: the always-on self-profiler "
                        "(ollamamq_router_overhead_ms{site}) feeds a "
                        "windowed p99; above this budget the health "
                        "monitor fires the router_overhead alert and "
                        "the bench fleet-chaos gate fails. 0 disables "
                        "the alert (the timers stay on)")
    # Router HA (fleet/ha.py): warm-standby router with epoch fencing.
    p.add_argument("--ha", action="store_true",
                   default=os.environ.get("HA", "").lower()
                   in ("1", "true", "yes"),
                   help="run this fleet router as the HA PRIMARY: expose "
                        "the replication stream (GET /admin/ha/sync — "
                        "WAL records + decision-journal events + shadow "
                        "placement state) a --standby-of router tails, "
                        "stamp every member-facing call with the router "
                        "epoch, and on SIGTERM hand the fleet to the "
                        "caught-up standby instead of draining. "
                        "Requires --wal-dir and a fleet")
    p.add_argument("--standby-of", default=os.environ.get("STANDBY_OF", ""),
                   help="run as the warm STANDBY of the primary router at "
                        "this base URL: tail its replication stream into "
                        "local WAL/journal replicas, shed clients with "
                        "503 + Retry-After meanwhile, and after "
                        "--takeover-grace-s of heartbeat loss PROMOTE — "
                        "bump the epoch (fencing the old primary if it "
                        "revives), re-register the members, replay every "
                        "unfinished stream through recovery, then serve. "
                        "Requires --wal-dir and --replica-urls naming "
                        "the same members the primary serves")
    p.add_argument("--takeover-grace-s", type=float,
                   default=float(os.environ.get("TAKEOVER_GRACE_S", 3.0)),
                   help="standby heartbeat-loss grace before promotion; "
                        "sync polls run at grace/4 (floored at 50ms)")
    p.add_argument("--no-federate-metrics", action="store_true",
                   default=os.environ.get("FEDERATE_METRICS", "").lower()
                   in ("0", "false", "no"),
                   help="disable metrics federation: the router's "
                        "/metrics stops re-exporting HTTP members' "
                        "series under a replica label (members stay "
                        "scrapable individually)")
    # Graceful degradation under load.
    p.add_argument("--max-queued", type=int, default=0,
                   help="global queued-request cap: past it, enqueues are "
                        "shed with 503 + Retry-After (derived from the "
                        "observed completion rate); 0 = unbounded")
    p.add_argument("--max-queued-per-user", type=int, default=0,
                   help="per-user queued-request cap: past it, that "
                        "user's enqueues are shed with 429 + Retry-After; "
                        "0 = unbounded")
    p.add_argument("--no-preempt", action="store_true",
                   help="disable preemption-with-recompute: decode-time "
                        "KV-pool exhaustion then errors the request "
                        "explicitly (done_reason kv_exhausted) instead "
                        "of preempting a victim for later recompute")
    p.add_argument("--preempt-max", type=int, default=3,
                   help="anti-livelock budget: preemptions allowed per "
                        "request before it holds its reservation and is "
                        "never picked as a victim again")
    p.add_argument("--fault-plan", default="",
                   help="deterministic fault-injection plan (JSON; see "
                        "ollamamq_tpu/testing/faults.py) wired into the "
                        "dispatch/allocation seams — chaos benching; "
                        "malformed plans fail startup loudly")
    # SLOs + alerting.
    p.add_argument("--slo-ttft-ms", type=float, default=0.0,
                   help="TTFT latency objective in ms (enqueue to first "
                        "token); 0 = no TTFT SLO. Violations burn the "
                        "error budget; multi-window burn-rate alerts "
                        "surface in /health, /metrics, and the TUI")
    p.add_argument("--slo-tpot-ms", type=float, default=0.0,
                   help="per-token decode latency objective in ms; "
                        "0 = no TPOT SLO")
    p.add_argument("--slo-target", type=float, default=0.99,
                   help="good-fraction target for both SLOs (0.99 = 1%% "
                        "error budget)")
    # Telemetry.
    p.add_argument("--log-file", default=os.environ.get("OLLAMAMQ_LOG_FILE",
                                                        ""),
                   help="write logs to this file as structured JSON lines "
                        "(one object per line, request-scoped lines carry "
                        "req_id). Default: ollamamq.log in CWD when the "
                        "TUI owns the terminal, stdout otherwise")
    p.add_argument("--log-rotate-mb", type=float, default=64.0,
                   help="rotate --log-file when it reaches this size "
                        "(MB); 0 disables rotation")
    p.add_argument("--log-keep", type=int, default=3,
                   help="rotated --log-file generations kept "
                        "(file.1 .. file.N)")
    p.add_argument("--journal-ring", type=int, default=2048,
                   help="scheduler decision-journal records kept for "
                        "GET /debug/journal (the engine flight recorder)")
    p.add_argument("--journal-file", default=os.environ.get(
                       "OLLAMAMQ_JOURNAL_FILE", ""),
                   help="spill every decision-journal record to this "
                        "JSONL file (analyze/replay offline with "
                        "`python -m ollamamq_tpu.tools.journal`)")
    p.add_argument("--journal-rotate-mb", type=float, default=64.0,
                   help="rotate --journal-file at this size (MB); "
                        "0 disables rotation")
    p.add_argument("--journal-keep", type=int, default=3,
                   help="rotated --journal-file generations kept")
    p.add_argument("--journal-sample", type=float,
                   default=float(os.environ.get("JOURNAL_SAMPLE", 1.0)),
                   help="probabilistic sampling rate (0, 1] for high-"
                        "rate journal kinds (batch/chunk/page_*/"
                        "broadcast) so the ring and spill survive 100x "
                        "event rates; decision-critical kinds (shed/"
                        "preempt/finish/migrate_*/recover_*) are always "
                        "retained. 1.0 (default) records everything; "
                        "tools/journal check understands sampled traces")
    # Crash durability: admission WAL + cold-restart recovery +
    # client-resumable streams (durability/).
    p.add_argument("--wal-dir", default=os.environ.get("WAL_DIR", ""),
                   help="write-ahead request log directory: every "
                        "accepted generation request is durably recorded "
                        "(batched fsync) BEFORE the enqueue is ACKed, "
                        "emitted tokens are logged behind it, and a "
                        "restart replays unfinished requests token-exact "
                        "— disconnected clients reattach via GET "
                        "/api/stream/{req_id}?from=N. Empty = no WAL")
    p.add_argument("--wal-fsync-ms", type=float,
                   default=float(os.environ.get("WAL_FSYNC_MS", 20.0)),
                   help="WAL group-commit window in ms: admissions wait "
                        "at most this long for their covering fsync; a "
                        "crash loses at most this much emitted-token "
                        "progress (regenerated identically on recovery "
                        "under greedy decoding). 0 = fsync every append")
    p.add_argument("--no-wal", action="store_true",
                   help="disable the admission WAL even when WAL_DIR is "
                        "set in the environment")
    p.add_argument("--stop-grace-s", type=float,
                   default=float(os.environ.get("STOP_GRACE_S", 30.0)),
                   help="graceful-shutdown budget: on SIGTERM/SIGINT the "
                        "server stops admission, lets in-flight streams "
                        "drain up to this long, flushes + fsyncs the "
                        "journal and WAL, then exits 0 (stragglers stay "
                        "in the WAL and recover on the next start)")
    p.add_argument("--metrics-buckets", default="",
                   help="comma-separated upper bounds (ms) for the latency "
                        "histograms on /metrics (ttft/tpot/step/prefill); "
                        "default is a 1ms..30s ladder")
    p.add_argument("--trace-ring", type=int, default=512,
                   help="finished request traces kept for /debug/trace "
                        "(Chrome trace-event export)")
    p.add_argument("--token-fairness", action="store_true",
                   help="fair-share by served tokens instead of request count")
    p.add_argument("--spmd", action="store_true",
                   help="multi-host SPMD serving: process 0 runs the "
                        "scheduler+HTTP and broadcasts step plans; other "
                        "processes replay them (requires jax.distributed "
                        "env vars)")
    p.add_argument("--cpu", type=int, nargs="?", const=1, default=0,
                   metavar="N",
                   help="force the CPU platform with N virtual devices "
                        "(development / CI; wins over a TPU-registering "
                        "sitecustomize)")
    return p


class JsonLineFormatter(logging.Formatter):
    """Structured log lines: one JSON object per line. Request-scoped
    records (logged with extra={"req_id": N}) carry the id, so a log line
    correlates directly with GET /debug/requests/{id}."""

    def format(self, record: logging.LogRecord) -> str:
        import json

        out = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S")
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        rid = getattr(record, "req_id", None)
        if rid is not None:
            out["req_id"] = rid
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def setup_logging(use_tui: bool, log_file: str = "",
                  rotate_mb: float = 64.0, keep: int = 3) -> None:
    """File logging (JSON lines) when --log-file names a path, or — TUI
    owning the terminal with no explicit path — the reference's
    ollamamq.log default; human-readable stdout otherwise. File logs
    rotate at --log-rotate-mb keeping --log-keep generations, so a
    long soak run cannot fill the disk."""
    level = os.environ.get("OLLAMAMQ_LOG", "INFO").upper()
    if not log_file and use_tui:
        log_file = "ollamamq.log"  # reference default (main.rs:66-87)
    if log_file:
        if rotate_mb and rotate_mb > 0:
            from logging.handlers import RotatingFileHandler

            handler: logging.Handler = RotatingFileHandler(
                log_file, maxBytes=int(rotate_mb * 1e6),
                backupCount=max(1, keep))
        else:
            handler = logging.FileHandler(log_file)
        handler.setFormatter(JsonLineFormatter())
    else:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"
        ))
    logging.basicConfig(level=getattr(logging, level, logging.INFO),
                        handlers=[handler])


def _fake_latency() -> float:
    """Per-token delay for --fake-engine servers (env
    FAKE_TOKEN_LATENCY_S): crash/restart and drain tests need streams
    that stay in flight long enough for the chaos to land mid-decode."""
    try:
        return max(0.0, float(os.environ.get("FAKE_TOKEN_LATENCY_S", 0.0)))
    except ValueError:
        return 0.0


def install_graceful_shutdown(engine, grace_s: float) -> None:
    """SIGTERM/SIGINT => zero-drop shutdown: stop admission (new
    enqueues shed with 503), let in-flight streams drain up to
    `grace_s`, flush + fsync the journal and WAL, exit 0. Stragglers
    past the grace stay recorded in the WAL (when --wal-dir is on) and
    recover token-exact on the next start — so `docker stop` with an
    adequate stop_grace_period drops nothing either way."""
    import signal
    import threading
    import time

    log = logging.getLogger("ollamamq")
    fired = threading.Event()

    def run(signum: int) -> None:
        # HA primary: hand the fleet to the caught-up standby (it
        # promotes with why="handover") instead of draining the world.
        # ha_handover quiesces first either way; False (no standby, or
        # it never confirmed) falls through to the normal drain below.
        handover = getattr(engine, "ha_handover", None)
        if handover is not None:
            try:
                if handover(timeout_s=min(10.0, max(1.0, grace_s))):
                    log.warning("signal %d: fleet handed over to the "
                                "standby; exiting 0", signum)
                    engine.stop()
                    os._exit(0)
            except Exception:  # noqa: BLE001
                log.exception("HA handover failed; draining instead")
        log.warning("signal %d: graceful shutdown — admission stopped, "
                    "draining in-flight work (grace %.0fs)",
                    signum, grace_s)
        try:
            engine.quiesce()
        except Exception:  # noqa: BLE001
            log.exception("quiesce failed; stopping anyway")
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            try:
                if engine.inflight_count() == 0:
                    break
            except Exception:  # noqa: BLE001
                break
            time.sleep(0.1)
        # The engine finishing a stream and the HTTP layer flushing its
        # final frames to the socket are asynchronous: give the event
        # loop a moment to drain before the hard exit cuts connections.
        time.sleep(min(1.0, max(0.0, deadline - time.monotonic())))
        try:
            left = engine.inflight_count()
        except Exception:  # noqa: BLE001
            left = -1
        if left:
            log.warning("grace expired with %s stream(s) still in "
                        "flight; they remain in the WAL and recover on "
                        "the next start", left)
        engine.stop()  # joins the loop, fsyncs journal + WAL
        log.warning("graceful shutdown complete; exiting 0")
        os._exit(0)

    def handler(signum, frame):  # noqa: ARG001
        if fired.is_set():
            os._exit(0)  # second signal: operator means NOW
        fired.set()
        threading.Thread(target=run, args=(signum,), daemon=True,
                         name="graceful-shutdown").start()

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    use_tui = not args.no_tui and sys.stdout.isatty()
    setup_logging(use_tui, log_file=args.log_file,
                  rotate_mb=args.log_rotate_mb, keep=args.log_keep)
    log = logging.getLogger("ollamamq")
    if not (0.0 < args.slo_target < 1.0):
        log.error("--slo-target must be in (0, 1), got %s", args.slo_target)
        return 2
    if args.max_queued < 0 or args.max_queued_per_user < 0 \
            or args.preempt_max < 0:
        log.error("--max-queued / --max-queued-per-user / --preempt-max "
                  "must be >= 0")
        return 2
    if args.journal_ring < 1 or args.journal_keep < 1 or args.log_keep < 1:
        log.error("--journal-ring / --journal-keep / --log-keep "
                  "must be >= 1")
        return 2
    if args.token_granule < 1 or args.max_batch_tokens < 1:
        log.error("--token-granule / --max-batch-tokens must be >= 1")
        return 2
    if args.spec_k < 1 or not (0.0 <= args.spec_min_accept <= 1.0):
        log.error("--spec-k must be >= 1 and --spec-min-accept in [0, 1]")
        return 2
    if args.journal_rotate_mb < 0 or args.log_rotate_mb < 0:
        log.error("--journal-rotate-mb / --log-rotate-mb must be >= 0 "
                  "(0 disables rotation)")
        return 2
    if not (0.0 < args.journal_sample <= 1.0):
        log.error("--journal-sample must be in (0, 1], got %s",
                  args.journal_sample)
        return 2
    if args.wal_fsync_ms < 0 or args.stop_grace_s < 0:
        log.error("--wal-fsync-ms / --stop-grace-s must be >= 0")
        return 2
    # Scheduler policy fails fast BEFORE any device work — argparse
    # doesn't validate env-supplied defaults, so a typo'd SCHEDULER env
    # must die here, not at the first admission pass.
    from ollamamq_tpu.config import validate_scheduler

    sched_err = validate_scheduler(args.scheduler)
    if sched_err is not None:
        log.error("%s", sched_err)
        return 2
    fleet_urls = [u.strip() for u in args.replica_urls.split(",")
                  if u.strip()]
    if args.replicas < 0 or (args.replicas == 0 and not fleet_urls):
        log.error("--replicas must be >= 1 (0 only with --replica-urls)")
        return 2
    if args.drain_timeout_s <= 0:
        log.error("--drain-timeout-s must be > 0")
        return 2
    if args.migrate_timeout_s <= 0:
        log.error("--migrate-timeout-s must be > 0")
        return 2
    if args.router_overhead_budget_ms < 0:
        log.error("--router-overhead-budget-ms must be >= 0 "
                  "(0 disables the alert)")
        return 2
    roster_names = ([f"r{i}" for i in range(max(0, args.replicas))]
                    + [f"h{j}" for j in range(len(fleet_urls))])
    if args.autoscale:
        # Autoscale knobs fail fast BEFORE any device work — argparse
        # doesn't validate env-supplied defaults (MIN_REPLICAS etc.), so
        # a bad compose file must die here, not at the first scale
        # decision.
        from ollamamq_tpu.config import validate_autoscale

        scale_err = validate_autoscale(
            args.min_replicas, args.max_replicas, args.scale_cooldown_s,
            replicas=args.replicas + len(fleet_urls))
        if scale_err is not None:
            log.error("%s", scale_err)
            return 2
    # HA knobs fail fast BEFORE any device work — argparse doesn't
    # validate env-supplied defaults (HA/STANDBY_OF/TAKEOVER_GRACE_S),
    # so a bad compose file must die here, not at the first heartbeat.
    from ollamamq_tpu.config import validate_ha

    ha_err = validate_ha(args.ha, args.standby_of or None,
                         args.takeover_grace_s,
                         (None if args.no_wal else (args.wal_dir or None)),
                         args.replica_urls or None)
    if ha_err is not None:
        log.error("%s", ha_err)
        return 2
    if args.ha and args.replicas <= 1 and not fleet_urls \
            and not args.autoscale:
        log.error("--ha needs a fleet (--replicas > 1, --replica-urls, "
                  "or --autoscale): the standby re-registers those "
                  "members at takeover")
        return 2
    if args.preemptible:
        want = [s.strip() for s in args.preemptible.split(",")
                if s.strip()]
        if args.replicas <= 1 and not fleet_urls and not args.autoscale:
            log.error("--preemptible needs a fleet (--replicas > 1, "
                      "--replica-urls, or --autoscale)")
            return 2
        unknown = sorted(set(want) - set(roster_names))
        if unknown:
            log.error("--preemptible names unknown members: %s "
                      "(fleet: %s)", ", ".join(unknown),
                      ", ".join(roster_names))
            return 2
    if args.tiers:
        # Tier spec fails fast BEFORE any device work: unknown tier
        # names, selectors naming no member, and a tier with no members
        # all kill the process at startup, not at the first placement.
        if args.replicas <= 1 and not fleet_urls:
            log.error("--tiers needs a fleet "
                      "(--replicas > 1 and/or --replica-urls)")
            return 2
        from ollamamq_tpu.config import validate_tiers

        roster = ([(f"r{i}", args.tp) for i in range(args.replicas)]
                  + [(f"h{j}", None) for j in range(len(fleet_urls))])
        tiers_err = validate_tiers(args.tiers, roster)
        if tiers_err is not None:
            log.error("invalid --tiers: %s", tiers_err)
            return 2
    # Quantization flags fail fast BEFORE any device/runtime work: an
    # unsupported combination must kill the process at startup, not at
    # the first dispatch (same validator the SPMD worker and the
    # runtimes run).
    from ollamamq_tpu.config import validate_quant_config

    quant_err = validate_quant_config(
        args.weights_dtype, args.kv_dtype, pp=args.pp, sp=args.sp,
        model_names=[m.strip() for m in args.models.split(",") if m.strip()])
    if quant_err is not None:
        log.error("%s", quant_err)
        return 2
    if args.fault_plan:
        # Schema-check the plan BEFORE any engine/device work: a typo'd
        # chaos plan must fail the process at startup, not mid-traffic.
        from ollamamq_tpu.testing.faults import FaultPlan, FaultPlanError

        try:
            FaultPlan.load(args.fault_plan)
        except FaultPlanError as e:
            log.error("invalid --fault-plan: %s", e)
            return 2

    if args.cpu:
        from ollamamq_tpu.parallel.distributed import multiprocess_configured
        from ollamamq_tpu.platform_force import force_cpu

        # Multi-process only: defer the backend-touch verification, since
        # jax.distributed.initialize below must run before the first
        # backend touch. Single-process keeps the loud platform check.
        force_cpu(args.cpu, check=not multiprocess_configured())

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.core import Fairness

    if args.metrics_buckets:
        from ollamamq_tpu.telemetry import schema as tm_schema

        try:
            bounds = tuple(float(b) for b in args.metrics_buckets.split(",")
                           if b.strip())
        except ValueError:
            log.error("invalid --metrics-buckets %r (want comma-separated "
                      "numbers)", args.metrics_buckets)
            return 2
        if not bounds:
            log.error("--metrics-buckets must name at least one bound")
            return 2
        tm_schema.configure_latency_buckets(bounds)

    # Multi-host control plane: no-op unless JAX_COORDINATOR_ADDRESS /
    # JAX_NUM_PROCESSES are set (or a TPU pod auto-detects). After this,
    # jax.devices() spans all hosts and tp=-1 shards over the whole pod.
    from ollamamq_tpu.parallel import distributed

    distributed.initialize()

    model_names = [m.strip() for m in args.models.split(",") if m.strip()]
    checkpoints = {}
    for pair in args.checkpoints.split(","):
        if "=" in pair:
            name, path = pair.split("=", 1)
            checkpoints[name.strip()] = path.strip()
    models = {name: checkpoints.get(name) for name in model_names}

    ecfg = EngineConfig(
        model=model_names[0] if model_names else "llama3:8b",
        max_slots=args.max_slots,
        num_pages=args.num_pages,
        page_size=args.page_size,
        max_pages_per_seq=args.max_pages_per_seq,
        max_new_tokens=args.max_new_tokens,
        decode_steps_per_iter=args.decode_steps,
        max_batch_tokens=args.max_batch_tokens,
        token_granule=args.token_granule,
        spec=args.spec,
        spec_k=args.spec_k,
        spec_min_accept=args.spec_min_accept,
        scheduler=args.scheduler,
        prefix_cache=args.prefix_cache,
        prefix_cache_min_pages=args.prefix_cache_min_pages,
        dp=args.dp,
        sp=args.sp,
        tp=args.tp,
        pp=args.pp,
        ep=args.ep,
        pp_microbatches=args.pp_microbatches or None,
        trace_ring=args.trace_ring,
        slo_ttft_ms=args.slo_ttft_ms or None,
        slo_tpot_ms=args.slo_tpot_ms or None,
        slo_target=args.slo_target,
        preempt=not args.no_preempt,
        preempt_max=args.preempt_max,
        max_queued=args.max_queued,
        max_queued_per_user=args.max_queued_per_user,
        fault_plan=args.fault_plan or None,
        journal_ring=args.journal_ring,
        journal_file=args.journal_file or None,
        journal_rotate_mb=args.journal_rotate_mb,
        journal_keep=args.journal_keep,
        journal_sample=args.journal_sample,
        wal_dir=(None if args.no_wal else (args.wal_dir or None)),
        wal_fsync_ms=args.wal_fsync_ms,
        ha=args.ha,
        standby_of=args.standby_of or None,
        takeover_grace_s=args.takeover_grace_s,
        weights_dtype=args.weights_dtype,
        kv_dtype=args.kv_dtype,
        replicas=args.replicas,
        placement=args.placement,
        drain_timeout_s=args.drain_timeout_s,
        migrate=not args.no_migrate,
        migrate_timeout_s=args.migrate_timeout_s,
        tiers=args.tiers or None,
        autoscale=args.autoscale,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        scale_cooldown_s=args.scale_cooldown_s,
        preemptible=args.preemptible or None,
        router_overhead_budget_ms=args.router_overhead_budget_ms,
        federate_metrics=not args.no_federate_metrics,
    )
    fairness = Fairness.TOKENS if args.token_fairness else Fairness.REQUESTS

    standby = None
    if args.spmd and args.fake_engine:
        log.error("--spmd and --fake-engine are mutually exclusive")
        return 2
    if (args.replicas > 1 or fleet_urls or args.autoscale) and args.spmd:
        log.error("--replicas/--replica-urls/--autoscale and --spmd are "
                  "mutually exclusive (the SPMD engine already owns a "
                  "worker pool; run the fleet router over separate SPMD "
                  "services via --replica-urls from a non-SPMD front-end "
                  "instead)")
        return 2
    if args.replicas > 1 or fleet_urls or args.autoscale:
        import dataclasses

        from ollamamq_tpu.fleet import FleetRouter, HttpMember, LocalMember

        # Members serve uncapped what the router placed (the router owns
        # the fleet-wide bounded-admission caps), keep no blocklist (the
        # router blocks at ingress), and leave the journal spill AND the
        # admission WAL to the router (a member WAL would double-record
        # and double-recover every stream).
        member_cfg = dataclasses.replace(
            ecfg, max_queued=0, max_queued_per_user=0, journal_file=None,
            wal_dir=None, tiers=None, ha=False, standby_of=None)
        # Tiered fleets: members assigned to a tier that declares an
        # @tpN width START at that width; the same factory rebuilds a
        # member at a new width when the TierBalancer regroups it.
        tier_assign, tier_widths = {}, {}
        if args.tiers:
            from ollamamq_tpu.config import assign_tiers

            roster = ([(f"r{i}", args.tp) for i in range(args.replicas)]
                      + [(f"h{j}", None)
                         for j in range(len(fleet_urls))])
            tier_assign, tier_widths = assign_tiers(args.tiers, roster)

        def _member_factory(base_cfg):
            def build(tp=None):
                cfg = (base_cfg if tp in (None, base_cfg.tp)
                       else dataclasses.replace(base_cfg, tp=tp))
                if args.fake_engine:
                    from ollamamq_tpu.engine.fake import FakeEngine

                    return FakeEngine(cfg, models=models,
                                      blocklist_path=None,
                                      fairness=fairness,
                                      token_latency_s=_fake_latency())
                from ollamamq_tpu.engine.engine import TPUEngine

                return TPUEngine(cfg, models=models, blocklist_path=None,
                                 fairness=fairness)
            return build

        members = []
        for i in range(args.replicas):
            name = f"r{i}"
            width = tier_widths.get(tier_assign.get(name))
            cfg_i = (member_cfg if width in (None, member_cfg.tp)
                     else dataclasses.replace(member_cfg, tp=width))
            factory = _member_factory(cfg_i)
            members.append(LocalMember(name, factory(),
                                       engine_factory=factory))
        for j, url in enumerate(fleet_urls):
            members.append(HttpMember(f"h{j}", url,
                                      timeout_s=args.timeout))
        provisioner = None
        if args.autoscale:
            if args.fake_engine:
                # The subprocess harness: scale-ups spawn real
                # `python -m ollamamq_tpu.cli --fake-engine` servers on
                # free ports and join them as HTTP members — the same
                # member shape the docker-compose fleet runs. The
                # member config rides as argv (router-owned caps, WAL,
                # journal spill all stay OFF member-side).
                from ollamamq_tpu.fleet.autoscaler import (
                    SubprocessProvisioner)

                member_argv = [
                    "--fake-engine", "--models", args.models,
                    "--scheduler", args.scheduler,
                    "--max-slots", str(args.max_slots),
                    "--max-new-tokens", str(args.max_new_tokens),
                ]
                provisioner = SubprocessProvisioner(
                    member_argv, env={"JAX_PLATFORMS": "cpu"})
            else:
                # Real engines share the local chips: provision in-
                # process replicas from the same factory the seed
                # members use. A cloud provisioner (TPU VM create/
                # delete) drops in via FleetRouter(provisioner=...).
                from ollamamq_tpu.fleet.autoscaler import LocalProvisioner

                provisioner = LocalProvisioner(
                    _member_factory(member_cfg))
        # A standby's router must not attach a primary-side coordinator
        # at construction — it becomes one only at promotion.
        router_cfg = (dataclasses.replace(ecfg, ha=False)
                      if args.standby_of else ecfg)
        engine = FleetRouter(
            members, router_cfg, blocklist_path=args.blocklist,
            fairness=fairness, placement=args.placement,
            drain_timeout_s=args.drain_timeout_s,
            provisioner=provisioner)
        if args.standby_of:
            from ollamamq_tpu.fleet.ha import HAStandby

            standby = HAStandby(engine, args.standby_of)
            engine.ha = standby
            engine.accepting = False  # shed until promotion opens the gate
    elif args.spmd:
        import jax

        from ollamamq_tpu.parallel.mesh import make_mesh

        # SPMD with an unspecified mesh means "the whole pod": default the
        # tensor axis to all global devices so worker hosts own shards.
        tp = args.tp
        if (args.dp, args.sp, args.pp, args.ep, tp) == (1, 1, 1, 1, 1):
            tp = -1
        mesh = make_mesh(dp=args.dp, sp=args.sp, tp=tp, pp=args.pp,
                         ep=args.ep)
        if not distributed.is_primary():
            # Worker host: replay the primary's step plans until shutdown.
            from ollamamq_tpu.engine import spmd

            log.info("SPMD worker %d starting for %s",
                     jax.process_index(), model_names)
            spmd.run_worker(models, ecfg, mesh)
            return 0

        from ollamamq_tpu.engine.spmd import SPMDEngine

        engine = SPMDEngine(ecfg, models=models, blocklist_path=args.blocklist,
                            fairness=fairness, mesh=mesh)
    elif args.fake_engine:
        from ollamamq_tpu.engine.fake import FakeEngine

        engine = FakeEngine(ecfg, models=models, blocklist_path=args.blocklist,
                            fairness=fairness,
                            token_latency_s=_fake_latency())
    else:
        from ollamamq_tpu.engine.engine import TPUEngine

        engine = TPUEngine(ecfg, models=models, blocklist_path=args.blocklist,
                           fairness=fairness)
    if standby is not None:
        # The standby's router stays UNSTARTED until promotion — no
        # member probes, no placements, just the replication tail.
        # Clients shed with 503 + Retry-After (takeover-cost EMA).
        standby.start()
        log.warning("warm standby: tailing primary %s "
                    "(takeover grace %.1fs)",
                    args.standby_of, args.takeover_grace_s)
    else:
        engine.start()

    from ollamamq_tpu.server.app import Server

    server = Server(engine, timeout_s=args.timeout,
                    allow_all_routes=args.allow_all_routes)
    app = server.build_app()
    log.info("serving %s on %s:%d (tui=%s)", model_names, args.host, args.port, use_tui)

    if use_tui:
        import threading

        from aiohttp import web as aioweb

        from ollamamq_tpu.admin.tui import run_tui

        # Server on a background thread; TUI owns the terminal (main thread),
        # like the reference (main.rs:134-150). TUI exit ends the process.
        def serve():
            aioweb.run_app(app, host=args.host, port=args.port,
                           print=None, handle_signals=False)

        t = threading.Thread(target=serve, daemon=True, name="http")
        t.start()
        run_tui(engine, server.registry)
        engine.stop()
        return 0

    from aiohttp import web as aioweb

    # Signals are ours, not aiohttp's: SIGTERM/SIGINT run the zero-drop
    # drain (stop admission -> drain -> fsync journal+WAL -> exit 0)
    # instead of aiohttp's immediate GracefulExit, which would cut live
    # streams mid-generation.
    install_graceful_shutdown(engine, args.stop_grace_s)
    aioweb.run_app(app, host=args.host, port=args.port, print=None,
                   handle_signals=False)
    engine.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
