"""TPU continuous-batching engine.

This module replaces the reference's entire backend layer: where the Rust
dispatcher forwarded one request per Ollama backend over HTTP
(/root/reference/src/dispatcher.rs:496-575) and gated parallelism at
`active_requests < 1` per backend (dispatcher.rs:438), here many requests
share one forward step on the TPU:

  - admission: the engine loop pops requests from the native fair-share
    core (cpp/mqcore.cpp) whenever a model runtime has slot+page capacity —
    the queue-side policy is identical to the reference, but what's being
    scheduled is a seat in the decode batch, not a backend slot.
  - prefill: one padded-bucket forward per new request writes its prompt KV
    into paged slots and samples the first token (TTFT path).
  - decode: ONE jitted step advances every active slot by one token; when
    no admissions are pending the engine runs K steps inside a lax.scan to
    amortize host dispatch (critical: per-dispatch latency to the chip
    dominates otherwise).
  - cancellation: client disconnects free the slot and its KV pages
    immediately (reference analogue: dispatcher.rs:537-551 drops the stream
    and frees the backend; here the reclaimed resource is HBM pages).

All step functions are shape-static (fixed slot count, fixed buckets,
donated caches) => each (bucket, K) compiles exactly once.
"""

from __future__ import annotations

import collections
import copy
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ollamamq_tpu.config import (EngineConfig, ModelConfig,
                                 get_model_config, smart_match,
                                 validate_quant_config)
from ollamamq_tpu.core import MQCore, Fairness, Family
from ollamamq_tpu.core.mqcore import BlockedError, StuckQueue
from ollamamq_tpu.engine import kv_cache as kvc
from ollamamq_tpu.engine.request import FinishReason, Request, StreamItem
from ollamamq_tpu.engine.scheduler import make_policy
from ollamamq_tpu.engine.tokenizer import load_tokenizer
from ollamamq_tpu.models import llama, weights
from ollamamq_tpu.ops.sampling import (accept_prefix, maybe_apply_penalties,
                                       per_row_keys, sample_tokens_rowwise,
                                       sampling_flags)
from ollamamq_tpu.parallel import pipeline
from ollamamq_tpu.parallel.mesh import (make_mesh, replica_submesh,
                                        validate_tp_for_model)
from ollamamq_tpu.parallel.sharding import (kv_cache_spec, kv_scale_spec,
                                            shard_params)
from ollamamq_tpu.telemetry import mfu as mfu_model
from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry import stepprof
from ollamamq_tpu.telemetry.journal import Journal
from ollamamq_tpu.telemetry.slo import AlertManager, SLOEngine
from ollamamq_tpu.telemetry.tracing import DECODE_EVENT_EVERY, Tracer

log = logging.getLogger("ollamamq.engine")


def sweep_blocked(core: MQCore, held_fn, last_version: int) -> int:
    """Cancel held requests of blocked users; returns the blocklist version
    the sweep ran against. No-op (zero FFI calls beyond the version read)
    unless the blocklist changed since `last_version` — blocks are rare,
    ticks are not. Starting runtimes at version -1 makes the first tick
    sweep once, covering blocklist entries loaded from disk at startup."""
    ver = core.block_version()
    if ver == last_version:
        return ver
    held = held_fn()
    users = {r.user for r in held if not r.cancelled.is_set()}
    blocked = {u for u in users if core.is_user_or_ip_blocked(u)}
    for req in held:
        if req.user in blocked:
            req.cancelled.set()
    return ver


def drop_expired(req: Request, core: MQCore, model: str,
                 journal=None) -> None:
    """Finish an expired request with the explicit deadline reason and
    count the shed — expired queued work is dropped without burning a
    single TPU cycle on it, and the client learns WHY. The journal
    record carries the slack (how long past the deadline the drop
    happened), the input that justifies the decision."""
    core.mark_dropped(req.user, started=getattr(req, "started", True))
    tm.DEADLINE_DROPS_TOTAL.labels(model=model or "?").inc()
    tm.SHED_TOTAL.labels(reason="deadline").inc()
    if journal is not None:
        slack = ((time.monotonic() - req.deadline) * 1e3
                 if req.deadline is not None else 0.0)
        journal.record("deadline_drop", req=req, model=model or None,
                       slack_ms=round(slack, 3))
    req.finish(FinishReason.DEADLINE,
               error="deadline expired before completion")


def per_chip_stats() -> List[dict]:
    """One row per LOCAL device: id, kind, HBM in use / limit. The TUI
    chips panel and /metrics render these per chip (a v5e-16 must not
    show chip 0's counters for the whole pod). Remote hosts' chips are
    merged in by the SPMD stats path (engine/spmd.py publishes them on
    the KV store alongside the heartbeat)."""
    out = []
    try:
        for d in jax.local_devices():
            # memory_stats=False marks a backend that doesn't report HBM
            # (CPU): /metrics omits the series and the TUI renders "n/a"
            # instead of a fake 0-byte reading.
            row = {"device": str(d), "id": int(d.id),
                   "process": int(getattr(d, "process_index", 0)),
                   "hbm_used": 0, "hbm_total": 0, "memory_stats": False}
            try:
                ms = d.memory_stats()
                if ms:
                    row["hbm_used"] = int(ms.get("bytes_in_use", 0))
                    row["hbm_total"] = int(ms.get("bytes_limit", 0) or 0)
                    row["memory_stats"] = True
            except Exception:
                pass
            out.append(row)
    except Exception:
        pass
    return out


class QueueFullError(Exception):
    """Bounded admission refused an enqueue: the queue (global or this
    user's) is at its --max-queued / --max-queued-per-user cap. Carries
    the Retry-After estimate (seconds) derived from the observed
    completion rate, so the HTTP layer can answer 503/429 honestly
    instead of growing the queue unboundedly."""

    def __init__(self, scope: str, retry_after_s: float, limit: int):
        self.scope = scope  # "queue_full" | "user_queue_full"
        self.retry_after_s = retry_after_s
        self.limit = limit
        super().__init__(
            f"{scope.replace('_', ' ')}: admission cap {limit} reached; "
            f"retry after ~{retry_after_s:.0f}s")


class MigrationError(RuntimeError):
    """A KV migration import could not land (no slot / no pages / shape
    mismatch / malformed blob). The caller falls back to recompute
    replay — a failed transfer degrades, it never drops."""


def request_migration_state(req: Request) -> dict:
    """Everything a Request carries that the TARGET member of a KV
    migration needs to continue the stream seamlessly: token history,
    detokenizer text + emitted watermark (stop-string holdback included),
    degradation budgets, and the sampling params verbatim."""
    s = req.sampling
    return {
        "user": req.user, "model": req.model, "kind": req.kind,
        "raw_prompt": req.raw_prompt,
        "prompt_tokens": [int(t) for t in req.prompt_tokens],
        "generated_ids": [int(t) for t in req.generated_ids],
        "replay_gen": int(req._replay_gen),
        "emitted_len": int(req.emitted_len),
        "detok_text": req._detok_text,
        "preemptions": int(req.preemptions),
        "retries": int(req.retries),
        "sampling": {
            "temperature": s.temperature, "top_k": s.top_k,
            "top_p": s.top_p, "repeat_penalty": s.repeat_penalty,
            "presence_penalty": s.presence_penalty,
            "frequency_penalty": s.frequency_penalty,
            "seed": s.seed, "max_tokens": s.max_tokens,
            "stop": list(s.stop), "deadline_ms": s.deadline_ms,
        },
    }


def request_from_migration_state(rid: int, state: dict) -> Request:
    """Rebuild a migrated Request. Sampling fields are set RAW (seed was
    already folded into its seeded form on the source — running
    __post_init__ again would re-fold it and fork the sampled stream)."""
    from ollamamq_tpu.ops.sampling import SamplingParams

    sp = SamplingParams()
    for key, val in (state.get("sampling") or {}).items():
        setattr(sp, key, val)
    sp.stop = tuple(sp.stop or ())
    req = Request(rid, state["user"], state.get("model", ""),
                  [int(t) for t in state.get("prompt_tokens", ())], sp,
                  kind=state.get("kind", "generate"),
                  raw_prompt=state.get("raw_prompt", ""))
    req.generated_ids = [int(t) for t in state.get("generated_ids", ())]
    req._replay_gen = int(state.get("replay_gen", 0))
    req.emitted_len = int(state.get("emitted_len", 0))
    req._detok_text = state.get("detok_text", "")
    req.preemptions = int(state.get("preemptions", 0))
    req.retries = int(state.get("retries", 0))
    return req


class WorkerDesyncError(RuntimeError):
    """An SPMD status sync reported a worker-host replay failure: device
    state diverged across hosts. Unlike a local batch failure this must
    NEVER be absorbed by a fail-only-this-batch handler — the runtime has
    to be killed and reloaded on every host (engine/spmd.py raises it)."""


class PeerDeadError(WorkerDesyncError):
    """A peer host's heartbeat went stale mid-sync: the host is presumed
    dead (process kill, host loss), so the barrier would only time out —
    fail the in-flight work loudly NOW instead of waiting it out
    (reference detects a dead backend in ~10s, dispatcher.rs:385)."""


def _sp_compile_evict(rt, cache, key_) -> None:
    """faults.py "compile" site: a fired rule evicts the jit cache entry
    before the lookup, so the next fill re-traces — the injected
    recompile loop the compile_storm health alert is tested against.
    Observer-style (draw): the eviction IS the enacted fault."""
    fp = getattr(rt, "fault_plan", None)
    if fp is not None and key_ in cache and fp.draw("compile"):
        cache.pop(key_, None)


def _sp_note_compile(rt, site: str, key_, cache, fn):
    """Wrap a freshly cached jit so its FIRST call — the one jax traces
    and XLA-compiles synchronously — is timed and recorded exactly once
    per cache key: journal `compile` record, ollamamq_compile_total/
    _compile_ms, the stepprof compile ledger, and the in-flight step's
    `compiled` flag. The wrapper then replaces itself with the raw jit,
    so steady state pays nothing. `.lower` passes through for the
    Pallas AOT probes."""
    def first_call(*a, **kw):
        t0 = time.monotonic()
        out = fn(*a, **kw)
        wall_ms = (time.monotonic() - t0) * 1e3
        cache[key_] = fn
        rt._stepprof_compiled = True
        stepprof.PROFILER.record_compile(site, key_, wall_ms, len(cache))
        j = getattr(rt, "journal", None)
        if j is not None:
            j.record("compile", model=rt.name, site=site, key=str(key_),
                     wall_ms=round(wall_ms, 3), cache_size=len(cache))
        return out

    first_call.lower = fn.lower
    cache[key_] = first_call
    return first_call


def _sp_take_compiled(rt) -> bool:
    """Read-and-clear the per-step compiled flag for the sample."""
    c = getattr(rt, "_stepprof_compiled", False)
    rt._stepprof_compiled = False
    return c


def serve_embed_batch(rt, core: "MQCore", pending, max_len: int,
                      dispatch, max_batch: int = 8) -> bool:
    """Pop up to `max_batch` ready embed requests, pad to a power-of-2
    bucket, run ONE stateless forward, finish each request. The single
    batching scheme for both embedding paths (EncoderRuntime.step and
    ModelRuntime.step_embed) so they cannot drift. Returns True if ran.

    On a dispatch failure the batch's requests are errored BEFORE the
    exception propagates — a popped request must never be left hanging
    (it is in no queue _fail_runtime can see)."""
    _sp = stepprof.PROFILER.start("embed")
    journal = getattr(rt, "journal", None)

    def jfinish(req: Request, reason: str) -> None:
        if journal is not None:
            journal.record("finish", req=req, model=rt.name, reason=reason,
                           tokens=len(req.prompt_tokens))

    batch: List[Request] = []
    while pending and len(batch) < max_batch:
        if pending[0]._retry_at > time.monotonic():
            break  # head is backing off after a contained fault
        req = pending.popleft()
        if req.cancelled.is_set():
            core.mark_dropped(req.user)
            jfinish(req, "cancelled")
            req.finish(FinishReason.CANCELLED)
            continue
        if req.expired():
            # Expired queued embeds are dropped before the batch forward.
            drop_expired(req, core, rt.name, journal=journal)
            continue
        n = len(req.prompt_tokens)
        if n > max_len:
            # Reject per-request: a failed batch forward errors every
            # pending request of this runtime (cross-user blast radius,
            # ADVICE r1).
            core.mark_dropped(req.user)
            jfinish(req, "error")
            req.finish(FinishReason.ERROR,
                       error=f"input length {n} exceeds maximum {max_len}")
            continue
        batch.append(req)
    if not batch:
        return False
    for r in batch:
        r.trace_event("embed_batch", tokens=len(r.prompt_tokens))
    longest = max(len(r.prompt_tokens) for r in batch)
    bucket = 32
    while bucket < longest:
        bucket *= 2
    # Two batch buckets per length bucket (like prefill): B=1 so a lone
    # request doesn't pay max_batch x compute, B=max_batch for bursts.
    B = 1 if len(batch) == 1 else max_batch
    tokens = np.zeros((B, bucket), np.int32)
    lens = np.zeros((B,), np.int32)
    for i, r in enumerate(batch):
        tokens[i, : len(r.prompt_tokens)] = r.prompt_tokens
        lens[i] = len(r.prompt_tokens)
    _sp.mark("host_prep")
    t0 = time.monotonic()
    try:
        out_dev = dispatch(B, bucket, tokens, lens)
        _sp.mark("dispatch")
        out = np.asarray(out_dev)
        _sp.mark("collect")
    except Exception as e:
        # Retry-or-poison each implicated request where the runtime
        # offers the seam (generative ModelRuntime keeps serving after an
        # embed failure); encoders error the batch as before — the
        # exception still propagates so the caller decides runtime fate.
        retry = getattr(rt, "_retry_embed", None)
        desync = isinstance(e, WorkerDesyncError)
        for r in batch:
            if not desync and retry is not None \
                    and retry(r, f"embed failed: {e}"):
                continue
            core.mark_dropped(r.user)
            poison = getattr(rt, "_poison_msg", None)
            msg = f"embed failed: {e}"
            jfinish(r, "error")
            r.finish(FinishReason.ERROR,
                     error=poison(r, msg) if poison else msg)
        raise
    rt.step_latency_ms = (time.monotonic() - t0) * 1e3
    for i, r in enumerate(batch):
        r.embedding = out[i].tolist()
        r.stats.first_token_at = time.monotonic()
        # Count processed tokens so embeddings traffic shows up in the
        # TUI tok/s telemetry.
        rt.tokens_generated += int(lens[i])
        core.mark_done(r.user, tokens=int(lens[i]))
        jfinish(r, "stop")
        r.finish(FinishReason.STOP)
    _sp.mark("detok")
    _sp.finish(T_pad=int(bucket), k_cap=0, n_prefill=len(batch),
               n_decode=0, tokens=int(lens.sum()),
               padded_tokens=int(B) * int(bucket),
               compiled=_sp_take_compiled(rt))
    return True


class ModelRuntime:
    """Per-model decode state: KV pool, slot table, compiled step fns."""

    # Generative runtimes also serve /api/embed: the reference's Ollama
    # backends compute embeddings from causal models (llama.cpp mean
    # pooling), so embed-on-llama3 must work here too (README /api/embed).
    SERVES = ("generate", "embed")

    # SLO recording hook (telemetry/slo.py SLOEngine), attached by the
    # owning engine's load_model/_swap_rebuilt. None on SPMD worker
    # hosts' replay runtimes — SLO accounting is primary-only.
    slo = None

    # Preemption hook, attached by the owning engine (load_model /
    # _swap_rebuilt) when cfg.preempt is on: callable(req) -> bool that
    # returns the victim to the FRONT of its user's native queue and
    # re-registers it (False = the hook finished the request instead —
    # blocked/cancelled/expired). None => preemption disabled: decode
    # page exhaustion errors EXPLICITLY (kv_exhausted), never truncates.
    on_preempt = None

    # Deterministic fault injection (testing/faults.py), attached by the
    # engine when --fault-plan is set. Shared across a process's runtimes
    # so the plan's call counters form one deterministic stream.
    fault_plan = None

    # Decision journal (telemetry/journal.py), attached by the owning
    # engine's _attach_hooks. None on SPMD worker hosts' replay runtimes —
    # journaling, like SLO accounting, is primary-only.
    journal = None

    # Scheduling policy (engine/scheduler.py), attached by the owning
    # engine's _attach_hooks (bench/tests attach directly). None behaves
    # exactly like fcfs: identity orderings, legacy victim key, no
    # output-length prediction.
    policy = None

    # Engine performance plane (telemetry/stepprof.py): the per-step
    # "paid a compile" flag (_sp_note_compile sets, the step's finish
    # read-and-clears) and the step timer parked between the two halves
    # of a split decode (dispatch -> collect).
    _stepprof_compiled = False
    _sp_decode = None

    def __init__(
        self,
        name: str,
        model_cfg: ModelConfig,
        engine_cfg: EngineConfig,
        mesh=None,
        checkpoint_path: Optional[str] = None,
        dtype=jnp.bfloat16,
        preloaded_params=None,
    ):
        self.name = name
        self.cfg = model_cfg
        # Pristine config as passed in: __init__ may rewrite num_kv_heads
        # below (replicated-group KV for tp > kv_heads), and a recovery
        # rebuild must start from the UN-mutated config or it would skip
        # replication and load weights against the wrong shapes.
        self._orig_cfg = model_cfg
        self.ecfg = engine_cfg
        self.mesh = mesh
        self.dtype = dtype
        self.tokenizer = load_tokenizer(checkpoint_path)
        # Int8 quantization (weights and/or KV pages): validated here
        # too — tests and embedders construct runtimes directly, and an
        # unsupported combination must fail at build, not first dispatch.
        _pp_probe = dict(mesh.shape).get("pipe", 1) if mesh is not None else 1
        _sp_probe = dict(mesh.shape).get("seq", 1) if mesh is not None else 1
        err = validate_quant_config(
            engine_cfg.weights_dtype, engine_cfg.kv_dtype,
            pp=_pp_probe, sp=_sp_probe, model_names=(name,))
        if err is not None:
            raise ValueError(err)
        self.weights_dtype = engine_cfg.weights_dtype
        self.kv_dtype = engine_cfg.kv_dtype
        if mesh is not None and mesh.shape.get("tensor", 1) > 1:
            validate_tp_for_model(
                mesh.shape["tensor"], model_cfg.num_kv_heads, model_cfg.num_heads
            )
        # Pipeline parallelism: layers (weights + KV pages) split over the
        # mesh "pipe" axis; forwards swap to the shard_map'd GPipe schedule
        # (parallel/pipeline.py).
        self._pp = dict(mesh.shape).get("pipe", 1) if mesh is not None else 1
        if self._pp > 1:
            if model_cfg.num_layers % self._pp != 0:
                raise ValueError(
                    f"pp={self._pp} must divide num_layers="
                    f"{model_cfg.num_layers} ({name})")
            if dict(mesh.shape).get("seq", 1) > 1:
                raise ValueError(
                    "pp and sp cannot combine on one runtime: pipeline "
                    "stages and sequence shards contend for the same "
                    "activation layout (use pp x tp, or sp x tp)")
            if model_cfg.num_experts:
                raise ValueError(
                    "pp with an MoE model is not supported: the pipeline "
                    "stage body runs the dense FFN (use ep x tp for MoE)")
            # forward_embed is a plain GSPMD scan: over pipe-sharded layer
            # stacks XLA would all-gather every stage's weights into each
            # group — an OOM on exactly the >HBM models pp exists for.
            # Serve generate only; embeds get the kind-gate's clean error.
            self.SERVES = ("generate",)
            log.info("%s: pp=%d runtime serves generate only "
                     "(embed needs pipe-replicated layers)", name, self._pp)
        # Dense models on an --ep mesh are fine (their weights carry no
        # expert-axis spec, so they replicate over it); only an MoE model
        # whose expert count doesn't divide is a real layout error.
        ep = dict(mesh.shape).get("expert", 1) if mesh is not None else 1
        if ep > 1 and model_cfg.num_experts and model_cfg.num_experts % ep:
            raise ValueError(
                f"ep={ep} must divide num_experts={model_cfg.num_experts} "
                f"({name})")
        # `preloaded_params`: host-side tree shared across dp replicas so a
        # checkpoint is read/parsed once, not once per replica; each replica
        # still device_puts its own copy via shard_params below.
        params = preloaded_params if preloaded_params is not None else (
            weights.load_params(
                model_cfg, checkpoint_path, seed=engine_cfg.seed, dtype=dtype,
                weights_dtype=engine_cfg.weights_dtype,
            )
        )
        tp_axis = mesh.shape.get("tensor", 1) if mesh is not None else 1
        if tp_axis > model_cfg.num_kv_heads:
            # Replicated-group KV sharding (e.g. qwen2.5's 4 KV heads on
            # tp=8): duplicate each KV head so every shard owns one copy.
            # validate_tp_for_model already guaranteed divisibility.
            r = tp_axis // model_cfg.num_kv_heads
            params = weights.replicate_kv_heads(params, model_cfg, r)
            import dataclasses as _dc

            model_cfg = _dc.replace(model_cfg, num_kv_heads=tp_axis)
            self.cfg = model_cfg
            log.info("replicated KV heads x%d for tp=%d (%s)", r, tp_axis,
                     name)
        kv_sharding = scale_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding

            params = shard_params(params, mesh, pp=self._pp > 1)
            kv_sharding = NamedSharding(mesh, kv_cache_spec(pp=self._pp > 1))
            scale_sharding = NamedSharding(
                mesh, kv_scale_spec(pp=self._pp > 1))
        self.params = params
        self.kc, self.vc = kvc.alloc_kv_pool(
            model_cfg, engine_cfg, kv_sharding, dtype,
            kv_dtype=engine_cfg.kv_dtype, scale_sharding=scale_sharding)
        # Repeat-penalty state: ring of each slot's last-W context token ids
        # (-1 = empty), llama.cpp repeat_last_n semantics. Row S is a trash
        # row so padded/inactive scatter targets never touch a live slot.
        self.recent = jnp.full(
            (engine_cfg.max_slots + 1, engine_cfg.repeat_last_n), -1, jnp.int32
        )
        self.alloc = kvc.PageAllocator(
            engine_cfg.num_pages, engine_cfg.page_size, engine_cfg.max_pages_per_seq
        )
        # Automatic prefix caching: host-side radix tree of finished
        # prompts' full KV pages (engine/prefix_cache.py). Under SPMD only
        # the primary's admission path ever walks it — the page tables it
        # produces already broadcast on the op wire.
        self.prefix_cache = None
        if engine_cfg.prefix_cache:
            from ollamamq_tpu.engine.prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(
                engine_cfg.page_size, self.alloc, model=name,
                min_pages=engine_cfg.prefix_cache_min_pages)

        S, MP = engine_cfg.max_slots, engine_cfg.max_pages_per_seq
        # Slots mid-chunked-prefill: reserved (not schedulable) but not yet
        # decoding — slot_req stays None so decode skips them.
        self.reserved_slots: set = set()
        # Slots holding a page reservation: their request exhausted its
        # preemption budget (or no victim was eligible) when the pool ran
        # dry, so it KEEPS slot + pages but sits out decode dispatches
        # until growth succeeds — never truncated, never a victim spiral.
        self._stalled_slots: set = set()
        self._stall_since: Optional[float] = None
        self.slot_req: List[Optional[Request]] = [None] * S
        self.slot_pages: List[List[int]] = [[] for _ in range(S)]
        # Pinned prefix-cache nodes per slot (always a PREFIX of
        # slot_pages: shared tree pages first, private pages after).
        self.slot_pins: List[list] = [[] for _ in range(S)]
        self.page_table = np.full((S, MP), kvc.TRASH_PAGE, np.int32)
        self.seq_lens = np.zeros((S,), np.int32)
        self.last_tokens = np.zeros((S,), np.int32)
        self.temp = np.zeros((S,), np.float32)
        self.top_k = np.zeros((S,), np.int32)
        self.top_p = np.ones((S,), np.float32)
        self.rep_pen = np.ones((S,), np.float32)
        self.pres_pen = np.zeros((S,), np.float32)
        self.freq_pen = np.zeros((S,), np.float32)
        self.seeds = np.zeros((S,), np.int32)  # >0 = per-request seed

        self.pending_prefill: collections.deque = collections.deque()
        # Embed-kind requests: stateless batch forwards, no slot/KV claim.
        self.pending_embed: collections.deque = collections.deque()
        self._block_ver = -1  # force one startup sweep (disk-loaded blocklist)
        # Long prompts mid-chunked-prefill (one chunk advanced per tick).
        self.chunking: collections.deque = collections.deque()
        # Requests inside a prefill forward right now (cancel() must still
        # find them; installation re-checks the cancelled flag).
        self.inflight_prefill: List[Request] = []
        # Keys carry the trace-time sampling flags: (bucket, B, flags) |
        # ("chunk", C, flags) | ("sp", T, flags); decode: (k_steps, flags).
        self._prefill_jits: Dict[tuple, callable] = {}
        # name -> (content bytes, device array); see _dev().
        self._dev_cache: Dict[str, tuple] = {}
        self._decode_jits: Dict[tuple, callable] = {}
        self._embed_jits: Dict[tuple, callable] = {}
        self._rng_counter = engine_cfg.seed
        # Sequence-parallel prefill available when the mesh has a seq axis.
        self._sp = mesh is not None and mesh.shape.get("seq", 1) > 1
        # Set after an unrecoverable step failure; the engine stops stepping
        # this runtime and rebuilds it (weights reloaded) when the device
        # answers again.
        self._failed = False
        # Ragged paged-attention Pallas kernel on TPU; jnp gather fallback
        # elsewhere (and under OLLAMAMQ_NO_PALLAS=1 for A/B benching).
        no_pallas = os.environ.get("OLLAMAMQ_NO_PALLAS", "").lower() not in (
            "", "0", "false", "no",
        )
        self.attn_impl = (
            "pallas"
            if jax.default_backend() == "tpu" and not no_pallas
            else "jnp"
        )
        if (self._pp > 1 and self.attn_impl == "pallas"
                and jax.process_count() > 1):
            # The AOT compile-probe that turns a Mosaic failure into a jnp
            # fallback is single-process only (a coordinated multi-host
            # flip doesn't exist); a cold pp+pallas compile failure on a
            # pod would fail-loop the runtime. Serve jnp, say so.
            log.warning(
                "%s: pp=%d on %d processes uses the jnp paged attention "
                "(no multi-host pallas fallback path)", name, self._pp,
                jax.process_count())
            self.attn_impl = "jnp"
        # Flips true after the first successful decode dispatch; until then
        # a pallas failure falls back to jnp instead of failing the runtime.
        self._pallas_proven = False
        # Ragged mixed-batch scheduling: prefill spans + decode tokens
        # pack into ONE token-budget dispatch (no bucket padding). The
        # pipeline-parallel forward is stage-scheduled and keeps the
        # bucketed prefill path (the --attention=bucketed oracle itself
        # was removed one release after ragged shipped, as scheduled).
        self.ragged = self._pp == 1
        if self._pp > 1:
            log.warning("%s: pp=%d serves the bucketed prefill path "
                        "(the ragged forward is single-stage)", name,
                        self._pp)
        g = max(1, engine_cfg.token_granule)
        # A full decode batch (one token per slot) plus at least one
        # granule of prefill must always fit one dispatch.
        self._granule = g
        self._ragged_budget = -(-max(engine_cfg.max_batch_tokens,
                                     engine_cfg.max_slots + g) // g) * g
        # Allowed stream totals: a power-of-two ladder over the granule,
        # capped by the budget — one compile per rung (like the bucketed
        # path's per-bucket compiles, but the composer TRIMS the last
        # span down to a rung instead of padding up to one, so steady-
        # state dispatches still pay (near) zero padding).
        ladder = []
        v = g
        while v < self._ragged_budget:
            ladder.append(v)
            v *= 2
        ladder.append(self._ragged_budget)
        self._ragged_ladder = ladder

        # Speculative decoding state (--spec): n-gram drafts verified on
        # the ragged span path. Host-side accounting feeds the accept-
        # rate gauge and the per-user auto-throttle; the actual accept/
        # rollback machinery lives in _get_ragged_jit / step_ragged.
        self.spec = bool(engine_cfg.spec) and self.ragged \
            and engine_cfg.spec_k > 0
        if engine_cfg.spec and not self.ragged:
            log.warning("%s: --spec needs the ragged attention path; "
                        "speculation disabled on this runtime", name)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_rollbacks = 0
        # user -> [proposed, accepted]; users whose observed accept rate
        # under-runs --spec-min-accept after a warmup sample stop
        # speculating (the verify FLOPs stopped paying for themselves).
        self._spec_user: Dict[str, list] = {}
        self._spec_throttled: set = set()
        self._tm_spec_prop = tm.SPEC_TOKENS_TOTAL.labels(
            model=name, outcome="proposed")
        self._tm_spec_acc = tm.SPEC_TOKENS_TOTAL.labels(
            model=name, outcome="accepted")
        self._tm_spec_rej = tm.SPEC_TOKENS_TOTAL.labels(
            model=name, outcome="rejected")
        self._tm_spec_rate = tm.SPEC_ACCEPT_RATE.labels(model=name)

        # Telemetry.
        self.step_latency_ms = 0.0
        self.prefill_latency_ms = 0.0
        self.tokens_generated = 0
        self.preempt_count = 0
        self.retry_count = 0
        self.ttft_window: collections.deque = collections.deque(maxlen=512)
        self.step_window: collections.deque = collections.deque(maxlen=512)
        # Registry handles resolved once (child lookup is a dict hit, but
        # the hot path shouldn't even pay that).
        self._tm_ttft = tm.TTFT_MS.labels(model=name)
        self._tm_tpot = tm.TPOT_MS.labels(model=name)
        self._tm_step = tm.STEP_LATENCY_MS.labels(model=name)
        self._tm_prefill = tm.PREFILL_LATENCY_MS.labels(model=name)
        self._tm_occupancy = tm.BATCH_OCCUPANCY.labels(model=name)
        self._tm_padding = tm.BATCH_PADDING_WASTE.labels(model=name)
        self._tm_pages = tm.KV_PAGES_USED.labels(model=name)
        self._tm_page_util = tm.KV_PAGE_UTILIZATION.labels(model=name)
        self._tm_mfu = tm.MFU.labels(model=name)
        self._tm_tokens = tm.TOKENS_GENERATED_TOTAL.labels(model=name)
        self._tm_prompt_tokens = tm.PROMPT_TOKENS_TOTAL.labels(model=name)
        self._tm_preempt = tm.PREEMPTIONS_TOTAL.labels(model=name)
        self._tm_retries = tm.RETRIES_TOTAL.labels(model=name)
        # MFU accounting: analytic FLOPs/token (models/llama config) over
        # this runtime's share of chip peak. Unknown accelerators (CPU
        # meshes) publish 0, never a made-up peak.
        try:
            kind = jax.local_devices()[0].device_kind
        except Exception:
            kind = ""
        self.peak_flops = mfu_model.peak_flops_per_chip(kind)
        self.n_chips = int(mesh.size) if mesh is not None else 1
        self.mfu = 0.0
        # FLOPs model on the PRISTINE config: the replicated-group KV
        # rewrite above duplicates KV heads as a sharding layout trick —
        # it adds no real math.
        tm.FLOPS_PER_TOKEN.labels(model=name).set(
            mfu_model.flops_per_token(self._orig_cfg))
        self._tm_occupancy.set(0.0)
        self._tm_mfu.set(0.0)
        self.param_bytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params)
        )
        self.kv_bytes = sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves((self.kc, self.vc))
        )
        # HBM density scoreboard: what weights and KV actually cost on
        # this runtime — the quantization PR's before/after lever.
        tm.HBM_WEIGHT_BYTES.labels(model=name).set(self.param_bytes)
        tm.HBM_KV_BYTES.labels(model=name).set(self.kv_bytes)

    # -- capacity ----------------------------------------------------------
    def free_slots(self) -> int:
        return sum(
            r is None and i not in self.reserved_slots
            for i, r in enumerate(self.slot_req)
        )

    def has_capacity(self, kind: Optional[str] = None) -> bool:
        """Can we take one more request from the scheduler right now?

        Kind-aware: embeds are stateless batch forwards bounded only by
        their queue (same 4x ceiling as EncoderRuntime), while generates
        need a decode slot + KV pages — independent pools, so a full
        decode batch must not park embeds and a deep embed backlog must
        not park generates. kind=None answers "either"."""
        if self._failed:
            return False
        embed_ok = len(self.pending_embed) < 4 * self.ecfg.max_slots
        if kind == "embed":
            return embed_ok
        evictable = (self.prefix_cache.evictable_pages
                     if self.prefix_cache is not None else 0)
        gen_ok = (
            len(self.pending_prefill) < 2 * self.ecfg.max_slots
            and self.free_slots() > 0
            # Unreferenced cached pages count as capacity: allocator
            # exhaustion under a full cache evicts, never rejects.
            and self.alloc.free_pages + evictable >= 2
        )
        return gen_ok if kind == "generate" else (gen_ok or embed_ok)

    def has_work(self) -> bool:
        return (
            bool(self.pending_prefill)
            or bool(self.pending_embed)
            or bool(self.chunking)
            or any(r is not None for r in self.slot_req)
        )

    def active_count(self) -> int:
        return sum(r is not None for r in self.slot_req)

    # -- submission --------------------------------------------------------
    def submit(self, req: Request) -> bool:
        if req.kind == "embed":
            self.pending_embed.append(req)
            return True
        if getattr(req, "_inc_decode", None) is None:
            # Preserved across preemption/retry requeues: the replay
            # prompt carries already-generated ids the decoder has seen.
            req._inc_decode = self.tokenizer.make_incremental_decoder()
        self.pending_prefill.append(req)
        return True

    # -- compiled steps ----------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        """Smallest prefill bucket covering n tokens (pp > 1 prefill/
        chunk path). Oversize pieces must have been routed to the
        chunked/sequence-parallel path by the caller — silently
        answering the largest bucket here would truncate the forward's
        view of the prompt and mask a packing bug, so it must fail
        loudly, not approximately."""
        for b in self.ecfg.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"piece of {n} tokens exceeds the largest prefill bucket "
            f"{self.ecfg.prefill_buckets[-1]}; oversize prompts must take "
            "the chunked or sequence-parallel prefill path")

    def _next_key(self):
        self._rng_counter += 1
        return jax.random.PRNGKey(self._rng_counter)

    def _fault(self, site: str) -> None:
        """Fault-injection seam, called at the top of every dispatch: a
        firing rule raises (exception/device_loss) or sleeps (slow)
        BEFORE the jit call, so donated buffers are never consumed by an
        injected failure — exactly the recoverable-fault shape the
        retry/containment paths exist for."""
        if self.fault_plan is not None:
            self.fault_plan.check(site)

    # -- decision journal seams --------------------------------------------
    def _jrec(self, kind: str, req=None, **fields) -> None:
        """Journal one decision with this runtime's model name; no-op
        when no journal is attached (SPMD workers, bare unit tests)."""
        j = self.journal
        if j is not None:
            j.record(kind, req=req, model=self.name, **fields)

    def _page_state(self) -> dict:
        """Allocator post-state for page events: the inputs the
        pages-conserved invariant (free+used+cached==pool) checks."""
        a = self.alloc
        return {"free": a.free_pages, "used": a.used_pages,
                "cached": a.cached_pages, "pool": a.num_pages - 1}

    # -- dispatch seams (SPMD subclass broadcasts before dispatching) ------
    # Each returns (sampled_tokens, kc', vc', recent'); the caller assigns
    # the three state arrays back.
    def _dispatch_prefill(self, bucket, B, tokens, lens, slot_ids, pt_rows,
                          temp, tk, tp, pen, pres, freq, seeds, key):
        self._fault("prefill")
        fn = self._get_prefill_jit(
            bucket, B, sampling_flags(temp, tk, tp, pen, pres, freq)
        )
        return fn(self.params, jnp.asarray(tokens), jnp.asarray(lens),
                  self.kc, self.vc, self.recent, jnp.asarray(slot_ids),
                  jnp.asarray(pt_rows), jnp.asarray(temp), jnp.asarray(tk),
                  jnp.asarray(tp), jnp.asarray(pen), jnp.asarray(pres),
                  jnp.asarray(freq), jnp.asarray(seeds), key)

    def _dispatch_chunk(self, chunk, tokens, start, cl, slot_id, is_final,
                        is_first, seed_row, pt_row, temp, tk, tp, pen, pres,
                        freq, seeds, key):
        self._fault("chunk")
        fn = self._get_chunk_jit(
            chunk, sampling_flags(temp, tk, tp, pen, pres, freq)
        )
        return fn(self.params, jnp.asarray(tokens), jnp.asarray(start),
                  jnp.asarray(cl), self.kc, self.vc, self.recent,
                  jnp.asarray(slot_id), jnp.asarray(is_final),
                  jnp.asarray(is_first), jnp.asarray(seed_row),
                  jnp.asarray(pt_row), jnp.asarray(temp), jnp.asarray(tk),
                  jnp.asarray(tp), jnp.asarray(pen), jnp.asarray(pres),
                  jnp.asarray(freq), jnp.asarray(seeds), key)

    def _dispatch_ragged(self, T_pad, k_cap, tokens, tok_seq, tok_pos,
                         write_slots, q_start, q_len, kv_len, ring_len,
                         is_first, append, is_spec, seed_rows, slot_ids, pt,
                         temp, tk, tp, pen, pres, freq, seeds, key):
        # Speculative dispatches get their own fault site: a chaos plan
        # can target the verify span without perturbing plain mixed
        # dispatches (and vice versa).
        self._fault("spec_verify" if k_cap else "ragged")
        fn = self._get_ragged_jit(
            T_pad, k_cap, sampling_flags(temp, tk, tp, pen, pres, freq)
        )
        # Content-fingerprinted upload cache (_dev, the decode path's
        # pattern): steady-state decode/spec ticks resend near-identical
        # per-slot metadata — sampling params, page tables, seed rows,
        # span flags — every dispatch; skipping unchanged uploads takes
        # the host cost of a tick from ~20 device_puts to the handful
        # that really changed. None of these are donated by the jit.
        d = self._dev
        return fn(self.params, d("rg_tok", tokens), d("rg_seq", tok_seq),
                  d("rg_pos", tok_pos), d("rg_ws", write_slots),
                  d("rg_qs", q_start), d("rg_ql", q_len),
                  d("rg_kv", kv_len), d("rg_rl", ring_len),
                  d("rg_first", is_first), d("rg_app", append),
                  d("rg_spec", is_spec), d("rg_seed_rows", seed_rows),
                  d("rg_slots", slot_ids), d("rg_pt", pt),
                  self.kc, self.vc, self.recent,
                  d("rg_temp", temp), d("rg_tk", tk), d("rg_tp", tp),
                  d("rg_pen", pen), d("rg_pres", pres), d("rg_freq", freq),
                  d("rg_seeds", seeds), key)

    def _get_ragged_jit(self, T_pad: int, k_cap: int = 0,
                        flags=(True, True, True)):
        """ONE mixed-batch step: forward the flattened [T_pad] token
        stream (prefill spans + decode tokens + speculative verify
        spans) through forward_ragged, then per-sequence penalty-ring
        maintenance and sampling — the ragged-mode replacement for the
        prefill, chunk, AND single-step decode jits. Compiles once per
        (padded token total, draft cap, sampling flags); the engine pads
        totals to the token granule and uses only k_cap in {0, spec_k},
        so the variant count stays small.

        Speculative rows (is_spec=1) carry a (d+1)-token span
        [last_token, draft_1..draft_d]: the forward reads a logit at
        EVERY span position, greedy verification accepts the longest
        prefix where draft == argmax (ops/sampling.accept_prefix), the
        model's own next token caps the emission, and the penalty ring
        advances by the ACCEPTED count — never by k — so ring state is
        byte-identical to emitting the same tokens one step at a time.
        Returns (toks [S, k_cap+1], n_emit [S], caches', recent'): row i
        emits toks[i, :n_emit[i]]."""
        key_ = ("ragged", T_pad, k_cap, flags)
        _sp_compile_evict(self, self._prefill_jits, key_)
        if key_ not in self._prefill_jits:
            cfg, ps = self.cfg, self.ecfg.page_size
            attn_impl = self.attn_impl
            need_pen, need_mask, need_sample = flags
            O = k_cap + 1

            def fn(params, tokens, tok_seq, tok_pos, write_slots, q_start,
                   q_len, kv_len, ring_len, is_first, append, is_spec,
                   seed_rows, slot_ids, pt, kc, vc, recent, temp, tk, tp,
                   pen, pres, freq, seeds, key):
                spec = is_spec > 0
                # Logit read positions: non-spec rows read only their
                # last valid token (every column aliases it — prefill
                # spans can be longer than O); spec rows read every span
                # position, so column j holds the argmax that verifies
                # draft j+1 (and column `accepted` the bonus token).
                j = jnp.arange(O)[None, :]
                col = jnp.where(spec[:, None],
                                jnp.minimum(j, q_len[:, None] - 1),
                                q_len[:, None] - 1)
                out_idx = jnp.clip(q_start[:, None] + col, 0, T_pad - 1)
                logits, kc, vc = llama.forward_ragged(
                    params, cfg, tokens, tok_seq, tok_pos, write_slots,
                    out_idx, kc, vc, pt, q_start, q_len, kv_len, ps,
                    attn_impl=attn_impl,
                )  # [S, O, V]
                greedy_all = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                last_logits = logits[:, -1, :]
                if k_cap > 0:
                    # Draft token j+1 sits in the stream right after the
                    # span's input token; its verifier is greedy column j.
                    jj = jnp.arange(k_cap)[None, :]
                    draft_idx = jnp.clip(q_start[:, None] + 1 + jj, 0,
                                         T_pad - 1)
                    accepted = accept_prefix(tokens[draft_idx],
                                             greedy_all[:, :k_cap],
                                             q_len - 1)
                    accepted = jnp.where(spec, accepted, 0)
                else:
                    accepted = jnp.zeros(q_start.shape[0], jnp.int32)
                W = recent.shape[1]
                rows = recent[slot_ids]  # [B, W]
                # First span of a request: the ring opens from seed_rows
                # (all -1 fresh, the cached prefix's last W tokens on a
                # prefix-cache hit) — chunk-jit semantics, vectorized.
                rows = jnp.where(is_first[:, None] > 0, seed_rows, rows)
                # Slide each ring by roll_n tokens taken from the row's
                # own stream span: span length for prefill rows, 0 for
                # plain decode rows (their input token already rolled in
                # when it was sampled), and the ACCEPTED count for spec
                # rows — whose rolled tokens start one past the span's
                # input token (the accepted drafts). new[j] is
                # (rows ++ rolled)[roll_n + j] kept to the last W.
                roll_n = jnp.where(spec, accepted, ring_len)
                base = q_start + spec.astype(jnp.int32)
                j_w = jnp.arange(W)[None, :]
                cidx = roll_n[:, None] + j_w - W  # offset into the span
                stream_idx = jnp.clip(base[:, None] + cidx, 0, T_pad - 1)
                from_stream = tokens[stream_idx]  # [B, W]
                row_idx = jnp.clip(roll_n[:, None] + j_w, 0, W - 1)
                from_row = jnp.take_along_axis(rows, row_idx, axis=1)
                new_rows = jnp.where(cidx >= 0, from_stream, from_row)
                pen_logits = maybe_apply_penalties(last_logits, new_rows,
                                                   pen, pres, freq,
                                                   need_pen)
                # kv_len IS the position being sampled in both shapes:
                # n for a span ending a prompt of n tokens (prefill
                # folded seq_lens) and positions+1 for a decode row.
                row_keys = per_row_keys(key, seeds, kv_len)
                tok = sample_tokens_rowwise(pen_logits, row_keys, temp, tk,
                                            tp, need_mask, need_sample)
                if k_cap > 0:
                    # Spec rows take the model's own token at the first
                    # rejected position (or past the last accepted draft)
                    # — exactly the token non-speculative greedy would
                    # sample next. Speculation is host-gated to greedy
                    # no-penalty rows, so raw argmax IS that token.
                    spec_next = jnp.take_along_axis(
                        greedy_all, accepted[:, None], axis=1)[:, 0]
                    tok = jnp.where(spec, spec_next, tok)
                # Rows that EMIT (decode/spec rows, final prefill spans)
                # roll the final token in; mid-prefill spans do not.
                appended = jnp.concatenate([new_rows[:, 1:], tok[:, None]],
                                           axis=1)
                final_rows = jnp.where(append[:, None] > 0, appended,
                                       new_rows)
                recent = recent.at[slot_ids].set(final_rows)
                # Emitted tokens, row-major: spec rows emit the accepted
                # drafts (greedy columns 0..accepted-1 — accepted drafts
                # ARE their verifying argmaxes) plus the bonus token at
                # column `accepted`; every other row emits column 0.
                n_emit = jnp.where(spec, accepted + 1, 1)
                col0 = jnp.where(spec, greedy_all[:, 0], tok)
                toks = jnp.concatenate([col0[:, None], greedy_all[:, 1:]],
                                       axis=1)
                return toks, n_emit, kc, vc, recent

            _sp_note_compile(self, "ragged", key_, self._prefill_jits,
                             jax.jit(fn, donate_argnums=(15, 16, 17)))
        return self._prefill_jits[key_]

    def _dev(self, name: str, arr) -> jnp.ndarray:
        """Content-fingerprinted device cache for small per-slot arrays.

        The decode hot loop re-dispatches the same sampling params, page
        table, and active mask for many consecutive chunks; re-uploading
        9 host arrays per dispatch costs milliseconds of host work (and a
        transfer each) for bytes that rarely change. A tobytes() compare
        (~us for [slots]-sized arrays) skips the upload when content is
        identical — self-correcting, no dirty-flag bookkeeping to miss a
        mutation site. None of these buffers are donated by the jits, so
        reuse across calls is safe."""
        a = np.asarray(arr)
        b = a.tobytes()
        hit = self._dev_cache.get(name)
        if hit is not None and hit[0] == b:
            return hit[1]
        dev = jnp.asarray(a)
        self._dev_cache[name] = (b, dev)
        return dev

    def _dispatch_decode(self, k_steps, tokens, positions, active, pt, temp,
                         tk, tp, pen, pres, freq, seeds, key):
        self._fault("decode")
        fn = self._get_decode_jit(
            k_steps, sampling_flags(temp, tk, tp, pen, pres, freq)
        )
        return fn(self.params, jnp.asarray(tokens), jnp.asarray(positions),
                  self.kc, self.vc, self.recent, self._dev("active", active),
                  self._dev("pt", pt), self._dev("temp", temp),
                  self._dev("tk", tk), self._dev("tp", tp),
                  self._dev("pen", pen), self._dev("pres", pres),
                  self._dev("freq", freq), self._dev("seeds", seeds), key)

    def _get_prefill_jit(self, bucket: int, batch: int = 1,
                         flags=(True, True, True)):
        key_ = (bucket, batch, flags)
        _sp_compile_evict(self, self._prefill_jits, key_)
        if key_ not in self._prefill_jits:
            cfg, ps = self.cfg, self.ecfg.page_size
            need_pen, need_mask, need_sample = flags
            pp, mesh = self._pp, self.mesh
            n_micro = self.ecfg.pp_microbatches

            def fn(params, tokens, seq_lens, kc, vc, recent, slot_ids, pt,
                   temp, tk, tp, pen, pres, freq, seeds, key):
                if pp > 1:
                    logits, kc, vc = pipeline.pp_forward_prefill(
                        params, cfg, tokens, seq_lens, kc, vc, pt, ps, mesh,
                        n_micro=n_micro,
                    )
                else:
                    logits, kc, vc = llama.forward_prefill(
                        params, cfg, tokens, seq_lens, kc, vc, pt, ps
                    )
                B, T = tokens.shape
                W = recent.shape[1]
                # Ring rows = the last W prompt tokens of each sequence.
                idx = seq_lens[:, None] - W + jnp.arange(W)[None, :]  # [B,W]
                gathered = jnp.take_along_axis(
                    tokens, jnp.clip(idx, 0, T - 1), axis=1
                )
                rows = jnp.where(idx >= 0, gathered, -1)
                pen_logits = maybe_apply_penalties(logits, rows, pen, pres,
                                                   freq, need_pen)
                row_keys = per_row_keys(key, seeds, seq_lens)
                tok = sample_tokens_rowwise(pen_logits, row_keys, temp, tk,
                                            tp, need_mask, need_sample)
                rows = jnp.concatenate([rows[:, 1:], tok[:, None]], axis=1)
                recent = recent.at[slot_ids].set(rows)
                return tok, kc, vc, recent

            _sp_note_compile(self, "prefill", key_, self._prefill_jits,
                             jax.jit(fn, donate_argnums=(3, 4, 5)))
        return self._prefill_jits[key_]

    def _get_chunk_jit(self, chunk: int, flags=(True, True, True)):
        """Chunked prefill step for prompts longer than the largest bucket:
        each call writes one chunk's K/V and attends over the prefix. The
        returned sampled token is only meaningful for the final chunk."""
        _sp_compile_evict(self, self._prefill_jits, ("chunk", chunk, flags))
        if ("chunk", chunk, flags) not in self._prefill_jits:
            cfg, ps = self.cfg, self.ecfg.page_size
            need_pen, need_mask, need_sample = flags
            pp, mesh = self._pp, self.mesh
            n_micro = self.ecfg.pp_microbatches

            def fn(params, tokens, start, chunk_lens, kc, vc, recent, slot_id,
                   is_final, is_first, seed_row, pt, temp, tk, tp, pen, pres,
                   freq, seeds, key):
                if pp > 1:
                    logits, kc, vc = pipeline.pp_forward_prefill_chunk(
                        params, cfg, tokens, start, chunk_lens, kc, vc, pt,
                        ps, mesh, n_micro=n_micro,
                    )
                else:
                    logits, kc, vc = llama.forward_prefill_chunk(
                        params, cfg, tokens, start, chunk_lens, kc, vc, pt, ps
                    )
                C = tokens.shape[1]
                W = recent.shape[1]
                row = recent[slot_id[0]]  # [W]
                # First chunk of a request: the penalty ring starts from
                # seed_row — all -1 for a fresh prompt, the cached
                # prefix's last W tokens on a prefix-cache hit (start > 0
                # then, so this can't key off start == 0). Travels on the
                # SPMD wire like every other input, so hosts stay in step.
                row = jnp.where(is_first[0] > 0, seed_row[0], row)
                # Slide the window: prev ++ this chunk's valid tokens, then
                # keep the last W (dynamic shift by chunk_len).
                chunk_toks = jnp.where(
                    jnp.arange(C) < chunk_lens[0], tokens[0], -1
                )
                combined = jnp.concatenate([row, chunk_toks])  # [W+C]
                row = jax.lax.dynamic_slice(combined, (chunk_lens[0],), (W,))
                pen_logits = maybe_apply_penalties(logits, row[None], pen,
                                                   pres, freq, need_pen)
                row_keys = per_row_keys(key, seeds, start + chunk_lens)
                tok = sample_tokens_rowwise(pen_logits, row_keys, temp, tk,
                                            tp, need_mask, need_sample)
                # Append the sampled token only on the final chunk.
                row_f = jnp.concatenate([row[1:], tok])
                row = jnp.where(is_final[0] > 0, row_f, row)
                recent = recent.at[slot_id[0]].set(row)
                return tok, kc, vc, recent

            _sp_note_compile(self, "chunk", ("chunk", chunk, flags),
                             self._prefill_jits,
                             jax.jit(fn, donate_argnums=(4, 5, 6)))
        return self._prefill_jits[("chunk", chunk, flags)]

    def _dispatch_prefill_sp(self, T, tokens, lens, slot_ids, pt_rows,
                             temp, tk, tp, pen, pres, freq, seeds, key):
        self._fault("sp_prefill")
        fn = self._get_sp_prefill_jit(
            T, sampling_flags(temp, tk, tp, pen, pres, freq)
        )
        return fn(self.params, jnp.asarray(tokens), jnp.asarray(lens),
                  self.kc, self.vc, self.recent, jnp.asarray(slot_ids),
                  jnp.asarray(pt_rows), jnp.asarray(temp), jnp.asarray(tk),
                  jnp.asarray(tp), jnp.asarray(pen), jnp.asarray(pres),
                  jnp.asarray(freq), jnp.asarray(seeds), key)

    def _get_sp_prefill_jit(self, T: int, flags=(True, True, True)):
        """Sequence-parallel long-prompt prefill: the whole prompt in one
        forward with activations sharded along T over the mesh "seq" axis
        (ring attention rotates K/V blocks over ICI —
        models/llama.py:forward_prefill_sp), then the returned K/V stacks
        scatter into the slot's pages. One compile per padded length T."""
        key_ = ("sp", T, flags)
        _sp_compile_evict(self, self._prefill_jits, key_)
        if key_ not in self._prefill_jits:
            cfg, ps, mesh = self.cfg, self.ecfg.page_size, self.mesh
            need_pen, need_mask, need_sample = flags

            def fn(params, tokens, seq_lens, kc, vc, recent, slot_ids, pt,
                   temp, tk, tp, pen, pres, freq, seeds, key):
                logits, k_stack, v_stack = llama.forward_prefill_sp(
                    params, cfg, tokens, seq_lens, mesh
                )
                # Scatter K/V (k_stack: [L, 1, T, Hk, hd]) into the paged
                # pool; positions past the real length land in the trash
                # page (pt rows beyond the allocation already hold it).
                t = jnp.arange(T)
                page_idx = pt[0, t // ps]
                page_idx = jnp.where(t < seq_lens[0], page_idx, kvc.TRASH_PAGE)
                dest = page_idx * ps + (t % ps)
                kc = kc.at[:, dest].set(k_stack[:, 0].astype(kc.dtype))
                vc = vc.at[:, dest].set(v_stack[:, 0].astype(vc.dtype))
                # First-token sampling + recent ring, as in batched prefill.
                W = recent.shape[1]
                idx = seq_lens[:, None] - W + jnp.arange(W)[None, :]
                gathered = jnp.take_along_axis(
                    tokens, jnp.clip(idx, 0, T - 1), axis=1
                )
                rows = jnp.where(idx >= 0, gathered, -1)
                pen_logits = maybe_apply_penalties(logits, rows, pen, pres,
                                                   freq, need_pen)
                row_keys = per_row_keys(key, seeds, seq_lens)
                tok = sample_tokens_rowwise(pen_logits, row_keys, temp, tk,
                                            tp, need_mask, need_sample)
                rows = jnp.concatenate([rows[:, 1:], tok[:, None]], axis=1)
                recent = recent.at[slot_ids].set(rows)
                return tok, kc, vc, recent

            _sp_note_compile(self, "sp_prefill", key_, self._prefill_jits,
                             jax.jit(fn, donate_argnums=(3, 4, 5)))
        return self._prefill_jits[key_]

    def _prefill_sp(self, req: Request, slot: int, n: int, core: MQCore) -> None:
        """Run the sequence-parallel prefill for one long prompt and install
        the slot. Caller has claimed the slot and allocated pages."""
        s = req.sampling
        sp = self.mesh.shape["seq"]
        largest = self.ecfg.prefill_buckets[-1]
        unit = -(-largest // sp) * sp  # bucket rounded up to sp-divisible
        T = -(-n // unit) * unit  # padded length, divisible by sp
        self.page_table[slot, :] = kvc.make_page_table_row(
            self.slot_pages[slot], self.ecfg.max_pages_per_seq
        )
        tokens = np.zeros((1, T), np.int32)
        tokens[0, :n] = req.prompt_tokens
        self.inflight_prefill = [req]  # cancel() must still find it
        req.trace_event("prefill", mode="sp", tokens=n)
        t0 = time.monotonic()
        try:
            tok, self.kc, self.vc, self.recent = self._dispatch_prefill_sp(
                T, tokens, np.asarray([n], np.int32),
                np.asarray([slot], np.int32), self.page_table[slot:slot + 1],
                np.asarray([s.temperature], np.float32),
                np.asarray([s.top_k], np.int32),
                np.asarray([s.top_p], np.float32),
                np.asarray([s.repeat_penalty], np.float32),
                np.asarray([s.presence_penalty], np.float32),
                np.asarray([s.frequency_penalty], np.float32),
                np.asarray([s.seed], np.int32),
                self._next_key(),
            )
        except Exception as e:
            # Contain the failure to THIS request (the batched path does the
            # same): release the never-installed slot's pages — _fail_runtime
            # would miss them since slot_req[slot] is still None — retry it
            # once, and keep every other in-flight request alive.
            log.exception("sequence-parallel prefill failed for req %d",
                          req.req_id, extra={"req_id": req.req_id})
            self._release_slot_pages(slot)
            desync = isinstance(e, WorkerDesyncError)
            if desync or not self._retry_requeue(
                    req, self.pending_prefill, f"sp prefill failed: {e}"):
                core.mark_dropped(req.user)
                req.finish(FinishReason.ERROR, error=self._poison_msg(
                    req, f"sp prefill failed: {e}"))
            if desync:
                raise  # diverged SPMD state: the runtime must kill+reload
            return
        finally:
            self.inflight_prefill = []
        self.prefill_latency_ms = (time.monotonic() - t0) * 1e3
        self._tm_prefill.observe(self.prefill_latency_ms)
        self._install_slot(slot, req, n, int(np.asarray(tok)[0]), core)

    def _get_decode_jit(self, k_steps: int, flags=(True, True, True)):
        key_ = (k_steps, flags)
        _sp_compile_evict(self, self._decode_jits, key_)
        if key_ not in self._decode_jits:
            cfg, ps = self.cfg, self.ecfg.page_size
            attn_impl = self.attn_impl
            need_pen, need_mask, need_sample = flags
            pp, mesh = self._pp, self.mesh
            n_micro = self.ecfg.pp_microbatches

            def fn(params, tokens, positions, kc, vc, recent, active, pt,
                   temp, tk, tp, pen, pres, freq, seeds, key):
                S = tokens.shape[0]

                def step(carry, _):
                    tokens, positions, kc, vc, recent, key = carry
                    if pp > 1:
                        # Pallas runs per-device inside the stage; the AOT
                        # probe in step_decode_dispatch covers this path
                        # too (a Mosaic failure flips to jnp as usual).
                        logits, kc, vc = pipeline.pp_forward_decode(
                            params, cfg, tokens, positions, kc, vc, pt, ps,
                            mesh, n_micro=n_micro, attn_impl=attn_impl,
                        )
                    else:
                        logits, kc, vc = llama.forward_decode(
                            params, cfg, tokens, positions, kc, vc, pt, ps,
                            attn_impl=attn_impl, active=active,
                        )
                    key, sub = jax.random.split(key)
                    pen_logits = maybe_apply_penalties(logits, recent[:S],
                                                       pen, pres, freq,
                                                       need_pen)
                    # Seeded streams fold in the position of the token being
                    # SAMPLED (positions holds the incoming token's slot):
                    # prefill folded n for the token at n, so the first
                    # decode step must fold n+1, not n, or the two
                    # consecutive sampling decisions share a key.
                    row_keys = per_row_keys(sub, seeds, positions + 1)
                    nxt = sample_tokens_rowwise(pen_logits, row_keys, temp,
                                                tk, tp, need_mask,
                                                need_sample)
                    # Roll the sampled token into ACTIVE slots' rings only —
                    # reserved (mid-chunked-prefill) slots must not collect
                    # garbage tokens.
                    rolled = jnp.concatenate(
                        [recent[:S, 1:], nxt[:, None]], axis=1
                    )
                    new_rows = jnp.where(active[:, None] > 0, rolled, recent[:S])
                    recent = recent.at[:S].set(new_rows)
                    return (nxt, positions + 1, kc, vc, recent, key), nxt

                (tokens, positions, kc, vc, recent, key), toks = jax.lax.scan(
                    step, (tokens, positions, kc, vc, recent, key), None,
                    length=k_steps,
                )
                return toks, kc, vc, recent  # toks: [K, S]

            _sp_note_compile(self, "decode", key_, self._decode_jits,
                             jax.jit(fn, donate_argnums=(3, 4, 5)))
        return self._decode_jits[key_]

    # -- slot lifecycle ----------------------------------------------------
    def _clear_slot(self, slot: int) -> None:
        """Reset a slot's sampling rows and bookkeeping (pages must be
        released by the caller — finish and preempt release differently)."""
        self.seq_lens[slot] = 0
        self.temp[slot] = 0.0
        self.top_k[slot] = 0
        self.top_p[slot] = 1.0
        self.rep_pen[slot] = 1.0
        self.pres_pen[slot] = 0.0
        self.freq_pen[slot] = 0.0
        self.seeds[slot] = 0
        self.slot_req[slot] = None
        self._stalled_slots.discard(slot)

    def _finish_slot(
        self, slot: int, reason: FinishReason, core: MQCore,
        flush: bool = True, error: str = "",
    ) -> None:
        """`flush=False` on the stop-string path: held-back text contains the
        stop sequence the client asked to suppress."""
        req = self.slot_req[slot]
        if req is None:
            return
        pol = self.policy
        extra = ({"predicted_tokens": pol.predict(req)}
                 if pol is not None else {})
        self._jrec("finish", req, reason=reason.value, slot=slot,
                   tokens=len(req.generated_ids), **extra)
        if pol is not None and reason in (FinishReason.STOP,
                                          FinishReason.LENGTH):
            # Served-to-completion outcomes feed the output-length
            # predictor; cancels/errors would teach it client behavior.
            pol.observe_finish(req, model=self.name)
        # Pass req: an installed slot's prompt KV is fully written, so
        # its full prompt pages are insertable into the prefix cache.
        self._release_slot_pages(slot, req)
        self._clear_slot(slot)
        req.stats.completion_tokens = len(req.generated_ids)
        if reason == FinishReason.CANCELLED:
            core.mark_dropped(req.user)
        elif reason in (FinishReason.KV_EXHAUSTED, FinishReason.ERROR,
                        FinishReason.DEADLINE):
            # Honest failure: the client keeps the text generated so far
            # (flushed) but the request counts dropped, not processed.
            if flush:
                chunk = req.flush_text()
                if chunk:
                    req.stream.push(StreamItem("token", text=chunk))
            core.mark_dropped(req.user)
        else:
            if flush:
                chunk = req.flush_text()
                if chunk:
                    req.stream.push(StreamItem("token", text=chunk))
            core.mark_done(req.user, tokens=len(req.generated_ids))
        req.finish(reason, error=error)

    def _emit_token(self, slot: int, tok: int, core: MQCore) -> bool:
        """Process one sampled token for a slot. Returns True if seq continues."""
        req = self.slot_req[slot]
        if req is None:
            return False
        if req.cancelled.is_set() or req.stream.overflowed:
            # Overflowed stream == consumer stopped reading == client gone.
            self._finish_slot(slot, FinishReason.CANCELLED, core)
            return False
        if tok == self.tokenizer.eos_id:
            self._finish_slot(slot, FinishReason.STOP, core)
            return False
        req.generated_ids.append(tok)
        if not req.stats.first_token_at:
            req.stats.first_token_at = time.monotonic()
            self.ttft_window.append(req.stats.ttft_ms)
            self._tm_ttft.observe(req.stats.ttft_ms)
            if self.slo is not None:
                self.slo.record("ttft", req.stats.ttft_ms)
            req.trace_event("first_token", ttft_ms=round(req.stats.ttft_ms, 3))
        elif len(req.generated_ids) % DECODE_EVENT_EVERY == 0:
            req.trace_event("decode", tokens=len(req.generated_ids))
        text = req._inc_decode(tok)
        chunk = req.emit_text(text) if text else ""
        if chunk is None:  # stop string fired: suppress held-back text
            self._finish_slot(slot, FinishReason.STOP, core, flush=False)
            return False
        # Push EVERY sampled token, text or not (held-back bytes mid
        # UTF-8 sequence, stop-string holdback): the id stream must be
        # complete for the fleet's token-space failover replay — text
        # consumers already skip empty chunks.
        req.stream.push(StreamItem("token", text=chunk, token_id=tok))
        # Stream-write stall attribution: a consumer backlog above the
        # high-water mark opens a "stream" span on the trace; dropping
        # back under closes it. Transition-edged so the event cap isn't
        # chewed up by a persistently slow reader.
        depth = req.stream.depth()
        if not req._stream_stalled and depth >= req.stream.high_water:
            req._stream_stalled = True
            req.trace_event("stream_stall", depth=depth)
        elif req._stream_stalled and depth < req.stream.high_water // 2:
            req._stream_stalled = False
            req.trace_event("stream_resume", depth=depth)
        if len(req.generated_ids) >= req.sampling.max_tokens:
            self._finish_slot(slot, FinishReason.LENGTH, core)
            return False
        max_ctx = min(self.ecfg.max_context, self.cfg.max_seq_len)
        if int(self.seq_lens[slot]) + 1 >= max_ctx:
            self._finish_slot(slot, FinishReason.LENGTH, core)
            return False
        return True

    # -- steps -------------------------------------------------------------
    MAX_PREFILL_BATCH = 4

    def step_prefill(self, core: MQCore) -> bool:
        """Admit pending requests into free slots. Same-bucket prompts
        prefill TOGETHER in one forward (up to MAX_PREFILL_BATCH), which
        collapses the cold-start TTFT of a burst of arrivals. Long prompts
        hand off to the incremental chunked path. Returns True if ran."""
        if self.policy is not None:
            # Decision point (a): slot-admission order. fcfs/None is a
            # no-op; srpt/edf stable-sort the released queue in place.
            self.policy.reorder_pending(self.pending_prefill)
        batch: List[tuple] = []  # (req, slot, pages, n)
        bucket = None
        claimed: set = set()
        largest = self.ecfg.prefill_buckets[-1]
        while self.pending_prefill and len(batch) < self.MAX_PREFILL_BATCH:
            req = self.pending_prefill[0]
            if req.cancelled.is_set():
                self.pending_prefill.popleft()
                core.mark_dropped(req.user)
                self._jrec("finish", req, reason="cancelled")
                req.finish(FinishReason.CANCELLED)
                continue
            if req._retry_at > time.monotonic():
                break  # head is backing off after a contained fault
            if req.expired():
                # Deadline check BEFORE the prefill dispatch: expired
                # queued work is dropped without burning TPU time.
                self.pending_prefill.popleft()
                drop_expired(req, core, self.name, journal=self.journal)
                continue
            n = len(req.prompt_tokens)
            # Prompts beyond the largest bucket stream through chunked
            # prefill; the hard ceiling is the paged context itself.
            max_prompt = min(self.ecfg.max_context - 1, self.cfg.max_seq_len - 1)
            if n > max_prompt:
                self.pending_prefill.popleft()
                core.mark_dropped(req.user)  # mark_started ran at admission
                self._jrec("finish", req, reason="error")
                req.finish(
                    FinishReason.ERROR,
                    error=f"prompt length {n} exceeds maximum {max_prompt}",
                )
                continue
            # Prefix-cache lookup: pin the longest cached full-page prefix
            # and prefill only the uncached tail through the chunked path.
            # SP runtimes keep their one-shot ring-attention forward for
            # prompts beyond the largest bucket.
            if (self.prefix_cache is not None
                    and not (self._sp and n > largest)):
                nodes, shared = self._match_prefix(req.prompt_tokens)
                if nodes:
                    if batch:
                        break  # run the collected batch first
                    slot = self._claim_slot(claimed)
                    if slot is None:
                        return False
                    # Pin BEFORE the tail allocation: its eviction
                    # backstop must never reclaim the very pages we
                    # matched.
                    self.prefix_cache.pin(nodes)
                    tail = self._alloc_tail(len(shared), n + 1)
                    if tail is None:
                        self.prefix_cache.release(nodes)
                        return False  # wait for frees
                    self.pending_prefill.popleft()
                    req.stats.prefill_started_at = time.monotonic()
                    prefix_len = len(shared) * self.ecfg.page_size
                    self.slot_pins[slot] = list(nodes)
                    self.slot_pages[slot] = list(shared) + tail
                    self.prefix_cache.note_hit(prefix_len)
                    req.trace_event("prefix_hit", cached_tokens=prefix_len,
                                    tokens=n)
                    req._pt_row = kvc.make_page_table_row(
                        self.slot_pages[slot], self.ecfg.max_pages_per_seq
                    )[None, :]
                    # The tail rides the chunked path starting at
                    # prefix_len; decode writes start past the shared
                    # pages, so they stay read-only (no copy-on-write).
                    req._chunk_pos = prefix_len
                    req._chunk_base = prefix_len
                    req._prefill_slot = slot
                    self.reserved_slots.add(slot)
                    self.chunking.append(req)
                    return True
            if n > largest:
                if batch:
                    break  # run the collected batch first; chunk next tick
                slot = self._claim_slot(claimed)
                if slot is None:
                    return False
                pages = self._alloc_pages(n + 1)
                if pages is None:
                    return False
                self.pending_prefill.popleft()
                self._pc_miss()
                req.stats.prefill_started_at = time.monotonic()
                self.slot_pages[slot] = pages
                if self._sp:
                    # Sequence-parallel prefill: ONE forward with the
                    # sequence sharded over the mesh "seq" axis (ring
                    # attention over ICI) instead of serial chunks —
                    # SURVEY §5 long-context row.
                    self._prefill_sp(req, slot, n, core)
                    return True
                # The row stays OFF the shared page table until the final
                # chunk installs the slot: interleaved decode steps write
                # every slot's position through self.page_table, and a
                # reserved slot must keep pointing at the trash page or the
                # chunk's KV would be stomped.
                req._pt_row = kvc.make_page_table_row(
                    pages, self.ecfg.max_pages_per_seq
                )[None, :]
                # Incremental chunked prefill: ONE chunk per engine tick so
                # concurrent decode streams keep flowing. _chunk_base reset
                # explicitly: a retry/preemption re-admission may have left
                # a cache-hit base from its previous life.
                req._chunk_pos = 0
                req._chunk_base = 0
                req._prefill_slot = slot
                self.reserved_slots.add(slot)
                self.chunking.append(req)
                return True
            b = self._bucket_for(n)
            if bucket is None:
                bucket = b
            elif b != bucket:
                break  # different bucket: next tick's batch
            slot = self._claim_slot(claimed)
            if slot is None:
                break
            pages = self._alloc_pages(n + 1)
            if pages is None:
                break  # pool exhausted; run what we have, retry after frees
            self.pending_prefill.popleft()
            self._pc_miss()
            req.stats.prefill_started_at = time.monotonic()
            self.slot_pages[slot] = pages
            self.page_table[slot, :] = kvc.make_page_table_row(
                pages, self.ecfg.max_pages_per_seq
            )
            claimed.add(slot)
            batch.append((req, slot, pages, n))

        if not batch:
            return False

        # Pad multi-request batches to the fixed MAX so each bucket compiles
        # at most twice (B=1 for sparse traffic, B=MAX for bursts); padding
        # rows use trash-page tables and zero lengths, so the extra compute
        # is bounded and writes land in the trash page.
        B = 1 if len(batch) == 1 else self.MAX_PREFILL_BATCH
        pt_rows = np.full(
            (B, self.ecfg.max_pages_per_seq), kvc.TRASH_PAGE, np.int32
        )
        tokens = np.zeros((B, bucket), np.int32)
        lens = np.zeros((B,), np.int32)
        temp = np.zeros((B,), np.float32)
        top_k = np.zeros((B,), np.int32)
        top_p = np.ones((B,), np.float32)
        pen = np.ones((B,), np.float32)
        pres = np.zeros((B,), np.float32)
        freq = np.zeros((B,), np.float32)
        seeds = np.zeros((B,), np.int32)
        # Padding rows target the trash ring-row (index max_slots), never a
        # live slot.
        slot_ids = np.full((B,), self.ecfg.max_slots, np.int32)
        for i, (req, slot, _, n) in enumerate(batch):
            tokens[i, :n] = req.prompt_tokens
            lens[i] = n
            temp[i] = req.sampling.temperature
            top_k[i] = req.sampling.top_k
            top_p[i] = req.sampling.top_p
            pen[i] = req.sampling.repeat_penalty
            pres[i] = req.sampling.presence_penalty
            freq[i] = req.sampling.frequency_penalty
            seeds[i] = req.sampling.seed
            slot_ids[i] = slot
            pt_rows[i] = self.page_table[slot]
        self.inflight_prefill = [req for req, *_ in batch]
        for req, _, _, n in batch:
            req.trace_event("prefill", bucket=bucket, tokens=n)
        # Batch-compose decision record: who shares this forward, the
        # padded shape it pays for, and the occupancy/backlog inputs the
        # composition saw — the offline analyzer's padding-waste and
        # occupancy stats read straight off these.
        real_tokens = int(sum(n for *_, n in batch))
        self._jrec("batch",
                   slots=[slot for _, slot, _, _ in batch],
                   reqs=[req.req_id for req, *_ in batch],
                   bucket=bucket, batch_size=B,
                   tokens=real_tokens,
                   occupancy=round(self.active_count()
                                   / max(1, self.ecfg.max_slots), 4),
                   pending=len(self.pending_prefill),
                   free_pages=self.alloc.free_pages,
                   mode="bucketed", padded_tokens=int(bucket * B))
        self._tm_padding.set(
            round(1.0 - real_tokens / max(1, bucket * B), 4))
        t0 = time.monotonic()
        try:
            toks, self.kc, self.vc, self.recent = self._dispatch_prefill(
                bucket, B, tokens, lens, slot_ids, pt_rows, temp, top_k,
                top_p, pen, pres, freq, seeds, self._next_key(),
            )
            toks = np.asarray(toks)
        except Exception as e:
            # Contain the failure to THIS batch: free its pages, then give
            # each implicated request one retried dispatch (with backoff)
            # before poisoning it — one bad input or transient device
            # fault must neither kill bystanders nor crash-loop.
            desync = isinstance(e, WorkerDesyncError)
            for req, slot, pages, _ in batch:
                self._release_slot_pages(slot)
                if desync or not self._retry_requeue(
                        req, self.pending_prefill, f"prefill failed: {e}"):
                    core.mark_dropped(req.user)
                    req.finish(FinishReason.ERROR, error=self._poison_msg(
                        req, f"prefill failed: {e}"))
            self.inflight_prefill = []
            log.exception("batched prefill failed (bucket=%d B=%d)", bucket, B)
            if desync:
                raise  # diverged SPMD state: the runtime must kill+reload
            return True
        finally:
            self.inflight_prefill = []
        self.prefill_latency_ms = (time.monotonic() - t0) * 1e3
        self._tm_prefill.observe(self.prefill_latency_ms)

        for i, (req, slot, _, n) in enumerate(batch):
            self._install_slot(slot, req, n, int(toks[i]), core)
        return True

    def _claim_slot(self, claimed: set) -> Optional[int]:
        for i, r in enumerate(self.slot_req):
            if r is None and i not in claimed and i not in self.reserved_slots:
                return i
        return None

    # -- prefix-cache seams ------------------------------------------------
    def _match_prefix(self, tokens: List[int]):
        """(nodes, pages) of the longest cached prefix, or ([], []) when
        below the reuse threshold."""
        nodes, pages = self.prefix_cache.match(tokens)
        if len(nodes) < self.prefix_cache.min_pages:
            return [], []
        return nodes, pages

    def _pc_miss(self) -> None:
        if self.prefix_cache is not None:
            self.prefix_cache.note_miss()

    def _alloc_pages(self, num_tokens: int) -> Optional[List[int]]:
        """alloc() with the prefix-cache eviction backstop: free-list
        exhaustion reclaims unreferenced cached pages (LRU sweep) instead
        of failing admission."""
        if self.fault_plan is not None and self.fault_plan.blocked("alloc"):
            return None  # injected allocation pressure
        pages = self.alloc.alloc(num_tokens)
        if pages is None and self.prefix_cache is not None:
            short = self.alloc.pages_needed(num_tokens) - self.alloc.free_pages
            if short > 0:
                freed = self.prefix_cache.evict(short)
                if freed > 0:
                    self._jrec("page_evict", n=freed, **self._page_state())
                    pages = self.alloc.alloc(num_tokens)
        if pages is not None:
            self._jrec("page_alloc", n=len(pages), **self._page_state())
        return pages

    def _alloc_tail(self, held: int, num_tokens: int) -> Optional[List[int]]:
        """Private tail pages for a cache-hit admission already holding
        `held` shared pages; same eviction backstop as _alloc_pages."""
        need = self.alloc.pages_needed(num_tokens) - held
        pages = self.alloc.alloc_n(need, held=held)
        if pages is None and self.prefix_cache is not None:
            short = need - self.alloc.free_pages
            if short > 0:
                freed = self.prefix_cache.evict(short)
                if freed > 0:
                    self._jrec("page_evict", n=freed, **self._page_state())
                    pages = self.alloc.alloc_n(need, held=held)
        if pages is not None:
            self._jrec("page_alloc", n=len(pages), **self._page_state())
        return pages

    def _extend_pages(self, pages: List[int], new_total_tokens: int) -> bool:
        """Decode-time page growth with the eviction backstop."""
        if self.fault_plan is not None and self.fault_plan.blocked("extend"):
            return False  # injected allocation pressure
        before = len(pages)
        if self.alloc.extend(pages, new_total_tokens):
            if len(pages) > before:
                self._jrec("page_alloc", n=len(pages) - before,
                           **self._page_state())
            return True
        if self.prefix_cache is None:
            return False
        need = self.alloc.pages_needed(new_total_tokens) - len(pages)
        if need <= 0 or len(pages) + need > self.alloc.max_pages_per_seq:
            return False  # per-seq cap: eviction can't help
        freed = self.prefix_cache.evict(need - self.alloc.free_pages)
        if freed > 0:
            self._jrec("page_evict", n=freed, **self._page_state())
            if self.alloc.extend(pages, new_total_tokens):
                if len(pages) > before:
                    self._jrec("page_alloc", n=len(pages) - before,
                               **self._page_state())
                return True
        return False

    def _release_slot_pages(self, slot: int,
                            req: Optional[Request] = None) -> None:
        """Free a slot's KV pages and reset its page-table row.

        With the prefix cache on: always release the slot's pins; when
        the finishing request is known (`req` passed — the slot was
        installed, so the prompt's KV is fully written) its full prompt
        pages MERGE into the tree instead of returning to the free list.
        Callers without a req (mid-prefill cancel, runtime failure) free
        every private page and only unpin."""
        pages = self.slot_pages[slot]
        pc = self.prefix_cache
        if pc is None:
            n_freed = len(pages)
            self.alloc.free(pages)
            if n_freed:
                self._jrec("page_free", n=n_freed, slot=slot,
                           **self._page_state())
        else:
            pins = self.slot_pins[slot]
            keep = len(pins)  # shared tree pages lead slot_pages
            if req is not None and req.prompt_tokens:
                full = min(len(req.prompt_tokens) // self.ecfg.page_size,
                           len(pages))
                if full > keep:
                    pc.insert(req.prompt_tokens, pages[:full])
                    keep = full
            n_freed = len(pages) - keep
            self.alloc.free(pages[keep:])
            pc.release(pins)
            self.slot_pages[slot] = []
            self.slot_pins[slot] = []
            if n_freed > 0:
                self._jrec("page_free", n=n_freed, slot=slot,
                           **self._page_state())
        self.page_table[slot, :] = kvc.TRASH_PAGE

    def _install_slot(self, slot: int, req: Request, n: int, tok: int,
                      core: MQCore) -> None:
        """Activate a freshly prefilled request in its decode slot and emit
        the first sampled token."""
        self._jrec("install", req, slot=slot, n_prompt=n)
        self.slot_req[slot] = req
        self._tm_prompt_tokens.inc(n)
        self.seq_lens[slot] = n
        self.temp[slot] = req.sampling.temperature
        self.top_k[slot] = req.sampling.top_k
        self.top_p[slot] = req.sampling.top_p
        self.rep_pen[slot] = req.sampling.repeat_penalty
        self.pres_pen[slot] = req.sampling.presence_penalty
        self.freq_pen[slot] = req.sampling.frequency_penalty
        self.seeds[slot] = req.sampling.seed
        self.tokens_generated += 1
        if self._emit_token(slot, tok, core):
            # Token written at position n during the next decode step.
            self.last_tokens[slot] = tok
            self.seq_lens[slot] = n

    # -- KV page migration (fleet export/import; engine-thread only) -------
    def export_request(self, rid: int):
        """Snapshot + DETACH one installed decode slot for migration.
        Returns (handle, blob) or None when `rid` holds no installed slot
        (queued / mid-prefill / chunking work replays cheaply via
        recompute — only written decode state is worth shipping). The
        detached slot keeps its pages (reserved, undispatchable) until
        release_export resolves the two-phase handoff."""
        for slot, req in enumerate(self.slot_req):
            if req is not None and req.req_id == rid:
                break
        else:
            return None
        blob = self._migration_snapshot(slot, req)
        self.slot_req[slot] = None
        self.reserved_slots.add(slot)
        self._stalled_slots.discard(slot)
        return {"slot": slot, "req": req}, blob

    def _migration_snapshot(self, slot: int, req: Request) -> dict:
        """The portable wire state of one decode slot: its page run
        (int8 payload + scale rows for quantized pools — ~2x cheaper to
        move), the decode cursor (written kv_len + the pending last
        token, mirroring the install convention), the penalty ring row,
        request state, and the scheduler predictor's view of the user."""
        pages = list(self.slot_pages[slot])
        data = kvc.gather_page_run(self.kc, self.vc, pages,
                                   self.ecfg.page_size)
        blob = {
            "version": 1, "kind": "stream", "model": self.name,
            "kv_dtype": self.kv_dtype, "page_size": self.ecfg.page_size,
            "num_layers": self.cfg.num_layers,
            "num_kv_heads": self.cfg.num_kv_heads,
            "head_dim": self.cfg.head_dim,
            "kv_len": int(self.seq_lens[slot]),
            "last_token": int(self.last_tokens[slot]),
            "n_pages": len(pages),
            "recent": np.asarray(self.recent[slot]),
            "request": request_migration_state(req),
            # In-process handoff carries the live incremental detokenizer
            # (exact stream continuity); the wire packer drops it and the
            # importer builds a fresh one off the carried detok text.
            "_inc_decode": req._inc_decode,
            **data,
        }
        pol = self.policy
        if pol is not None:
            blob["predictor"] = pol.predictor.export_user(req.user)
        return blob

    def release_export(self, handle: dict) -> None:
        """Resolve a detached export (commit OR abort): the pages go the
        same way a finished slot's do — full prompt pages merge into the
        prefix cache (a recompute fallback then replays mostly from
        cache), the rest return to the free list."""
        slot, req = handle["slot"], handle["req"]
        self.reserved_slots.discard(slot)
        self._release_slot_pages(slot, req)
        self._clear_slot(slot)

    def import_request(self, blob: dict, req: Request) -> bool:
        """Install a migrated stream into a fresh slot from shipped
        state: allocate a same-length page run, scatter the wire pages
        into this pool, and resume the decode cursor exactly where the
        source froze it — no token is ever recomputed. False when the
        blob's shape doesn't match this runtime or capacity is gone
        (the caller falls back to recompute replay)."""
        if (blob.get("kind") != "stream"
                or int(blob.get("page_size", -1)) != self.ecfg.page_size
                or blob.get("kv_dtype") != self.kv_dtype
                or int(blob.get("num_layers", -1)) != self.cfg.num_layers
                or int(blob.get("num_kv_heads", -1)) != self.cfg.num_kv_heads
                or int(blob.get("head_dim", -1)) != self.cfg.head_dim):
            return False
        n = int(blob["n_pages"])
        if n <= 0 or n > self.alloc.max_pages_per_seq:
            return False
        slot = self._claim_slot(set())
        if slot is None:
            return False
        pages = self._alloc_tail(0, n * self.ecfg.page_size)
        if pages is None:
            return False
        self.kc, self.vc = kvc.scatter_page_run(
            self.kc, self.vc, pages, self.ecfg.page_size, blob)
        self.recent = self.recent.at[slot].set(
            jnp.asarray(np.asarray(blob["recent"], np.int32)))
        self.slot_pages[slot] = pages
        self.slot_pins[slot] = []
        self.page_table[slot, :] = kvc.make_page_table_row(
            pages, self.ecfg.max_pages_per_seq)
        s = req.sampling
        self.slot_req[slot] = req
        self.seq_lens[slot] = int(blob["kv_len"])
        self.last_tokens[slot] = int(blob["last_token"])
        self.temp[slot] = s.temperature
        self.top_k[slot] = s.top_k
        self.top_p[slot] = s.top_p
        self.rep_pen[slot] = s.repeat_penalty
        self.pres_pen[slot] = s.presence_penalty
        self.freq_pen[slot] = s.frequency_penalty
        self.seeds[slot] = s.seed
        if req._inc_decode is None:
            req._inc_decode = self.tokenizer.make_incremental_decoder()
        pol = self.policy
        if pol is not None and blob.get("predictor"):
            pol.predictor.import_user(req.user, blob["predictor"])
        self._jrec("install", req, slot=slot,
                   n_prompt=len(req.prompt_tokens))
        return True

    def export_prefix(self, tokens: List[int]):
        """Affinity-miss prefix shipping, source side: the longest cached
        full-page prefix of `tokens` as a wire blob (pages pinned only
        for the device->host copy). None when nothing caches."""
        pc = self.prefix_cache
        if pc is None:
            return None
        nodes, pages = pc.match(list(tokens))
        if not pages:
            return None
        pc.pin(nodes)
        try:
            data = kvc.gather_page_run(self.kc, self.vc, pages,
                                       self.ecfg.page_size)
        finally:
            pc.release(nodes)
        ps = self.ecfg.page_size
        return {
            "version": 1, "kind": "prefix", "model": self.name,
            "kv_dtype": self.kv_dtype, "page_size": ps,
            "num_layers": self.cfg.num_layers,
            "num_kv_heads": self.cfg.num_kv_heads,
            "head_dim": self.cfg.head_dim,
            "n_pages": len(pages),
            "prefix_tokens": [int(t) for t in tokens[:len(pages) * ps]],
            **data,
        }

    def import_prefix(self, blob: dict) -> int:
        """Affinity-miss prefix shipping, target side: land shipped
        prefix pages in this pool and merge them into the radix tree, so
        the request admitted next prefills only the tail. Plain alloc_n
        (no eviction backstop): shipping a remote prefix must never
        evict locally-earned cache. Returns pages adopted (0 = no-op)."""
        pc = self.prefix_cache
        if (pc is None or blob.get("kind") != "prefix"
                or int(blob.get("page_size", -1)) != self.ecfg.page_size
                or blob.get("kv_dtype") != self.kv_dtype
                or int(blob.get("num_layers", -1)) != self.cfg.num_layers
                or int(blob.get("num_kv_heads", -1)) != self.cfg.num_kv_heads
                or int(blob.get("head_dim", -1)) != self.cfg.head_dim):
            return 0
        n = int(blob["n_pages"])
        pages = self.alloc.alloc_n(n) if n > 0 else None
        if pages is None:
            return 0
        self._jrec("page_alloc", n=n, **self._page_state())
        self.kc, self.vc = kvc.scatter_page_run(
            self.kc, self.vc, pages, self.ecfg.page_size, blob)
        return pc.insert([int(t) for t in blob["prefix_tokens"]], pages)

    # -- speculative decoding (n-gram draft + ragged verify) ---------------
    # Accept-rate warmup sample per user before the auto-throttle may
    # fire, and how far back the n-gram proposer searches (longer
    # contexts still match — recency wins — but the scan stays O(window)
    # per tick, never O(context)).
    SPEC_THROTTLE_SAMPLE = 64
    SPEC_LOOKUP_WINDOW = 1024
    SPEC_NGRAMS = (3, 2)

    def _spec_eligible(self, req: Request) -> bool:
        """Speculation is host-gated to rows whose sampling the greedy
        verifier reproduces exactly: temperature 0 (argmax) with neutral
        penalties — a penalized row's argmax depends on the ring state
        at EACH draft position, which the single-dispatch verify does
        not replay. Sampled/penalized requests stay 1-token decode rows
        (byte-identical either way); throttled users sit out."""
        s = req.sampling
        return (s.temperature == 0.0 and s.repeat_penalty == 1.0
                and s.presence_penalty == 0.0 and s.frequency_penalty == 0.0
                and req.user not in self._spec_throttled)

    def _propose_drafts(self, req: Request, slot: int) -> List[int]:
        """Prompt-lookup draft proposal: match the context's trailing
        n-gram (n in SPEC_NGRAMS, longest first) against its most recent
        earlier occurrence and propose the tokens that followed — free
        (no second model, no device work) and strong exactly when the
        model is reproducing earlier text (repetitive generation, quote-
        the-prompt workloads). Returns [] when nothing matches or no
        budget remains; caps at spec_k, the request's remaining token
        budget, and the context ceiling."""
        k = self.ecfg.spec_k
        remaining = req.sampling.max_tokens - len(req.generated_ids) - 1
        max_ctx = min(self.ecfg.max_context, self.cfg.max_seq_len)
        pos = int(self.seq_lens[slot])
        k = min(k, remaining, max_ctx - pos - 2)
        if k <= 0:
            return []
        # Full token history as the decoder saw it: a preempted request
        # folded already-streamed ids into prompt_tokens, so only the
        # post-replay generated tail appends.
        ctx = req.prompt_tokens + req.generated_ids[req._replay_gen:]
        lo = max(0, len(ctx) - self.SPEC_LOOKUP_WINDOW)
        for n in self.SPEC_NGRAMS:
            if len(ctx) - lo < n + 1:
                continue
            key = ctx[-n:]
            for s in range(len(ctx) - n - 1, lo - 1, -1):
                if ctx[s:s + n] == key:
                    drafts = ctx[s + n:s + n + k]
                    if drafts:
                        return list(drafts)
                    break
        return []

    def _note_spec_outcome(self, req: Request, proposed: int,
                           accepted: int) -> None:
        """Per-dispatch speculative accounting: totals, the accept-rate
        gauge, and the per-user auto-throttle — a user whose drafts keep
        getting rejected stops paying the (proposed - accepted) wasted
        verify tokens on every dispatch."""
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self._tm_spec_prop.inc(proposed)
        self._tm_spec_acc.inc(accepted)
        self._tm_spec_rej.inc(proposed - accepted)
        if self.spec_proposed:
            self._tm_spec_rate.set(
                round(self.spec_accepted / self.spec_proposed, 4))
        row = self._spec_user.setdefault(req.user, [0, 0])
        row[0] += proposed
        row[1] += accepted
        min_rate = self.ecfg.spec_min_accept
        if (min_rate > 0 and row[0] >= self.SPEC_THROTTLE_SAMPLE
                and row[1] / row[0] < min_rate
                and req.user not in self._spec_throttled):
            self._spec_throttled.add(req.user)
            log.info("%s: speculation throttled for user %s (accept rate "
                     "%.2f < %.2f over %d proposed)", self.name, req.user,
                     row[1] / row[0], min_rate, row[0])

    def _rollback_spec(self, slot: int, req: Request, kv_before: int,
                       kv_after: int) -> None:
        """Release the page claim of rejected draft tokens: the slot
        keeps exactly the pages its ACCEPTED context needs. Shared
        prefix-tree pages lead slot_pages and are floored out of the
        truncation — speculation must never free a page the radix tree
        owns. Rejected positions on device need no un-write: they sit
        past the rolled-back kv_len, masked by attention and overwritten
        by the next real decode step."""
        self.spec_rollbacks += 1
        keep = len(self.slot_pins[slot])
        freed = self.alloc.rollback_to(self.slot_pages[slot], kv_after,
                                       keep=keep)
        if freed:
            self.page_table[slot, :] = kvc.make_page_table_row(
                self.slot_pages[slot], self.ecfg.max_pages_per_seq)
        self._jrec("spec_rollback", req, slot=slot, kv_before=kv_before,
                   kv_after=kv_after, freed=freed, **self._page_state())

    def _drop_expired_slot(self, slot: int, core: MQCore) -> None:
        """Deadline enforcement at the speculative composer: an expired
        request must not burn a k-token verify span (the same
        before-the-dispatch check prefill and chunking already make).
        The slot finishes with the explicit deadline reason — text
        streamed so far flushes, the drop counts as dropped work."""
        req = self.slot_req[slot]
        tm.DEADLINE_DROPS_TOTAL.labels(model=self.name).inc()
        tm.SHED_TOTAL.labels(reason="deadline").inc()
        slack = ((time.monotonic() - req.deadline) * 1e3
                 if req.deadline is not None else 0.0)
        self._jrec("deadline_drop", req, slack_ms=round(slack, 3))
        self._finish_slot(slot, FinishReason.DEADLINE, core,
                          error="deadline expired before completion")

    # -- preemption with recompute -----------------------------------------
    KV_EXHAUSTED_MSG = ("KV page pool exhausted mid-decode and preemption "
                       "is disabled; retry, shorten the prompt, or raise "
                       "--num-pages")

    def _pick_victim(self) -> Optional[int]:
        """Victim slot for a preemption. Decision point (c) of the
        scheduler policy: eligibility stays here — NEVER the VIP, never
        a request that spent its preemption budget (anti-livelock: it
        holds a reservation) — while the preference among eligible slots
        is the policy's victim_key (max wins). fcfs/None keeps the
        legacy heuristic: lowest fair-share priority first (the user
        with the most lifetime served requests), youngest arrival as
        tie-break. Stalled reservation-holders under budget still
        qualify — they hold pages too. None = nobody is preemptible."""
        vip = None
        users: dict = {}
        try:
            snap = self.core_snapshot_for_preempt()
            vip = snap.get("vip")
            users = snap.get("users", {})
        except Exception:
            pass  # degraded victim pick (age only) beats no preemption
        pol = self.policy
        best, best_key = None, None
        for i, r in enumerate(self.slot_req):
            if r is None or r.preemptions >= self.ecfg.preempt_max:
                continue
            if vip is not None and r.user == vip:
                continue
            served = users.get(r.user, {}).get("processed", 0)
            key = (pol.victim_key(r, served) if pol is not None
                   else (served, r.stats.enqueued_at))
            if best_key is None or key > best_key:
                best, best_key = i, key
        if best is not None and pol is not None and pol.name != "fcfs":
            victim = self.slot_req[best]
            self._jrec("sched", victim, policy=pol.name, point="victim",
                       predicted=pol.predict(victim),
                       score=round(pol.remaining(victim), 3))
        return best

    # Seam for _pick_victim's policy inputs: the engine loop owns `core`
    # only inside step calls, so the snapshot source is stashed per call.
    def core_snapshot_for_preempt(self) -> dict:
        core = getattr(self, "_preempt_core", None)
        return core.snapshot() if core is not None else {}

    def _preempt_slot(self, slot: int, core: MQCore) -> None:
        """Evict `slot` for recompute: snapshot prompt + generated tokens,
        merge the WRITTEN KV pages into the prefix cache (re-admission
        then replays mostly from cache), free the rest, and hand the
        request to the engine's requeue-front hook. The stream, the
        incremental detokenizer, and generated_ids survive untouched, so
        the client sees one seamless token stream across the preemption."""
        req = self.slot_req[slot]
        self.preempt_count += 1
        self._tm_preempt.inc()
        req.preemptions += 1
        # KV is written for prompt + all generated tokens but the LAST
        # sampled one (its write belongs to the decode step that never
        # ran). The replay prompt carries that token too — its KV is
        # recomputed by the re-prefill, and the forward samples the NEXT
        # token, continuing the stream exactly where it stopped.
        replay = req.prompt_tokens + req.generated_ids[req._replay_gen:]
        written = len(replay) - 1 if req.generated_ids else len(replay)
        req.trace_event("preempt", slot=slot, tokens=written,
                        n=req.preemptions)
        if self.journal is not None:
            # Decision inputs: pool pressure plus the victim's fair-share
            # standing (most-served user loses) and the VIP it must never
            # be — the explainability contract for every preemption.
            vip, served = None, None
            try:
                snap = self.core_snapshot_for_preempt()
                vip = snap.get("vip")
                served = snap.get("users", {}).get(req.user, {}).get(
                    "processed")
            except Exception:
                pass
            self._jrec("preempt", req, slot=slot, why="kv_pressure",
                       n=req.preemptions, free_pages=self.alloc.free_pages,
                       victim_served=served, vip=vip)
        req.prompt_tokens = replay[:written]
        self._release_slot_pages(slot, req if written else None)
        req.prompt_tokens = replay
        req._replay_gen = len(req.generated_ids)
        self._clear_slot(slot)
        hook = self.on_preempt
        if hook is not None:
            hook(req)  # False => hook finished it (blocked/expired)

    def _page_exhausted(self, slot: int, need_tokens: int,
                        core: MQCore) -> None:
        """Decode-time page growth failed for `slot`. Never a silent
        LENGTH: preempt a victim and retry, stall on a reservation, or —
        with preemption off — error explicitly as kv_exhausted. A genuine
        per-sequence context-cap hit is still an honest LENGTH (that IS
        the context budget, not pool pressure)."""
        pages = self.slot_pages[slot]
        if (self.alloc.pages_needed(need_tokens) > self.alloc.max_pages_per_seq
                or len(pages) >= self.alloc.max_pages_per_seq):
            self._finish_slot(slot, FinishReason.LENGTH, core)
            return
        if self.on_preempt is None or not self.ecfg.preempt:
            tm.SHED_TOTAL.labels(reason="kv_exhausted").inc()
            self._finish_slot(slot, FinishReason.KV_EXHAUSTED, core,
                              error=self.KV_EXHAUSTED_MSG)
            return
        self._preempt_core = core
        try:
            # Bounded: each pass preempts one victim or gives up — at
            # most one pass per occupied slot, so an injected/persistent
            # extend failure can't spin this loop forever.
            for _ in range(len(self.slot_req)):
                victim = self._pick_victim()
                if victim is None:
                    # Nobody preemptible: hold the reservation (slot +
                    # pages), sit out dispatches until pages free up.
                    self.slot_req[slot].trace_event(
                        "kv_stall", pages=len(pages))
                    self._jrec("kv_stall", self.slot_req[slot], slot=slot,
                               free_pages=self.alloc.free_pages,
                               need=need_tokens)
                    self._stalled_slots.add(slot)
                    return
                self._preempt_slot(victim, core)
                if self.slot_req[slot] is None:
                    return  # this slot WAS the victim
                if self._extend_pages(pages, need_tokens):
                    self._stalled_slots.discard(slot)
                    return
            self.slot_req[slot].trace_event("kv_stall", pages=len(pages))
            self._jrec("kv_stall", self.slot_req[slot], slot=slot,
                       free_pages=self.alloc.free_pages, need=need_tokens)
            self._stalled_slots.add(slot)
        finally:
            self._preempt_core = None

    # Reservation-holders may only stall this long with the whole batch
    # blocked before the youngest is failed loudly (full-deadlock escape;
    # any other slot finishing or a client cancel clears it sooner).
    STALL_BREAK_S = 5.0

    def _break_stall_deadlock(self, core: MQCore) -> None:
        """Every active slot is a stalled reservation-holder and has been
        for STALL_BREAK_S: nothing can finish, so nothing will ever free
        pages. Fail the youngest reservation with the explicit exhaustion
        error rather than wedging the runtime."""
        youngest = max(self._stalled_slots,
                       key=lambda i: self.slot_req[i].stats.enqueued_at)
        tm.SHED_TOTAL.labels(reason="kv_exhausted").inc()
        log.warning("breaking KV-reservation deadlock: failing slot %d "
                    "(req %d)", youngest, self.slot_req[youngest].req_id)
        self._finish_slot(youngest, FinishReason.KV_EXHAUSTED, core,
                          error=self.KV_EXHAUSTED_MSG)

    # -- fault-retry containment -------------------------------------------
    def _retry_requeue(self, req: Request, queue: collections.deque,
                       msg: str) -> bool:
        """Queue a fault-implicated request for ONE more attempt on this
        runtime (front of the pending queue, exponential backoff) —
        False once its budget is spent or it's already gone (caller
        errors it: poisoned inputs must not crash-loop the engine)."""
        if req.retries >= self.ecfg.step_retries or req.cancelled.is_set():
            return False
        req.retries += 1
        self.retry_count += 1
        self._tm_retries.inc()
        req._retry_at = time.monotonic() + (
            self.ecfg.retry_backoff_s * (2 ** (req.retries - 1)))
        req.trace_event("retry", error=msg[:200], n=req.retries)
        self._jrec("retry", req, n=req.retries, error=msg[:120])
        queue.appendleft(req)
        return True

    def _retry_embed(self, req: Request, msg: str) -> bool:
        return self._retry_requeue(req, self.pending_embed, msg)

    def _poison_msg(self, req: Request, msg: str) -> str:
        """Error text for a request whose retry budget is spent: the
        client (and the log) must see that retries happened and stopped
        on purpose."""
        self._jrec("poison", req, retries=req.retries, error=msg[:120])
        if req.retries:
            return (f"{msg} (request poisoned after {req.retries} "
                    f"retr{'y' if req.retries == 1 else 'ies'})")
        return msg

    def step_chunk(self, core: MQCore) -> bool:
        """Advance ONE chunk of one long-prompt prefill. Returns True if a
        chunk ran (the engine loop interleaves these with decode steps)."""
        if not self.chunking:
            return False
        req = self.chunking[0]
        slot = req._prefill_slot
        largest = self.ecfg.prefill_buckets[-1]
        n = len(req.prompt_tokens)

        if req.cancelled.is_set() or req.stream.overflowed:
            self.chunking.popleft()
            self._release_slot_pages(slot)
            self.reserved_slots.discard(slot)
            core.mark_dropped(req.user)
            self._jrec("finish", req, reason="cancelled")
            req.finish(FinishReason.CANCELLED)
            return True
        if req.expired():
            # Deadline passed mid-chunked-prefill: stop burning chunks on
            # a response nobody will wait for.
            self.chunking.popleft()
            self._release_slot_pages(slot)
            self.reserved_slots.discard(slot)
            drop_expired(req, core, self.name, journal=self.journal)
            return True

        s = req.sampling
        chunk_start = req._chunk_pos
        base = getattr(req, "_chunk_base", 0)  # >0: cached-prefix tail
        # Chunk size = smallest bucket covering the remainder (compiles
        # once per bucket, like batched prefill): a short cache-hit tail
        # must not pay a largest-bucket forward.
        piece = req.prompt_tokens[chunk_start:chunk_start + largest]
        cl = len(piece)
        chunk = self._bucket_for(cl)
        tokens = np.zeros((1, chunk), np.int32)
        tokens[0, :cl] = piece
        is_first = 1 if chunk_start == base else 0
        W = self.ecfg.repeat_last_n
        seed_row = np.full((1, W), -1, np.int32)
        if is_first and chunk_start > 0:
            # Cache hit: the penalty ring opens with the cached prefix's
            # last W tokens, exactly as a full prefill would set it.
            prev = req.prompt_tokens[max(0, chunk_start - W):chunk_start]
            seed_row[0, W - len(prev):] = prev
        req.trace_event("prefill_chunk", pos=chunk_start, tokens=cl)
        self._jrec("chunk", req, slot=slot, pos=chunk_start, tokens=cl,
                   cached=base)
        t0 = time.monotonic()
        is_final = 1 if chunk_start + cl >= n else 0
        try:
            tok, self.kc, self.vc, self.recent = self._dispatch_chunk(
                chunk, tokens,
                np.asarray([chunk_start], np.int32), np.asarray([cl], np.int32),
                np.asarray([slot], np.int32), np.asarray([is_final], np.int32),
                np.asarray([is_first], np.int32), seed_row,
                req._pt_row,
                np.asarray([s.temperature], np.float32),
                np.asarray([s.top_k], np.int32),
                np.asarray([s.top_p], np.float32),
                np.asarray([s.repeat_penalty], np.float32),
                np.asarray([s.presence_penalty], np.float32),
                np.asarray([s.frequency_penalty], np.float32),
                np.asarray([s.seed], np.int32),
                self._next_key(),
            )
        except Exception as e:
            # Contain to THIS request: release the reserved slot's pages
            # (and pinned prefix), retry once from scratch, else poison.
            log.exception("chunked prefill failed for req %d",
                          req.req_id, extra={"req_id": req.req_id})
            self.chunking.popleft()
            self._release_slot_pages(slot)
            self.reserved_slots.discard(slot)
            desync = isinstance(e, WorkerDesyncError)
            if desync or not self._retry_requeue(
                    req, self.pending_prefill, f"prefill failed: {e}"):
                core.mark_dropped(req.user)
                req.finish(FinishReason.ERROR, error=self._poison_msg(
                    req, f"prefill failed: {e}"))
            if desync:
                raise  # diverged SPMD state: the runtime must kill+reload
            return True
        self.prefill_latency_ms = (time.monotonic() - t0) * 1e3
        self._tm_prefill.observe(self.prefill_latency_ms)
        req._chunk_pos = chunk_start + cl
        if req._chunk_pos < n:
            return True  # more chunks next tick

        # Final chunk: publish the page-table row (decode may write through
        # it from now on), install the slot, emit the first token.
        self.chunking.popleft()
        self.reserved_slots.discard(slot)
        self.page_table[slot, :] = req._pt_row[0]
        self._install_slot(slot, req, n, int(np.asarray(tok)[0]), core)
        return True

    # -- ragged mixed-batch scheduling -------------------------------------
    def _admit_ragged(self, core: MQCore) -> bool:
        """Admission for the ragged path: claim a reserved slot + the
        full page allocation for each pending prompt and queue it on
        `chunking` — EVERY prefill rides the span path, sized each tick
        by the token budget instead of a bucket. Prefix-cache hits pin
        their shared pages and start the span at the cached boundary.
        Returns True if anything was admitted."""
        if self.policy is not None:
            # Decision point (a): slot-admission order out of the
            # released queue (fcfs/None: untouched FIFO).
            self.policy.reorder_pending(self.pending_prefill)
        did = False
        largest = self.ecfg.prefill_buckets[-1]
        while self.pending_prefill:
            req = self.pending_prefill[0]
            if req.cancelled.is_set():
                self.pending_prefill.popleft()
                core.mark_dropped(req.user)
                self._jrec("finish", req, reason="cancelled")
                req.finish(FinishReason.CANCELLED)
                continue
            if req._retry_at > time.monotonic():
                break  # head is backing off after a contained fault
            if req.expired():
                self.pending_prefill.popleft()
                drop_expired(req, core, self.name, journal=self.journal)
                continue
            n = len(req.prompt_tokens)
            max_prompt = min(self.ecfg.max_context - 1,
                             self.cfg.max_seq_len - 1)
            if n > max_prompt:
                self.pending_prefill.popleft()
                core.mark_dropped(req.user)
                self._jrec("finish", req, reason="error")
                req.finish(
                    FinishReason.ERROR,
                    error=f"prompt length {n} exceeds maximum {max_prompt}",
                )
                continue
            if self._sp and n > largest:
                # Long prompts on a sequence-parallel mesh keep the
                # one-shot ring-attention prefill (its activations shard
                # over the seq axis; the ragged stream does not).
                slot = self._claim_slot(set())
                if slot is None:
                    return did
                pages = self._alloc_pages(n + 1)
                if pages is None:
                    return did
                self.pending_prefill.popleft()
                self._pc_miss()
                req.stats.prefill_started_at = time.monotonic()
                self.slot_pages[slot] = pages
                self._prefill_sp(req, slot, n, core)
                return True
            nodes, shared = ([], [])
            if self.prefix_cache is not None:
                nodes, shared = self._match_prefix(req.prompt_tokens)
            slot = self._claim_slot(set())
            if slot is None:
                break
            if nodes:
                # Pin BEFORE the tail allocation: its eviction backstop
                # must never reclaim the very pages we matched.
                self.prefix_cache.pin(nodes)
                tail = self._alloc_tail(len(shared), n + 1)
                if tail is None:
                    self.prefix_cache.release(nodes)
                    break  # wait for frees
                prefix_len = len(shared) * self.ecfg.page_size
                self.slot_pins[slot] = list(nodes)
                self.slot_pages[slot] = list(shared) + tail
                self.prefix_cache.note_hit(prefix_len)
                req.trace_event("prefix_hit", cached_tokens=prefix_len,
                                tokens=n)
                req._chunk_pos = prefix_len
                req._chunk_base = prefix_len
            else:
                pages = self._alloc_pages(n + 1)
                if pages is None:
                    break  # pool exhausted; retry after frees
                self._pc_miss()
                self.slot_pages[slot] = pages
                req._chunk_pos = 0
                req._chunk_base = 0
            self.pending_prefill.popleft()
            req.stats.prefill_started_at = time.monotonic()
            # The row stays OFF the shared page table until install —
            # decode steps write through self.page_table and a reserved
            # slot must keep pointing at the trash page meanwhile.
            req._pt_row = kvc.make_page_table_row(
                self.slot_pages[slot], self.ecfg.max_pages_per_seq
            )[None, :]
            req._prefill_slot = slot
            self.reserved_slots.add(slot)
            self.chunking.append(req)
            did = True
        return did

    def _drop_chunking(self, req: Request, slot: int) -> None:
        """Remove a span-path request (cancel/overflow): release its
        pages + reservation without finishing it (caller decides)."""
        try:
            self.chunking.remove(req)
        except ValueError:
            pass
        self._release_slot_pages(slot)
        self.reserved_slots.discard(slot)

    def step_ragged(self, core: MQCore) -> bool:
        """ONE ragged mixed-batch tick: admit pending prompts, then pack
        every live decode slot (one token each — or, with --spec, a
        (1+k)-token speculative verify span) plus as many prefill-span
        tokens as the --max-batch-tokens budget allows into a single
        dispatch — prompts of any length mix freely, and the only
        padding is the stream total rounding up to the token granule.
        Returns True when a mixed dispatch ran (decode slots advanced
        inside it); False leaves decode to the fused-scan path.
        """
        # Step profiler: phases are contiguous marks of one timer, so an
        # early return or a faulted dispatch just abandons it — no
        # partial samples in the ring.
        _sp = stepprof.PROFILER.start("ragged")
        self._admit_ragged(core)
        if not self.chunking and not self.spec:
            return False
        if not self.chunking and not any(r is not None
                                         for r in self.slot_req):
            return False

        # Decode-row page headroom, as step_decode_dispatch does per
        # chunk (reservation-holders get their retry first). Speculating
        # slots claim headroom for their whole draft span OPTIMISTICALLY
        # — rejected drafts' pages roll back after the verify — but a
        # draft is dropped, never stalled on, when the pool can't cover
        # it: speculation is an optimization, not a page priority.
        for i in sorted(self._stalled_slots):
            if self.slot_req[i] is None:
                self._stalled_slots.discard(i)
            elif self._extend_pages(self.slot_pages[i],
                                    int(self.seq_lens[i]) + 1):
                self._stalled_slots.discard(i)
        spec_plan: Dict[int, List[int]] = {}  # slot -> draft tokens
        n_active = sum(1 for i, r in enumerate(self.slot_req)
                       if r is not None and i not in self._stalled_slots)
        # Draft budget: the stream must always fit every decode row at
        # one token plus whatever drafts we compose.
        spec_budget = self._ragged_budget - n_active
        for i, r in enumerate(self.slot_req):
            if r is None or i in self._stalled_slots:
                continue
            drafts: List[int] = []
            if self.spec and self._spec_eligible(r):
                if r.expired():
                    # Deadline check BEFORE composing the verify span —
                    # an expired request must not burn a k-token
                    # verification (satellite bugfix; prefill and chunk
                    # already check at their dispatch sites).
                    self._drop_expired_slot(i, core)
                    continue
                drafts = self._propose_drafts(r, i)[:max(0, spec_budget)]
            need = int(self.seq_lens[i]) + 1 + len(drafts)
            if drafts and not self._extend_pages(self.slot_pages[i], need):
                drafts = []  # no headroom to speculate: plain decode row
                need = int(self.seq_lens[i]) + 1
            if not drafts and not self._extend_pages(self.slot_pages[i],
                                                     need):
                self._page_exhausted(i, need, core)
            if self.slot_req[i] is not None and i not in self._stalled_slots:
                self.page_table[i, :] = kvc.make_page_table_row(
                    self.slot_pages[i], self.ecfg.max_pages_per_seq
                )
                if drafts:
                    spec_plan[i] = drafts
                    spec_budget -= len(drafts)
                    self._jrec("speculate", r, slot=i, k=len(drafts),
                               source="ngram")
        if not self.chunking and not spec_plan:
            return False  # nothing multi-token this tick: decode fused

        # Compose: decode/spec rows first (every live stream advances,
        # and the ladder trim below must only ever shorten prefill
        # tails), then prefill spans in FIFO order until the budget runs
        # out. Spec rows ride as (kind="spec", slot, req, drafts, 1+d).
        rows: List[tuple] = []  # (kind, slot, req, chunk_pos|drafts, span)
        for i, r in enumerate(self.slot_req):
            if r is not None and i not in self._stalled_slots:
                d = spec_plan.get(i)
                if d:
                    rows.append(("spec", i, r, d, 1 + len(d)))
                else:
                    rows.append(("decode", i, r, 0, 1))
        n_decode = len(rows)
        fixed_tokens = sum(span for *_, span in rows)
        budget = self._ragged_budget - fixed_tokens
        now = time.monotonic()
        # Decision point (b): prefill-span packing order — which
        # in-flight prefills the remaining token budget goes to first
        # (fcfs/None: FIFO, exactly the legacy composition).
        chunk_order = (self.policy.pack_order(self.chunking)
                       if self.policy is not None else list(self.chunking))
        for req in chunk_order:
            if budget <= 0:
                break
            slot = req._prefill_slot
            if req.cancelled.is_set() or req.stream.overflowed:
                self._drop_chunking(req, slot)
                core.mark_dropped(req.user)
                self._jrec("finish", req, reason="cancelled")
                req.finish(FinishReason.CANCELLED)
                continue
            if req.expired():
                self._drop_chunking(req, slot)
                drop_expired(req, core, self.name, journal=self.journal)
                continue
            if req._retry_at > now:
                continue  # backing off after a contained fault
            span = min(len(req.prompt_tokens) - req._chunk_pos, budget)
            if span <= 0:
                continue
            rows.append(("prefill", slot, req, req._chunk_pos, span))
            budget -= span
        if len(rows) == n_decode and not spec_plan:
            return False  # no span ready this tick: decode runs fused

        # Pick the dispatch total from the compile ladder. Prefer the
        # largest rung we can TRIM down to (tail prefill tokens just go
        # next tick — no compute wasted); pad up to the next rung only
        # when the decode/spec rows alone nearly fill the stream and
        # leave no prefill slack to trim. Spec spans are never trimmed:
        # the lower bound covers every fixed token (decode rows + draft
        # spans), so the cut below only ever shortens prefill tails.
        T_raw = sum(span for *_, span in rows)
        lower = fixed_tokens + (1 if len(rows) > n_decode else 0)
        L = None
        for v in reversed(self._ragged_ladder):
            if v <= T_raw and v >= lower:
                L = v
                break
        if L is None:
            L = next(v for v in self._ragged_ladder if v >= T_raw)
        if L < T_raw:
            cut, acc = [], 0
            for row in rows:
                take = min(row[4], L - acc)
                if take <= 0:
                    break  # trailing spans wait for the next tick
                cut.append(row[:4] + (take,))
                acc += take
            rows = cut

        S = self.ecfg.max_slots
        MP = self.ecfg.max_pages_per_seq
        W = self.ecfg.repeat_last_n
        ps = self.ecfg.page_size
        T_real = sum(span for *_, span in rows)
        T_pad = L

        tokens = np.zeros(T_pad, np.int32)
        # Padding tokens belong to padding row len(rows) (trash pages,
        # position -1 => masked everywhere) and write into the trash page.
        tok_seq = np.full(T_pad, min(len(rows), S - 1), np.int32)
        tok_pos = np.full(T_pad, -1, np.int32)
        write_slots = np.zeros(T_pad, np.int32)  # trash page slot 0
        q_start = np.full(S, T_pad, np.int32)
        q_len = np.zeros(S, np.int32)
        kv_len = np.zeros(S, np.int32)
        ring_len = np.zeros(S, np.int32)
        is_first = np.zeros(S, np.int32)
        append = np.zeros(S, np.int32)
        is_spec = np.zeros(S, np.int32)
        seed_rows = np.full((S, W), -1, np.int32)
        slot_ids = np.full(S, S, np.int32)  # padding -> trash ring row
        pt_rows = np.full((S, MP), kvc.TRASH_PAGE, np.int32)
        temp = np.zeros(S, np.float32)
        top_k = np.zeros(S, np.int32)
        top_p = np.ones(S, np.float32)
        pen = np.ones(S, np.float32)
        pres = np.zeros(S, np.float32)
        freq = np.zeros(S, np.float32)
        seeds = np.zeros(S, np.int32)

        off = 0
        for idx, (kind, slot, req, cpos, span) in enumerate(rows):
            s = req.sampling
            slot_ids[idx] = slot
            q_start[idx] = off
            q_len[idx] = span
            temp[idx] = s.temperature
            top_k[idx] = s.top_k
            top_p[idx] = s.top_p
            pen[idx] = s.repeat_penalty
            pres[idx] = s.presence_penalty
            freq[idx] = s.frequency_penalty
            seeds[idx] = s.seed
            if kind == "decode":
                pos = int(self.seq_lens[slot])
                tokens[off] = self.last_tokens[slot]
                tok_seq[off] = idx
                tok_pos[off] = pos
                row = self.page_table[slot]
                write_slots[off] = row[pos // ps] * ps + pos % ps
                kv_len[idx] = pos + 1
                append[idx] = 1  # ring_len 0: input token already rolled
                pt_rows[idx] = row
            elif kind == "spec":
                # Speculative verify span: the slot's input token plus
                # its drafts, written optimistically at positions
                # pos..pos+d (rejected positions are masked by the
                # rolled-back kv_len and overwritten later). The jit
                # computes the accepted count and advances the ring by
                # it; append always rolls in the bonus token.
                drafts = cpos  # rows tuple carries the draft list here
                pos = int(self.seq_lens[slot])
                d = len(drafts)
                tokens[off:off + d + 1] = [self.last_tokens[slot]] + drafts
                tok_seq[off:off + d + 1] = idx
                positions = np.arange(pos, pos + d + 1, dtype=np.int32)
                tok_pos[off:off + d + 1] = positions
                row = self.page_table[slot]
                write_slots[off:off + d + 1] = (
                    row[positions // ps] * ps + positions % ps)
                kv_len[idx] = pos + 1 + d
                is_spec[idx] = 1
                append[idx] = 1
                pt_rows[idx] = row
            else:
                piece = req.prompt_tokens[cpos:cpos + span]
                tokens[off:off + span] = piece
                tok_seq[off:off + span] = idx
                positions = np.arange(cpos, cpos + span, dtype=np.int32)
                tok_pos[off:off + span] = positions
                row = req._pt_row[0]
                write_slots[off:off + span] = (
                    row[positions // ps] * ps + positions % ps)
                kv_len[idx] = cpos + span
                ring_len[idx] = span
                first = 1 if cpos == req._chunk_base else 0
                is_first[idx] = first
                if first and cpos > 0:
                    # Prefix-cache hit: the ring opens with the cached
                    # prefix's last W tokens, as a full prefill would.
                    prev = req.prompt_tokens[max(0, cpos - W):cpos]
                    seed_rows[idx, W - len(prev):] = prev
                final = cpos + span >= len(req.prompt_tokens)
                append[idx] = 1 if final else 0
                pt_rows[idx] = row
                req.trace_event("prefill_chunk", pos=cpos, tokens=span)
                self._jrec("chunk", req, slot=slot, pos=cpos, tokens=span,
                           cached=req._chunk_base)
            off += span

        prefill_rows = [r for r in rows if r[0] == "prefill"]
        spec_rows = [r for r in rows if r[0] == "spec"]
        spec_tokens = sum(len(r[3]) for r in spec_rows)
        # k_cap in {0, spec_k}: one extra compile variant total when
        # speculation is live, not one per observed draft length.
        k_cap = self.ecfg.spec_k if spec_rows else 0
        self.inflight_prefill = [req for _, _, req, _, _ in prefill_rows]
        # Batch-compose decision inputs, recorded AFTER the dispatch so
        # the record can also carry the per-dispatch accepted-token
        # count (the speculative scoreboard reads straight off batch
        # records); a failed dispatch records them without it.
        batch_fields = dict(
            slots=[slot for _, slot, *_ in rows],
            reqs=[req.req_id for _, _, req, _, _ in rows],
            batch_size=len(rows), tokens=int(T_real),
            occupancy=round(len(rows) / max(1, S), 4),
            pending=(len(self.pending_prefill) + len(self.chunking)),
            free_pages=self.alloc.free_pages,
            mode="ragged", padded_tokens=int(T_pad),
            n_decode=n_decode - len(spec_rows),
            n_prefill=len(prefill_rows))
        if spec_rows:
            batch_fields["n_spec"] = len(spec_rows)
            batch_fields["spec_tokens"] = int(spec_tokens)
        if (self.attn_impl == "pallas" and not self._pallas_proven
                and jax.process_count() == 1):
            # Probe the unproven Pallas ragged kernel with an AOT compile
            # BEFORE the real dispatch (the decode path's pattern):
            # lower().compile() executes nothing and donates nothing, so
            # a Mosaic compile failure flips us to the jnp reference
            # attention with the KV state untouched.
            try:
                probe_flags = sampling_flags(temp, top_k, top_p, pen,
                                             pres, freq)
                self._get_ragged_jit(T_pad, k_cap, probe_flags).lower(
                    self.params, jnp.asarray(tokens), jnp.asarray(tok_seq),
                    jnp.asarray(tok_pos), jnp.asarray(write_slots),
                    jnp.asarray(q_start), jnp.asarray(q_len),
                    jnp.asarray(kv_len), jnp.asarray(ring_len),
                    jnp.asarray(is_first), jnp.asarray(append),
                    jnp.asarray(is_spec), jnp.asarray(seed_rows),
                    jnp.asarray(slot_ids), jnp.asarray(pt_rows),
                    self.kc, self.vc, self.recent,
                    jnp.asarray(temp), jnp.asarray(top_k),
                    jnp.asarray(top_p), jnp.asarray(pen),
                    jnp.asarray(pres), jnp.asarray(freq),
                    jnp.asarray(seeds), jax.random.PRNGKey(0),
                ).compile()
                self._pallas_proven = True
            except Exception:
                log.exception(
                    "pallas ragged kernel failed to compile; serving falls "
                    "back to jnp attention for runtime %s", self.name,
                )
                self.attn_impl = "jnp"
                self._decode_jits.clear()
                self._prefill_jits = {
                    k: v for k, v in self._prefill_jits.items()
                    if not (isinstance(k, tuple) and k
                            and k[0] == "ragged")
                }
        _sp.mark("host_prep")
        t0 = time.monotonic()
        try:
            toks_dev, n_emit_dev, self.kc, self.vc, self.recent = \
                self._dispatch_ragged(
                    T_pad, k_cap, tokens, tok_seq, tok_pos, write_slots,
                    q_start, q_len, kv_len, ring_len, is_first, append,
                    is_spec, seed_rows, slot_ids, pt_rows, temp, top_k,
                    top_p, pen, pres, freq, seeds, self._next_key(),
                )
            _sp.mark("dispatch")
            toks = np.asarray(toks_dev)  # [S, k_cap+1]
            n_emit = np.asarray(n_emit_dev)  # [S]
            _sp.mark("collect")
        except Exception as e:
            self._jrec("batch", **batch_fields)
            self._ragged_failed(rows, e, core)
            return True
        finally:
            self.inflight_prefill = []
        dt = time.monotonic() - t0
        if spec_rows:
            batch_fields["spec_accepted"] = int(sum(
                int(n_emit[idx]) - 1
                for idx, r in enumerate(rows) if r[0] == "spec"))
        self._jrec("batch", **batch_fields)

        waste = (T_pad - T_real) / max(1, T_pad)
        self._tm_padding.set(round(waste, 4))
        if prefill_rows:
            self.prefill_latency_ms = dt * 1e3
            self._tm_prefill.observe(self.prefill_latency_ms)
        if n_decode:
            self.step_latency_ms = dt * 1e3
            self.step_window.append(self.step_latency_ms)
            self._tm_step.observe(self.step_latency_ms)
            self._tm_tpot.observe(self.step_latency_ms)
            if self.slo is not None:
                self.slo.record("tpot", self.step_latency_ms, n=n_decode)

        emitted = 0
        for idx, (kind, slot, req, cpos, span) in enumerate(rows):
            if kind in ("decode", "spec"):
                if self.slot_req[slot] is not req:
                    continue  # finished/cancelled between compose & emit
                n = int(n_emit[idx])  # 1 for decode; accepted+1 for spec
                kv_before = int(self.seq_lens[slot]) + span
                for jtok in range(n):
                    if self.slot_req[slot] is not req:
                        break  # EOS / stop string / cap hit mid-emission
                    tok = int(toks[idx, jtok])
                    self.seq_lens[slot] += 1
                    self.tokens_generated += 1
                    emitted += 1
                    if self._emit_token(slot, tok, core):
                        self.last_tokens[slot] = tok
                if kind == "spec":
                    proposed = span - 1
                    accepted = n - 1
                    self._note_spec_outcome(req, proposed, accepted)
                    self._jrec("spec_verify", req, slot=slot,
                               proposed=proposed, accepted=accepted,
                               rolled_back=proposed - accepted)
                    if (proposed > accepted
                            and self.slot_req[slot] is req):
                        # Rejected drafts wrote KV past the accepted
                        # context: release their page claim (the finish
                        # paths above already freed everything when the
                        # stream ended mid-emission).
                        self._rollback_spec(
                            slot, req, kv_before,
                            int(self.seq_lens[slot]) + 1)
            else:
                req._chunk_pos = cpos + span
                if req._chunk_pos >= len(req.prompt_tokens):
                    # Final span: publish the page-table row (decode may
                    # write through it from now on), install, emit.
                    try:
                        self.chunking.remove(req)
                    except ValueError:
                        pass
                    self.reserved_slots.discard(slot)
                    self.page_table[slot, :] = req._pt_row[0]
                    self._install_slot(slot, req,
                                       len(req.prompt_tokens),
                                       int(toks[idx, 0]), core)

        self._tm_tokens.inc(emitted)
        active = [i for i, r in enumerate(self.slot_req) if r is not None]
        self._tm_occupancy.set(len(active) / max(1, S))
        self._tm_pages.set(self.alloc.used_pages)
        self._tm_page_util.set(
            self.alloc.used_pages / max(1, self.alloc.num_pages - 1))
        mean_ctx = (float(np.mean([kv_len[i] for i in range(len(rows))]))
                    if rows else 0.0)
        # MFU over EVERY real token the dispatch processed (prefill
        # spans do the same per-token matmuls as decode rows).
        self.mfu = mfu_model.mfu(self._orig_cfg, int(T_real), dt,
                                 self.peak_flops, n_chips=self.n_chips,
                                 context_len=mean_ctx)
        self._tm_mfu.set(self.mfu)
        _sp.mark("detok")
        _sp.mode = "spec_verify" if spec_rows else "ragged"
        _sp.finish(T_pad=int(T_pad), k_cap=int(k_cap),
                   n_prefill=len(prefill_rows),
                   n_decode=n_decode - len(spec_rows),
                   tokens=int(T_real), padded_tokens=int(T_pad),
                   compiled=_sp_take_compiled(self))
        return True

    def _ragged_failed(self, rows, e: Exception, core: MQCore) -> None:
        """Contain a failed mixed dispatch: prefill spans release their
        reservation and retry from scratch; decode rows fold their
        generated tokens into a replay prompt (preemption semantics —
        the stream resumes byte-identically) and retry too. A worker
        desync still propagates: diverged SPMD state must kill+reload."""
        desync = isinstance(e, WorkerDesyncError)
        log.exception("ragged mixed dispatch failed (%d rows)", len(rows))
        for kind, slot, req, _cpos, _span in rows:
            if kind == "prefill":
                self._drop_chunking(req, slot)
                if desync or not self._retry_requeue(
                        req, self.pending_prefill,
                        f"ragged dispatch failed: {e}"):
                    core.mark_dropped(req.user)
                    req.finish(FinishReason.ERROR, error=self._poison_msg(
                        req, f"ragged dispatch failed: {e}"))
            else:
                r = self.slot_req[slot]
                if r is None:
                    continue
                # Journaled as a preempt: the slot's holder is released
                # for replay-recompute — the invariant checker (and any
                # postmortem) must see the seat change hands.
                self._jrec("preempt", r, slot=slot, why="dispatch_fault",
                           n=r.retries + 1,
                           free_pages=self.alloc.free_pages)
                replay = r.prompt_tokens + r.generated_ids[r._replay_gen:]
                written = len(replay) - 1 if r.generated_ids else len(replay)
                r.prompt_tokens = replay[:written]
                self._release_slot_pages(slot, r if written else None)
                r.prompt_tokens = replay
                r._replay_gen = len(r.generated_ids)
                self._clear_slot(slot)
                if desync or not self._retry_requeue(
                        r, self.pending_prefill,
                        f"ragged dispatch failed: {e}"):
                    core.mark_dropped(r.user)
                    r.finish(FinishReason.ERROR, error=self._poison_msg(
                        r, f"ragged dispatch failed: {e}"))
        if desync:
            raise e

    def step_decode(self, core: MQCore, k_steps: int = 1) -> int:
        """Advance all active slots by up to k_steps tokens. Returns #tokens."""
        handle = self.step_decode_dispatch(core, k_steps)
        if handle is None:
            return 0
        return self.step_decode_collect(handle, core)

    def step_decode_dispatch(self, core: MQCore, k_steps: int = 1):
        """Dispatch one fused decode chunk WITHOUT blocking on the result.

        JAX dispatch is asynchronous: the returned handle holds device
        arrays that are still computing. The engine loop dispatches every
        runtime's chunk first and only then collects (step_decode_collect),
        so dp replicas' fused scans — which live on disjoint device sets —
        execute concurrently instead of serializing on the host thread
        (round-2 verdict weak #1). Returns None when nothing is active."""
        if not any(r is not None for r in self.slot_req):
            return None
        # Step profiler: the timer spans dispatch AND collect (the two
        # halves of one step); it rides self._sp_decode between them.
        # Early returns and faulted dispatches abandon it.
        _sp = stepprof.PROFILER.start("decode")
        # Reservation-holders first: pages may have freed since they
        # stalled — growth success puts them back into the batch.
        for i in sorted(self._stalled_slots):
            if self.slot_req[i] is None:
                self._stalled_slots.discard(i)
            elif self._extend_pages(self.slot_pages[i],
                                    int(self.seq_lens[i]) + k_steps):
                self._stalled_slots.discard(i)
        # Ensure page headroom for k_steps new tokens per active slot.
        for i, r in enumerate(self.slot_req):
            if r is None or i in self._stalled_slots:
                continue
            need = int(self.seq_lens[i]) + k_steps
            if not self._extend_pages(self.slot_pages[i], need):
                # Never a silent LENGTH: preempt-with-recompute, stall on
                # a reservation, or error explicitly (kv_exhausted).
                self._page_exhausted(i, need, core)
            if self.slot_req[i] is not None and i not in self._stalled_slots:
                self.page_table[i, :] = kvc.make_page_table_row(
                    self.slot_pages[i], self.ecfg.max_pages_per_seq
                )
        active = [i for i, r in enumerate(self.slot_req)
                  if r is not None and i not in self._stalled_slots]
        if not active:
            # Whole batch is stalled reservations: nothing can finish, so
            # nothing will free pages — after a grace window, break the
            # deadlock loudly instead of wedging (any other in-flight
            # work, e.g. a chunked prefill, can still unblock it first).
            if self._stalled_slots and not self.chunking:
                now = time.monotonic()
                if self._stall_since is None:
                    self._stall_since = now
                elif now - self._stall_since > self.STALL_BREAK_S:
                    self._break_stall_deadlock(core)
                    self._stall_since = None
            return None
        self._stall_since = None

        t0 = time.monotonic()
        active_mask = np.asarray(
            [1 if (r is not None and i not in self._stalled_slots) else 0
             for i, r in enumerate(self.slot_req)], np.int32
        )

        if (self.attn_impl == "pallas" and not self._pallas_proven
                and jax.process_count() == 1):
            # Probe the unproven Pallas kernel with an AOT compile BEFORE
            # the real dispatch: lower().compile() executes nothing and
            # donates nothing, so a Mosaic compile failure flips us to the
            # jnp reference attention with the KV state untouched. A kernel
            # that compiles but faults at runtime goes down the normal
            # _fail_runtime -> rebuild path like any other device error.
            try:
                probe_flags = sampling_flags(self.temp, self.top_k,
                                             self.top_p, self.rep_pen,
                                             self.pres_pen, self.freq_pen)
                self._get_decode_jit(k_steps, probe_flags).lower(
                    self.params, jnp.asarray(self.last_tokens),
                    jnp.asarray(self.seq_lens), self.kc, self.vc,
                    self.recent, jnp.asarray(active_mask),
                    jnp.asarray(self.page_table), jnp.asarray(self.temp),
                    jnp.asarray(self.top_k), jnp.asarray(self.top_p),
                    jnp.asarray(self.rep_pen), jnp.asarray(self.pres_pen),
                    jnp.asarray(self.freq_pen), jnp.asarray(self.seeds),
                    jax.random.PRNGKey(0),
                ).compile()
                self._pallas_proven = True
            except Exception:
                log.exception(
                    "pallas decode kernel failed to compile; serving falls "
                    "back to jnp attention for runtime %s", self.name,
                )
                self.attn_impl = "jnp"
                self._decode_jits.clear()

        _sp.mark("host_prep")
        toks, self.kc, self.vc, self.recent = self._dispatch_decode(
            k_steps, self.last_tokens,
            self.seq_lens,  # position of the incoming token
            active_mask, self.page_table, self.temp, self.top_k, self.top_p,
            self.rep_pen, self.pres_pen, self.freq_pen, self.seeds,
            self._next_key(),
        )
        _sp.mark("dispatch")
        self._sp_decode = _sp
        return (toks, active, k_steps, t0)

    def step_decode_collect(self, handle, core: MQCore) -> int:
        """Block on a dispatched decode chunk and emit its tokens. A device
        error in the chunk surfaces HERE (np.asarray materializes the async
        result), so callers must route collect failures through the same
        runtime-failure path as dispatch failures.

        Step-latency telemetry counts only the time this collect actually
        BLOCKS: when the engine loop overlaps several runtimes' chunks,
        host work and sibling collects between dispatch and this collect
        happened while the device ran concurrently, so a runtime whose
        chunk finished during that overlap reports (correctly) near-zero
        marginal step cost. Strictly an under- never an over-estimate."""
        toks_dev, active, k_steps, _dispatch_t0 = handle
        # The in-flight step timer parked by step_decode_dispatch; its
        # "collect" phase spans dispatch-issue to materialized — the
        # device compute the engine loop overlapped with other work.
        _sp = getattr(self, "_sp_decode", None)
        self._sp_decode = None
        # Mean context BEFORE the emit loop advances seq_lens: feeds the
        # attention term of the per-step FLOPs model.
        mean_ctx = float(np.mean([self.seq_lens[i] for i in active]))
        t_block = time.monotonic()
        toks = np.asarray(toks_dev)  # [K, S] — blocks until the chunk is done
        t_done = time.monotonic()
        if _sp is not None:
            _sp.mark("collect")
        self.step_latency_ms = (t_done - t_block) * 1e3 / k_steps
        self.step_window.append(self.step_latency_ms)
        self._tm_step.observe(self.step_latency_ms)
        # TPOT: every active slot gains one token per step, so step
        # latency IS time-per-output-token for each stream in the batch.
        self._tm_tpot.observe(self.step_latency_ms)
        if self.slo is not None:
            # One SLO observation per emitted token, not per chunk: the
            # objective is per-token latency and the budget math needs
            # event counts that match what users experienced.
            self.slo.record("tpot", self.step_latency_ms,
                            n=max(1, len(active) * k_steps))

        emitted = 0
        for k in range(k_steps):
            for i in active:
                if self.slot_req[i] is None:
                    continue  # finished at an earlier k
                tok = int(toks[k, i])
                self.seq_lens[i] += 1
                self.tokens_generated += 1
                emitted += 1
                if self._emit_token(i, tok, core):
                    self.last_tokens[i] = tok

        # Per-step engine telemetry: occupancy, KV-page pressure, MFU.
        # Wall time is dispatch->collect-done — the device-side span of
        # this chunk (an over-estimate under host overlap, so the MFU it
        # yields is conservative, never flattering).
        self._tm_tokens.inc(emitted)
        self._tm_occupancy.set(len(active) / max(1, self.ecfg.max_slots))
        self._tm_pages.set(self.alloc.used_pages)
        self._tm_page_util.set(
            self.alloc.used_pages / max(1, self.alloc.num_pages - 1))
        wall = t_done - _dispatch_t0
        # _orig_cfg, not self.cfg: replicated-group KV inflates kv_dim as
        # a layout trick, not real FLOPs.
        self.mfu = mfu_model.mfu(self._orig_cfg, emitted, wall,
                                 self.peak_flops, n_chips=self.n_chips,
                                 context_len=mean_ctx)
        self._tm_mfu.set(self.mfu)
        if _sp is not None:
            _sp.mark("detok")
            _sp.finish(T_pad=0, k_cap=int(k_steps), n_prefill=0,
                       n_decode=len(active), tokens=emitted,
                       padded_tokens=int(k_steps) * self.ecfg.max_slots,
                       compiled=_sp_take_compiled(self))
        return emitted

    def check_cancellations(self, core: MQCore) -> None:
        """Reap cancelled requests and requests whose user was blocked after
        admission. The reference re-checks the blocklist at dispatch time
        (dispatcher.rs:503-512); with continuous batching a request is
        'dispatched' for its whole lifetime, so the late re-check covers the
        slots and prefill queues — version-gated so the hot loop pays no FFI
        cost unless the blocklist actually changed. Blocked ⇒ cancel: the
        existing cancel paths (slot finish, chunked-prefill abort,
        pending-prefill pop) do the page reclaim and dropped accounting."""
        self._block_ver = sweep_blocked(core, self._held_requests, self._block_ver)
        for i, req in enumerate(self.slot_req):
            if req is not None and req.cancelled.is_set():
                self._finish_slot(i, FinishReason.CANCELLED, core)

    def _held_requests(self):
        return (
            [r for r in self.slot_req if r is not None]
            + list(self.pending_prefill)
            + list(self.pending_embed)
            + list(self.chunking)
        )

    # -- embeddings on a generative model ----------------------------------
    def _get_embed_jit(self, batch: int, bucket: int):
        key = (batch, bucket)
        _sp_compile_evict(self, self._embed_jits, key)
        if key not in self._embed_jits:
            cfg = self.cfg

            def fn(params, tokens, seq_lens):
                return llama.forward_embed(params, cfg, tokens, seq_lens)

            _sp_note_compile(self, "embed", key, self._embed_jits,
                             jax.jit(fn))
        return self._embed_jits[key]

    # Dispatch seam: the SPMD subclass broadcasts (OP_EMBED, payload) to
    # worker hosts before issuing the same jit call.
    def _dispatch_embed(self, B, bucket, tokens, lens):
        self._fault("embed")
        return self._get_embed_jit(B, bucket)(
            self.params, jnp.asarray(tokens), jnp.asarray(lens)
        )

    def step_embed(self, core: MQCore) -> bool:
        """Serve pending embed requests — stateless forwards (no KV
        write), so no generated-token position is reserved from the
        length budget and a failure never needs to touch decode state.
        Returns True if a batch ran."""
        max_len = min(self.ecfg.max_context, self.cfg.max_seq_len)
        try:
            return serve_embed_batch(self, core, self.pending_embed,
                                     max_len, self._dispatch_embed)
        except WorkerDesyncError:
            raise  # diverged device state: engine loop must kill + reload
        except Exception:
            # Local embed failure (the batch is already errored by the
            # helper): keep the runtime — its decode slots are healthy,
            # and a genuinely dead device will fail the next decode
            # dispatch, which DOES kill + rebuild.
            log.exception("embed forward failed on %s", self.name)
            return True

    def stats(self) -> dict:
        def pctl(window, q):
            if not window:
                return 0.0
            xs = sorted(window)
            return round(xs[min(len(xs) - 1, int(q * len(xs)))], 3)

        return {
            "model": self.name,
            "active_slots": self.active_count(),
            "max_slots": self.ecfg.max_slots,
            "pending_prefill": len(self.pending_prefill),
            "pages_used": self.alloc.used_pages,
            "pages_total": self.alloc.num_pages - 1,
            "step_latency_ms": round(self.step_latency_ms, 3),
            "step_p50_ms": pctl(self.step_window, 0.50),
            "step_p99_ms": pctl(self.step_window, 0.99),
            "prefill_latency_ms": round(self.prefill_latency_ms, 3),
            "ttft_p50_ms": pctl(self.ttft_window, 0.50),
            "ttft_p99_ms": pctl(self.ttft_window, 0.99),
            "tokens_generated": self.tokens_generated,
            "preemptions": self.preempt_count,
            "retries": self.retry_count,
            "stalled_slots": len(self._stalled_slots),
            "mfu": round(self.mfu, 4),
            "param_bytes": self.param_bytes,
            "kv_bytes": self.kv_bytes,
            "weights_dtype": self.weights_dtype,
            "kv_dtype": self.kv_dtype,
            # None = caching disabled (the TUI renders "cache n/a").
            "prefix_cache": (self.prefix_cache.stats()
                             if self.prefix_cache is not None else None),
            # None = speculation disabled on this runtime.
            "spec": ({
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "accept_rate": round(
                    self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed else 0.0,
                "rollbacks": self.spec_rollbacks,
                "throttled_users": len(self._spec_throttled),
            } if self.spec else None),
        }


class EncoderRuntime:

    SERVES = ("embed",)
    """Embedding model runtime: batch encode, no KV cache."""

    slo = None  # encoders emit no tokens; attached but never recorded into
    fault_plan = None  # attached by the engine like ModelRuntime's
    on_preempt = None  # encoders hold no KV pages; attached but unused
    journal = None  # decision journal (the SPMD broadcast seam reads it)

    def __init__(self, name, model_cfg, engine_cfg, mesh=None,
                 checkpoint_path=None, dtype=jnp.bfloat16):
        self.name = name
        self.cfg = model_cfg
        self.ecfg = engine_cfg
        self.mesh = mesh
        self._failed = False
        self.tokenizer = load_tokenizer(checkpoint_path)
        params = weights.load_params(model_cfg, checkpoint_path,
                                     seed=engine_cfg.seed, dtype=dtype,
                                     weights_dtype=engine_cfg.weights_dtype)
        if mesh is not None:
            params = shard_params(params, mesh)
        self.params = params
        self.pending: collections.deque = collections.deque()
        self._block_ver = -1  # force one startup sweep (disk-loaded blocklist)
        self._jits: Dict[Tuple[int, int], callable] = {}
        self.param_bytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params)
        )
        tm.HBM_WEIGHT_BYTES.labels(model=name).set(self.param_bytes)
        tm.HBM_KV_BYTES.labels(model=name).set(0)
        self.kv_bytes = 0
        self.tokens_generated = 0
        self.step_latency_ms = 0.0

    def has_capacity(self, kind: Optional[str] = None) -> bool:
        return not self._failed and len(self.pending) < 4 * self.ecfg.max_slots

    def has_work(self) -> bool:
        return bool(self.pending)

    def active_count(self) -> int:
        return 0

    def submit(self, req: Request) -> bool:
        self.pending.append(req)
        return True

    def check_cancellations(self, core: MQCore) -> None:
        # Late blocked re-check (see ModelRuntime.check_cancellations).
        self._block_ver = sweep_blocked(core, lambda: self.pending,
                                        self._block_ver)

    def _get_jit(self, batch: int, bucket: int):
        key = (batch, bucket)
        _sp_compile_evict(self, self._jits, key)
        if key not in self._jits:
            cfg = self.cfg

            def fn(params, tokens, seq_lens):
                return llama.forward_encoder(params, cfg, tokens, seq_lens)

            _sp_note_compile(self, "embed", key, self._jits, jax.jit(fn))
        return self._jits[key]

    # Dispatch seam: the SPMD subclass broadcasts (OP_ENCODE, payload) to
    # worker hosts before issuing the same jit call.
    def _dispatch_encode(self, B, bucket, tokens, lens):
        if self.fault_plan is not None and not getattr(self, "_spmd", False):
            # (multi-host: the check runs pre-broadcast in the SPMD seam)
            self.fault_plan.check("encode")
        return self._get_jit(B, bucket)(
            self.params, jnp.asarray(tokens), jnp.asarray(lens)
        )

    def step(self, core: MQCore) -> None:
        """Encode pending requests in padded batches (shared scheme:
        serve_embed_batch). A dispatch failure errors the batch, then
        propagates so the engine loop kills + rebuilds this runtime —
        an encoder has no decode path that could prove the device dead."""
        serve_embed_batch(self, core, self.pending, self.cfg.max_seq_len,
                          self._dispatch_encode)

    def stats(self) -> dict:
        return {
            "model": self.name,
            "active_slots": 0,
            "max_slots": 0,
            "pending_prefill": len(self.pending),
            "pages_used": 0,
            "pages_total": 0,
            "step_latency_ms": round(self.step_latency_ms, 3),
            "prefill_latency_ms": 0.0,
            "tokens_generated": self.tokens_generated,
            "preemptions": 0,  # encoders hold no decode slots to preempt
            "retries": 0,
            "stalled_slots": 0,
            "mfu": 0.0,  # encoders don't publish decode-step MFU
            "param_bytes": self.param_bytes,
            "kv_bytes": self.kv_bytes,
            "weights_dtype": self.ecfg.weights_dtype,
            "kv_dtype": "bfloat16",  # encoders hold no KV pool
            "prefix_cache": None,  # encoders hold no KV to share
            "spec": None,  # encoders decode nothing to speculate on
        }


def build_model_runtimes(name, cfg, engine_cfg, mesh, dtype, checkpoint_path,
                         model_cls, encoder_cls):
    """Replica list for one model — THE construction path, shared by
    TPUEngine.load_model and the SPMD worker (engine/spmd.py). Under SPMD
    every host must build byte-identical computations, so there is exactly
    one copy of the dp-submesh / preloaded-params / encoder branching.

    dp generative replicas each land on their own slice of the mesh's
    data axis (a [1, sp, tp] submesh): N param copies + KV pools serving
    concurrently — the reference's "one request per backend, N backends"
    scale-out story with backends = mesh slices. The checkpoint is
    read/parsed once and shared host-side across replicas."""
    if cfg.is_encoder:
        return [encoder_cls(name, cfg, engine_cfg, mesh=mesh,
                            checkpoint_path=checkpoint_path, dtype=dtype)]
    if engine_cfg.dp > 1 and mesh is not None:
        host_params = weights.load_params(
            cfg, checkpoint_path, seed=engine_cfg.seed, dtype=dtype,
            weights_dtype=engine_cfg.weights_dtype,
        )
        reps = [
            model_cls(name, cfg, engine_cfg, mesh=replica_submesh(mesh, r),
                      checkpoint_path=checkpoint_path, dtype=dtype,
                      preloaded_params=host_params)
            for r in range(engine_cfg.dp)
        ]
        del host_params  # replicas hold their own device copies
        return reps
    return [model_cls(name, cfg, engine_cfg, mesh=mesh,
                      checkpoint_path=checkpoint_path, dtype=dtype)]


def merge_prefix_cache_stats(stats_list) -> Optional[dict]:
    """Sum per-replica prefix-cache stat dicts (None entries = replicas
    without a cache). Returns None when no replica caches."""
    live = [s for s in stats_list if s]
    if not live:
        return None
    keys = ("hits", "misses", "evictions", "tokens_saved", "cached_pages",
            "evictable_pages", "pinned_pages")
    merged = {k: sum(s.get(k, 0) for s in live) for k in keys}
    total = merged["hits"] + merged["misses"]
    merged["hit_ratio"] = round(merged["hits"] / total, 4) if total else 0.0
    return merged


class ReplicaSet:
    """Data parallelism as replica serving: dp independent ModelRuntimes for
    one model, each TP-sharded over its own slice of the mesh's data axis,
    with least-loaded placement and round-robin rotation among ties — the
    TPU analogue of the reference's least-connections backend pick
    (dispatcher.rs:475-487). Each replica holds its own params copy, KV
    pool, and jits, so replicas step independently (and their dispatches
    overlap on disjoint device sets)."""

    def __init__(self, replicas: List[ModelRuntime]):
        assert replicas
        self.replicas = list(replicas)
        self.name = self.replicas[0].name
        self.cfg = self.replicas[0].cfg
        self.ecfg = self.replicas[0].ecfg
        self._last_idx = 0  # rotation cursor (dispatcher.rs last_backend_idx)

    # -- placement ---------------------------------------------------------
    @staticmethod
    def _load(rt: ModelRuntime) -> int:
        return (rt.active_count() + len(rt.pending_prefill)
                + len(getattr(rt, "pending_embed", ()))
                + len(rt.chunking))

    def has_capacity(self, kind: Optional[str] = None) -> bool:
        return any(r.has_capacity(kind) for r in self.replicas)

    def submit(self, req: Request) -> bool:
        """Least-loaded replica wins; ties rotate after the previous pick.
        Returns False when NO replica has capacity (the admission gate
        raced): the caller returns the request to the native queue — the
        reference's wait-in-queue semantics (dispatcher.rs:467-473) —
        instead of parking it on a full replica where it would jump the
        fair-share order."""
        eligible = [i for i, r in enumerate(self.replicas)
                    if r.has_capacity(req.kind)]
        if not eligible:
            return False
        best = min(self._load(self.replicas[i]) for i in eligible)
        ties = {i for i in eligible if self._load(self.replicas[i]) == best}
        n = len(self.replicas)
        for off in range(1, n + 1):
            i = (self._last_idx + off) % n
            if i in ties:
                self._last_idx = i
                return self.replicas[i].submit(req)
        return False

    def force_submit(self, req: Request) -> None:
        """Place even with zero capacity (least-loaded live replica): for
        requests the native queue can't hold back (empty model name)."""
        live = ([i for i, r in enumerate(self.replicas) if not r._failed]
                or list(range(len(self.replicas))))
        best = min(live, key=lambda i: self._load(self.replicas[i]))
        self.replicas[best].submit(req)

    # -- aggregate runtime surface (registry / health / TUI / app) ---------
    @property
    def tokenizer(self):
        return self.replicas[0].tokenizer

    @property
    def param_bytes(self) -> int:
        return sum(r.param_bytes for r in self.replicas)

    @property
    def kv_bytes(self) -> int:
        return sum(r.kv_bytes for r in self.replicas)

    @property
    def tokens_generated(self) -> int:
        return sum(r.tokens_generated for r in self.replicas)

    def has_work(self) -> bool:
        return any(r.has_work() for r in self.replicas)

    def active_count(self) -> int:
        return sum(r.active_count() for r in self.replicas)

    def check_cancellations(self, core: MQCore) -> None:
        for r in self.replicas:
            r.check_cancellations(core)

    def stats(self) -> dict:
        per = [r.stats() for r in self.replicas]
        agg = dict(per[0])
        for key in ("active_slots", "max_slots", "pending_prefill",
                    "pages_used", "pages_total", "tokens_generated",
                    "preemptions", "retries", "stalled_slots",
                    "param_bytes", "kv_bytes"):
            agg[key] = sum(p[key] for p in per)
        for key in ("step_latency_ms", "step_p50_ms", "step_p99_ms",
                    "prefill_latency_ms", "ttft_p50_ms", "ttft_p99_ms",
                    "mfu"):
            agg[key] = max(p.get(key, 0.0) for p in per)
        agg["prefix_cache"] = merge_prefix_cache_stats(
            [p.get("prefix_cache") for p in per])
        agg["replicas"] = len(per)
        return agg


class TPUEngine:
    """Engine front: owns the scheduler core, model runtimes, and the loop."""

    # Runtime classes; SPMD deployments swap in SPMD variants so every
    # device dispatch is broadcast to worker hosts first.
    runtime_class = ModelRuntime
    encoder_runtime_class = EncoderRuntime

    def __init__(
        self,
        engine_cfg: EngineConfig,
        models: Optional[Dict[str, Optional[str]]] = None,  # name -> ckpt path
        blocklist_path: Optional[str] = "blocked_items.json",
        mesh=None,
        fairness: Fairness = Fairness.REQUESTS,
        dtype=None,
    ):
        self.ecfg = engine_cfg
        # Scheduling policy (engine/scheduler.py): built BEFORE any
        # device/model work so an unknown --scheduler fails loudly at
        # startup. fcfs (the default) is bit-identical to the
        # pre-extraction engine; srpt/edf reorder admission, prefill
        # packing, and victim picks within what fairness releases.
        self.policy = make_policy(engine_cfg)
        self.core = MQCore(blocklist_path)
        self.core.set_fairness(fairness)
        if mesh is None and (engine_cfg.dp, engine_cfg.sp, engine_cfg.tp,
                             engine_cfg.pp, engine_cfg.ep) != (1, 1, 1, 1, 1):
            mesh = make_mesh(dp=engine_cfg.dp, sp=engine_cfg.sp,
                             tp=engine_cfg.tp, pp=engine_cfg.pp,
                             ep=engine_cfg.ep)
        self.mesh = mesh
        self.dtype = dtype if dtype is not None else jnp.dtype(engine_cfg.dtype)
        self.runtimes: Dict[str, object] = {}
        self.pending: Dict[int, Request] = {}
        # Load-shed accounting by reason (mirrors ollamamq_shed_total;
        # kept engine-side too so the TUI chip needs no registry walk).
        self.shed_counts: Dict[str, int] = {}
        self._engine_retries = 0  # retries issued by _retry_or_error
        self._orphans: List[tuple] = []
        self._expired_orphans: Dict[int, float] = {}
        # In-flight KV migration exports: rid -> (runtime, handle). A
        # detached slot parks here between migrate_export and the
        # commit/abort that resolves the two-phase handoff.
        self._migrations: Dict[int, tuple] = {}
        self._last_stuck_log = 0.0
        self._pending_lock = threading.Lock()
        self._cond = threading.Condition()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # Deferred engine-thread calls (call_on_loop): work that must run in
        # order with device dispatches — e.g. SPMD control broadcasts, which
        # would race the dispatch broadcast stream from any other thread.
        self._engine_calls: collections.deque = collections.deque()
        self.health = None
        self.started_at = time.time()
        # Request-lifecycle tracing: bounded ring of finished traces plus
        # the in-flight table, exported at GET /debug/trace.
        self.tracer = Tracer(capacity=engine_cfg.trace_ring)
        # Alerting + SLO burn-rate engine: the one alert table /health,
        # /metrics, /debug/bundle, and the TUI alerts panel all read.
        # Objectives are opt-in (--slo-ttft-ms / --slo-tpot-ms); the
        # alert table exists regardless — the stall watchdog uses it too.
        self.alerts = AlertManager()
        self.slo = SLOEngine(self.alerts,
                             ttft_ms=engine_cfg.slo_ttft_ms or None,
                             tpot_ms=engine_cfg.slo_tpot_ms or None,
                             target=engine_cfg.slo_target)
        # Flight recorder: every scheduler decision (admit/shed/batch/
        # preempt/...) as a typed record in a bounded ring, tailed at
        # GET /debug/journal and optionally spilled to --journal-file.
        self.journal = Journal(
            capacity=engine_cfg.journal_ring,
            path=engine_cfg.journal_file,
            rotate_bytes=int(engine_cfg.journal_rotate_mb * 1e6),
            keep=engine_cfg.journal_keep,
            sample=getattr(engine_cfg, "journal_sample", 1.0),
            meta={"model": engine_cfg.model,
                  "max_slots": engine_cfg.max_slots,
                  "num_pages": engine_cfg.num_pages})
        # Engine-loop liveness tick for the stall watchdog: bumped at the
        # top of every _loop_once, so a dispatch wedged inside a step
        # leaves it stale while work is pending.
        self.last_tick_at = time.monotonic()
        # Graceful-shutdown gate: quiesce() flips it and every later
        # enqueue sheds honestly (503) while in-flight streams drain.
        self.accepting = True
        # Deterministic fault injection: a plan path (--fault-plan) loads
        # here — fail-fast on a malformed file — or tests hand an already
        # built FaultPlan instance via EngineConfig.fault_plan.
        self.fault_plan = None
        if engine_cfg.fault_plan:
            from ollamamq_tpu.testing.faults import FaultPlan

            self.fault_plan = (
                FaultPlan.load(engine_cfg.fault_plan)
                if isinstance(engine_cfg.fault_plan, str)
                else engine_cfg.fault_plan)
        # Crash durability (--wal-dir): admission WAL + cold-restart
        # recovery + the resumable-stream registry. None = no overhead.
        self.durability = None
        if getattr(engine_cfg, "wal_dir", None):
            from ollamamq_tpu.durability import DurabilityManager

            self.durability = DurabilityManager(
                engine_cfg, journal=self.journal, alerts=self.alerts,
                fault_plan=self.fault_plan)
        # CPU-gloo can't run two cross-host computations concurrently: XLA's
        # CPU thread pool executes them in nondeterministic order and their
        # collective ops interleave differently per process on the shared
        # TCP pairs (observed as gloo size-mismatch aborts). On TPU each
        # replica's collectives ride its own disjoint ICI clique, so the
        # dispatch/collect overlap is safe — serialize only multi-host CPU.
        self._serialize_multihost = (
            jax.process_count() > 1 and jax.default_backend() == "cpu"
        )
        # Failure recovery: runtimes marked failed are rebuilt (weights
        # reloaded) on this cadence instead of requiring a process restart.
        self._model_sources: Dict[str, Optional[str]] = {}
        self._failed_runtimes: List[object] = []
        self._recovering: set = set()  # id(rt) with a rebuild in flight
        self._rebuilt: List[tuple] = []  # (dead_rt, fresh_rt) awaiting swap
        self._rebuilt_lock = threading.Lock()
        self._last_recover_attempt = 0.0
        self.recover_interval = 5.0
        models = models if models is not None else {engine_cfg.model: None}
        for name, ckpt in models.items():
            self.load_model(name, ckpt)

    # -- model management (registry-facing; /api/pull and /api/delete) -----
    def load_model(self, name: str, checkpoint_path: Optional[str] = None) -> None:
        cfg = get_model_config(name)
        if cfg is None:
            raise KeyError(f"unknown model architecture: {name}")
        if name in self.runtimes:
            return
        self._model_sources[name] = checkpoint_path
        reps = build_model_runtimes(
            name, cfg, self.ecfg, self.mesh, self.dtype, checkpoint_path,
            self.runtime_class, self.encoder_runtime_class,
        )
        for rep in reps:
            self._attach_hooks(rep)
        self.runtimes[name] = reps[0] if len(reps) == 1 else ReplicaSet(reps)
        log.info("loaded model %s (%.1f MB params)", name,
                 self.runtimes[name].param_bytes / 1e6)
        self.notify()

    def _attach_hooks(self, rep) -> None:
        """Primary-side engine hooks on a (re)built runtime: SLO
        accounting, fault injection, decision journaling, and the
        preemption requeue path."""
        rep.slo = self.slo
        rep.fault_plan = self.fault_plan
        rep.journal = self.journal
        rep.policy = self.policy
        if self.ecfg.preempt:
            rep.on_preempt = self._requeue_preempted

    def evict_model(self, name: str) -> bool:
        rt = self.runtimes.get(name)
        if rt is None:
            return False
        if rt.has_work():
            raise RuntimeError(f"model {name} has in-flight work")
        del self.runtimes[name]
        return True

    def loaded_models(self) -> List[str]:
        return list(self.runtimes.keys())

    # -- request flow ------------------------------------------------------
    def enqueue_request(
        self,
        user: str,
        ip: str,
        model: str,
        family=None,
        prompt_tokens=None,
        sampling=None,
        kind: str = "generate",
        raw_prompt: str = "",
        context_ids=None,
        trace_ctx=None,
    ) -> Request:
        """Atomically enqueue into the native core AND register the Request,
        so the engine loop can never pop a req_id it doesn't know yet.
        Raises BlockedError for blocked users/IPs, QueueFullError when a
        bounded-admission cap (--max-queued / --max-queued-per-user) is
        hit — honest backpressure instead of an unbounded queue.

        `trace_ctx` (the `traceparent` header / fleet router context):
        a propagated fleet-stable trace id this request's spans adopt,
        so a member process's timeline stitches under the router's rid
        at GET /debug/trace/{rid}. None mints a fresh root context.

        `context_ids` (Ollama's /api/generate `context` field, also the
        fleet's token-space HTTP failover replay): token ids already
        generated in a prior turn/attempt. They fold into the replay
        prompt with generated_ids pre-filled — the engine's own
        preemption-replay convention — so the decode continues exactly
        after them and max_tokens still budgets NEW tokens only."""
        cfg = self.ecfg
        if not self.accepting:
            # Graceful shutdown in progress: shed honestly while the
            # in-flight streams drain (limit 0 = "the door is closed").
            self._count_shed("queue_full")
            self.journal.record(
                "shed", user=user, model=model or None, reason="queue_full",
                queued=self.core.total_queued(), limit=0,
                retry_after_s=5.0, n_prompt=len(prompt_tokens or []))
            raise QueueFullError("queue_full", 5.0, 0)
        if cfg.max_queued and self.core.total_queued() >= cfg.max_queued:
            self._count_shed("queue_full")
            retry_s = self.retry_after_s()
            self.journal.record(
                "shed", user=user, model=model or None, reason="queue_full",
                queued=self.core.total_queued(), limit=cfg.max_queued,
                retry_after_s=round(retry_s, 3),
                n_prompt=len(prompt_tokens or []),
                max_tokens=getattr(sampling, "max_tokens", None))
            raise QueueFullError("queue_full", retry_s, cfg.max_queued)
        if (cfg.max_queued_per_user
                and self.core.queue_len(user) >= cfg.max_queued_per_user):
            self._count_shed("user_queue_full")
            retry_s = self.retry_after_s()
            self.journal.record(
                "shed", user=user, model=model or None,
                reason="user_queue_full", queued=self.core.queue_len(user),
                limit=cfg.max_queued_per_user,
                retry_after_s=round(retry_s, 3),
                n_prompt=len(prompt_tokens or []),
                max_tokens=getattr(sampling, "max_tokens", None))
            raise QueueFullError("user_queue_full", retry_s,
                                 cfg.max_queued_per_user)
        with self._pending_lock:
            rid = self.core.enqueue(
                user, ip, model,
                family if family is not None else Family.UNKNOWN, kind=kind,
            )
            req = Request(rid, user, model, prompt_tokens or [], sampling,
                          kind=kind, raw_prompt=raw_prompt)
            if context_ids:
                ctx = [int(t) for t in context_ids]
                sp = copy.copy(req.sampling)  # skip __post_init__ refold
                sp.max_tokens = sp.max_tokens + len(ctx)
                req.sampling = sp
                req.prompt_tokens = list(req.prompt_tokens) + ctx
                req.generated_ids = list(ctx)
                req._replay_gen = len(ctx)
                req.stats.prompt_tokens = len(req.prompt_tokens)
            req.trace = self.tracer.begin(rid, user, model, kind=kind,
                                          ctx=trace_ctx)
            self.pending[rid] = req
        self.journal.record(
            "enqueue", req=req, n_prompt=len(req.prompt_tokens),
            queued=self.core.total_queued(), kind_req=kind,
            max_tokens=req.sampling.max_tokens,
            deadline_ms=getattr(req.sampling, "deadline_ms", 0.0) or None)
        if self.durability is not None:
            # Durable admission: the WAL fsync must land BEFORE this
            # enqueue is ACKed to the caller — a kill -9 after return
            # can never lose an admitted request. The pristine prompt
            # (pre context-fold) is what recovery re-folds from.
            self.durability.admit(req, prompt_tokens=prompt_tokens or [])
        self.notify()
        return req

    def submit(self, req: Request) -> None:
        """Register a pre-built Request (req.req_id from core.enqueue).
        NOTE: prefer enqueue_request — with this two-step flow the engine
        loop may observe the queued id before registration; _admit tolerates
        that by parking the id as an orphan, but only enqueue_request is
        race-free."""
        with self._pending_lock:
            if req.req_id in self._expired_orphans:
                # Its queue slot was already dropped after the orphan grace
                # period; registering it now would leak it in `pending`.
                del self._expired_orphans[req.req_id]
                expired = True
            else:
                self.pending[req.req_id] = req
                expired = False
        if expired:
            req.finish(FinishReason.ERROR,
                       error="request expired before registration")
            return
        self.notify()

    def inject_request(self, req: Request, ip: str = "",
                       family=None, trace_ctx=None,
                       trace_meter: bool = True) -> Request:
        """Fleet handoff seam: atomically enqueue AND register a
        PRE-BUILT Request (the fleet router's attempt objects, which may
        carry replayed generation state — generated_ids, detokenizer,
        penalty context folded into the prompt — that enqueue_request
        could not construct). Bypasses bounded admission on purpose: the
        router owns the fleet-wide caps; a member must never second-guess
        a placement the router already admitted.

        `trace_ctx` gives the member-side attempt its own Trace under
        the router's fleet context, so its prefill/decode spans stitch
        into the client's /debug/trace/{rid} timeline. `trace_meter`
        False = an in-process LocalMember attempt: the router's root
        trace already meters this stream into requests_inflight/total —
        the member copy must not double-count the shared registry."""
        with self._pending_lock:
            rid = self.core.enqueue(
                req.user, ip, req.model,
                family if family is not None else Family.UNKNOWN,
                kind=req.kind)
            req.req_id = rid
            if trace_ctx is not None and req.trace is None:
                req.trace = self.tracer.begin(
                    rid, req.user, req.model, kind=req.kind,
                    ctx=trace_ctx, metered=trace_meter)
            self.pending[rid] = req
        self.journal.record(
            "enqueue", req=req, n_prompt=len(req.prompt_tokens),
            queued=self.core.total_queued(), kind_req=req.kind,
            max_tokens=req.sampling.max_tokens)
        self.notify()
        return req

    def prefix_match_pages(self, model: str, tokens) -> int:
        """Longest cached-prefix match (in full pages) any runtime of
        `model` holds for this prompt — the fleet router's placement-
        affinity probe. Advisory read from another thread: the radix walk
        only follows dict gets under the GIL, so a racing engine-loop
        mutation can at worst return a stale count (a placement-quality
        issue, never a correctness one). 0 when nothing caches."""
        rt = self.resolve_runtime(model)
        if rt is None:
            return 0
        reps = rt.replicas if isinstance(rt, ReplicaSet) else [rt]
        best = 0
        for rep in reps:
            pc = getattr(rep, "prefix_cache", None)
            if pc is None:
                continue
            try:
                _nodes, pages = pc.match(list(tokens))
            except Exception:  # noqa: BLE001 — advisory probe only
                continue
            best = max(best, len(pages))
        return best

    # -- KV page migration (fleet export/import seam) ----------------------
    def export_stream(self, rid: int, deadline: Optional[float] = None):
        """Phase 1 of the two-phase handoff: snapshot + detach `rid`'s
        decode slot into a portable blob, parking the source state until
        resolve_export commits or aborts. Runs on the engine thread
        (slot tables and the KV pool are loop state); `deadline` bounds
        how long a caller will wait on a wedged loop — a late-running
        export past it is a no-op, so the caller's recompute fallback
        can never race a zombie detach. None = not exportable."""
        def _do():
            if deadline is not None and time.monotonic() > deadline:
                return None
            for rt in self._step_targets():
                export = getattr(rt, "export_request", None)
                if export is None:
                    continue
                out = export(rid)
                if out is None:
                    continue
                handle, blob = out
                self._migrations[rid] = (rt, handle)
                req = handle["req"]
                self.journal.record(
                    "migrate_export", req=req,
                    tokens=len(req.generated_ids),
                    kv_len=blob.get("kv_len"), pages=blob.get("n_pages"))
                return blob
            return None

        timeout = (max(0.05, deadline - time.monotonic())
                   if deadline is not None else 30.0)
        if not self._running:
            # Crashed member (fleet kill): call_on_loop would run the
            # export inline — but the loop thread may still be INSIDE
            # its final iteration, mutating the very slot state the
            # snapshot reads. Wait for it to die first; a loop that
            # won't die within the budget is a recompute fallback, not
            # a torn snapshot.
            t = self._thread
            if t is not None and t.is_alive():
                t.join(timeout=timeout)
                if t.is_alive():
                    return None
        try:
            return self.call_on_loop(_do, timeout=timeout)
        except TimeoutError:
            return None  # wedged loop: the guarded fn no-ops if it runs

    def resolve_export(self, rid: int, commit: bool = True,
                       why: str = "") -> bool:
        """Phase 2: release the parked source state. Commit and abort
        free identically (full prompt pages merge into the prefix
        cache); they differ in the journal story — an abort records WHY
        the transfer failed, and the caller falls back to recompute.
        The parked member-side request finishes CANCELLED either way so
        its server handler / stream consumers unblock."""
        def _do():
            ent = self._migrations.pop(rid, None)
            if ent is None:
                return False
            rt, handle = ent
            req = handle["req"]
            try:
                rt.release_export(handle)
            except Exception:  # noqa: BLE001 — state release must not wedge
                log.exception("release of migrated slot failed (%s)",
                              getattr(rt, "name", "?"))
            if not commit:
                self.journal.record("migrate_abort", req=req,
                                    why=why or "transfer_failed")
            self.core.mark_dropped(req.user)
            # The finish carries the freed slot so the journal's
            # slot-occupancy story stays consistent: the next install
            # into this slot is a reuse, not a double-assignment.
            extra = ({"slot": handle["slot"]} if "slot" in handle else {})
            self.journal.record("finish", req=req, reason="cancelled",
                                tokens=len(req.generated_ids),
                                model=getattr(rt, "name", None), **extra)
            req.finish(FinishReason.CANCELLED)
            self.notify()
            return True

        return self.call_on_loop(_do)

    def import_stream(self, blob: dict, ip: str = "", family=None,
                      deadline: Optional[float] = None, trace_ctx=None,
                      trace_meter: bool = True) -> Request:
        """Target side of a migration: rebuild the Request and install
        it DIRECTLY into a decode slot from the shipped pages — no
        queue wait, no re-prefill. Raises MigrationError when it cannot
        land (caller falls back to recompute). Bypasses bounded
        admission like inject_request: the router already admitted.
        `trace_ctx`/`trace_meter` as in inject_request: the continuation
        traces under the router's fleet context."""
        state = blob.get("request") or {}
        if not state.get("user"):
            raise MigrationError("malformed migration blob (no request)")

        def _do():
            rid = self.core.enqueue(
                state["user"], ip, state.get("model"),
                family if family is not None else Family.UNKNOWN)
            # The id is all we need — the stream never waits in this
            # member's queue (it resumes mid-decode), so take the queue
            # entry straight back out and count it started instead.
            self.core.cancel(rid)
            req = request_from_migration_state(rid, state)
            req._inc_decode = blob.get("_inc_decode")
            req.deadline = deadline
            if trace_ctx is not None:
                req.trace = self.tracer.begin(
                    rid, req.user, req.model, kind=req.kind,
                    ctx=trace_ctx, metered=trace_meter)
            rt = self.resolve_runtime(state.get("model"), kind="generate")
            if rt is None:
                raise MigrationError(
                    f"model not loaded: {state.get('model')}")
            reps = rt.replicas if isinstance(rt, ReplicaSet) else [rt]
            for rep in reps:
                import_fn = getattr(rep, "import_request", None)
                if import_fn is not None and import_fn(blob, req):
                    break
            else:
                raise MigrationError("no slot/pages for migrated stream")
            self.core.mark_started(req.user)
            req.started = True
            self.journal.record(
                "migrate_import", req=req, tokens=len(req.generated_ids),
                pages=blob.get("n_pages"))
            self.notify()
            return req

        return self.call_on_loop(_do)

    def export_prefix(self, model: str, tokens) -> Optional[dict]:
        """Affinity-miss prefix shipping, source side (router seam)."""
        def _do():
            rt = self.resolve_runtime(model)
            if rt is None:
                return None
            reps = rt.replicas if isinstance(rt, ReplicaSet) else [rt]
            for rep in reps:
                fn = getattr(rep, "export_prefix", None)
                if fn is not None:
                    blob = fn(list(tokens))
                    if blob is not None:
                        return blob
            return None

        try:
            return self.call_on_loop(_do, timeout=10.0)
        except TimeoutError:
            return None

    def import_prefix(self, model: str, blob: dict) -> int:
        """Affinity-miss prefix shipping, target side: pages adopted."""
        def _do():
            rt = self.resolve_runtime(model)
            if rt is None:
                return 0
            reps = rt.replicas if isinstance(rt, ReplicaSet) else [rt]
            for rep in reps:
                fn = getattr(rep, "import_prefix", None)
                if fn is not None:
                    n = fn(blob)
                    if n:
                        return n
            return 0

        try:
            return self.call_on_loop(_do, timeout=10.0)
        except TimeoutError:
            return 0

    def _count_shed(self, reason: str) -> None:
        tm.SHED_TOTAL.labels(reason=reason).inc()
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + 1

    def retry_after_s(self) -> float:
        """Retry-After estimate for shed responses: queue depth over the
        OBSERVED completion rate (recent finish timestamps from the
        tracer), clamped to [1, 300]. No completions observed yet =>
        a conservative small default — better an honest guess than a
        magic constant pretending precision."""
        queued = max(1, self.core.total_queued())
        window = getattr(self.tracer, "finish_times", None)
        if window and len(window) >= 2:
            span = window[-1] - window[0]
            if span > 0:
                rate = (len(window) - 1) / span  # completions per second
                return float(min(300.0, max(1.0, queued / rate)))
        # Cold start: no completions observed yet, so queue depth says
        # nothing about drain rate — clamp to a small fixed window
        # instead of extrapolating (a 500-deep startup queue must not
        # answer "Retry-After: 500 seconds" off zero samples).
        return float(min(10.0, max(2.0, float(queued))))

    def _requeue_preempted(self, req: Request) -> bool:
        """on_preempt hook: return a preempted request to the FRONT of
        its user's native queue for recompute re-admission. False => the
        request could not be requeued (cancelled/expired/blocked) and was
        finished here — its pages are already released by the caller."""
        if req.cancelled.is_set():
            self.core.mark_dropped(req.user)
            self.journal.record("finish", req=req, reason="cancelled")
            req.finish(FinishReason.CANCELLED)
            return False
        if req.expired():
            # Deadline check at preemption re-admission: recompute for a
            # response nobody will wait for is pure waste.
            drop_expired(req, self.core, req.model, journal=self.journal)
            return False
        try:
            with self._pending_lock:
                new_rid = self.core.requeue_front(req.user, "", req.model,
                                                  kind=req.kind)
                req.req_id = new_rid
                self.pending[new_rid] = req
            req.trace_event("requeue")
            self.journal.record("requeue", req=req, why="preempt")
            self.notify()
            return True
        except BlockedError:
            self.core.mark_dropped(req.user)
            self.journal.record("finish", req=req, reason="cancelled")
            req.finish(FinishReason.CANCELLED)
            return False

    def _retry_or_error(self, req: Request, msg: str,
                        replay: bool = False) -> None:
        """Route a request implicated in a runtime failure: one retried
        dispatch via the front of its user's native queue (backoff
        honored by the runtime's pending gate), or a poisoned explicit
        error once the budget is spent. `replay=True` folds generated
        ids into the prompt so a mid-decode victim resumes its stream."""
        started = getattr(req, "started", True)
        if req.cancelled.is_set():
            self.core.mark_dropped(req.user, started=started)
            self.journal.record("finish", req=req, reason="cancelled")
            req.finish(FinishReason.CANCELLED)
            return
        if req.expired():
            drop_expired(req, self.core, req.model, journal=self.journal)
            return
        if req.retries >= self.ecfg.step_retries:
            self.core.mark_dropped(req.user, started=started)
            self.journal.record("poison", req=req, retries=req.retries,
                                error=msg[:120])
            req.finish(FinishReason.ERROR, error=(
                f"{msg} (request poisoned after {req.retries} retr"
                f"{'y' if req.retries == 1 else 'ies'})"))
            return
        req.retries += 1
        self._engine_retries += 1
        tm.RETRIES_TOTAL.labels(model=req.model or "?").inc()
        req._retry_at = time.monotonic() + (
            self.ecfg.retry_backoff_s * (2 ** (req.retries - 1)))
        if replay and req.generated_ids:
            # Resume-from-failure recompute: the fresh runtime re-prefills
            # prompt + everything already streamed, then continues.
            req.prompt_tokens = (req.prompt_tokens
                                 + req.generated_ids[req._replay_gen:])
            req._replay_gen = len(req.generated_ids)
        req.trace_event("retry", error=msg[:200], n=req.retries)
        self.journal.record("retry", req=req, n=req.retries,
                            error=msg[:120])
        try:
            with self._pending_lock:
                new_rid = self.core.requeue_front(req.user, "", req.model,
                                                  kind=req.kind)
                req.req_id = new_rid
                self.pending[new_rid] = req
            self.notify()
        except BlockedError:
            self.core.mark_dropped(req.user, started=started)
            self.journal.record("finish", req=req, reason="cancelled")
            req.finish(FinishReason.CANCELLED)

    def cancel(self, req_id: int) -> None:
        with self._pending_lock:
            req = self.pending.get(req_id)
        if req is not None:
            req.cancelled.set()
            # Still in the native queue (never admitted): remove it there and
            # finish the stream now — nothing else will ever pop it.
            if self.core.cancel(req_id):
                with self._pending_lock:
                    self.pending.pop(req_id, None)
                req.finish(FinishReason.CANCELLED)
            self.notify()
            return
        if req is None:
            # Mid-migration: the request is detached from every slot but
            # still parked in the two-phase handoff table.
            for _rt, handle in self._migrations.values():
                if handle["req"].req_id == req_id:
                    req = handle["req"]
                    break
        if req is None:
            # Already admitted: find it in a runtime (active slot or
            # waiting for prefill). _step_targets flattens replica sets —
            # requests live on the individual replicas, never the set.
            for rt in self._step_targets():
                holders = (
                    list(getattr(rt, "slot_req", []))
                    + list(getattr(rt, "active", []))
                    + list(getattr(rt, "pending_prefill", []))
                    + list(getattr(rt, "pending_embed", []))
                    + list(getattr(rt, "chunking", []))
                    + list(getattr(rt, "inflight_prefill", []))
                    + list(getattr(rt, "pending", []))
                )
                for cand in holders:
                    if cand is not None and cand.req_id == req_id:
                        req = cand
                        break
                if req is not None:
                    break
        if req is not None:
            req.cancelled.set()
        else:
            self.core.cancel(req_id)  # still queued in the native core
        self.notify()

    def notify(self) -> None:
        with self._cond:
            self._cond.notify()

    def call_on_loop(self, fn, timeout: float = 900.0):
        """Run `fn` on the engine thread, serialized with device dispatches,
        and return its result (raising what it raised). When the loop isn't
        running — or we ARE the engine thread — runs inline. The generous
        default timeout covers weight reloads behind queued work."""
        if not self._running or threading.current_thread() is self._thread:
            return fn()
        ev = threading.Event()
        box: dict = {}
        entry = (fn, ev, box)
        self._engine_calls.append(entry)
        self.notify()
        if not self._running:
            # stop() may have drained the queue just before our append; if
            # our entry is still there, nothing will ever run it — reclaim
            # and run inline.
            try:
                self._engine_calls.remove(entry)
            except ValueError:
                pass  # loop or stop() took it; the event will fire
            else:
                return fn()
        if not ev.wait(timeout):
            raise TimeoutError("engine-loop call timed out")
        if "err" in box:
            raise box["err"]
        return box.get("ret")

    def _drain_engine_calls(self) -> None:
        while self._engine_calls:
            fn, ev, box = self._engine_calls.popleft()
            try:
                box["ret"] = fn()
            except BaseException as e:  # delivered to the waiting thread
                box["err"] = e
            ev.set()

    def resolve_runtime(self, model: str, kind: str = "generate"):
        if not model:
            # No model requested: any LIVE runtime of the right KIND
            # (reference lets Unknown-family tasks hit any online backend,
            # dispatcher.rs:453-461 — offline ones are skipped). The kind
            # filter keeps a generative request off an EncoderRuntime when
            # only encoders are loaded: it would "finish" with an embedding
            # and no tokens.
            def kind_ok(rt):
                return kind in getattr(rt, "SERVES", ("generate",))

            for rt in self.runtimes.values():
                if isinstance(rt, ReplicaSet) and kind_ok(rt.replicas[0]) \
                        and any(not r._failed for r in rt.replicas):
                    return rt
                if isinstance(rt, (ModelRuntime, EncoderRuntime)) \
                        and kind_ok(rt) and not rt._failed:
                    return rt
            # Everything of the right kind is failed (mid-recovery): pick
            # one anyway — the request parks on it and drains post-reload.
            for rt in self.runtimes.values():
                probe = rt.replicas[0] if isinstance(rt, ReplicaSet) else rt
                if kind_ok(probe):
                    return rt
            return None
        key = smart_match(model, self.runtimes.keys())
        return self.runtimes[key] if key is not None else None

    # -- main loop ---------------------------------------------------------
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, name="engine", daemon=True)
        self._thread.start()
        if self.health is None:
            from ollamamq_tpu.engine.health import HealthMonitor

            self.health = HealthMonitor(self)
            self.health.start()
        if self.durability is not None:
            # WAL recovery runs with the loop live (re-admissions flow
            # through the normal enqueue path) and before the HTTP
            # front-end starts serving — readiness is gated on it.
            self.durability.start(self)

    def stop(self) -> None:
        self._running = False
        self.notify()
        if self._thread:
            self._thread.join(timeout=10)
            self._thread = None
        # Fail any deferred engine-thread calls that raced the shutdown —
        # their waiters would otherwise block until the call_on_loop
        # timeout.
        while self._engine_calls:
            _fn, ev, box = self._engine_calls.popleft()
            box["err"] = RuntimeError("engine stopped")
            ev.set()
        if self.health is not None:
            self.health.stop()
            self.health = None
        if self.durability is not None:
            self.durability.close()  # final WAL flush + fsync
        self.journal.close()  # flush any --journal-file spill

    def quiesce(self) -> None:
        """Graceful-shutdown gate: stop accepting new requests (later
        enqueues shed with 503) while everything in flight drains."""
        self.accepting = False

    def inflight_count(self) -> int:
        """Queued + admitted-but-unfinished work — what a graceful
        shutdown waits on before flushing and exiting."""
        n = self.core.total_queued()
        for rt in self._step_targets():
            n += rt.active_count()
            for attr in ("pending_prefill", "pending_embed", "chunking",
                         "pending"):
                n += len(getattr(rt, attr, ()) or ())
        return n + len(self._migrations)

    @staticmethod
    def _gate_eligible(rt, kind: str) -> bool:
        """Gate-eligibility of a runtime for one request kind: it can
        accept one NOW, or it permanently cannot serve the kind — then
        the pop must still reach _place so the mismatch errors loudly
        (never parks as unservable)."""
        probe = rt.replicas[0] if isinstance(rt, ReplicaSet) else rt
        if kind not in getattr(probe, "SERVES", ("generate",)):
            return True
        return rt.has_capacity(kind)

    def _admit(self) -> int:
        admitted = 0
        pol = self.policy
        # One batch tick on the scheduler clock — the anti-starvation
        # aging runs on admission passes, which fire once per engine
        # loop iteration in the live engine AND once per virtual tick in
        # the synchronous replay/simulate drivers.
        pol.on_admit_tick()
        # Retry orphans: ids popped before their Request was registered
        # (two-step submit flow); give them a 5 s grace. Expiry always runs;
        # the capacity gate only defers placement of registered requests.
        now = time.monotonic()
        for rid, user, model, ts in list(self._orphans):
            with self._pending_lock:
                req = self.pending.pop(rid, None)
                if req is None and now - ts > 5.0:
                    # Expire under the lock so submit() can't slip the
                    # Request into `pending` between our check and write.
                    self._orphans.remove((rid, user, model, ts))
                    self._expired_orphans[rid] = now
                    req_expired = True
                else:
                    req_expired = False
            if req_expired:
                self.core.mark_dropped(user, started=False)
                continue
            if req is None:
                continue  # still within grace, not yet registered
            rt = self.resolve_runtime(model, kind=req.kind)
            if rt is not None and not self._gate_eligible(rt, req.kind):
                # Runtime full for this kind: put the Request back and
                # retry later.
                with self._pending_lock:
                    self.pending[rid] = req
                continue
            self._orphans.remove((rid, user, model, ts))
            req.trace_event("admit")
            self.journal.record("admit", req=req,
                                queued=self.core.total_queued())
            if self._place(req, user, model):
                admitted += 1
        # Age out expiry tombstones nothing ever claimed (slow leak guard).
        for rid, ts in list(self._expired_orphans.items()):
            if now - ts > 60.0:
                del self._expired_orphans[rid]
        # Candidate batch: the window of pops the fair-share core
        # released this pass, placed in POLICY order (decision point
        # (a)). fcfs has admission_window == 1, so each pop flushes
        # immediately — the exact legacy pop-and-place flow.
        batch: List[tuple] = []  # (rid, user, model, req)

        def flush() -> None:
            nonlocal admitted
            if not batch:
                return
            ordered = pol.order_admission(list(batch))
            batch.clear()
            if len(ordered) > 1 and pol.name != "fcfs":
                first = ordered[0][3]
                self.journal.record(
                    "sched", req=first, policy=pol.name, point="admit",
                    candidates=len(ordered),
                    predicted=pol.predict(first),
                    score=round(pol.score(first), 3))
            for rid, user, model, req in ordered:
                req.trace_event("admit")
                self.journal.record("admit", req=req,
                                    queued=self.core.total_queued())
                if self._place(req, user, model):
                    admitted += 1

        while True:
            # Two capacity pools, one gate each: the native pop gates an
            # embed task on the embed list and a generate task on the
            # generate list, so neither kind's backlog parks the other.
            gen_ok = [name for name, rt in self.runtimes.items()
                      if self._gate_eligible(rt, "generate")]
            emb_ok = [name for name, rt in self.runtimes.items()
                      if self._gate_eligible(rt, "embed")]
            if not gen_ok and not emb_ok:
                break
            items, stuck = self.core.next_window(
                pol.admission_window, eligible_models=gen_ok,
                eligible_embed=emb_ok)
            for rid, user, model in items:
                with self._pending_lock:
                    req = self.pending.pop(rid, None)
                if req is None:
                    # Popped before registration (legacy two-step
                    # submit): park it and retry for a grace period.
                    self._orphans.append((rid, user, model,
                                          time.monotonic()))
                    continue
                batch.append((rid, user, model, req))
            flush()
            if stuck:
                # Policy pick unservable; cursor advanced, retry on wake.
                # Rate-limited warn for operator visibility (the reference
                # logs "Request stuck in queue", dispatcher.rs:467-473).
                now = time.monotonic()
                if now - self._last_stuck_log > 10.0:
                    self._last_stuck_log = now
                    log.warning(
                        "request stuck in queue: scheduler pick needs a model "
                        "not currently servable (generate-ready: %s, "
                        "embed-ready: %s; %d queued)",
                        gen_ok, emb_ok, self.core.total_queued(),
                    )
                break
            if not items:
                break
        return admitted

    def _place(self, req: Request, user: str, model: str) -> bool:
        # Late re-check (dispatcher.rs:503-512): client gone OR user/IP
        # blocked after enqueueing ⇒ drop, never serve.
        if req.cancelled.is_set() or self.core.is_user_or_ip_blocked(user):
            self.core.mark_dropped(user, started=req.started)
            self.journal.record("finish", req=req, reason="cancelled")
            req.finish(FinishReason.CANCELLED)
            return False
        if req.expired():
            # Deadline check at admission: an expired pop is dropped here,
            # before it can claim a slot or a prefill forward.
            drop_expired(req, self.core, model, journal=self.journal)
            return False
        rt = self.resolve_runtime(model, kind=req.kind)
        if rt is None and model:
            # The native eligibility gate raced an evict: the model vanished
            # between mq_next's model check and placement. Stuck-queue
            # semantics (the reference parks requests whose backend is gone,
            # dispatcher.rs:467-473) — put it back rather than erroring.
            # Named models only: an empty model always passes the native
            # gate, so requeueing it would spin.
            return self._requeue(req, user, model)
        if rt is None:
            self.core.mark_dropped(user, started=req.started)
            self.journal.record("finish", req=req, reason="error")
            req.finish(FinishReason.ERROR, error=f"model not loaded: {model}")
            return False
        # Named-model kind check: generate on an encoder would "finish"
        # with an embedding and zero tokens — a permanent mismatch, so
        # error, don't park. (Generative runtimes serve BOTH kinds via
        # step_embed; the embed-side message is kept for runtime kinds
        # that opt out of embedding.)
        probe = rt.replicas[0] if isinstance(rt, ReplicaSet) else rt
        if req.kind not in getattr(probe, "SERVES", ("generate",)):
            self.core.mark_dropped(user, started=req.started)
            self.journal.record("finish", req=req, reason="error")
            req.finish(FinishReason.ERROR, error=(
                f"model {model or probe.name} is an embedding-only model"
                if req.kind == "generate"
                else f"model {model or probe.name} does not support "
                     "embeddings"))
            return False
        if not rt.submit(req):
            if model:
                # Replica capacity raced away between the admission gate
                # and placement: wait-in-queue, same as the evict race
                # above — the native gate holds it until capacity returns.
                return self._requeue(req, user, model)
            # Empty-model requests always pass the native gate, so a
            # requeue would spin; park on the least-loaded live replica.
            rt.force_submit(req)
        req.trace_event("place", runtime=getattr(rt, "name", model))
        self.journal.record("place", req=req,
                            runtime=getattr(rt, "name", model))
        if not req.started:
            # Preempted/retried requeues were already counted as started;
            # a second mark would leak a processing count forever.
            self.core.mark_started(user)
            req.started = True
        return True

    def _requeue(self, req: Request, user: str, model: str) -> bool:
        """Return a popped-but-unplaceable request to the FRONT of its
        user's native queue (wait-don't-fail, FIFO preserved: the evict/
        capacity race must never let the user's later request overtake
        this one). Always returns False (nothing was placed)."""
        try:
            with self._pending_lock:
                new_rid = self.core.requeue_front(user, "", model,
                                                  kind=req.kind)
                req.req_id = new_rid
                self.pending[new_rid] = req
            req.trace_event("requeue")
            self.journal.record("requeue", req=req, why="unplaceable")
        except BlockedError:
            self.core.mark_dropped(user, started=False)
            self.journal.record("finish", req=req, reason="cancelled")
            req.finish(FinishReason.CANCELLED)
        return False

    def _step_targets(self) -> List[object]:
        """Individually-steppable runtimes: replica sets flatten so each
        replica advances every tick. The loop dispatches every runtime's
        decode chunk before collecting any (dispatch/collect split in
        ModelRuntime), so replicas on disjoint device sets genuinely
        execute concurrently rather than serializing on this thread."""
        out: List[object] = []
        for rt in self.runtimes.values():
            if isinstance(rt, ReplicaSet):
                out.extend(rt.replicas)
            else:
                out.append(rt)
        return out

    def _kill_runtime(self, rt) -> None:
        """A runtime failure must not kill the engine loop: fail every
        request this runtime holds and keep serving the rest (reference
        analogue: an errored dispatch returns 500 and counts dropped,
        dispatcher.rs:555-559)."""
        self._fail_runtime(rt, "engine step failed")
        rt._failed = True
        # Drop the dead runtime's device buffers NOW: the HBM must be free
        # before the replacement loads, or a large model could never
        # recover (params + KV would be resident twice).
        rt.params = None
        if hasattr(rt, "kc"):
            rt.kc = rt.vc = None
        self._failed_runtimes.append(rt)

    def _loop(self) -> None:
        while self._running:
            try:
                self._loop_once()
            except Exception:
                # The engine thread must never die: a control-plane bug
                # (admission, recovery bookkeeping) would otherwise stop
                # ALL serving with requests parked forever. Runtime step
                # errors are already handled per-runtime inside _loop_once.
                log.exception("engine loop iteration failed; continuing")
                time.sleep(0.1)

    # HBM/allocator timeline (telemetry/stepprof.py): one bounded-ring
    # sample per period — the engine ticks far faster — of every
    # runtime's page-pool state + weight/KV footprint, the trend
    # /debug/hbm serves and an OOM postmortem reads back over time.
    HBM_SAMPLE_PERIOD_S = 1.0
    _hbm_last_sample = 0.0

    def _sample_hbm_timeline(self) -> None:
        now = time.monotonic()
        if now - self._hbm_last_sample < self.HBM_SAMPLE_PERIOD_S:
            return
        self._hbm_last_sample = now
        models = {}
        for name, rt in self.runtimes.items():
            entry = {"weight_bytes": int(getattr(rt, "param_bytes", 0)),
                     "kv_bytes": int(getattr(rt, "kv_bytes", 0))}
            alloc = getattr(rt, "alloc", None)
            if alloc is not None:
                entry.update(free=alloc.free_pages, used=alloc.used_pages,
                             cached=alloc.cached_pages,
                             pool=alloc.num_pages - 1)
            models[name] = entry
        stepprof.PROFILER.hbm_record({"models": models})

    def _loop_once(self) -> None:
        self.last_tick_at = time.monotonic()
        self.journal.tick += 1
        self._sample_hbm_timeline()
        self._drain_engine_calls()
        self._swap_rebuilt()
        if (self._failed_runtimes
                and time.monotonic() - self._last_recover_attempt
                > self.recover_interval):
            self._try_recover()
        self._admit()
        did_work = False
        # Phase 1: prefills + decode DISPATCH for every runtime. JAX
        # dispatch is async, so once runtime A's chunk is in flight the
        # loop immediately dispatches runtime B's — dp replicas (and
        # distinct models on disjoint submeshes) overlap on device.
        handles: List[tuple] = []  # (rt, decode handle)
        for rt in self._step_targets():
            if getattr(rt, "_failed", False):
                continue
            try:
                rt.check_cancellations(self.core)
                if isinstance(rt, ModelRuntime):
                    ran_ragged = False
                    if getattr(rt, "ragged", False):
                        # Ragged mixed batch: admission + ONE token-budget
                        # dispatch packing prefill spans AND every live
                        # decode slot (each advances one token inside it).
                        if rt.step_ragged(self.core):
                            ran_ragged = True
                            did_work = True
                    else:
                        # Pipeline-parallel path (pp > 1): stage-scheduled
                        # bucketed prefill + fused decode.
                        # TTFT first: admit pending prefills into free
                        # slots — but bounded per tick, so a sustained
                        # arrival storm can't starve the active decode
                        # streams below (VERDICT r3 weak #5).
                        budget = self.ecfg.prefill_batches_per_tick
                        while (budget > 0 and rt.pending_prefill
                               and rt.step_prefill(self.core)):
                            budget -= 1
                            did_work = True
                        # One chunk of any long-prompt prefill per tick,
                        # interleaved with decode below.
                        if rt.step_chunk(self.core):
                            did_work = True
                    # Embeds on a generative model: one stateless batch
                    # forward, no slot/page contention with decode.
                    if rt.pending_embed and rt.step_embed(self.core):
                        did_work = True
                    if ran_ragged:
                        pass  # decode advanced inside the mixed dispatch
                    elif any(r is not None for r in rt.slot_req):
                        # Short decode chunks (k=1) keep TTFT low ONLY
                        # when an admission could actually land between
                        # steps: pending work AND a free seat, or a
                        # chunked prefill to interleave. A saturated
                        # batch with a deep backlog must run the full
                        # fused chunk — per-step dispatch latency (the
                        # TPU tunnel round trip) would otherwise gate
                        # every token under exactly the 64-user load
                        # the engine is built for.
                        # Scoped to work THIS runtime could serve:
                        # backlog parked for another (or evicted) model
                        # must not hold a healthy runtime at k=1.
                        waiting = bool(rt.pending_prefill) or bool(
                            self.core.queued_matching(rt.name)
                        )
                        can_admit = waiting and rt.has_capacity("generate")
                        k = (1 if (can_admit or rt.chunking)
                             else self.ecfg.decode_steps_per_iter)
                        h = rt.step_decode_dispatch(self.core, k_steps=k)
                        if h is not None:
                            if self._serialize_multihost:
                                rt.step_decode_collect(h, self.core)
                            else:
                                handles.append((rt, h))
                            did_work = True
                        # h None with slots occupied = every occupant is a
                        # stalled page reservation: nap on the condvar
                        # (did_work stays False) instead of spinning.
                else:
                    if rt.has_work():
                        rt.step(self.core)
                        did_work = True
            except Exception:
                log.exception("runtime %s step failed", rt.name)
                self._kill_runtime(rt)
                did_work = True
        # Phase 2: collect every in-flight chunk. Device errors in the
        # async computation surface here, not at dispatch.
        for rt, h in handles:
            if getattr(rt, "_failed", False):
                continue
            try:
                rt.step_decode_collect(h, self.core)
            except Exception:
                log.exception("runtime %s decode collect failed", rt.name)
                self._kill_runtime(rt)
        if not did_work:
            with self._cond:
                self._cond.wait(timeout=0.05)

    def _try_recover(self) -> None:
        """Kick off background rebuilds of failed runtimes. The reference's
        recovery story is backends re-entering rotation when the health
        probe succeeds (dispatcher.rs:373-377); here re-entering rotation
        means a fresh runtime (weights reloaded), since the old one's
        device state is gone. The reload runs OFF the engine thread so
        healthy runtimes keep serving; _swap_rebuilt installs the result."""
        self._last_recover_attempt = time.monotonic()
        for rt in list(self._failed_runtimes):
            if id(rt) in self._recovering:
                continue
            self._recovering.add(id(rt))
            self._start_rebuild(rt)

    def _start_rebuild(self, rt) -> None:
        """Rebuild seam: background thread here; the SPMD engine overrides
        to broadcast a reload opcode and rebuild inline on the engine thread
        (ordered with the dispatch broadcast stream)."""
        threading.Thread(
            target=self._rebuild_runtime, args=(rt,),
            name=f"recover-{rt.name}", daemon=True,
        ).start()

    def _rebuild_runtime(self, rt) -> bool:
        """(background thread) Build a replacement runtime; post it for the
        engine thread to swap in. Returns success — the SPMD rebuild path
        must report its OWN failure truthfully at the status sync (ADVICE
        r3: claiming ok while failed re-broadcasts OP_RELOAD every retry,
        making healthy workers re-download weights each cycle)."""
        try:
            fresh = type(rt)(
                rt.name, getattr(rt, "_orig_cfg", rt.cfg), self.ecfg,
                mesh=rt.mesh,
                checkpoint_path=self._model_sources.get(rt.name),
                dtype=self.dtype,
            )
        except Exception:
            log.exception(
                "recovery reload of %s failed; retrying in %.0fs",
                rt.name, self.recover_interval,
            )
            self._recovering.discard(id(rt))  # next interval retries
            return False
        with self._rebuilt_lock:
            self._rebuilt.append((rt, fresh))
        self.notify()
        return True

    def _swap_rebuilt(self) -> None:
        """(engine thread) Install finished rebuilds and hand over any
        requests that raced into the dead runtime between failure and
        swap."""
        with self._rebuilt_lock:
            if not self._rebuilt:
                return
            items, self._rebuilt = self._rebuilt, []
        for rt, fresh in items:
            self._attach_hooks(fresh)
            if hasattr(rt, "spmd_index"):
                fresh.spmd_index = rt.spmd_index
                fresh.spmd_replica = getattr(rt, "spmd_replica", 0)
            cur = self.runtimes.get(rt.name)
            if isinstance(cur, ReplicaSet) and rt in cur.replicas:
                cur.replicas[cur.replicas.index(rt)] = fresh
            elif cur is rt:
                self.runtimes[rt.name] = fresh
            # else: evicted while failed — drop the rebuild silently.
            for attr in ("pending_prefill", "pending_embed", "chunking",
                         "pending"):
                q = getattr(rt, attr, None)
                while q:
                    fresh.submit(q.popleft())  # restart from scratch
            self._failed_runtimes.remove(rt)
            self._recovering.discard(id(rt))
            self.journal.record("rebuild", model=rt.name)
            log.warning("runtime %s recovered: weights reloaded, serving "
                        "resumes", rt.name)
            self.notify()

    def _fail_runtime(self, rt, msg: str) -> None:
        """Contain a runtime-step failure to the implicated requests: each
        one is retried ONCE on a fresh dispatch (front of its user's
        queue, exponential backoff; mid-decode victims replay
        prompt+generated so their stream resumes seamlessly after the
        rebuild), and requests that keep failing are poisoned with an
        explicit error — one bad input can't crash-loop the engine."""
        try:
            if isinstance(rt, ModelRuntime):
                for i, req in enumerate(rt.slot_req):
                    if req is not None:
                        rt._release_slot_pages(i)
                        rt.seq_lens[i] = 0
                        rt.slot_req[i] = None
                        self._retry_or_error(req, msg, replay=True)
                rt._stalled_slots.clear()
            act = getattr(rt, "active", None)
            if isinstance(act, list):  # FakeRuntime's slot table
                while act:
                    self._retry_or_error(act.pop(), msg, replay=True)
            for attr in ("pending_prefill", "pending_embed", "chunking",
                         "pending"):
                pending = getattr(rt, attr, None)
                while pending:
                    self._retry_or_error(pending.popleft(), msg)
            if hasattr(rt, "reserved_slots"):
                for slot in list(rt.reserved_slots):
                    rt._release_slot_pages(slot)
                rt.reserved_slots.clear()
        except Exception:
            log.exception("error while failing runtime %s", rt.name)

    # -- prefix cache (GET/POST /debug/prefix_cache) -----------------------
    def scheduler_stats(self) -> dict:
        """Live scheduling-policy readout (TUI sched chip, engine stats,
        /metrics.json): active policy, output-length predictor accuracy
        over its recent window (None until warmed up — rendered as
        "acc n/a"), observation count, and reorder decisions applied."""
        p = self.policy
        acc = p.predictor.accuracy()
        return {"policy": p.name,
                "pred_accuracy": round(acc, 4) if acc is not None else None,
                "pred_observed": p.predictor.observed,
                "decisions": p.decisions}

    def prefix_cache_stats(self) -> dict:
        """Per-model prefix-cache stats (replicas summed); works on any
        engine subclass — runtimes without a cache are skipped."""
        models: Dict[str, list] = {}
        for rt in self._step_targets():
            pc = getattr(rt, "prefix_cache", None)
            if pc is not None:
                models.setdefault(rt.name, []).append(pc.stats())
        merged = {name: merge_prefix_cache_stats(reps)
                  for name, reps in models.items()}
        return {"enabled": bool(merged), "models": merged}

    def prefix_cache_flush(self) -> int:
        """Evict every unreferenced cached page on every runtime. Runs on
        the engine thread: the tree and allocator are engine-loop state."""
        def _do() -> int:
            freed = 0
            for rt in self._step_targets():
                pc = getattr(rt, "prefix_cache", None)
                if pc is not None:
                    freed += pc.flush()
            return freed

        if not any(getattr(rt, "prefix_cache", None) is not None
                   for rt in self._step_targets()):
            return 0  # nothing to flush (also: FakeEngine's loop has no
            #           call_on_loop drain — don't park on it)
        return self.call_on_loop(_do)

    # -- telemetry ---------------------------------------------------------
    def preemption_count(self) -> int:
        """Total KV-pressure preemptions across runtimes (TUI chip; the
        health monitor's preemption-storm rule rates this)."""
        return sum(getattr(rt, "preempt_count", 0)
                   for rt in self._step_targets())

    def retry_count(self) -> int:
        return self._engine_retries + sum(
            getattr(rt, "retry_count", 0) for rt in self._step_targets())

    def chip_stats(self) -> List[dict]:
        """Per-chip rows; the SPMD engine overrides to merge worker
        hosts' chips from the KV store."""
        return per_chip_stats()

    def worker_metric_snapshots(self) -> List[dict]:
        """Peer-host registry snapshots to merge into /metrics; the SPMD
        engine overrides to read them off the KV store."""
        return []

    def stale_worker_hosts(self) -> List[int]:
        """Process ids of SPMD worker hosts whose KV-store snapshots have
        stopped advancing; the stall watchdog alerts on them. The SPMD
        engine overrides — single-host engines have no peers."""
        return []

    def stats(self) -> dict:
        runtime_stats = [rt.stats() for rt in self.runtimes.values()]
        # Per-chip HBM (north star: "per-chip HBM occupancy", not one
        # device's counters standing in for the pod — VERDICT r3 weak #6).
        chips = self.chip_stats()
        hbm_used = sum(c["hbm_used"] for c in chips) or sum(
            r["param_bytes"] + r["kv_bytes"] for r in runtime_stats)
        hbm_total = sum(c["hbm_total"] for c in chips) or None
        return {
            "runtimes": runtime_stats,
            "chips": chips,
            # Mesh layout so operators can see WHICH parallelism the pod
            # is running (axis name -> size), not just how many chips.
            "mesh": dict(self.mesh.shape) if self.mesh is not None else None,
            "hbm_used_bytes": hbm_used,
            "hbm_total_bytes": hbm_total,
            "devices": [str(d) for d in jax.devices()],
            "uptime_s": round(time.time() - self.started_at, 1),
            "health": health.status() if (health := self.health) else None,
            "queue": self.core.snapshot(),
            # Degradation counters: sheds by reason (admission caps,
            # deadlines, kv exhaustion) + total preemptions/retries.
            "shed": dict(self.shed_counts),
            "preemptions": self.preemption_count(),
            "retries": self.retry_count(),
            # Scheduling policy + output-length predictor accuracy.
            "scheduler": self.scheduler_stats(),
            # Engine performance plane: compile count + rolling step p99
            # (the TUI `compiles N · step p99` chip's source).
            "stepprof": stepprof.PROFILER.brief(),
        }
