"""Crash-safe serving: durable admission WAL + cold-restart recovery.

`--wal-dir` layers a write-ahead request log on the serving front-end
(single engine or fleet router): every accepted generation request is
durably recorded — prompt token ids, user, sampling params, request id —
with batched fsync BEFORE the enqueue is ACKed to the client, and every
emitted token is appended behind it, so a `kill -9` of the serving
process loses at most one fsync window of progress and NO admitted
request. On the next start a recovery pass replays the WAL: unfinished
requests are re-admitted token-exact (the Ollama `context` re-prefill
path with generated_ids pre-filled), journaled as `recover_replay`, and
disconnected clients reattach with `GET /api/stream/{req_id}?from=N` to
receive the remainder byte- and token-identical to an uninterrupted run.

The fallback ladder only ever extends: migration -> recompute replay ->
WAL recovery -> explicit error. Never a silent drop.
"""

from ollamamq_tpu.durability.manager import DurabilityManager, StreamEntry
from ollamamq_tpu.durability.wal import RequestWAL, load_wal_records

__all__ = ["DurabilityManager", "RequestWAL", "StreamEntry",
           "load_wal_records"]
