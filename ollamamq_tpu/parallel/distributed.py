"""Multi-host control plane: jax.distributed + cross-host mesh building.

The reference's distribution story is N independent HTTP backends glued by
a proxy; here a deployment is one SPMD program across hosts: every host
runs the same engine binary, `jax.distributed.initialize` wires the
control plane, the mesh spans all hosts' devices (ICI within a slice, DCN
across slices), and XLA's collectives do the data movement that reqwest
did in the reference. Host 0 additionally runs the HTTP front + scheduler;
the other hosts participate in the jitted steps via SPMD.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

from ollamamq_tpu.parallel.mesh import make_mesh

log = logging.getLogger("ollamamq.distributed")


def multiprocess_configured() -> bool:
    """True when the env opts into a multi-process runtime — the SAME
    condition initialize() uses to decide whether to bring one up (callers
    that must defer backend-touching work until after initialize() share
    this instead of re-deriving it)."""
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = int(env_np) if env_np else None
    return bool(os.environ.get("JAX_COORDINATOR_ADDRESS")) or (
        num_processes not in (None, 1)
    )


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize the multi-host control plane. No-ops for single-process.

    Args fall back to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID). Multi-host is strictly OPT-IN via
    those vars (or explicit args): a bare jax.distributed.initialize()
    auto-detect is NOT attempted, because on a plain single host it can
    hang waiting for a coordinator. Returns True if a multi-process
    runtime was initialized.
    """
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if not coordinator_address and num_processes in (None, 1):
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "distributed runtime up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


def global_mesh(dp: int = 1, sp: int = 1, tp: int = -1, pp: int = 1,
                ep: int = 1):
    """Mesh over ALL processes' devices. Axis order puts "tensor" innermost
    so TP collectives ride ICI within a host/slice and only the outer axes
    ("data", "pipe", "seq") cross DCN — the layout the scaling playbook
    prescribes."""
    return make_mesh(dp=dp, sp=sp, tp=tp, pp=pp, ep=ep,
                     devices=jax.devices())


def is_primary() -> bool:
    """The host that runs the HTTP front + scheduler (process 0)."""
    return jax.process_index() == 0


def barrier(name: str = "ollamamq") -> None:
    """Cross-host sync point (e.g. after weight loading, before serving)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
