/* mqcore implementation. See mqcore.h for the policy contract. */

#include "mqcore.h"

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <vector>

namespace {

struct Task {
  int64_t req_id;
  std::string user;
  std::string model;  // empty = none requested
  int api_family;
  int kind = 0;  // MQ_KIND_GENERATE / MQ_KIND_EMBED
};

std::string lower(const std::string &s) {
  std::string r = s;
  std::transform(r.begin(), r.end(), r.begin(),
                 [](unsigned char c) { return std::tolower(c); });
  return r;
}

std::string strip_tag(const std::string &s) {
  auto pos = s.find(':');
  return pos == std::string::npos ? s : s.substr(0, pos);
}

/* smart model match (dispatcher.rs:231-252): exact -> lowercase ->
 * tag-stripped, each tried against the available set both ways. */
bool smart_model_match(const std::string &want,
                       const std::vector<std::string> &have) {
  for (const auto &h : have)
    if (h == want) return true;
  std::string wl = lower(want);
  for (const auto &h : have)
    if (lower(h) == wl) return true;
  std::string wb = strip_tag(wl);
  for (const auto &h : have)
    if (strip_tag(lower(h)) == wb) return true;
  return false;
}

void json_escape(std::string &out, const std::string &s) {
  out += '"';
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;
        }
    }
  }
  out += '"';
}

/* Tiny JSON string-array scanner sufficient for the blocklist schema
 * {"blocked_ips": [...], "blocked_users": [...]} (dispatcher.rs:19-25).
 * Not a general parser; unknown content is ignored. */
std::vector<std::string> scan_string_array(const std::string &text,
                                           const std::string &key) {
  std::vector<std::string> out;
  auto kpos = text.find("\"" + key + "\"");
  if (kpos == std::string::npos) return out;
  auto open = text.find('[', kpos);
  if (open == std::string::npos) return out;
  size_t i = open + 1;
  while (i < text.size() && text[i] != ']') {
    if (text[i] == '"') {
      std::string s;
      ++i;
      while (i < text.size() && text[i] != '"') {
        if (text[i] == '\\' && i + 1 < text.size()) {
          char n = text[i + 1];
          if (n == 'n') s += '\n';
          else if (n == 't') s += '\t';
          else if (n == 'r') s += '\r';
          else s += n;
          i += 2;
        } else {
          s += text[i++];
        }
      }
      ++i;
      out.push_back(s);
    } else {
      ++i;
    }
  }
  return out;
}

}  // namespace

struct mq_state {
  std::mutex mu;

  std::map<std::string, std::deque<Task>> queues;
  std::map<std::string, int64_t> processing_counts;
  std::map<std::string, int64_t> processed_counts;
  std::map<std::string, int64_t> dropped_counts;
  std::map<std::string, int64_t> served_tokens;
  std::map<std::string, std::string> user_ips;
  std::set<std::string> blocked_users;
  std::set<std::string> blocked_ips;
  std::string vip_user;    // empty = none
  std::string boost_user;  // empty = none
  int64_t global_counter = 0;
  size_t rr_cursor = 0;  // persistent across rounds (dispatcher.rs run_worker local)
  int64_t next_req_id = 1;
  int fairness_mode = MQ_FAIR_REQUESTS;
  // Bumped on every block mutation (user or IP, from any caller incl. the
  // native TUI thread); lets the engine's late blocked re-check sweep held
  // requests only when the blocklist actually changed.
  int64_t block_version = 0;
  std::string blocklist_path;

  void save_blocklist_locked() {
    if (blocklist_path.empty()) return;
    std::string out = "{\n  \"blocked_ips\": [";
    bool first = true;
    for (const auto &ip : blocked_ips) {
      if (!first) out += ", ";
      json_escape(out, ip);
      first = false;
    }
    out += "],\n  \"blocked_users\": [";
    first = true;
    for (const auto &u : blocked_users) {
      if (!first) out += ", ";
      json_escape(out, u);
      first = false;
    }
    out += "]\n}\n";
    std::ofstream f(blocklist_path, std::ios::trunc);
    f << out;
  }

  void load_blocklist() {
    if (blocklist_path.empty()) return;
    std::ifstream f(blocklist_path);
    if (!f) return;
    std::stringstream ss;
    ss << f.rdbuf();
    std::string text = ss.str();
    for (auto &ip : scan_string_array(text, "blocked_ips")) blocked_ips.insert(ip);
    for (auto &u : scan_string_array(text, "blocked_users")) blocked_users.insert(u);
  }

  int64_t fairness_count_locked(const std::string &user) {
    auto &m = fairness_mode == MQ_FAIR_TOKENS ? served_tokens : processed_counts;
    auto it = m.find(user);
    return it == m.end() ? 0 : it->second;
  }
};

extern "C" {

mq_state *mq_new(const char *blocklist_path) {
  auto *s = new mq_state();
  if (blocklist_path) s->blocklist_path = blocklist_path;
  s->load_blocklist();
  return s;
}

void mq_destroy(mq_state *s) { delete s; }

int64_t mq_enqueue_kind(mq_state *s, const char *user, const char *ip,
                        const char *model, int api_family, int kind) {
  std::lock_guard<std::mutex> g(s->mu);
  std::string u = user ? user : "anonymous";
  std::string i = ip ? ip : "";
  if (s->blocked_users.count(u)) return -1;
  if (!i.empty() && s->blocked_ips.count(i)) return -2;
  if (!i.empty()) s->user_ips[u] = i;
  Task t;
  t.req_id = s->next_req_id++;
  t.user = u;
  t.model = model ? model : "";
  t.api_family = api_family;
  t.kind = kind;
  s->queues[u].push_back(std::move(t));
  return s->queues[u].back().req_id;
}

int64_t mq_enqueue(mq_state *s, const char *user, const char *ip,
                   const char *model, int api_family) {
  return mq_enqueue_kind(s, user, ip, model, api_family, MQ_KIND_GENERATE);
}

/* Return a popped-but-unplaceable task to the FRONT of its user's queue
 * (fresh req_id). The reference never pops until it can dispatch (peek,
 * dispatcher.rs:427-431); when a placement races an evict or capacity
 * loss we must undo the pop without reordering the user's own requests —
 * a tail re-enqueue would let their request B serve before their earlier
 * A. Undoes the pop's global_counter advance so the boost cadence is
 * unchanged by the race. */
int64_t mq_requeue_front(mq_state *s, const char *user, const char *ip,
                         const char *model, int api_family, int kind) {
  std::lock_guard<std::mutex> g(s->mu);
  std::string u = user ? user : "anonymous";
  std::string i = ip ? ip : "";
  if (s->blocked_users.count(u)) return -1;
  if (!i.empty() && s->blocked_ips.count(i)) return -2;
  Task t;
  t.req_id = s->next_req_id++;
  t.user = u;
  t.model = model ? model : "";
  t.api_family = api_family;
  t.kind = kind;
  s->queues[u].push_front(std::move(t));
  if (s->global_counter > 0) s->global_counter -= 1;
  return s->queues[u].front().req_id;
}

int64_t mq_next(mq_state *s, const char *eligible_models, char *out_user,
                int user_cap, char *out_model, int model_cap) {
  return mq_next2(s, eligible_models, nullptr, out_user, user_cap, out_model,
                  model_cap);
}

int64_t mq_next2(mq_state *s, const char *eligible_generate,
                 const char *eligible_embed, char *out_user, int user_cap,
                 char *out_model, int model_cap) {
  std::lock_guard<std::mutex> g(s->mu);

  std::vector<std::string> active;
  for (auto &kv : s->queues)
    if (!kv.second.empty()) active.push_back(kv.first);
  if (active.empty()) return MQ_EMPTY;

  std::stable_sort(active.begin(), active.end(),
                   [&](const std::string &a, const std::string &b) {
                     int64_t at = s->fairness_count_locked(a);
                     int64_t bt = s->fairness_count_locked(b);
                     if (at != bt) return at < bt;
                     return a < b;
                   });

  std::string target;
  if (!s->vip_user.empty() &&
      std::find(active.begin(), active.end(), s->vip_user) != active.end()) {
    target = s->vip_user;
  }
  if (target.empty() && !s->boost_user.empty() && s->global_counter % 2 == 0 &&
      std::find(active.begin(), active.end(), s->boost_user) != active.end()) {
    target = s->boost_user;
  }
  if (target.empty()) {
    if (s->rr_cursor >= active.size()) s->rr_cursor = 0;
    target = active[s->rr_cursor];
    s->rr_cursor += 1;  // advances even if this pick turns out unservable
  }

  Task &front = s->queues[target].front();

  /* Model/capability gate: the TPU-era analogue of the backend filter
   * (dispatcher.rs:444-465). The list is chosen by the front task's KIND
   * — embed capacity (stateless batch forwards) and generate capacity
   * (decode slots + KV pages) are independent pools, so a saturated
   * decode batch must not park embeds and vice versa. NULL embed list =>
   * kind-blind (generate list for everything); NULL generate list =>
   * everything eligible. */
  const char *eligible = (front.kind == MQ_KIND_EMBED && eligible_embed)
                             ? eligible_embed
                             : eligible_generate;
  if (eligible != nullptr && !front.model.empty()) {
    std::vector<std::string> have;
    std::stringstream ss(eligible);
    std::string line;
    while (std::getline(ss, line, '\n'))
      if (!line.empty()) have.push_back(line);
    if (!smart_model_match(front.model, have)) return MQ_STUCK;
  }

  Task task = std::move(s->queues[target].front());
  s->queues[target].pop_front();
  if (s->queues[target].empty()) s->queues.erase(target);
  s->global_counter += 1;  // only on successful pop (dispatcher.rs:476)

  std::snprintf(out_user, user_cap, "%s", task.user.c_str());
  std::snprintf(out_model, model_cap, "%s", task.model.c_str());
  return task.req_id;
}

// Crash recovery (durability/): advance the request-id counter past the
// ids a previous process generation handed out (read back from its WAL),
// so re-admitted streams keep their old ids as stable client handles
// while fresh requests can never collide with them.
void mq_reserve_req_ids(mq_state *s, int64_t min_next) {
  std::lock_guard<std::mutex> g(s->mu);
  if (min_next > s->next_req_id) s->next_req_id = min_next;
}

int mq_cancel(mq_state *s, int64_t req_id) {
  std::lock_guard<std::mutex> g(s->mu);
  for (auto it = s->queues.begin(); it != s->queues.end(); ++it) {
    auto &dq = it->second;
    for (auto t = dq.begin(); t != dq.end(); ++t) {
      if (t->req_id == req_id) {
        s->dropped_counts[t->user] += 1;
        dq.erase(t);
        if (dq.empty()) s->queues.erase(it);
        return 1;
      }
    }
  }
  return 0;
}

void mq_mark_started(mq_state *s, const char *user) {
  std::lock_guard<std::mutex> g(s->mu);
  s->processing_counts[user] += 1;
}

void mq_mark_done(mq_state *s, const char *user, int64_t tokens_served) {
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->processing_counts.find(user);
  if (it != s->processing_counts.end() && it->second > 0) it->second -= 1;
  s->processed_counts[user] += 1;
  s->served_tokens[user] += tokens_served;
}

void mq_mark_dropped(mq_state *s, const char *user, int was_started) {
  std::lock_guard<std::mutex> g(s->mu);
  if (was_started) {
    auto it = s->processing_counts.find(user);
    if (it != s->processing_counts.end() && it->second > 0) it->second -= 1;
  }
  s->dropped_counts[user] += 1;
}

void mq_block_user(mq_state *s, const char *user) {
  std::lock_guard<std::mutex> g(s->mu);
  s->blocked_users.insert(user);
  s->block_version += 1;
  s->save_blocklist_locked();
}

void mq_unblock_user(mq_state *s, const char *user) {
  std::lock_guard<std::mutex> g(s->mu);
  s->blocked_users.erase(user);
  s->save_blocklist_locked();
}

void mq_block_ip(mq_state *s, const char *ip) {
  std::lock_guard<std::mutex> g(s->mu);
  s->blocked_ips.insert(ip);
  s->block_version += 1;
  s->save_blocklist_locked();
}

void mq_unblock_ip(mq_state *s, const char *ip) {
  std::lock_guard<std::mutex> g(s->mu);
  s->blocked_ips.erase(ip);
  s->save_blocklist_locked();
}

int mq_is_user_blocked(mq_state *s, const char *user) {
  std::lock_guard<std::mutex> g(s->mu);
  return s->blocked_users.count(user) ? 1 : 0;
}

int mq_is_ip_blocked(mq_state *s, const char *ip) {
  std::lock_guard<std::mutex> g(s->mu);
  return s->blocked_ips.count(ip) ? 1 : 0;
}

int64_t mq_block_version(mq_state *s) {
  std::lock_guard<std::mutex> g(s->mu);
  return s->block_version;
}

int mq_is_user_or_ip_blocked(mq_state *s, const char *user) {
  // One lock + one FFI round trip for the late re-check: blocked directly,
  // or via the last IP this user was seen from (dispatcher.rs:503-512
  // re-checks both sets).
  std::lock_guard<std::mutex> g(s->mu);
  if (s->blocked_users.count(user)) return 1;
  auto it = s->user_ips.find(user);
  return (it != s->user_ips.end() && s->blocked_ips.count(it->second)) ? 1 : 0;
}

int mq_unblock_item(mq_state *s, const char *item) {
  std::lock_guard<std::mutex> g(s->mu);
  int n = (int)s->blocked_users.erase(item) + (int)s->blocked_ips.erase(item);
  if (n) s->save_blocklist_locked();
  return n ? 1 : 0;
}

void mq_set_vip(mq_state *s, const char *user_or_null) {
  std::lock_guard<std::mutex> g(s->mu);
  s->vip_user = user_or_null ? user_or_null : "";
}

void mq_set_boost(mq_state *s, const char *user_or_null) {
  std::lock_guard<std::mutex> g(s->mu);
  s->boost_user = user_or_null ? user_or_null : "";
}

void mq_set_fairness_mode(mq_state *s, int mode) {
  std::lock_guard<std::mutex> g(s->mu);
  s->fairness_mode = mode;
}

int64_t mq_queue_len(mq_state *s, const char *user) {
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->queues.find(user);
  return it == s->queues.end() ? 0 : (int64_t)it->second.size();
}

int64_t mq_total_queued(mq_state *s) {
  std::lock_guard<std::mutex> g(s->mu);
  int64_t n = 0;
  for (auto &kv : s->queues) n += (int64_t)kv.second.size();
  return n;
}

int64_t mq_queued_matching(mq_state *s, const char *model) {
  /* Queued tasks THIS model could serve (no model requested, or a smart
   * match) — lets the engine's decode-chunk policy ignore backlog that can
   * never admit into a given runtime (e.g. requests parked for an evicted
   * model) instead of dropping to per-token dispatch for the outage. */
  std::lock_guard<std::mutex> g(s->mu);
  std::vector<std::string> have{model ? model : ""};
  int64_t n = 0;
  for (auto &kv : s->queues)
    for (auto &t : kv.second)
      if (t.model.empty() || smart_model_match(t.model, have)) n += 1;
  return n;
}

int64_t mq_snapshot_json(mq_state *s, char *out, int64_t cap) {
  std::lock_guard<std::mutex> g(s->mu);
  std::string j = "{";

  std::set<std::string> users;
  for (auto &kv : s->queues) users.insert(kv.first);
  for (auto &kv : s->processing_counts) users.insert(kv.first);
  for (auto &kv : s->processed_counts) users.insert(kv.first);
  for (auto &kv : s->dropped_counts) users.insert(kv.first);

  j += "\"users\":{";
  bool first = true;
  for (const auto &u : users) {
    if (!first) j += ",";
    first = false;
    json_escape(j, u);
    auto get = [](std::map<std::string, int64_t> &m, const std::string &k) {
      auto it = m.find(k);
      return it == m.end() ? (int64_t)0 : it->second;
    };
    auto qit = s->queues.find(u);
    int64_t queued = qit == s->queues.end() ? 0 : (int64_t)qit->second.size();
    char buf[256];
    std::snprintf(buf, sizeof buf,
                  ":{\"queued\":%lld,\"processing\":%lld,\"processed\":%lld,"
                  "\"dropped\":%lld,\"tokens\":%lld",
                  (long long)queued,
                  (long long)get(s->processing_counts, u),
                  (long long)get(s->processed_counts, u),
                  (long long)get(s->dropped_counts, u),
                  (long long)get(s->served_tokens, u));
    j += buf;
    auto ipit = s->user_ips.find(u);
    if (ipit != s->user_ips.end()) {
      j += ",\"ip\":";
      json_escape(j, ipit->second);
    }
    j += "}";
  }
  j += "},";

  j += "\"vip\":";
  if (s->vip_user.empty()) j += "null"; else json_escape(j, s->vip_user);
  j += ",\"boost\":";
  if (s->boost_user.empty()) j += "null"; else json_escape(j, s->boost_user);

  char buf[128];
  std::snprintf(buf, sizeof buf, ",\"global_counter\":%lld,",
                (long long)s->global_counter);
  j += buf;

  j += "\"blocked_users\":[";
  first = true;
  for (const auto &u : s->blocked_users) {
    if (!first) j += ",";
    json_escape(j, u);
    first = false;
  }
  j += "],\"blocked_ips\":[";
  first = true;
  for (const auto &ip : s->blocked_ips) {
    if (!first) j += ",";
    json_escape(j, ip);
    first = false;
  }
  j += "]}";

  int64_t need = (int64_t)j.size();
  if (out && cap > need) {
    std::memcpy(out, j.data(), j.size());
    out[j.size()] = '\0';
    return need;
  }
  return need;
}

}  // extern "C"
