"""Pallas TPU kernel: ragged paged decode attention.

The jnp reference path (ops/attention.py:paged_decode_attention) gathers a
padded [B, max_pages*page_size, Hk, hd] context per step — materializing
the whole window in HBM traffic even for short sequences. This kernel
instead walks each sequence's ACTUAL pages: per batch element, double-
buffered DMA streams K/V pages HBM→VMEM while the previous page's partial
attention accumulates with an online (flash-style) softmax, so HBM reads
scale with true context length (ragged), not the padded maximum.

Mosaic layout constraints (learned against the real v5e compiler):
  - DMA slices must be tile-aligned: a [.., Hk, hd=64] block sits padded
    inside 128-lane tiles and cannot be sliced, so K/V move as flattened
    [page_size, Hk*hd] rows (Hk*hd is a multiple of 128).
  - In-kernel reshapes/transposes that split or merge the lane dim are
    "unsupported shape cast" relayouts. GQA head bookkeeping therefore
    happens OUTSIDE the kernel: q arrives packed as [B, group, Hk*hd]
    (query-group-major, kv-segment lanes) and per-head score/weight
    segmentation uses constant 0/1 segment matrices on the MXU:
        scores_g = (k_row * q_g) @ SEG          [ps, Hk]
        expand_g = p_g @ SEG.T                  [ps, Hk*hd]
    so every vector op keeps its layout end to end.

Layout contract (matches engine/kv_cache.py):
    k_cache, v_cache: [S, Hk, hd] flat slot pool; a page is `page_size`
    contiguous slots starting at page_id * page_size.
    page_table: [B, max_pages] int32 (trash page 0 padding)
    seq_lens:   [B] int32 — context length INCLUDING the current token

Grid: one program per batch element; page_table/seq_lens ride scalar
prefetch so the DMA offsets are known before the body runs
(PrefetchScalarGridSpec pattern from the Pallas TPU guide).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, max_pages] SMEM
    seq_lens_ref,  # [B] SMEM
    # inputs + output + scratch (quantized pools append scale planes —
    # see the unpack below; layouts match the unquantized kernel)
    *refs,
    page_size: int,
    max_pages: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    ring: int,
    quantized: bool,
):
    if quantized:
        (q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref,
         k_buf, v_buf, ks_buf, vs_buf, acc, m_i, l_i, sems) = refs
    else:
        (q_ref, k_hbm, v_hbm, o_ref,
         k_buf, v_buf, acc, m_i, l_i, sems) = refs
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    seq_len = seq_lens_ref[b]

    # Clamp to the table width: a seq_len beyond capacity must not index
    # page_table out of bounds (the jnp reference implicitly truncates the
    # context the same way).
    def pages_of(row):
        return jnp.minimum(
            pl.cdiv(seq_lens_ref[row], page_size), max_pages
        )

    num_pages = pages_of(b)
    group = num_heads // num_kv_heads
    lanes = num_kv_heads * head_dim

    def page_dma(slot, row, page_idx):
        page_id = page_table_ref[row, page_idx]
        start = page_id * page_size
        copies = [
            pltpu.make_async_copy(
                k_hbm.at[pl.ds(start, page_size)], k_buf.at[slot],
                sems.at[slot, 0]),
            pltpu.make_async_copy(
                v_hbm.at[pl.ds(start, page_size)], v_buf.at[slot],
                sems.at[slot, 1]),
        ]
        if quantized:
            copies.append(pltpu.make_async_copy(
                ks_hbm.at[pl.ds(start, page_size)], ks_buf.at[slot],
                sems.at[slot, 2]))
            copies.append(pltpu.make_async_copy(
                vs_hbm.at[pl.ds(start, page_size)], vs_buf.at[slot],
                sems.at[slot, 3]))
        return copies

    def start_page(slot, row, page_idx):
        for dma in page_dma(slot, row, page_idx):
            dma.start()

    # Fill the ring — but ONLY for the first grid program: every later
    # program's first `ring` pages were started by its predecessor's
    # epilogue (cross-program prefetch), so the DMA pipeline never drains
    # at a program boundary. Starts and waits share the same `i <
    # num_pages` condition, so semaphore counts always balance.
    for i in range(ring):
        @pl.when((b == 0) & (i < num_pages))
        def _(i=i):
            start_page(i % ring, b, i)

    acc[...] = jnp.zeros_like(acc)
    m_i[...] = jnp.full_like(m_i, NEG_INF)
    l_i[...] = jnp.zeros_like(l_i)

    scale = 1.0 / (head_dim ** 0.5)
    # Segment matrices: SEG[d, h] = 1 iff lane d belongs to kv head h.
    # Constant f32 [lanes, Hk] / [Hk, lanes]; they ride VMEM and let the
    # MXU do per-head lane reductions/expansions without relayouts.
    seg = (
        jax.lax.broadcasted_iota(jnp.int32, (lanes, num_kv_heads), 0)
        // head_dim
        == jax.lax.broadcasted_iota(jnp.int32, (lanes, num_kv_heads), 1)
    ).astype(jnp.float32)
    seg_t = (
        jax.lax.broadcasted_iota(jnp.int32, (num_kv_heads, lanes), 1)
        // head_dim
        == jax.lax.broadcasted_iota(jnp.int32, (num_kv_heads, lanes), 0)
    ).astype(jnp.float32)

    def body(p, _):
        slot = p % ring

        for dma in page_dma(slot, b, p):
            dma.wait()

        k = k_buf[slot].astype(jnp.float32)  # [ps, lanes]
        v = v_buf[slot].astype(jnp.float32)
        if quantized:
            # In-kernel dequant: per-head scale rows expand to lane
            # segments via the seg_t MXU trick (no relayouts).
            k = k * jax.lax.dot_general(
                ks_buf[slot], seg_t,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            v = v * jax.lax.dot_general(
                vs_buf[slot], seg_t,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        # Ring slot consumed (values loaded above): refill it with the
        # page `ring` ahead, keeping ring-1 copies in flight.
        @pl.when(p + ring < num_pages)
        def _():
            start_page(slot, b, p + ring)
        # Valid-position mask for this page (final page may be partial).
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, num_kv_heads), 0
        )
        valid = pos < seq_len  # [ps, Hk]

        for g in range(group):  # static unroll; group is small (1-8)
            qg = q_ref[0, g : g + 1, :].astype(jnp.float32)  # [1, lanes]
            # scores[t, h] = sum_d q[h-seg d] * k[t, d]  via masked-lane
            # elementwise product + segment-sum on the MXU.
            s = jax.lax.dot_general(
                k * qg, seg,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [ps, Hk]
            s = jnp.where(valid, s, NEG_INF)

            # Online softmax update for this query group.
            m_prev = m_i[g : g + 1, :]  # [1, Hk]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=0, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)  # [1, Hk]
            p_ij = jnp.exp(s - m_new)  # [ps, Hk]
            l_i[g : g + 1, :] = l_i[g : g + 1, :] * alpha + jnp.sum(
                p_ij, axis=0, keepdims=True
            )
            # Per-head weights expanded back to lane segments, then a
            # sublane reduction contracts over page positions.
            e = jax.lax.dot_general(
                p_ij, seg_t,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [ps, lanes]
            contrib = jnp.sum(e * v, axis=0, keepdims=True)  # [1, lanes]
            alpha_l = jax.lax.dot_general(
                alpha, seg_t,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [1, lanes]
            acc[g : g + 1, :] = acc[g : g + 1, :] * alpha_l + contrib
            m_i[g : g + 1, :] = m_new
        return ()

    jax.lax.fori_loop(0, num_pages, body, ())

    # Cross-program prefetch: start the NEXT batch element's first `ring`
    # pages. Every one of this program's copies has been consumed by the
    # loop above (refills are guarded to < num_pages), so all ring slots
    # are free; the next program starts no DMAs of its own and its body
    # waits land on copies already in flight. The row index is clamped
    # BEFORE the predicate so the last program never reads seq_lens_ref
    # out of bounds (the b+1 < nb guard then discards the dummy value).
    succ = jnp.minimum(b + 1, nb - 1)
    for i in range(ring):
        @pl.when((b + 1 < nb) & (i < pages_of(succ)))
        def _(i=i):
            start_page(i % ring, succ, i)

    denom = jax.lax.dot_general(
        jnp.maximum(l_i[...], 1e-20), seg_t,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [group, lanes]
    o_ref[0] = (acc[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_decode_attention_pallas(
    q: jnp.ndarray,  # [B, H, hd]
    k_cache: jnp.ndarray,  # [S, Hk, hd] (int8 when k_scale is passed)
    v_cache: jnp.ndarray,  # [S, Hk, hd]
    page_table: jnp.ndarray,  # [B, max_pages]
    seq_lens: jnp.ndarray,  # [B]
    page_size: int,
    interpret: bool = False,
    k_scale=None,  # [S, Hk] f32 per-slot per-head scales (int8 pools)
    v_scale=None,
) -> jnp.ndarray:
    quantized = k_scale is not None
    B, H, hd = q.shape
    _, Hk, _ = k_cache.shape
    max_pages = page_table.shape[1]
    group = H // Hk
    lanes = Hk * hd

    # Pages in flight per sequence: measured on v5e, 4-16 are within noise
    # of each other (the DMA path is issue-overhead-bound); 8 is the middle.
    ring = 8
    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        max_pages=max_pages,
        num_heads=H,
        num_kv_heads=Hk,
        head_dim=hd,
        ring=ring,
        quantized=quantized,
    )

    in_specs = [
        pl.BlockSpec((1, group, lanes), lambda b, *_: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pl.ANY),  # k stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),  # v stays in HBM
    ]
    scratch = [
        pltpu.VMEM((ring, page_size, lanes), k_cache.dtype),
        pltpu.VMEM((ring, page_size, lanes), v_cache.dtype),
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),  # k scale rows (HBM)
            pl.BlockSpec(memory_space=pl.ANY),  # v scale rows (HBM)
        ]
        scratch += [
            pltpu.VMEM((ring, page_size, Hk), jnp.float32),
            pltpu.VMEM((ring, page_size, Hk), jnp.float32),
        ]
    scratch += [
        pltpu.VMEM((group, lanes), jnp.float32),
        pltpu.VMEM((group, Hk), jnp.float32),
        pltpu.VMEM((group, Hk), jnp.float32),
        pltpu.SemaphoreType.DMA((ring, 4 if quantized else 2)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, group, lanes), lambda b, *_: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=scratch,
    )

    # Pack q head-group-major so each kernel row g holds every kv head's
    # group-g query in its lane segment: q_packed[b, g, h*hd + d] =
    # q[b, h*group + g, d]. (Plain XLA transposes are free of Mosaic's
    # relayout limits; doing this outside the kernel keeps the kernel
    # relayout-free.)
    q_packed = (
        q.reshape(B, Hk, group, hd).transpose(0, 2, 1, 3).reshape(B, group, lanes)
    )
    operands = [q_packed, k_cache.reshape(-1, lanes),
                v_cache.reshape(-1, lanes)]
    if quantized:
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, group, lanes), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
      *operands)
    return (
        out.reshape(B, group, Hk, hd).transpose(0, 2, 1, 3).reshape(B, H, hd)
    )
