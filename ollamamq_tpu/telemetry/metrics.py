"""Process-wide metrics registry with Prometheus text exposition.

Three metric types (the Prometheus core set minus summaries — quantiles
are derived from fixed-bucket histograms instead, so merging across SPMD
hosts stays exact):

  Counter    monotonically increasing float
  Gauge      set/inc/dec float
  Histogram  fixed upper-bound buckets + sum + count

Design constraints, in order:
  - hot-path cheap: an observe() is one lock acquire, one bisect, three
    adds — no string formatting, no allocation beyond the first call for
    a given label set (children are cached on the parent).
  - thread-safe: the engine thread, HTTP threads, and the SPMD heartbeat
    publisher all touch the registry concurrently.
  - mergeable: snapshot() emits a JSON-able dict a peer host can publish
    over the jax.distributed KV store; render(extra=...) folds peer
    snapshots into one exposition (counters/histograms sum; gauge series
    union with local-wins, which is correct for the per-chip gauges whose
    label sets are disjoint across hosts).

No third-party deps, no jax: this module must import in the doc checker
and on worker hosts before any backend exists.
"""

from __future__ import annotations

import bisect
import json
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Default histogram ladder for millisecond latencies: sub-ms dispatch up
# to the 300 s request timeout, roughly x2.5 per step.
DEFAULT_LATENCY_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def format_float(v: float) -> str:
    """Exposition float formatting: integers bare, +Inf spelled out."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v != v:  # NaN
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Child:
    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class CounterChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount


class GaugeChild(_Child):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class HistogramChild(_Child):
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Tuple[float, ...]):
        super().__init__()
        self.buckets = buckets  # sorted finite upper bounds; +Inf implicit
        self.counts = [0] * (len(buckets) + 1)  # last = overflow (+Inf)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        # bisect_left: observe(boundary) lands IN the le=boundary bucket
        # (Prometheus le is inclusive).
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1) by linear interpolation within the
        owning bucket; the +Inf bucket clamps to the last finite bound."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if seen + c >= rank and c > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i] if i < len(self.buckets) else self.buckets[-1]
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.buckets[-1]

    def _reset_to(self, buckets: Tuple[float, ...]) -> None:
        with self._lock:
            self.buckets = buckets
            self.counts = [0] * (len(buckets) + 1)
            self.sum = 0.0
            self.count = 0


class Metric:
    """A named metric family; label combinations materialize children."""

    type: str = "untyped"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}

    def _new_child(self) -> _Child:
        raise NotImplementedError

    def labels(self, **kw) -> _Child:
        if set(kw) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kw)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kw[k]) for k in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    def _default(self) -> _Child:
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled; use .labels(...)")
        return self.labels()

    def clear(self) -> None:
        """Drop all children (for scrape-time rebuilt gauges: users and
        chips come and go; stale series must not linger)."""
        with self._lock:
            self._children = {}

    def series(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return list(self._children.items())


class Counter(Metric):
    type = "counter"

    def _new_child(self):
        return CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(Metric):
    type = "gauge"

    def _new_child(self):
        return GaugeChild()

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(Metric):
    type = "histogram"

    def __init__(self, name, help, buckets: Sequence[float],
                 labelnames: Tuple[str, ...] = ()):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b or not all(math.isfinite(x) for x in b):
            raise ValueError(
                f"{name}: buckets must be finite bounds (+Inf is implicit)")
        self.buckets = b

    def _new_child(self):
        return HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)

    def set_buckets(self, buckets: Sequence[float]) -> None:
        """Re-bucket (operator --metrics-buckets): resets every child's
        observations — boundaries can't be translated between ladders."""
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"{self.name}: empty bucket list")
        self.buckets = b
        for _, child in self.series():
            child._reset_to(b)


class MetricsRegistry:
    """Named metric families; the module-level REGISTRY is process-wide."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _register(self, cls, name, help, labels, **kw) -> Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                # Idempotent re-registration (tests build many engines in
                # one process); a TYPE flip is a bug, not a re-use.
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{existing.type}, not {cls.type}")
                return existing
            m = cls(name, help, labelnames=tuple(labels), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str, labels: Iterable[str] = ()) -> Counter:
        return self._register(Counter, name, help, labels)

    def gauge(self, name: str, help: str, labels: Iterable[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labels)

    def histogram(self, name: str, help: str, buckets: Sequence[float],
                  labels: Iterable[str] = ()) -> Histogram:
        return self._register(Histogram, name, help, labels, buckets=buckets)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    # -- exposition --------------------------------------------------------
    @staticmethod
    def _labels_str(labelnames, labelvalues, extra: str = "") -> str:
        parts = [f'{k}="{escape_label_value(v)}"'
                 for k, v in zip(labelnames, labelvalues)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self, extra_snapshots: Optional[List[dict]] = None,
               federated: Optional[List[Tuple[str, dict]]] = None) -> str:
        """Prometheus text exposition (format version 0.0.4). Peer-host
        snapshots merge in: counter/histogram series with identical
        labels sum; gauge series union with local values winning.

        `federated` is the fleet-router path: (replica_name, snapshot)
        pairs whose every series re-exports UNLABELED-MERGED-NEVER —
        each lands verbatim under the same family with an extra
        `replica` label next to the router's own series, so ONE
        Prometheus scrape of the router sees the whole fleet without
        double counting (label sets may differ per sample within a
        family; Prometheus accepts that)."""
        merged = self._merged_view(extra_snapshots or [])
        fed = self._federated_view(federated or [])
        out: List[str] = []
        for name in sorted(set(merged) | set(fed)):
            local = merged.get(name)
            fed_rows = fed.get(name, [])
            typ, help_ = ((local[0], local[1]) if local is not None
                          else (fed_rows[0][0], fed_rows[0][1]))
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {typ}")
            if local is not None:
                _, _, labelnames, buckets, series = local
                for labelvalues in sorted(series):
                    self._render_sample(out, name, typ, labelnames,
                                        buckets, labelvalues,
                                        series[labelvalues])
            for ftyp, _fhelp, labelnames, buckets, series in fed_rows:
                if ftyp != typ:
                    continue  # cross-process type drift: local wins
                for labelvalues in sorted(series):
                    self._render_sample(out, name, typ, labelnames,
                                        buckets, labelvalues,
                                        series[labelvalues])
        return "\n".join(out) + "\n"

    def _render_sample(self, out: List[str], name, typ, labelnames,
                       buckets, labelvalues, val) -> None:
        if typ == "histogram":
            counts, hsum, hcount = val
            cum = 0
            for i, ub in enumerate(list(buckets) + [math.inf]):
                cum += counts[i] if i < len(counts) else 0
                ls = self._labels_str(
                    labelnames, labelvalues, f'le="{format_float(ub)}"')
                out.append(f"{name}_bucket{ls} {cum}")
            ls = self._labels_str(labelnames, labelvalues)
            out.append(f"{name}_sum{ls} {format_float(hsum)}")
            out.append(f"{name}_count{ls} {hcount}")
        else:
            ls = self._labels_str(labelnames, labelvalues)
            out.append(f"{name}{ls} {format_float(val)}")

    @staticmethod
    def _federated_view(federated: List[Tuple[str, dict]]) -> dict:
        """name -> [(type, help, labelnames+('replica',), buckets,
        {labelvalues+(replica,): value})] rows, one per (replica,
        metric). Malformed member snapshots are skipped, never fail the
        scrape."""
        view: dict = {}
        for replica, snap in federated:
            for name, rec in (snap or {}).items():
                try:
                    typ = rec["type"]
                    labelnames = tuple(rec["labels"]) + ("replica",)
                    buckets = tuple(rec.get("buckets", ()))
                    series = {tuple(lv) + (str(replica),): v
                              for lv, v in rec["series"]}
                except (KeyError, TypeError):
                    continue
                view.setdefault(name, []).append(
                    (typ, rec.get("help", ""), labelnames, buckets,
                     series))
        return view

    def _merged_view(self, extras: List[dict]) -> dict:
        view: dict = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            buckets = getattr(m, "buckets", ())
            series: dict = {}
            for labelvalues, child in m.series():
                if m.type == "histogram":
                    with child._lock:
                        series[labelvalues] = (
                            list(child.counts), child.sum, child.count)
                else:
                    series[labelvalues] = child.value
            view[m.name] = (m.type, m.help, m.labelnames, buckets, series)
        for snap in extras:
            self._merge_snapshot(view, snap)
        return view

    @staticmethod
    def _merge_snapshot(view: dict, snap: dict) -> None:
        for name, rec in snap.items():
            try:
                typ = rec["type"]
                labelnames = tuple(rec["labels"])
                buckets = tuple(rec.get("buckets", ()))
                incoming = {tuple(lv): v for lv, v in rec["series"]}
            except (KeyError, TypeError):
                continue  # malformed peer snapshot: skip, never fail scrape
            if name not in view:
                view[name] = (typ, rec.get("help", ""), labelnames, buckets,
                              dict(incoming))
                continue
            vtyp, vhelp, vnames, vbuckets, series = view[name]
            if vtyp != typ or vnames != labelnames:
                continue  # schema drift across hosts: local wins
            for lv, v in incoming.items():
                if vtyp == "histogram":
                    if tuple(vbuckets) != buckets:
                        continue  # different ladders can't sum
                    if lv in series:
                        counts, s, c = series[lv]
                        counts = [a + b for a, b in zip(counts, v[0])]
                        series[lv] = (counts, s + v[1], c + v[2])
                    else:
                        series[lv] = (list(v[0]), v[1], v[2])
                elif vtyp == "counter":
                    series[lv] = series.get(lv, 0.0) + v
                else:  # gauge: union, local wins on collision
                    series.setdefault(lv, v)

    # -- snapshots (SPMD host merge) ---------------------------------------
    def snapshot(self) -> dict:
        """JSON-able dump of every series, for publishing to peers."""
        out: dict = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            series = []
            for labelvalues, child in m.series():
                if m.type == "histogram":
                    with child._lock:
                        series.append([list(labelvalues),
                                       [list(child.counts), child.sum,
                                        child.count]])
                else:
                    series.append([list(labelvalues), child.value])
            rec = {"type": m.type, "help": m.help,
                   "labels": list(m.labelnames), "series": series}
            if m.type == "histogram":
                rec["buckets"] = list(m.buckets)
            out[m.name] = rec
        return out

    def snapshot_json(self) -> str:
        return json.dumps(self.snapshot())


REGISTRY = MetricsRegistry()
