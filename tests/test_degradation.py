"""Graceful degradation under load: preemption with recompute, bounded
admission & shedding, deadlines, retry containment, fault injection.

Every path here is driven by the deterministic fault plan
(ollamamq_tpu/testing/faults.py) rather than real resource races, so the
chaos is replayable: the same plan fires the same faults in the same
order on every run.
"""

import asyncio
import json
import time

import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.request import FinishReason
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.testing.faults import (DeviceLostError, FaultInjected,
                                         FaultPlan, FaultPlanError)
from testutil import collect

TINY = dict(model="test-tiny", max_slots=2, num_pages=64, page_size=8,
            max_pages_per_seq=16, prefill_buckets=(16, 32, 64),
            decode_steps_per_iter=2)


def _tpu_engine(plan=None, **over):
    import jax.numpy as jnp

    from ollamamq_tpu.engine.engine import TPUEngine

    cfg = dict(TINY)
    cfg.update(over)
    eng = TPUEngine(EngineConfig(fault_plan=plan, **cfg),
                    models={"test-tiny": None}, blocklist_path=None,
                    dtype=jnp.float32)
    eng.start()
    return eng


def _run(eng, user, prompt="the quick brown fox jumps", max_tokens=10,
         deadline_ms=0.0):
    tok = eng.resolve_runtime("test-tiny").tokenizer
    req = eng.enqueue_request(
        user, "", "test-tiny", prompt_tokens=tok.encode(prompt),
        sampling=SamplingParams(max_tokens=max_tokens,
                                deadline_ms=deadline_ms))
    return req


def _text(items):
    return "".join(i.text for i in items if i.kind == "token")


# ---------------------------------------------------------------- fault plan
def test_fault_plan_schema_rejects_malformed(tmp_path):
    bad = [
        {"faults": "nope"},
        {"faults": []},
        {"faults": [{"site": "warp", "kind": "exception", "at": [1]}]},
        {"faults": [{"site": "decode", "kind": "explode", "at": [1]}]},
        {"faults": [{"site": "decode", "kind": "exception"}]},
        {"faults": [{"site": "decode", "kind": "exception", "at": [0]}]},
        {"faults": [{"site": "decode", "kind": "exception", "at": [1],
                     "p": 0.5}]},
        {"faults": [{"site": "decode", "kind": "exception", "at": [1],
                     "bogus_key": 1}]},
        {"faults": [{"site": "decode", "kind": "slow", "at": [1]}]},
        {"seed": "x", "faults": [{"site": "decode", "kind": "exception",
                                  "at": [1]}]},
    ]
    for d in bad:
        with pytest.raises(FaultPlanError):
            FaultPlan.from_dict(d)
    # File-level failures: unreadable and non-JSON both fail fast.
    with pytest.raises(FaultPlanError):
        FaultPlan.load(str(tmp_path / "missing.json"))
    p = tmp_path / "junk.json"
    p.write_text("{not json")
    with pytest.raises(FaultPlanError):
        FaultPlan.load(str(p))
    # And a valid file loads.
    good = tmp_path / "plan.json"
    good.write_text(json.dumps({"seed": 3, "faults": [
        {"site": "prefill", "kind": "exception", "at": [1]}]}))
    assert FaultPlan.load(str(good)).stats()["injected"] == 0


def test_fault_plan_cli_flag_fails_fast(tmp_path):
    from ollamamq_tpu.cli import main

    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"faults": [{"site": "nope"}]}))
    assert main(["--fault-plan", str(p), "--no-tui"]) == 2


def test_fault_plan_device_loss_heals():
    plan = FaultPlan([{"site": "decode", "kind": "device_loss", "at": [1],
                       "heal_after_s": 0.05}])
    with pytest.raises(DeviceLostError):
        plan.check("decode")
    with pytest.raises(DeviceLostError):
        plan.check("prefill")  # a lost device fails EVERY site
    assert plan.blocked("extend")  # ...and can't grow allocations
    time.sleep(0.06)
    plan.check("decode")  # healed


# ------------------------------------------------- preemption with recompute
@pytest.mark.parametrize("prefix_cache", [False, True],
                         ids=["cache-off", "cache-on"])
def test_preemption_round_trip_byte_identical(prefix_cache):
    """A preempted+recomputed greedy request produces EXACTLY the token
    stream an unloaded run produces — preemption must be invisible to
    the client beyond latency."""
    eng = _tpu_engine(prefix_cache=prefix_cache)
    try:
        base_items = collect(_run(eng, "base"))
        base_rt = eng.runtimes["test-tiny"]
    finally:
        eng.stop()
    base_text = _text(base_items)
    assert base_items[-1].kind == "done" and base_text

    # Same engine shape, but the 3rd decode-time page growth "fails":
    # the lone request preempts ITSELF, requeues to the front, replays
    # prompt+generated through prefill, and continues.
    plan = FaultPlan([{"site": "extend", "kind": "alloc_fail", "at": [3]}])
    eng = _tpu_engine(plan=plan, prefix_cache=prefix_cache)
    try:
        req = _run(eng, "victim")
        items = collect(req)
        rt = eng.runtimes["test-tiny"]
        assert req.preemptions >= 1
        assert rt.preempt_count >= 1
        if prefix_cache:
            # The replay re-admission walks the tree seeded by the
            # preemption's page insert: recompute is mostly cached.
            assert rt.prefix_cache.stats()["hits"] >= 1
        # Invariant: no page leaked across preempt/replay.
        assert rt.alloc.used_pages == 0
    finally:
        eng.stop()
    assert items[-1].kind == "done", items[-1].error
    assert _text(items) == base_text
    assert [i.token_id for i in items if i.kind == "token" and
            i.token_id >= 0] == [i.token_id for i in base_items
                                 if i.kind == "token" and i.token_id >= 0]
    del base_rt


def test_kv_exhausted_explicit_when_preemption_disabled():
    """Satellite: decode-time page exhaustion must NEVER report a silent
    LENGTH — with preemption off it errors with the distinct
    kv_exhausted done_reason and counts into ollamamq_shed_total."""
    from ollamamq_tpu.telemetry import schema as tm

    shed0 = sum(c.value for (labels, c) in tm.SHED_TOTAL.series()
                if "kv_exhausted" in labels)
    plan = FaultPlan([{"site": "extend", "kind": "alloc_fail", "at": [3]}])
    eng = _tpu_engine(plan=plan, preempt=False)
    try:
        req = _run(eng, "u")
        items = collect(req)
    finally:
        eng.stop()
    assert items[-1].kind == "error"
    assert items[-1].finish_reason == FinishReason.KV_EXHAUSTED
    assert "exhausted" in items[-1].error
    shed1 = sum(c.value for (labels, c) in tm.SHED_TOTAL.series()
                if "kv_exhausted" in labels)
    assert shed1 == shed0 + 1


# ------------------------------------------------ bounded admission/shedding
def test_queue_full_returns_429_and_503_with_retry_after():
    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.engine.fake import FakeEngine
    from ollamamq_tpu.server.app import Server

    async def main():
        eng = FakeEngine(
            EngineConfig(model="test-tiny", max_slots=1, max_queued=2,
                         max_queued_per_user=1),
            models={"test-tiny": None}, token_latency_s=0.05)
        eng.start()
        cl = TestClient(TestServer(Server(eng, timeout_s=60).build_app()))
        await cl.start_server()
        try:
            async def fire(user):
                return asyncio.create_task(cl.post(
                    "/api/generate",
                    json={"model": "test-tiny", "prompt": "x",
                          "stream": False},
                    headers={"X-User-ID": user}))

            # One running (slot), one queued for alice: alice is at her
            # per-user cap of 1.
            t1 = await fire("alice")
            await asyncio.sleep(0.2)
            t2 = await fire("alice")
            await asyncio.sleep(0.2)
            r = await (await fire("alice"))
            assert r.status == 429, await r.text()
            assert int(r.headers["Retry-After"]) >= 1
            body = await r.json()
            assert "cap" in body["error"]
            # Global cap (2): bob fills the second queue seat, carol is
            # shed with 503.
            t3 = await fire("bob")
            await asyncio.sleep(0.2)
            r = await (await fire("carol"))
            assert r.status == 503, await r.text()
            assert int(r.headers["Retry-After"]) >= 1
            for t in (t1, t2, t3):
                resp = await t
                assert resp.status == 200
                await resp.read()
            from ollamamq_tpu.telemetry import schema as tm

            reasons = {labels[0] for labels, c in tm.SHED_TOTAL.series()
                       if c.value > 0}
            assert {"queue_full", "user_queue_full"} <= reasons
            assert eng.shed_counts["queue_full"] >= 1
            assert eng.shed_counts["user_queue_full"] >= 1
        finally:
            await cl.close()
            eng.stop()

    asyncio.run(main())


# ------------------------------------------------------------------ deadline
def test_expired_queued_request_drops_before_prefill():
    """A request whose deadline expires while it waits in queue is
    dropped at admission — no prefill is ever dispatched for it — and
    the client gets the explicit deadline reason."""
    from ollamamq_tpu.engine.fake import FakeEngine
    from ollamamq_tpu.telemetry import schema as tm

    drops0 = sum(c.value for _, c in tm.DEADLINE_DROPS_TOTAL.series())
    eng = FakeEngine(EngineConfig(model="test-tiny", max_slots=1),
                     models={"test-tiny": None}, token_latency_s=0.05)
    eng.start()
    try:
        blocker = _run(eng, "hog", max_tokens=16)  # holds the only slot
        time.sleep(0.15)  # let it admit
        doomed = _run(eng, "late", max_tokens=4, deadline_ms=50.0)
        items = collect(doomed)
        assert items[-1].kind == "error"
        assert items[-1].finish_reason == FinishReason.DEADLINE
        # Dropped BEFORE any compute: its trace never saw a prefill.
        names = [e[0] for e in doomed.trace.events]
        assert "prefill" not in names and "first_token" not in names
        assert not _text(items)
        collect(blocker)
        drops1 = sum(c.value for _, c in tm.DEADLINE_DROPS_TOTAL.series())
        assert drops1 == drops0 + 1
    finally:
        eng.stop()


def test_deadline_header_rides_the_http_surface():
    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.engine.fake import FakeEngine
    from ollamamq_tpu.server.app import Server

    async def main():
        eng = FakeEngine(EngineConfig(model="test-tiny", max_slots=1),
                         models={"test-tiny": None}, token_latency_s=0.05)
        eng.start()
        cl = TestClient(TestServer(Server(eng, timeout_s=60).build_app()))
        await cl.start_server()
        try:
            r = await cl.post("/api/generate", json={
                "model": "test-tiny", "prompt": "x", "stream": False},
                headers={"X-Deadline-Ms": "junk"})
            assert r.status == 400
            # Occupy the slot, then an impossible deadline => 504 with
            # the explicit deadline reason, not a generic 500.
            hog = asyncio.create_task(cl.post(
                "/api/generate", json={"model": "test-tiny", "prompt": "x",
                                       "stream": False},
                headers={"X-User-ID": "hog"}))
            await asyncio.sleep(0.2)
            r = await cl.post("/api/generate", json={
                "model": "test-tiny", "prompt": "x", "stream": False},
                headers={"X-User-ID": "late", "X-Deadline-Ms": "40"})
            assert r.status == 504, await r.text()
            assert "deadline" in (await r.json())["error"]
            resp = await hog
            assert resp.status == 200
        finally:
            await cl.close()
            eng.stop()

    asyncio.run(main())


# ------------------------------------------------------- retry / containment
def test_injected_prefill_fault_retries_and_succeeds():
    from ollamamq_tpu.telemetry import schema as tm

    # "ragged" is the default mode's prefill-path dispatch site (the
    # mixed token-budget dispatch replaced batched prefill).
    plan = FaultPlan([{"site": "ragged", "kind": "exception", "at": [1]}])
    eng = _tpu_engine(plan=plan)
    try:
        req = _run(eng, "u")
        items = collect(req)
        rt = eng.runtimes["test-tiny"]
        assert req.retries == 1
        assert rt.retry_count == 1
        assert sum(c.value for _, c in tm.RETRIES_TOTAL.series()) >= 1
    finally:
        eng.stop()
    assert items[-1].kind == "done", items[-1].error
    assert _text(items)
    names = [e[0] for e in req.trace.events]
    assert "retry" in names


def test_repeated_fault_poisons_engine_keeps_serving():
    """Two consecutive injected prefill faults exhaust the retry budget:
    the request is poisoned with an explicit error, and the NEXT request
    (fault plan spent) serves normally — no crash loop."""
    plan = FaultPlan([{"site": "ragged", "kind": "exception", "at": [1, 2]}])
    eng = _tpu_engine(plan=plan)
    try:
        poisoned = collect(_run(eng, "bad"), timeout=60)
        assert poisoned[-1].kind == "error"
        assert "poisoned" in poisoned[-1].error
        survivor = collect(_run(eng, "good"))
        assert survivor[-1].kind == "done", survivor[-1].error
        assert _text(survivor)
        snap = eng.core.snapshot()
        assert snap["users"]["bad"]["dropped"] == 1
        assert snap["users"]["good"]["processed"] == 1
        assert sum(u["processing"] for u in snap["users"].values()) == 0
    finally:
        eng.stop()


# --------------------------------------------- server timeout leak (fixed)
def test_server_timeout_cancels_engine_side():
    """Satellite: the per-request timeout must cancel the engine-side
    request (freeing its slot) — not just yield an error item while the
    generation keeps burning resources."""
    from aiohttp.test_utils import TestClient, TestServer

    from ollamamq_tpu.engine.fake import FakeEngine
    from ollamamq_tpu.server.app import Server

    async def main():
        # 16 fake tokens at 80 ms each = ~1.3 s of generation vs a
        # 0.3 s server timeout.
        eng = FakeEngine(EngineConfig(model="test-tiny", max_slots=2),
                         models={"test-tiny": None}, token_latency_s=0.08)
        eng.start()
        cl = TestClient(TestServer(Server(eng, timeout_s=0.3).build_app()))
        await cl.start_server()
        try:
            t0 = time.monotonic()
            r = await cl.post("/api/generate", json={
                "model": "test-tiny", "prompt": "x", "stream": False})
            assert r.status == 500
            assert "timeout" in (await r.json())["error"]
            # The engine-side request must be reaped well before the
            # generation would have finished on its own.
            rt = eng.runtimes["test-tiny"]
            while rt.active and time.monotonic() - t0 < 1.0:
                await asyncio.sleep(0.02)
            assert not rt.active, "slot still held after client timeout"
            snap = eng.core.snapshot()
            assert sum(u["processing"] for u in snap["users"].values()) == 0
        finally:
            await cl.close()
            eng.stop()

    asyncio.run(main())


# -------------------------------------------------------- preemption storm
def test_preempt_storm_alert_fires_and_resolves(monkeypatch):
    from ollamamq_tpu.engine import health as health_mod
    from ollamamq_tpu.engine.health import HealthMonitor
    from ollamamq_tpu.telemetry.slo import AlertManager

    class Stub:
        def __init__(self):
            self.alerts = AlertManager()
            self._n = 0

        def preemption_count(self):
            return self._n

    eng = Stub()
    mon = HealthMonitor(eng)
    monkeypatch.setattr(health_mod, "PREEMPT_STORM_PER_MIN", 10.0)
    # Two samples 1s apart with +2 preemptions => 120/min => storm.
    now = time.monotonic()
    mon._preempt_samples = [(now - 1.0, 0)]
    eng._n = 2
    mon._check_preempt_storm()
    assert any(a.name == "preempt_storm" for a in eng.alerts.active())
    # Rate decays (no new preemptions over a long window) => resolves.
    mon._preempt_samples = [(now - 30.0, 2)]
    mon._check_preempt_storm()
    assert not any(a.name == "preempt_storm" for a in eng.alerts.active())


# ------------------------------------------------------------- embed cancel
def test_cancel_finds_pending_embed_requests():
    """engine.cancel's holder scan must cover pending_embed — a timed-out
    embed on a generative runtime previously leaked until served."""
    import jax.numpy as jnp

    from ollamamq_tpu.engine.engine import TPUEngine

    eng = TPUEngine(EngineConfig(**TINY), models={"test-tiny": None},
                    blocklist_path=None, dtype=jnp.float32)
    # NOT started: the request stays parked in pending_embed.
    rt = eng.runtimes["test-tiny"]
    req = eng.enqueue_request("u", "", "test-tiny", prompt_tokens=[1, 2, 3],
                              kind="embed")
    rt.submit(req)
    eng.pending.pop(req.req_id, None)  # simulate post-admission state
    eng.cancel(req.req_id)
    assert req.cancelled.is_set()
