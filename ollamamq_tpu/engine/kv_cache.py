"""Paged KV cache: device slot pool + host-side page allocator.

Device side: two arrays per model, [num_layers, num_pages*page_size,
kv_heads, head_dim] for K and V, kv-heads sharded over the "tensor" mesh
axis. The pool is allocated ONCE at engine start (static shape => no
recompiles, no fragmentation in HBM).

Host side: a free-list allocator of page indices. Page 0 is RESERVED as the
trash page: page-table rows are padded with it so static-shaped prefill
scatter writes of padding tokens land harmlessly (see
models/llama.py:forward_prefill).

Cancellation reclaims pages immediately — the TPU analogue of the
reference dropping a disconnected client's stream
(/root/reference/src/dispatcher.rs:537-551) plus freeing the backend slot.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ollamamq_tpu.config import EngineConfig, ModelConfig

TRASH_PAGE = 0


class PageAllocator:
    """Free-list allocator over page indices [1, num_pages).

    With the prefix cache enabled (engine/prefix_cache.py) every page is
    exactly one of FREE (on the free list), USED (private to a decode
    slot), or CACHED (owned by the radix tree, possibly pinned by live
    requests); `cached_pages` tracks the third bucket so
    free + used + cached == num_pages - 1 always holds.
    """

    def __init__(self, num_pages: int, page_size: int, max_pages_per_seq: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self._free: List[int] = list(range(num_pages - 1, 0, -1))  # page 0 reserved
        self.cached_pages = 0  # tree-owned (prefix cache accounting)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free) - self.cached_pages

    def pages_needed(self, num_tokens: int) -> int:
        return max(1, -(-num_tokens // self.page_size))

    def can_alloc(self, num_tokens: int) -> bool:
        return self.pages_needed(num_tokens) <= len(self._free)

    def alloc(self, num_tokens: int) -> Optional[List[int]]:
        """Allocate pages to hold num_tokens; None if pool exhausted or the
        request exceeds the per-sequence page cap."""
        return self.alloc_n(self.pages_needed(num_tokens))

    def alloc_n(self, n: int, held: int = 0) -> Optional[List[int]]:
        """Allocate exactly n pages for a sequence already holding `held`
        (cache-hit admission: shared prefix pages count against the
        per-sequence cap but come from the tree, not the free list)."""
        if n > len(self._free) or held + n > self.max_pages_per_seq:
            return None
        return [self._free.pop() for _ in range(n)]

    # -- prefix-cache ownership transfer -----------------------------------
    def adopt_cached(self, n: int = 1) -> None:
        """A slot's page(s) moved into the prefix-cache tree: no longer
        used, not free either."""
        self.cached_pages += n

    def reclaim_cached(self, page: int) -> None:
        """An evicted tree page returns to the free list."""
        self.cached_pages -= 1
        if page != TRASH_PAGE:
            self._free.append(page)

    def extend(self, pages: List[int], new_total_tokens: int) -> bool:
        """Grow an allocation to cover new_total_tokens. False if exhausted
        or per-seq page cap reached."""
        need = self.pages_needed(new_total_tokens)
        while len(pages) < need:
            if not self._free or len(pages) >= self.max_pages_per_seq:
                return False
            pages.append(self._free.pop())
        return True

    def rollback_to(self, pages: List[int], kv_len: int,
                    keep: int = 0) -> int:
        """Speculative rollback: shrink an allocation (in place) to the
        pages a sequence of `kv_len` WRITTEN tokens actually needs,
        returning the rejected tail pages to the free list. `keep` floors
        the truncation at the sequence's shared prefix-tree pages (they
        lead the list and are owned by the tree, never this allocator's
        free list). Returns the number of pages freed.

        The device-side "un-write" is free: rejected draft positions sit
        past the rolled-back kv_len, so attention masks them out and the
        next real decode step overwrites them — only the host-side page
        claim needs releasing."""
        target = max(self.pages_needed(max(1, kv_len)), keep)
        freed = 0
        while len(pages) > target:
            p = pages.pop()
            if p != TRASH_PAGE:
                self._free.append(p)
                freed += 1
        return freed

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p != TRASH_PAGE:
                self._free.append(p)
        pages.clear()


def make_page_table_row(pages: List[int], max_pages: int) -> np.ndarray:
    """Pad a page list with the trash page to the static table width."""
    row = np.full((max_pages,), TRASH_PAGE, dtype=np.int32)
    row[: len(pages)] = pages
    return row


def alloc_kv_pool(
    model_cfg: ModelConfig,
    engine_cfg: EngineConfig,
    sharding=None,
    dtype=jnp.bfloat16,
    kv_dtype: str = "bfloat16",
    scale_sharding=None,
):
    """Allocate the device K/V slot pools (zeros). Returns (k_cache,
    v_cache) — plain arrays, or QuantKV pairs when kv_dtype="int8": an
    int8 payload pool plus fp32 per-slot per-head scale rows stored
    page-aligned alongside it (slot = page * page_size + offset), so the
    page allocator, prefix tree, preemption, and rollback machinery are
    untouched while every page shrinks ~2x."""
    from ollamamq_tpu.ops.quant import QuantKV

    S = engine_cfg.num_pages * engine_cfg.page_size
    shape = (model_cfg.num_layers, S, model_cfg.num_kv_heads,
             model_cfg.head_dim)

    def zeros(shp, dt, shard):
        if shard is not None:
            return jax.jit(lambda: jnp.zeros(shp, dt), out_shardings=shard)()
        return jnp.zeros(shp, dt)

    if kv_dtype == "int8":
        sshape = shape[:-1]  # [L, S, Hk] scale rows
        k = QuantKV(zeros(shape, jnp.int8, sharding),
                    jnp.ones(sshape, jnp.float32) if scale_sharding is None
                    else jax.jit(lambda: jnp.ones(sshape, jnp.float32),
                                 out_shardings=scale_sharding)())
        v = QuantKV(zeros(shape, jnp.int8, sharding),
                    jnp.ones(sshape, jnp.float32) if scale_sharding is None
                    else jax.jit(lambda: jnp.ones(sshape, jnp.float32),
                                 out_shardings=scale_sharding)())
        return k, v
    k = zeros(shape, dtype, sharding)
    v = zeros(shape, dtype, sharding)
    return k, v


def kv_pool_bytes(model_cfg: ModelConfig, engine_cfg: EngineConfig,
                  bytes_per_el=2, kv_dtype: str = "bfloat16") -> int:
    """Planning-time pool size; int8 pools count 1 payload byte plus the
    4-byte fp32 scale each (slot, head) row carries."""
    per_tok_head = (model_cfg.head_dim + 4 if kv_dtype == "int8"
                    else model_cfg.head_dim * bytes_per_el)
    return (
        2
        * model_cfg.num_layers
        * engine_cfg.num_pages
        * engine_cfg.page_size
        * model_cfg.num_kv_heads
        * per_tok_head
    )


def kv_page_bytes(model_cfg: ModelConfig, page_size: int,
                  bytes_per_el=2, kv_dtype: str = "bfloat16") -> int:
    """Bytes ONE page costs (K and V, all layers) — the density math's
    unit: equal-HBM pool sizing divides a byte budget by this."""
    per_tok_head = (model_cfg.head_dim + 4 if kv_dtype == "int8"
                    else model_cfg.head_dim * bytes_per_el)
    return (2 * model_cfg.num_layers * page_size
            * model_cfg.num_kv_heads * per_tok_head)
