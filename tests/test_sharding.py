"""Mesh/sharding: TP-sharded forward must match unsharded numerics."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as PS

from ollamamq_tpu.engine import kv_cache as kvc
from ollamamq_tpu.models import llama
from ollamamq_tpu.parallel import (
    make_mesh,
    param_partition_specs,
    kv_cache_spec,
    shard_params,
)

PAGE_SIZE = 8
MAX_PAGES = 8


def test_mesh_shapes():
    mesh = make_mesh(dp=2, tp=-1)
    assert mesh.shape["data"] == 2 and mesh.shape["tensor"] == 4
    mesh = make_mesh(dp=1, sp=2, tp=4)
    assert mesh.shape["seq"] == 2


def test_multihost_dp_picks_devices_from_every_process():
    """When k = dp*sp*tp < total devices, the multi-host dp mesh must take
    k/nproc devices FROM EACH process — devices[:k] of a process-major
    list would come entirely from the first host(s) (ADVICE r3)."""
    import pytest

    from ollamamq_tpu.parallel.mesh import _pick_per_process

    class Dev:
        def __init__(self, i, p):
            self.id, self.process_index = i, p

        def __repr__(self):
            return f"d{self.id}p{self.process_index}"

    # 2 processes x 4 devices, but k=4 (per_proc=2): naive [:4] would be
    # all of process 0.
    devs = [Dev(i, i // 4) for i in range(8)]
    picked = _pick_per_process(devs, k=4, nproc=2, per_proc=2)
    assert [d.process_index for d in picked] == [0, 0, 1, 1]
    assert [d.id for d in picked] == [0, 1, 4, 5]
    # A process short of devices fails loudly.
    devs_short = [Dev(i, 0) for i in range(6)] + [Dev(6, 1)]
    with pytest.raises(ValueError, match="every"):
        _pick_per_process(devs_short, k=4, nproc=2, per_proc=2)
    # Single-process simulations (all process_index 0) keep the
    # positional split.
    devs_sim = [Dev(i, 0) for i in range(8)]
    assert _pick_per_process(devs_sim, k=4, nproc=2, per_proc=2) == devs_sim[:4]


def test_partition_specs(tiny_cfg, tiny_params):
    specs = param_partition_specs(tiny_params)
    assert specs["layers"]["wq"] == PS(None, None, "tensor")
    assert specs["layers"]["wo"] == PS(None, "tensor", None)
    assert specs["embed"] == PS("tensor", None)
    assert specs["final_norm"] == PS()


def test_tp_forward_matches_single_device(tiny_cfg, tiny_params):
    cfg, params = tiny_cfg, tiny_params
    tokens = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    seq_lens = jnp.array([8])

    def run(params, kc, vc, pt):
        return llama.forward_prefill(params, cfg, tokens, seq_lens, kc, vc, pt, PAGE_SIZE)

    # Unsharded reference.
    shape = (cfg.num_layers, 32 * PAGE_SIZE, cfg.num_kv_heads, cfg.head_dim)
    kc = jnp.zeros(shape, jnp.float32)
    vc = jnp.zeros(shape, jnp.float32)
    a = kvc.PageAllocator(32, PAGE_SIZE, MAX_PAGES)
    pt = jnp.asarray(np.stack([kvc.make_page_table_row(a.alloc(8), MAX_PAGES)]))
    ref_logits, ref_kc, _ = run(params, kc, vc, pt)

    # TP=2 sharded on the virtual CPU mesh.
    mesh = make_mesh(dp=1, tp=2, devices=jax.devices()[:2])
    sp = shard_params(params, mesh)
    kv_shard = NamedSharding(mesh, kv_cache_spec())
    kc2 = jax.device_put(jnp.zeros(shape, jnp.float32), kv_shard)
    vc2 = jax.device_put(jnp.zeros(shape, jnp.float32), kv_shard)
    with jax.set_mesh(mesh):
        tp_logits, tp_kc, _ = jax.jit(run)(sp, kc2, vc2, pt)

    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(tp_logits), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(ref_kc), np.asarray(tp_kc), rtol=1e-4, atol=1e-4
    )


def test_tp_over_kv_heads_replicated_groups():
    """tp=8 over a 4-KV-head model (qwen2.5 shape): KV heads replicate so
    every shard owns one copy, and generation matches tp=1 exactly
    (duplicated heads are numerically transparent)."""
    import time

    from ollamamq_tpu.config import EngineConfig
    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.ops.sampling import SamplingParams

    def cfg(tp):
        return EngineConfig(model="test-tiny-gqa", max_slots=2, num_pages=64,
                            page_size=8, max_pages_per_seq=16,
                            prefill_buckets=(16, 32), max_new_tokens=6,
                            decode_steps_per_iter=2, tp=tp)

    def run(eng, user):
        rt = eng.runtimes["test-tiny-gqa"]
        tok = rt.tokenizer
        rid = eng.core.enqueue(user, "", "test-tiny-gqa")
        req = Request(rid, user, "test-tiny-gqa", tok.encode("grouped kv"),
                      SamplingParams(max_tokens=5))
        eng.submit(req)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.2)
            if item and item.kind in ("done", "error"):
                assert item.kind == "done", getattr(item, "error", None)
                return req.generated_ids
        raise TimeoutError

    eng8 = TPUEngine(cfg(8), blocklist_path=None)
    eng1 = TPUEngine(cfg(1), blocklist_path=None)
    eng8.start()
    eng1.start()
    try:
        rt8 = eng8.runtimes["test-tiny-gqa"]
        assert rt8.cfg.num_kv_heads == 8  # 4 heads replicated x2
        # KV cache sharded over all 8 devices, one (duplicated) head each.
        assert len(rt8.kc.sharding.device_set) == 8
        ids8 = run(eng8, "tp8")
        ids1 = run(eng1, "tp1")
        assert ids8 == ids1, f"{ids8} != {ids1}"
    finally:
        eng8.stop()
        eng1.stop()
