"""Partition specs for model params, KV cache, and activations.

Standard Megatron-style TP layout expressed as jax.sharding PartitionSpecs —
XLA inserts the allgather/reduce-scatter collectives over ICI when the jitted
step consumes these shardings (no explicit NCCL-style calls, unlike the
reference's HTTP fan-out):

  - wq/wk/wv  [D, heads*hd]  -> shard output (head) dim on "tensor"
  - wo        [heads*hd, D]  -> shard input  (head) dim on "tensor"
                                (row-parallel: psum happens via sharding)
  - w_gate/w_up [D, F]       -> shard F on "tensor"
  - w_down     [F, D]        -> shard F on "tensor"
  - embed     [V, D]         -> shard vocab on "tensor" (logits computed
                                shard-local then allgathered by XLA)
  - norms                    -> replicated
  - KV pages  [L, P, page, kv_heads, hd] -> shard kv_heads on "tensor"
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from ollamamq_tpu.ops.quant import QuantTensor
from ollamamq_tpu.parallel.mesh import AXIS_EXPERT, AXIS_PIPE, AXIS_TENSOR


def param_partition_specs(params: Dict[str, Any]) -> Dict[str, Any]:
    """Map a params pytree (nested dicts keyed by layer/tensor name) to
    PartitionSpecs by leaf path name."""

    def spec_for(path: str, leaf) -> PS:
        if isinstance(leaf, QuantTensor):
            # Quantized leaf: payload takes the bf16 tensor's spec; the
            # per-channel scale vector shards with the channel when the
            # payload's SHARDED axis is the channel axis (column-parallel
            # weights, vocab-sharded embed/lm_head) and replicates when
            # the sharded axis is the contraction (row-parallel wo /
            # w_down — their channel dim is unsharded).
            name = path.split("/")[-1]
            qspec = spec_for(path, leaf.q)
            if name in ("wq", "wk", "wv", "w_gate", "w_up"):
                sspec = PS(*([None] * (leaf.s.ndim - 1)), AXIS_TENSOR)
            elif name in ("embed", "lm_head"):
                sspec = PS(AXIS_TENSOR)  # per-row scales follow the rows
            else:
                sspec = PS()
            return QuantTensor(qspec, sspec)
        name = path.split("/")[-1]
        nd = leaf.ndim
        # Layer weights are stacked on a leading num_layers axis (scan over
        # layers), so the sharded dim is addressed from the right.
        if name in ("wq", "wk", "wv", "w_gate", "w_up") and nd >= 2:
            return PS(*([None] * (nd - 1)), AXIS_TENSOR)  # column-parallel
        if name in ("wo", "w_down") and nd >= 2:
            return PS(*([None] * (nd - 2)), AXIS_TENSOR, None)  # row-parallel
        # MoE: experts over "expert", per-expert FFN dim over "tensor"
        # (EP x TP composition); the tiny router stays replicated.
        if name in ("we_gate", "we_up"):  # [L, E, D, F]
            return PS(None, AXIS_EXPERT, None, AXIS_TENSOR)
        if name == "we_down":  # [L, E, F, D]
            return PS(None, AXIS_EXPERT, AXIS_TENSOR, None)
        if name in ("bq", "bk", "bv") and nd >= 1:
            return PS(*([None] * (nd - 1)), AXIS_TENSOR)
        if name in ("embed", "lm_head"):
            return PS(AXIS_TENSOR, None)  # vocab-sharded
        return PS()  # norms: replicated

    return _named_map(spec_for, params)


def pipeline_param_specs(params: Dict[str, Any]) -> Dict[str, Any]:
    """Partition specs for PP(xTP): the usual TP specs, plus every leaf of
    the stacked `layers` subtree sharded over "pipe" on its leading
    num_layers dim (parallel/pipeline.py stages scan their local slice)."""
    specs = param_partition_specs(params)

    def add_pipe(leaf, spec):
        dims = list(spec) + [None] * (leaf.ndim - len(spec))
        dims[0] = AXIS_PIPE
        return PS(*dims)

    specs["layers"] = jax.tree_util.tree_map(
        add_pipe, params["layers"], specs["layers"]
    )
    return specs


def kv_cache_spec(pp: bool = False) -> PS:
    """KV slot pool [L, slots, kv_heads, head_dim]: heads on tensor axis;
    under pipeline parallelism layers also split over the pipe axis."""
    return PS(AXIS_PIPE if pp else None, None, AXIS_TENSOR, None)


def kv_scale_spec(pp: bool = False) -> PS:
    """Quantized-pool scale rows [L, slots, kv_heads]: same layout as
    the payload minus the head_dim axis, so each tensor shard owns its
    own heads' scales."""
    return PS(AXIS_PIPE if pp else None, None, AXIS_TENSOR)


def shard_params(params, mesh: Mesh, pp: bool = False):
    """Place a params pytree onto the mesh per the partition rules.

    `pp=True` additionally splits layer stacks over the pipe axis — the
    CALLER decides, because only runtimes that actually run the pipelined
    forwards (parallel/pipeline.py) want pipe-sharded weights; an encoder
    or embed runtime sharing a --pp mesh runs plain GSPMD scans and must
    keep layers pipe-replicated."""
    specs = pipeline_param_specs(params) if pp else param_partition_specs(params)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )


def _named_map(fn, tree, path=""):
    if isinstance(tree, dict):
        return {k: _named_map(fn, v, f"{path}/{k}") for k, v in tree.items()}
    return fn(path, tree)
