"""ollamamq_tpu — a TPU-native LLM serving framework.

A brand-new framework with the capabilities of Chleba/ollamaMQ (per-user FIFO
queuing, fair-share scheduling with VIP/Boost, model-aware routing, dual
Ollama `/api/*` + OpenAI `/v1/*` API surfaces, streaming, health monitoring,
user/IP blocking, admin TUI) — but the pool of HTTP-proxied backends is
replaced by an in-tree JAX/XLA continuous-batching inference engine running
on TPU: prefill + paged-KV decode, tensor-parallel collectives over ICI,
a token-level batch scheduler fed by the per-user fair-share queues.

Reference capability map: /root/reference/src/{main,dispatcher,tui}.rs
(studied for behavior only; architecture here is TPU-first).
"""

__version__ = "0.1.0"
