/* mqcore — native serving core: per-user FIFO queues, fair-share scheduling
 * with VIP/Boost, user/IP blocklist with JSON persistence, counters.
 *
 * This is the C++ re-expression of the reference's dispatcher state machine
 * (/root/reference/src/dispatcher.rs:112-163 state, :389-494 selection),
 * re-targeted at a TPU continuous-batching engine: instead of backend URLs,
 * the caller passes the set of models the engine currently serves, and the
 * scheduler admits whole requests into the engine's token budget.
 *
 * Exact policy parity with the reference:
 *   - active users sorted by lifetime processed count asc, tie lexicographic
 *     (dispatcher.rs:408-412)
 *   - VIP absolute override (dispatcher.rs:415)
 *   - Boost wins only when global_counter is even (dispatcher.rs:416-419)
 *   - otherwise a PERSISTENT round-robin cursor that advances on every
 *     non-VIP/boost selection, even when the pick turns out unservable
 *     (dispatcher.rs:421-424)
 *   - global counter increments only on successful pop (dispatcher.rs:476)
 *   - VIP and Boost are independent slots; both may be held, by different
 *     users (tui.rs:169-206 clears the other slot only for the SAME user)
 *   - "stuck in queue": if the policy-selected user's front request can't be
 *     served, nothing is popped this round (dispatcher.rs:467-473)
 *
 * TPU-era extension: served-token accounting per user; fairness can be
 * switched from request-count to token-count (fairness unit changes when
 * requests share a batch).
 *
 * Thread-safe: one internal mutex; every exported call is atomic.
 * C ABI for ctypes binding from Python.
 */
#ifndef MQCORE_H
#define MQCORE_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct mq_state mq_state;

/* api_family values (dispatcher.rs:42-55) */
enum { MQ_FAMILY_UNKNOWN = 0, MQ_FAMILY_OLLAMA = 1, MQ_FAMILY_OPENAI = 2 };

/* fairness modes */
enum { MQ_FAIR_REQUESTS = 0, MQ_FAIR_TOKENS = 1 };

/* mq_next result codes */
enum { MQ_EMPTY = 0, MQ_STUCK = -1 };

mq_state *mq_new(const char *blocklist_path);
void mq_destroy(mq_state *);

/* Request kinds (engine work classes with separate capacity pools). */
enum { MQ_KIND_GENERATE = 0, MQ_KIND_EMBED = 1 };

/* Enqueue. Returns req_id > 0, or -1 if user blocked, -2 if IP blocked.
 * Also records user->ip (dispatcher.rs:612-615). */
int64_t mq_enqueue(mq_state *, const char *user, const char *ip,
                   const char *model /*nullable*/, int api_family);
/* Enqueue with an explicit request kind (mq_enqueue = kind GENERATE). */
int64_t mq_enqueue_kind(mq_state *, const char *user, const char *ip,
                        const char *model /*nullable*/, int api_family,
                        int kind);
/* Return a popped-but-unplaceable task to the FRONT of its user's queue
 * (fresh req_id; FIFO preserved — the reference peeks and never pops
 * until dispatchable, dispatcher.rs:427-431). */
int64_t mq_requeue_front(mq_state *, const char *user, const char *ip,
                         const char *model /*nullable*/, int api_family,
                         int kind);

/* Pick per policy. eligible_models: '\n'-separated model names the engine
 * can serve right now (empty string => nothing loaded; NULL => everything
 * eligible). Returns req_id popped (>0), MQ_EMPTY, or MQ_STUCK. On success
 * fills out_user/out_model (model may be empty). */
int64_t mq_next(mq_state *, const char *eligible_models,
                char *out_user, int user_cap,
                char *out_model, int model_cap);
/* Kind-aware pick: the gate list is chosen by the FRONT task's kind, so
 * embed capacity and decode-slot capacity are independent pools (a full
 * decode batch must not park embeds, and a deep embed backlog must not
 * park generates). eligible_embed == NULL falls back to
 * eligible_generate (kind-blind behavior). */
int64_t mq_next2(mq_state *, const char *eligible_generate,
                 const char *eligible_embed,
                 char *out_user, int user_cap,
                 char *out_model, int model_cap);

/* Remove a still-queued request (client cancel/disconnect before dispatch).
 * Returns 1 if found+removed (counts dropped), 0 otherwise. */
int mq_cancel(mq_state *, int64_t req_id);

/* Lifecycle accounting (dispatcher.rs:514-517, 562-573). */
void mq_mark_started(mq_state *, const char *user);
void mq_mark_done(mq_state *, const char *user, int64_t tokens_served);
/* was_started: 1 if mq_mark_started ran for this request (decrements the
 * processing gauge); 0 if it was dropped before dispatch. */
void mq_mark_dropped(mq_state *, const char *user, int was_started);

/* Block admin (dispatcher.rs:184-228); persists on every mutation. */
void mq_block_user(mq_state *, const char *user);
void mq_unblock_user(mq_state *, const char *user);
void mq_block_ip(mq_state *, const char *ip);
void mq_unblock_ip(mq_state *, const char *ip);
int mq_is_user_blocked(mq_state *, const char *user);
int mq_is_ip_blocked(mq_state *, const char *ip);
/* Unblock by either kind (tui 'u' key); returns 1 if anything removed. */
int mq_unblock_item(mq_state *, const char *item);
/* Monotonic counter bumped by every block mutation; the engine's late
 * re-check sweeps held requests only when this changes. */
int64_t mq_block_version(mq_state *);
/* Blocked directly, or via the user's last recorded IP (the reference's
 * dispatch-time re-check covers both sets, dispatcher.rs:503-512). */
int mq_is_user_or_ip_blocked(mq_state *, const char *user);

/* VIP/boost: set to user or clear with NULL. Toggle semantics (same user
 * clears the other slot) are the caller's job, mirroring the TUI. */
void mq_set_vip(mq_state *, const char *user_or_null);
void mq_set_boost(mq_state *, const char *user_or_null);

void mq_set_fairness_mode(mq_state *, int mode);

/* Queue depth for one user / total queued. */
int64_t mq_queue_len(mq_state *, const char *user);
int64_t mq_total_queued(mq_state *);
/* Queued tasks a given model could serve (empty-model tasks count). */
int64_t mq_queued_matching(mq_state *, const char *model);

/* Full state snapshot as JSON (users, counters, queues, vip/boost, blocked).
 * Returns bytes written (excluding NUL), or required size if cap too small. */
int64_t mq_snapshot_json(mq_state *, char *out, int64_t cap);

#ifdef __cplusplus
}
#endif
#endif
