/* Native admin TUI — ANSI/termios, no curses dependency.
 *
 * Re-creates the reference dashboard's semantics (tui.rs) on top of the
 * TPU engine: the backends panel becomes a CHIPS/MODELS panel showing HBM
 * occupancy, decode step latency, and tok/s per model runtime instead of
 * Ollama URL status. Key map preserved from the reference
 * (tui.rs:102-303):
 *
 *   q/Esc quit (whole app)     ?        toggle help
 *   Tab/h/l  cycle panel       j/k      move selection
 *   Space/Enter expand model detail
 *   p  VIP toggle on selected user (clears boost only if the SAME user
 *      held it — tui.rs:169-175)
 *   b  boost toggle (symmetric — tui.rs:196-202)
 *   x  block selected user     X  block selected user's IP
 *   u  unblock selected blocked item
 *
 * Data feeds: the mqcore snapshot (same-process, via mq_snapshot_json)
 * and an engine-stats callback provided by the embedding Python process
 * (model runtimes, HBM, step latency). Rendering double-buffers into a
 * string and writes one frame per refresh to avoid flicker; input is
 * select(2)-polled at the reference's 100 ms cadence (tui.rs:112).
 */

#include <sys/ioctl.h>
#include <sys/select.h>
#include <termios.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>
#include <vector>

#include "minijson.h"
#include "mqcore.h"

extern "C" {
typedef long long (*mq_stats_cb)(char *buf, long long cap);
int mqtui_run(mq_state *state, mq_stats_cb stats_cb, int refresh_ms);
}

namespace {

struct TermGuard {
  termios orig{};
  bool ok = false;
  TermGuard() {
    if (tcgetattr(STDIN_FILENO, &orig) == 0) {
      termios raw = orig;
      raw.c_lflag &= ~(ICANON | ECHO);
      raw.c_cc[VMIN] = 0;
      raw.c_cc[VTIME] = 0;
      tcsetattr(STDIN_FILENO, TCSANOW, &raw);
      ok = true;
    }
    // Alt screen + hide cursor.
    (void)!write(STDOUT_FILENO, "\x1b[?1049h\x1b[?25l", 14);
  }
  ~TermGuard() {
    (void)!write(STDOUT_FILENO, "\x1b[?1049l\x1b[?25h", 14);
    if (ok) tcsetattr(STDIN_FILENO, TCSANOW, &orig);
  }
};

struct UserRow {
  std::string name;
  long long queued = 0, processing = 0, processed = 0, dropped = 0, tokens = 0;
  std::string ip;
};

// Colors.
const char *RST = "\x1b[0m";
const char *BOLD = "\x1b[1m";
const char *DIM = "\x1b[2m";
const char *CYAN = "\x1b[36m";
const char *GREEN = "\x1b[32m";
const char *YELLOW = "\x1b[33m";
const char *RED = "\x1b[31m";
const char *MAGENTA = "\x1b[35m";
const char *INV = "\x1b[7m";

std::string pad(const std::string &s, size_t w) {
  // Width-naive truncate/pad (ASCII data; user ids clipped hard).
  if (s.size() >= w) return s.substr(0, w);
  return s + std::string(w - s.size(), ' ');
}

std::string human_bytes(double b) {
  char buf[32];
  if (b >= 1e9) std::snprintf(buf, sizeof buf, "%.1fG", b / 1e9);
  else if (b >= 1e6) std::snprintf(buf, sizeof buf, "%.0fM", b / 1e6);
  else if (b >= 1e3) std::snprintf(buf, sizeof buf, "%.0fK", b / 1e3);
  else std::snprintf(buf, sizeof buf, "%.0fB", b);
  return buf;
}

struct Tui {
  mq_state *state;
  mq_stats_cb stats_cb;
  int panel = 0;  // 0 chips/models, 1 users, 2 queues, 3 blocked
  int sel[4] = {0, 0, 0, 0};
  bool expanded = false;
  bool help = false;
  // tok/s rate from successive tokens_generated samples.
  double last_tokens = -1;
  double tok_rate = 0;
  timespec last_sample{};

  std::string frame;

  void put(const std::string &s) { frame += s; }
  void line(const std::string &s, int width) {
    frame += pad_visible(s, width);
    frame += "\x1b[K\r\n";
  }

  // pad to visible width ignoring escape sequences
  static std::string pad_visible(const std::string &s, int width) {
    int vis = 0;
    std::string out;
    for (size_t i = 0; i < s.size();) {
      if (s[i] == '\x1b') {
        size_t j = i + 1;
        while (j < s.size() && s[j] != 'm') ++j;
        out += s.substr(i, j - i + 1);
        i = j + 1;
      } else {
        if (vis < width) {
          out += s[i];
          ++vis;
        }
        ++i;
      }
    }
    while (vis < width) {
      out += ' ';
      ++vis;
    }
    return out;
  }

  mj::ValuePtr snapshot() {
    long long need = mq_snapshot_json(state, nullptr, 0);
    std::string buf(need + 16, '\0');
    mq_snapshot_json(state, buf.data(), (long long)buf.size());
    buf.resize(std::strlen(buf.c_str()));
    return mj::parse(buf);
  }

  bool quit_requested = false;

  mj::ValuePtr engine_stats() {
    if (!stats_cb) return std::make_shared<mj::Value>();
    std::string buf(65536, '\0');
    long long n = stats_cb(buf.data(), (long long)buf.size());
    if (n == -9) {  // embedder requests shutdown (e.g. Ctrl-C in Python)
      quit_requested = true;
      return std::make_shared<mj::Value>();
    }
    // Bounds-check hard: a failed ctypes callback can return garbage.
    if (n <= 0 || n >= (long long)buf.size())
      return std::make_shared<mj::Value>();
    buf.resize((size_t)n);
    return mj::parse(buf);
  }

  std::vector<UserRow> user_rows(const mj::ValuePtr &snap) {
    std::vector<UserRow> rows;
    auto users = snap->get("users");
    if (!users) return rows;
    for (auto &kv : users->obj) {
      UserRow r;
      r.name = kv.first;
      auto &u = kv.second;
      r.queued = u->get("queued") ? u->get("queued")->as_int() : 0;
      r.processing = u->get("processing") ? u->get("processing")->as_int() : 0;
      r.processed = u->get("processed") ? u->get("processed")->as_int() : 0;
      r.dropped = u->get("dropped") ? u->get("dropped")->as_int() : 0;
      r.tokens = u->get("tokens") ? u->get("tokens")->as_int() : 0;
      if (u->get("ip")) r.ip = u->get("ip")->as_str();
      rows.push_back(std::move(r));
    }
    // Reference ordering (tui.rs:76-85): active first (queued+processing
    // desc), then lifetime (processed+dropped desc), then name.
    std::sort(rows.begin(), rows.end(), [](const UserRow &a, const UserRow &b) {
      long long aa = a.queued + a.processing, bb = b.queued + b.processing;
      if (aa != bb) return aa > bb;
      long long al = a.processed + a.dropped, bl = b.processed + b.dropped;
      if (al != bl) return al > bl;
      return a.name < b.name;
    });
    return rows;
  }

  void render(int rows, int cols) {
    frame.clear();
    put("\x1b[H");  // home

    auto snap = snapshot();
    auto stats = engine_stats();
    auto users = user_rows(snap);
    std::string vip = snap->get("vip") && !snap->get("vip")->is_null()
                          ? snap->get("vip")->as_str() : "";
    std::string boost = snap->get("boost") && !snap->get("boost")->is_null()
                            ? snap->get("boost")->as_str() : "";

    // ---- stats bar ----
    long long tq = 0, tp = 0, tdone = 0, tdrop = 0, ttok = 0;
    for (auto &u : users) {
      tq += u.queued; tp += u.processing; tdone += u.processed;
      tdrop += u.dropped; ttok += u.tokens;
    }
    // tok/s from engine counter deltas.
    double tokens_now = 0;
    auto models = stats->get("models");
    if (models)
      for (auto &m : models->arr)
        tokens_now += m->get("tokens_generated")
                          ? m->get("tokens_generated")->as_num() : 0;
    timespec now{};
    clock_gettime(CLOCK_MONOTONIC, &now);
    if (last_tokens >= 0) {
      double dt = (now.tv_sec - last_sample.tv_sec) +
                  (now.tv_nsec - last_sample.tv_nsec) / 1e9;
      if (dt > 0.5) {
        tok_rate = 0.7 * tok_rate + 0.3 * ((tokens_now - last_tokens) / dt);
        last_tokens = tokens_now;
        last_sample = now;
      }
    } else {
      last_tokens = tokens_now;
      last_sample = now;
    }

    char bar[512];
    std::snprintf(bar, sizeof bar,
                  " ollamaMQ-TPU   queued %lld   processing %lld   served %lld   "
                  "dropped %lld   tok/s %.0f",
                  tq, tp, tdone, tdrop, tok_rate > 0 ? tok_rate : 0.0);
    put(std::string(BOLD) + INV);
    line(bar, cols);
    put(RST);

    if (help) {
      render_help(rows, cols);
      return;
    }

    // ---- three columns: chips/models | users | queues ----
    int col1 = cols * 35 / 100, col2 = cols * 35 / 100;
    int col3 = cols - col1 - col2 - 2;
    int body = rows - 2 /*bars*/ - 6 /*blocked + headers*/ - 3 /*alerts*/
               - 1 /*last-decision line*/;
    if (body < 4) body = 4;

    std::vector<std::string> c1 = render_models(stats, col1, body);
    std::vector<std::string> c2 = render_users(users, vip, boost, col2, body);
    std::vector<std::string> c3 = render_queues(users, tq, col3, body);
    for (int i = 0; i < body; ++i) {
      std::string l;
      l += pad_visible(i < (int)c1.size() ? c1[i] : "", col1);
      l += "\x1b[2m|\x1b[0m";
      l += pad_visible(i < (int)c2.size() ? c2[i] : "", col2);
      l += "\x1b[2m|\x1b[0m";
      l += pad_visible(i < (int)c3.size() ? c3[i] : "", col3);
      line(l, cols);
    }

    // ---- flight recorder: newest scheduler decision, full width (the
    // explain() one-liner from the engine's decision journal; fixed one
    // row so the layout never jumps) ----
    auto last = stats->get("last_decision");
    if (last && last->type == mj::Value::STR && !last->str.empty())
      line(std::string(DIM) + " last: " + last->str + RST, cols);
    else
      line(std::string(DIM) + " last: (no decisions yet)" + RST, cols);

    // ---- alerts (SLO burn-rate + stall watchdog, via the engine's
    // shared alert table; ok when quiet, red rows when firing) ----
    render_alerts(stats, cols);

    // ---- blocked items ----
    put(std::string(BOLD));
    line(panel == 3 ? "> BLOCKED ITEMS" : "  BLOCKED ITEMS", cols);
    put(RST);
    std::vector<std::string> blocked;
    if (snap->get("blocked_users"))
      for (auto &b : snap->get("blocked_users")->arr)
        blocked.push_back("user " + b->as_str());
    if (snap->get("blocked_ips"))
      for (auto &b : snap->get("blocked_ips")->arr)
        blocked.push_back("ip   " + b->as_str());
    if (sel[3] >= (int)blocked.size()) sel[3] = blocked.empty() ? 0 : blocked.size() - 1;
    for (int i = 0; i < 3; ++i) {
      if (i < (int)blocked.size()) {
        std::string marker = (panel == 3 && i == sel[3]) ? "> " : "  ";
        line(marker + std::string(RED) + "✖ " + RST + blocked[i], cols);
      } else {
        line(i == 0 && blocked.empty() ? std::string(DIM) + "  (none)" + RST : "", cols);
      }
    }

    // ---- help bar ----
    put(DIM);
    line(" q quit  ? help  Tab panel  j/k move  p VIP  b boost  x block  X block-ip  u unblock  Space expand",
         cols);
    put(RST);
  }

  std::vector<std::string> render_models(const mj::ValuePtr &stats, int w, int body) {
    std::vector<std::string> out;
    std::string hdr = panel == 0 ? "> CHIPS / MODELS" : "  CHIPS / MODELS";
    out.push_back(std::string(BOLD) + hdr + RST);
    double hbm_used = stats->get("hbm_used") ? stats->get("hbm_used")->as_num() : 0;
    double hbm_total = stats->get("hbm_total") ? stats->get("hbm_total")->as_num() : 0;
    std::string dev = stats->get("device") ? stats->get("device")->as_str() : "?";
    char l[256];
    if (hbm_total > 0)
      std::snprintf(l, sizeof l, " %s  HBM %s/%s (%.0f%%)", dev.c_str(),
                    human_bytes(hbm_used).c_str(), human_bytes(hbm_total).c_str(),
                    100.0 * hbm_used / hbm_total);
    else
      std::snprintf(l, sizeof l, " %s  HBM %s", dev.c_str(),
                    human_bytes(hbm_used).c_str());
    out.push_back(std::string(CYAN) + l + RST);
    /* Throughput + MFU: the "is the pod earning its keep" line. MFU is
     * the max over runtimes (fraction 0..1 from the engine's analytic
     * FLOPs model over chip peak); 0 renders as "--" (unknown peak, e.g.
     * CPU meshes, or no decode step yet). */
    double mfu = 0;
    /* Prefix-cache hit ratio: summed hits/misses over runtimes that
     * cache ("prefix_cache" non-null). No caching runtime => "n/a". */
    bool cache_on = false;
    double cache_hits = 0, cache_lookups = 0;
    auto models_mfu = stats->get("models");
    if (models_mfu)
      for (auto &m : models_mfu->arr) {
        double v = m->get("mfu") ? m->get("mfu")->as_num() : 0;
        if (v > mfu) mfu = v;
        auto pc = m->get("prefix_cache");
        if (pc && !pc->is_null()) {
          cache_on = true;
          double h = pc->get("hits") ? pc->get("hits")->as_num() : 0;
          double mi = pc->get("misses") ? pc->get("misses")->as_num() : 0;
          cache_hits += h;
          cache_lookups += h + mi;
        }
      }
    char cache[32];
    if (!cache_on)
      std::snprintf(cache, sizeof cache, "cache n/a");
    else
      std::snprintf(cache, sizeof cache, "cache %.0f%%",
                    cache_lookups > 0 ? 100.0 * cache_hits / cache_lookups
                                      : 0.0);
    /* Degradation chip: requests shed (admission caps / deadlines / KV
     * exhaustion) and KV-pressure preemptions. Both nonzero is the
     * "saturated but degrading gracefully" signature; shed rising with
     * preempt flat means the queue caps are doing the shedding. */
    double shed = stats->get("shed") ? stats->get("shed")->as_num() : 0;
    double preempt =
        stats->get("preempt") ? stats->get("preempt")->as_num() : 0;
    char degrade[48];
    std::snprintf(degrade, sizeof degrade, "shed %.0f  preempt %.0f", shed,
                  preempt);
    /* Scheduler chip: active policy (fcfs/srpt/edf) + the output-length
     * predictor's accuracy over its recent window. "acc n/a" until the
     * predictor has observed enough finishes to warm up. */
    char schedc[64];
    auto sched = stats->get("sched");
    if (sched && sched->type == mj::Value::OBJ) {
      std::string pol =
          sched->get("policy") ? sched->get("policy")->as_str() : "?";
      auto acc = sched->get("pred_accuracy");
      if (acc && !acc->is_null())
        std::snprintf(schedc, sizeof schedc, "sched %s acc %.0f%%",
                      pol.c_str(), acc->as_num() * 100.0);
      else
        std::snprintf(schedc, sizeof schedc, "sched %s acc n/a",
                      pol.c_str());
    } else {
      std::snprintf(schedc, sizeof schedc, "sched n/a");
    }
    if (mfu > 0)
      std::snprintf(l, sizeof l,
                    " throughput %.0f tok/s   MFU %.2f%%   %s   %s   %s",
                    tok_rate > 0 ? tok_rate : 0.0, mfu * 100.0, cache, degrade,
                    schedc);
    else
      std::snprintf(l, sizeof l,
                    " throughput %.0f tok/s   MFU --   %s   %s   %s",
                    tok_rate > 0 ? tok_rate : 0.0, cache, degrade, schedc);
    out.push_back(std::string(CYAN) + l + RST);
    /* Engine performance plane chip (its own line, present once the
     * engine has dispatched or compiled): compile-ladder fill count +
     * rolling step p99 off the always-on step profiler. A compile
     * count still climbing in steady state is ladder thrash (the
     * compile_storm alert's TUI face). */
    auto sp = stats->get("stepprof");
    if (sp && sp->type == mj::Value::OBJ) {
      double comp =
          sp->get("compiles") ? sp->get("compiles")->as_num() : 0;
      auto sp99 = sp->get("p99_ms");
      if (sp99 && sp99->type == mj::Value::NUM)
        std::snprintf(l, sizeof l, " compiles %.0f · step p99 %.2fms",
                      comp, sp99->as_num());
      else
        std::snprintf(l, sizeof l, " compiles %.0f · step p99 n/a", comp);
      out.push_back(std::string(CYAN) + l + RST);
    }
    /* Fleet replicas chip (only under a fleet router): N healthy / M
     * ejected / K draining. Red when any member is out of rotation —
     * capacity is reduced and streams may be mid-failover. */
    auto fleet = stats->get("replicas");
    if (fleet && fleet->type == mj::Value::OBJ) {
      double fh = fleet->get("healthy") ? fleet->get("healthy")->as_num() : 0;
      double fe = fleet->get("ejected") ? fleet->get("ejected")->as_num() : 0;
      double fd = fleet->get("draining") ? fleet->get("draining")->as_num() : 0;
      std::snprintf(l, sizeof l,
                    " replicas %.0f healthy / %.0f ejected / %.0f draining",
                    fh, fe, fd);
      out.push_back(std::string(fe > 0 ? RED : CYAN) + l + RST);
      /* Router-overhead chip (its own line — the chips column is a
       * third of the terminal): the windowed placement-decision p99 vs
       * its budget (ollamamq_router_overhead_ms{site="place"}). RED
       * when the router hot path itself is over budget — the fleet is
       * paying routing tax on every stream, not just serving slower. */
      auto ro = stats->get("router_overhead");
      if (ro && ro->type == mj::Value::OBJ) {
        bool over = false;
        if (ro->get("p99_ms") && ro->get("p99_ms")->type == mj::Value::NUM) {
          double p99 = ro->get("p99_ms")->as_num();
          double budget = ro->get("budget_ms")
                              ? ro->get("budget_ms")->as_num() : 0;
          over = budget > 0 && p99 > budget;
          if (budget > 0)
            std::snprintf(l, sizeof l,
                          " router p99 %.2fms (budget %.0fms)", p99, budget);
          else
            std::snprintf(l, sizeof l, " router p99 %.2fms", p99);
        } else {
          std::snprintf(l, sizeof l, " router p99 n/a");
        }
        out.push_back(std::string(over ? RED : CYAN) + l + RST);
      }
    }
    /* Fleet-size chip (elastic fleets only): current size against the
     * autoscaler's [min, max] band, plus how much of the fleet is
     * preemptible (spot) capacity that a reclamation notice can take. */
    auto fsz = stats->get("fleet_size");
    if (fsz && fsz->type == mj::Value::OBJ) {
      double fn = fsz->get("n") ? fsz->get("n")->as_num() : 0;
      double fp =
          fsz->get("preemptible") ? fsz->get("preemptible")->as_num() : 0;
      double fmin = fsz->get("min") ? fsz->get("min")->as_num() : 0;
      double fmax = fsz->get("max") ? fsz->get("max")->as_num() : 0;
      if (fp > 0)
        std::snprintf(l, sizeof l,
                      " fleet %.0f (+%.0f preemptible)  [%.0f..%.0f]",
                      fn, fp, fmin, fmax);
      else
        std::snprintf(l, sizeof l, " fleet %.0f  [%.0f..%.0f]", fn, fmin,
                      fmax);
      out.push_back(std::string(CYAN) + l + RST);
    }
    /* HA role chip (HA fleets only): role + fencing epoch, e.g.
     * "ha primary/3"; a standby adds its replication lag in records.
     * RED while "promoting" (takeover ladder in flight) and for a
     * standby that has not caught up to its primary's stream — in both
     * states the fleet is one failure away from dropping streams. */
    auto ha = stats->get("ha");
    if (ha && ha->type == mj::Value::OBJ) {
      std::string role = ha->get("role") ? ha->get("role")->str : "?";
      long long epoch = ha->get("epoch") ? ha->get("epoch")->as_int() : 0;
      bool synced = !ha->get("synced") ||
                    ha->get("synced")->type != mj::Value::BOOL ||
                    ha->get("synced")->b;
      auto lag = ha->get("lag");
      if (role != "primary" && lag && lag->type == mj::Value::NUM)
        std::snprintf(l, sizeof l, " ha %s/%lld  lag %.0f", role.c_str(),
                      epoch, lag->as_num());
      else
        std::snprintf(l, sizeof l, " ha %s/%lld", role.c_str(), epoch);
      bool alarm = role == "promoting" || (role == "standby" && !synced);
      out.push_back(std::string(alarm ? RED : CYAN) + l + RST);
    }
    /* Tiers line (tiered fleets only): healthy/total per replica tier.
     * RED when any tier has ZERO healthy members — that tier's traffic
     * is being served cross-tier (journaled overflow) until a member
     * heals or regroups in. */
    auto tiers = stats->get("tiers");
    if (tiers && tiers->type == mj::Value::OBJ) {
      std::string line = " tiers";
      bool starved = false;
      for (auto &kv : tiers->obj) {
        auto &t = kv.second;
        if (!t || t->type != mj::Value::OBJ) continue;
        double th = t->get("healthy") ? t->get("healthy")->as_num() : 0;
        double tt = t->get("total") ? t->get("total")->as_num() : 0;
        if (tt > 0 && th <= 0) starved = true;
        std::snprintf(l, sizeof l, "  %s %.0f/%.0f", kv.first.c_str(), th,
                      tt);
        line += l;
      }
      out.push_back(std::string(starved ? RED : CYAN) + line + RST);
    }
    /* One row PER chip (pod-wide under SPMD): the north star's "per-chip
     * HBM occupancy" — a v5e-16 must not show chip 0 for the pod. */
    auto chips = stats->get("chips");
    if (chips && !chips->arr.empty()) {
      /* Cap the rows so a big pod (v5e-64+) can't push the MODELS list —
       * the panel the admin verbs operate on — off a 40-row terminal. */
      int cap = body - 4 - (int)(stats->get("models")
                                     ? stats->get("models")->arr.size() : 0);
      if (cap < 2) cap = 2;
      int shown = 0;
      for (auto &c : chips->arr) {
        if (shown >= cap) break;
        long long id = c->get("id") ? c->get("id")->as_int() : 0;
        long long proc = c->get("process") ? c->get("process")->as_int() : 0;
        double cu = c->get("hbm_used") ? c->get("hbm_used")->as_num() : 0;
        double ct = c->get("hbm_total") ? c->get("hbm_total")->as_num() : 0;
        /* Backend without memory_stats (CPU): say "n/a", never a fake
         * 0-byte HBM reading. Missing key = legacy row = assume real. */
        auto ms = c->get("memory_stats");
        if (ms && ms->type == mj::Value::BOOL && !ms->b) {
          std::snprintf(l, sizeof l, "  chip %lld (host %lld)  HBM n/a",
                        id, proc);
          out.push_back(std::string(DIM) + l + RST);
          ++shown;
          continue;
        }
        if (ct > 0)
          std::snprintf(l, sizeof l, "  chip %lld (host %lld)  %s/%s (%.0f%%)",
                        id, proc, human_bytes(cu).c_str(),
                        human_bytes(ct).c_str(), 100.0 * cu / ct);
        else
          std::snprintf(l, sizeof l, "  chip %lld (host %lld)  %s", id, proc,
                        human_bytes(cu).c_str());
        out.push_back(std::string(DIM) + l + RST);
        ++shown;
      }
      if ((int)chips->arr.size() > shown) {
        std::snprintf(l, sizeof l, "  … +%d more chips",
                      (int)chips->arr.size() - shown);
        out.push_back(std::string(DIM) + l + RST);
      }
    }
    auto models = stats->get("models");
    if (!models) return out;
    int idx = 0;
    if (sel[0] >= (int)models->arr.size())
      sel[0] = models->arr.empty() ? 0 : models->arr.size() - 1;
    for (auto &m : models->arr) {
      std::string name = m->get("model") ? m->get("model")->as_str() : "?";
      long long act = m->get("active_slots") ? m->get("active_slots")->as_int() : 0;
      long long slots = m->get("max_slots") ? m->get("max_slots")->as_int() : 0;
      double step = m->get("step_latency_ms") ? m->get("step_latency_ms")->as_num() : 0;
      std::string marker = (panel == 0 && idx == sel[0]) ? "> " : "  ";
      const char *color = act > 0 ? GREEN : DIM;
      std::snprintf(l, sizeof l, "%s%s  %lld/%lld slots  %.1fms/step",
                    marker.c_str(), name.c_str(), act, slots, step);
      out.push_back(std::string(color) + l + RST);
      if (expanded && panel == 0 && idx == sel[0]) {
        long long pu = m->get("pages_used") ? m->get("pages_used")->as_int() : 0;
        long long pt = m->get("pages_total") ? m->get("pages_total")->as_int() : 0;
        double pb = m->get("param_bytes") ? m->get("param_bytes")->as_num() : 0;
        double kb = m->get("kv_bytes") ? m->get("kv_bytes")->as_num() : 0;
        long long pend = m->get("pending_prefill")
                             ? m->get("pending_prefill")->as_int() : 0;
        std::snprintf(l, sizeof l, "    KV pages %lld/%lld  prefillQ %lld", pu, pt, pend);
        out.push_back(std::string(DIM) + l + RST);
        std::snprintf(l, sizeof l, "    params %s  kv-pool %s",
                      human_bytes(pb).c_str(), human_bytes(kb).c_str());
        out.push_back(std::string(DIM) + l + RST);
        double pfms = m->get("prefill_latency_ms")
                          ? m->get("prefill_latency_ms")->as_num() : 0;
        double ttft50 = m->get("ttft_p50_ms") ? m->get("ttft_p50_ms")->as_num() : 0;
        double st50 = m->get("step_p50_ms") ? m->get("step_p50_ms")->as_num() : 0;
        std::snprintf(l, sizeof l, "    last prefill %.1fms  TTFT p50 %.0fms  step p50 %.1fms",
                      pfms, ttft50, st50);
        out.push_back(std::string(DIM) + l + RST);
      }
      ++idx;
      if ((int)out.size() >= body) break;
    }
    return out;
  }

  void render_alerts(const mj::ValuePtr &stats, int cols) {
    /* Fixed 3-row section (header + 2 rows) so the layout never jumps
     * when alerts come and go. Overflow collapses into a "+N more". */
    auto alerts = stats->get("alerts");
    size_t n = alerts ? alerts->arr.size() : 0;
    char hdr[64];
    if (n > 0)
      std::snprintf(hdr, sizeof hdr, "  ALERTS (%d firing)", (int)n);
    else
      std::snprintf(hdr, sizeof hdr, "  ALERTS");
    put(std::string(BOLD) + (n > 0 ? RED : ""));
    line(hdr, cols);
    put(RST);
    int shown = 0;
    const int cap = 2;
    if (alerts) {
      for (auto &a : alerts->arr) {
        if (shown >= cap) break;
        std::string name = a->get("name") ? a->get("name")->as_str() : "?";
        std::string sev =
            a->get("severity") ? a->get("severity")->as_str() : "?";
        std::string msg =
            a->get("message") ? a->get("message")->as_str() : "";
        long long age = a->get("age_s") ? a->get("age_s")->as_int() : 0;
        char l[512];
        std::snprintf(l, sizeof l, "  ⚠ [%s] %s (%llds): %s", sev.c_str(),
                      name.c_str(), age, msg.c_str());
        line(std::string(RED) + l + RST, cols);
        ++shown;
      }
      if ((int)n > shown) {
        char l[64];
        std::snprintf(l, sizeof l, "    … +%d more alert(s)",
                      (int)n - shown);
        line(std::string(RED) + l + RST, cols);
        ++shown;
      }
    }
    if (shown == 0) {
      line(std::string(DIM) + "  (none)" + RST, cols);
      ++shown;
    }
    for (; shown < cap; ++shown) line("", cols);
  }

  std::vector<std::string> render_users(const std::vector<UserRow> &users,
                                        const std::string &vip,
                                        const std::string &boost,
                                        int w, int body) {
    std::vector<std::string> out;
    std::string hdr = panel == 1 ? "> USERS" : "  USERS";
    out.push_back(std::string(BOLD) + hdr + RST);
    if (sel[1] >= (int)users.size()) sel[1] = users.empty() ? 0 : users.size() - 1;
    int idx = 0;
    for (auto &u : users) {
      std::string sym, color = DIM;
      if (u.name == vip) { sym += "★"; color = YELLOW; }
      if (u.name == boost) { sym += "⚡"; color = MAGENTA; }
      if (mq_is_user_blocked(state, u.name.c_str())) { sym += "✖"; color = RED; }
      if (u.processing > 0) { sym += "▶"; if (color == DIM) color = GREEN; }
      else if (u.queued > 0) { sym += "●"; if (color == DIM) color = CYAN; }
      std::string marker = (panel == 1 && idx == sel[1]) ? "> " : "  ";
      char l[256];
      std::snprintf(l, sizeof l, "%s%s %s  q%lld r%lld d%lld x%lld t%lld",
                    marker.c_str(), pad(u.name, 14).c_str(), pad(sym, 3).c_str(),
                    u.queued, u.processing, u.processed, u.dropped, u.tokens);
      out.push_back(color + l + RST);
      ++idx;
      if ((int)out.size() >= body) break;
    }
    if (users.empty())
      out.push_back(std::string(DIM) + "  (no users yet)" + RST);
    return out;
  }

  std::vector<std::string> render_queues(const std::vector<UserRow> &users,
                                         long long total_queued, int w, int body) {
    std::vector<std::string> out;
    std::string hdr = panel == 2 ? "> QUEUES" : "  QUEUES";
    out.push_back(std::string(BOLD) + hdr + RST);
    int barw = w - 22;
    if (barw < 5) barw = 5;
    int idx = 0;
    for (auto &u : users) {
      if (u.queued == 0 && idx >= 3) continue;
      // Reference scaling: 20 queued requests = full bar (tui.rs:529-547).
      int fill = (int)std::min<long long>(u.queued * barw / 20, barw);
      double pct = total_queued > 0 ? 100.0 * u.queued / total_queued : 0;
      char l[256];
      std::string bar = std::string(fill, '#') + std::string(barw - fill, ' ');
      std::snprintf(l, sizeof l, "  %s [%s] %3.0f%%",
                    pad(u.name, 10).c_str(), bar.c_str(), pct);
      out.push_back((u.queued > 0 ? std::string(CYAN) : std::string(DIM)) + l + RST);
      ++idx;
      if ((int)out.size() >= body) break;
    }
    return out;
  }

  void render_help(int rows, int cols) {
    const char *lines[] = {
      "",
      "  KEYS",
      "    q / Esc      quit (stops the whole server)",
      "    ?            toggle this help",
      "    Tab / h / l  cycle focused panel",
      "    j / k        move selection in the focused panel",
      "    Space/Enter  expand model details (chips panel)",
      "    p            toggle VIP on the selected user (absolute priority)",
      "    b            toggle Boost on the selected user (wins every 2nd tick)",
      "    x            block the selected user   (persists to blocked_items.json)",
      "    X            block the selected user's IP",
      "    u            unblock the selected blocked item",
      "",
      "  PANELS",
      "    CHIPS/MODELS  model runtimes on the TPU: slots, step latency, HBM",
      "    USERS         fair-share state: ★VIP ⚡boost ✖blocked ▶processing ●queued",
      "    QUEUES        per-user queue depth (full bar = 20 requests)",
      "    ALERTS        firing alerts: SLO burn-rate + stall watchdog",
      "    BLOCKED       persisted user/IP blocklist",
      "",
      "  press ? to return",
    };
    for (auto *l : lines) line(l, cols);
    for (int i = 0; i < rows - 2 - (int)(sizeof(lines) / sizeof(*lines)); ++i)
      line("", cols);
  }

  // ---- actions ----
  void act_on_key(char c, const std::vector<UserRow> &users,
                  const std::vector<std::string> &blocked_items,
                  const std::string &vip, const std::string &boost) {
    switch (c) {
      case '\t': case 'l': panel = (panel + 1) % 4; break;
      case 'h': panel = (panel + 3) % 4; break;
      case 'j': sel[panel] += 1; break;
      case 'k': if (sel[panel] > 0) sel[panel] -= 1; break;
      case ' ': case '\r': expanded = !expanded; break;
      case '?': help = !help; break;
      case 'p': {
        if (panel == 1 && sel[1] < (int)users.size()) {
          const std::string &u = users[sel[1]].name;
          if (vip == u) {
            mq_set_vip(state, nullptr);
          } else {
            mq_set_vip(state, u.c_str());
            if (boost == u) mq_set_boost(state, nullptr);  // tui.rs:169-175
          }
        }
        break;
      }
      case 'b': {
        if (panel == 1 && sel[1] < (int)users.size()) {
          const std::string &u = users[sel[1]].name;
          if (boost == u) {
            mq_set_boost(state, nullptr);
          } else {
            mq_set_boost(state, u.c_str());
            if (vip == u) mq_set_vip(state, nullptr);  // tui.rs:196-202
          }
        }
        break;
      }
      case 'x': {
        if (panel == 1 && sel[1] < (int)users.size())
          mq_block_user(state, users[sel[1]].name.c_str());
        break;
      }
      case 'X': {
        if (panel == 1 && sel[1] < (int)users.size() &&
            !users[sel[1]].ip.empty())
          mq_block_ip(state, users[sel[1]].ip.c_str());
        break;
      }
      case 'u': {
        if (panel == 3 && sel[3] < (int)blocked_items.size())
          mq_unblock_item(state, blocked_items[sel[3]].c_str());
        break;
      }
    }
  }
};

}  // namespace

extern "C" int mqtui_run(mq_state *state, mq_stats_cb stats_cb, int refresh_ms) {
  if (!isatty(STDIN_FILENO) || !isatty(STDOUT_FILENO)) return 1;
  TermGuard guard;
  Tui tui;
  tui.state = state;
  tui.stats_cb = stats_cb;
  if (refresh_ms <= 0) refresh_ms = 100;

  while (true) {
    winsize ws{};
    ioctl(STDOUT_FILENO, TIOCGWINSZ, &ws);
    int rows = ws.ws_row > 0 ? ws.ws_row : 24;
    int cols = ws.ws_col > 0 ? ws.ws_col : 80;
    tui.render(rows, cols);
    if (tui.quit_requested) return 0;
    (void)!write(STDOUT_FILENO, tui.frame.data(), tui.frame.size());

    fd_set rfds;
    FD_ZERO(&rfds);
    FD_SET(STDIN_FILENO, &rfds);
    timeval tv{refresh_ms / 1000, (refresh_ms % 1000) * 1000};
    int r = select(STDIN_FILENO + 1, &rfds, nullptr, nullptr, &tv);
    if (r > 0) {
      char c = 0;
      if (read(STDIN_FILENO, &c, 1) == 1) {
        if (c == 'q' || c == '\x1b') {
          // Check for a bare Esc (not an escape sequence).
          if (c == '\x1b') {
            char seq[2];
            timeval zero{0, 0};
            fd_set f2;
            FD_ZERO(&f2);
            FD_SET(STDIN_FILENO, &f2);
            if (select(STDIN_FILENO + 1, &f2, nullptr, nullptr, &zero) > 0) {
              (void)!read(STDIN_FILENO, seq, 2);  // swallow arrow keys etc.
              continue;
            }
          }
          return 0;  // quit => caller stops the whole app (main.rs:174-177)
        }
        // Need fresh data for the action context.
        auto snap = tui.snapshot();
        auto users = tui.user_rows(snap);
        std::vector<std::string> blocked;
        if (snap->get("blocked_users"))
          for (auto &b : snap->get("blocked_users")->arr)
            blocked.push_back(b->as_str());
        if (snap->get("blocked_ips"))
          for (auto &b : snap->get("blocked_ips")->arr)
            blocked.push_back(b->as_str());
        std::string vip = snap->get("vip") && !snap->get("vip")->is_null()
                              ? snap->get("vip")->as_str() : "";
        std::string boost = snap->get("boost") && !snap->get("boost")->is_null()
                                ? snap->get("boost")->as_str() : "";
        tui.act_on_key(c, users, blocked, vip, boost);
      }
    }
  }
}
