"""API conformance: the 21-route surface, both wire formats, streaming,
blocking, user identity — driven against the FakeEngine (deterministic
tokens), mirroring how the reference is black-box tested against live
Ollama backends (test_dispatcher.sh)."""

import asyncio
import json
import tempfile

from aiohttp.test_utils import TestClient, TestServer

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.server.app import Server


def api_test(fn):
    """Run an async test against a fresh FakeEngine-backed server (no
    async pytest plugin in the image, so each test owns its event loop)."""

    # NOT functools.wraps: it would expose fn's (client) signature and make
    # pytest hunt for a 'client' fixture.
    def wrapper():
        async def main():
            with tempfile.TemporaryDirectory() as tmp:
                eng = FakeEngine(
                    EngineConfig(model="test-tiny", max_slots=8),
                    models={"test-tiny": None, "test-tiny-embed": None},
                    blocklist_path=f"{tmp}/blocked_items.json",
                )
                eng.start()
                server = Server(eng, timeout_s=30)
                cl = TestClient(TestServer(server.build_app()))
                cl.engine = eng  # handle for tests that poke the admin surface
                await cl.start_server()
                try:
                    await fn(cl)
                finally:
                    await cl.close()
                    eng.stop()

        asyncio.run(main())

    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


@api_test
async def test_health(client):
    r = await client.get("/health")
    assert r.status == 200
    body = await r.json()
    assert body["status"] == "ok"
    assert body["alerts"] == []


@api_test
async def test_root_liveness(client):
    r = await client.get("/")
    assert r.status == 200
    assert "running" in await r.text()


@api_test
async def test_generate_non_streaming(client):
    r = await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": "hi", "stream": False,
        "options": {"num_predict": 4},
    })
    assert r.status == 200
    body = await r.json()
    assert body["model"] == "test-tiny"
    assert body["done"] is True
    assert body["response"] == "word0 word1 word2 word3 "
    assert body["eval_count"] == 4
    assert body["prompt_eval_count"] > 0
    assert "total_duration" in body


@api_test
async def test_generate_streaming_ndjson(client):
    r = await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": "hi",
        "options": {"num_predict": 3},
    })
    assert r.status == 200
    assert r.content_type == "application/x-ndjson"
    lines = [json.loads(l) for l in (await r.text()).strip().split("\n")]
    assert [c.get("response") for c in lines[:-1]] == ["word0 ", "word1 ", "word2 "]
    assert all(c["done"] is False for c in lines[:-1])
    final = lines[-1]
    assert final["done"] is True and final["done_reason"] in ("stop", "length")
    assert final["eval_count"] == 3


@api_test
async def test_chat_streaming(client):
    r = await client.post("/api/chat", json={
        "model": "test-tiny",
        "messages": [{"role": "user", "content": "hello"}],
        "options": {"num_predict": 2},
    })
    lines = [json.loads(l) for l in (await r.text()).strip().split("\n")]
    assert lines[0]["message"]["role"] == "assistant"
    assert lines[0]["message"]["content"] == "word0 "
    assert lines[-1]["done"] is True


@api_test
async def test_chat_non_streaming(client):
    r = await client.post("/api/chat", json={
        "model": "test-tiny", "stream": False,
        "messages": [{"role": "user", "content": "hello"}],
        "options": {"num_predict": 2},
    })
    body = await r.json()
    assert body["message"]["content"] == "word0 word1 "


@api_test
async def test_openai_chat_non_streaming(client):
    r = await client.post("/v1/chat/completions", json={
        "model": "test-tiny", "max_tokens": 3,
        "messages": [{"role": "user", "content": "hello"}],
    })
    assert r.status == 200
    body = await r.json()
    assert body["object"] == "chat.completion"
    assert body["choices"][0]["message"]["content"] == "word0 word1 word2 "
    assert body["choices"][0]["finish_reason"] in ("stop", "length")
    assert body["usage"]["completion_tokens"] == 3


@api_test
async def test_openai_chat_streaming_sse(client):
    r = await client.post("/v1/chat/completions", json={
        "model": "test-tiny", "max_tokens": 2, "stream": True,
        "messages": [{"role": "user", "content": "hello"}],
    })
    assert r.content_type == "text/event-stream"
    text = await r.text()
    events = [l[6:] for l in text.split("\n") if l.startswith("data: ")]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert chunks[0]["object"] == "chat.completion.chunk"
    assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
    joined = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
    assert joined == "word0 word1 "
    assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")


@api_test
async def test_openai_completions(client):
    r = await client.post("/v1/completions", json={
        "model": "test-tiny", "prompt": "once", "max_tokens": 2,
    })
    body = await r.json()
    assert body["object"] == "text_completion"
    assert body["choices"][0]["text"] == "word0 word1 "


@api_test
async def test_embeddings_all_shapes(client):
    r = await client.post("/api/embed", json={
        "model": "test-tiny-embed", "input": ["a", "b"],
    })
    body = await r.json()
    assert len(body["embeddings"]) == 2

    r = await client.post("/api/embeddings", json={
        "model": "test-tiny-embed", "prompt": "a",
    })
    body = await r.json()
    assert isinstance(body["embedding"], list) and body["embedding"]

    r = await client.post("/v1/embeddings", json={
        "model": "test-tiny-embed", "input": "a",
    })
    body = await r.json()
    assert body["object"] == "list"
    assert body["data"][0]["object"] == "embedding"


@api_test
async def test_images_ignored_is_loud(client):
    """Image payloads get an explicit `warnings` field — never a silently
    text-only answer (VERDICT r3 missing #4; the reference forwards
    images to vision backends, test_dispatcher.sh:81-104)."""
    png = "aGVsbG8="  # content is irrelevant; presence is the contract
    r = await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": "what is this?", "stream": False,
        "images": [png]})
    body = await r.json()
    assert "images ignored" in body["warnings"][0]

    r = await client.post("/api/chat", json={
        "model": "test-tiny", "stream": True,
        "messages": [{"role": "user", "content": "hi", "images": [png]}]})
    lines = [json.loads(l) for l in (await r.text()).splitlines()]
    assert any("images ignored" in w
               for l in lines for w in l.get("warnings", []))

    r = await client.post("/v1/chat/completions", json={
        "model": "test-tiny",
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "hi"},
            {"type": "image_url", "image_url": {"url": "data:x"}}]}]})
    body = await r.json()
    assert "images ignored" in body["warnings"][0]

    # No images => no warnings field at all.
    r = await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": "hi", "stream": False})
    assert "warnings" not in (await r.json())


@api_test
async def test_embed_on_generative_model_serves(client):
    """Embedding routes against a GENERATIVE model serve (mean-pooled
    causal embeddings, like the reference's Ollama backends on llama
    models); unknown models still 400."""
    for route, body, key in (
        ("/api/embed", {"model": "test-tiny", "input": "a"}, "embeddings"),
        ("/api/embeddings", {"model": "test-tiny", "prompt": "a"}, "embedding"),
        ("/v1/embeddings", {"model": "test-tiny", "input": "a"}, "data"),
    ):
        r = await client.post(route, json=body)
        assert r.status == 200, f"{route}: {r.status}"
        assert key in (await r.json())
    r = await client.post("/api/embed", json={"model": "nope", "input": "a"})
    assert r.status == 404


@api_test
async def test_embed_token_counts(client):
    """prompt_eval_count / usage count TOKENS, not characters (ADVICE r1:
    '☃' is one char but several byte-tokens)."""
    r = await client.post("/api/embed", json={
        "model": "test-tiny-embed", "input": ["☃☃"],
    })
    body = await r.json()
    # byte tokenizer: bos + 6 utf-8 bytes = 7 tokens (chars would say 2)
    assert body["prompt_eval_count"] == 7

    r = await client.post("/v1/embeddings", json={
        "model": "test-tiny-embed", "input": "☃☃",
    })
    usage = (await r.json())["usage"]
    assert usage["prompt_tokens"] == 7 and usage["total_tokens"] == 7


@api_test
async def test_tags_ps_show_version(client):
    r = await client.get("/api/tags")
    tags = await r.json()
    names = [m["name"] for m in tags["models"]]
    assert "test-tiny" in names and "test-tiny-embed" in names

    r = await client.get("/api/ps")
    ps = await r.json()
    assert any(m["name"] == "test-tiny" for m in ps["models"])
    assert all("size_vram" in m for m in ps["models"])

    r = await client.post("/api/show", json={"model": "test-tiny"})
    show = await r.json()
    assert show["details"]["family"] in ("llama", "qwen2", "bert")
    assert show["model_info"]["general.architecture"] in ("llama", "qwen2")

    r = await client.get("/api/version")
    assert "version" in await r.json()


@api_test
async def test_openai_models(client):
    r = await client.get("/v1/models")
    body = await r.json()
    ids = [m["id"] for m in body["data"]]
    assert "test-tiny" in ids
    r = await client.get("/v1/models/test-tiny")
    assert (await r.json())["id"] == "test-tiny"
    r = await client.get("/v1/models/nope")
    assert r.status == 404


@api_test
async def test_pull_and_delete_lifecycle(client):
    # Pull a new architecture into HBM.
    r = await client.post("/api/pull", json={"model": "test-tiny-qwen", "stream": False})
    assert r.status == 200
    r = await client.get("/api/ps")
    assert any(m["name"] == "test-tiny-qwen" for m in (await r.json())["models"])
    # Evict it.
    r = await client.post("/api/delete", json={"model": "test-tiny-qwen"})
    assert r.status == 200
    r = await client.get("/api/ps")
    assert not any(m["name"] == "test-tiny-qwen" for m in (await r.json())["models"])


@api_test
async def test_pull_streaming_progress(client):
    r = await client.post("/api/pull", json={"model": "test-tiny-qwen"})
    lines = [json.loads(l) for l in (await r.text()).strip().split("\n")]
    assert lines[0]["status"] == "pulling manifest"
    assert lines[-1]["status"] == "success"
    await client.post("/api/delete", json={"model": "test-tiny-qwen"})


@api_test
async def test_copy_alias(client):
    r = await client.post("/api/copy", json={
        "source": "test-tiny", "destination": "my-alias",
    })
    assert r.status == 200
    r = await client.get("/api/tags")
    assert any(m["name"] == "my-alias" for m in (await r.json())["models"])


@api_test
async def test_unsupported_routes_are_honest(client):
    assert (await client.post("/api/create", json={})).status == 501
    assert (await client.post("/api/push", json={})).status == 501
    assert (await client.post("/api/blobs/sha256:abc", json={})).status == 501


@api_test
async def test_unknown_model_404(client):
    r = await client.post("/api/generate", json={
        "model": "definitely-not-a-model", "prompt": "x", "stream": False,
    })
    assert r.status == 404
    assert "not found" in (await r.json())["error"]


@api_test
async def test_missing_model_field_400(client):
    r = await client.post("/api/generate", json={"prompt": "x"})
    assert r.status == 400


@api_test
async def test_invalid_json_400(client):
    r = await client.post("/api/generate", data=b"{not json")
    assert r.status == 400


@api_test
async def test_block_user_403(client):
    """Blocked user => 403 at ingress (dispatcher.rs:602-610), and the
    blocklist round-trips through /metrics; unblock restores service."""
    core = client.engine.core
    core.block_user("mallory")
    r = await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": "x", "stream": False,
    }, headers={"X-User-ID": "mallory"})
    assert r.status == 403
    assert "blocked" in (await r.json())["error"]
    # Even non-generation routes refuse blocked users.
    r = await client.get("/api/tags", headers={"X-User-ID": "mallory"})
    assert r.status == 403
    core.unblock_user("mallory")
    r = await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": "x", "stream": False,
        "options": {"num_predict": 1},
    }, headers={"X-User-ID": "mallory"})
    assert r.status == 200


@api_test
async def test_user_id_header_default_anonymous(client):
    await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": "x", "stream": False,
        "options": {"num_predict": 1},
    })
    r = await client.get("/metrics.json")
    stats = await r.json()
    assert "anonymous" in stats["queue"]["users"]


@api_test
async def test_user_id_header_tracked(client):
    await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": "x", "stream": False,
        "options": {"num_predict": 1},
    }, headers={"X-User-ID": "alice"})
    r = await client.get("/metrics.json")
    stats = await r.json()
    assert stats["queue"]["users"]["alice"]["processed"] == 1


@api_test
async def test_blocked_user_403_on_all_proxied_routes(client):
    """The reference routes '/', /api/version etc. through the blocked
    check (every proxy_handler route 403s); only /health is exempt."""
    client.engine.core.block_user("banned")
    hdr = {"X-User-ID": "banned"}
    for path in ("/", "/api/version", "/api/tags", "/v1/models", "/metrics",
                 "/metrics.json", "/debug/trace"):
        r = await client.get(path, headers=hdr)
        assert r.status == 403, path
    r = await client.get("/health", headers=hdr)
    assert r.status == 200  # liveness stays open, like the reference


@api_test
async def test_debug_profile_validation(client):
    r = await client.post("/debug/profile", json={"seconds": "abc"})
    assert r.status == 400
