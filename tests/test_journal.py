"""Engine flight recorder: scheduler decision journal, explainability,
deterministic replay, and invariant checking.

Load-bearing guarantees pinned here:
  - the journal ring stays O(capacity) no matter how many records land;
  - the event schema is loud: unknown kinds / missing-or-unknown fields
    raise at the instrumentation site, never at incident-review time;
  - a seeded chaos run recorded via the harness replays with an
    IDENTICAL decision sequence, and a tampered recording is detected;
  - the invariant checker catches each violation class (pages conserved,
    slot double-assignment, VIP victim, under-bound shed, starvation)
    and stays CLEAN over randomized overload traffic on a real
    ModelRuntime with injected allocation pressure;
  - /debug/journal filter semantics, the per-request journal slice in
    /debug/requests/{id}, and the bundle's journal section;
  - --journal-file spill rotates at the size bound, keeping N files;
  - engine.retry_after_s is clamped on cold start (no completions yet).
"""

import asyncio
import glob
import itertools
import random
import tempfile

import jax.numpy as jnp
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.core import MQCore
from ollamamq_tpu.engine.engine import ModelRuntime
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.request import FinishReason, Request
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry.journal import (DECISION_KINDS, EVENTS,
                                            Journal, JournalError,
                                            batch_stats, check_invariants,
                                            decision_signature, explain,
                                            fair_share_audit, load_jsonl)
from ollamamq_tpu.tools.journal import record_chaos, replay_journal

_IDS = itertools.count(1)


# ------------------------------------------------------------------ schema
def test_ring_stays_bounded():
    j = Journal(capacity=64)
    for i in range(1000):
        j.record("admit", req_id=i, user="u", queued=i)
    snap = j.snapshot()
    assert snap["size"] == 64
    assert snap["seq"] == 1000
    assert snap["evicted"] == 936
    assert len(j.tail(None)) == 64
    # Newest-last, oldest evicted.
    assert j.tail(None)[-1]["req_id"] == 999
    assert j.tail(None)[0]["req_id"] == 936


def test_schema_validation_is_loud():
    j = Journal(capacity=8)
    with pytest.raises(JournalError):
        j.record("warp_speed", req_id=1)
    with pytest.raises(JournalError):
        j.record("shed", user="u")  # missing required 'reason'
    with pytest.raises(JournalError):
        j.record("admit", queued=1, bogus_field=2)  # unknown field
    # Every vocabulary kind has a field spec and a working explanation.
    assert j.seq == 0  # rejected records never land


_MINIMAL = {
    "enqueue": dict(n_prompt=4, queued=1),
    "admit": dict(queued=0),
    "sched": dict(policy="srpt", point="admit", candidates=3, score=5.25,
                  predicted=6),
    "place": dict(runtime="m"),
    "shed": dict(reason="queue_full", queued=9, limit=8, retry_after_s=2.0),
    "batch": dict(slots=[0, 1], bucket=32, batch_size=4, tokens=40,
                  occupancy=0.5),
    "chunk": dict(slot=0, pos=64, tokens=32),
    "install": dict(slot=1, n_prompt=7),
    "speculate": dict(slot=1, k=4, source="ngram"),
    "spec_verify": dict(slot=1, proposed=4, accepted=2, rolled_back=2),
    "spec_rollback": dict(slot=1, kv_before=20, kv_after=18, freed=1,
                          free=11, used=19, cached=1, pool=31),
    "preempt": dict(slot=2, why="kv_pressure", n=1, free_pages=0,
                    victim_served=9, vip="alice"),
    "kv_stall": dict(slot=0, free_pages=0),
    "requeue": dict(why="preempt"),
    "retry": dict(n=1, error="boom"),
    "poison": dict(retries=1),
    "deadline_drop": dict(slack_ms=12.5),
    "finish": dict(reason="stop", slot=0, tokens=8),
    "page_alloc": dict(n=2, free=10, used=20, cached=1, pool=31),
    "page_free": dict(n=2, free=12, used=18, cached=1, pool=31),
    "page_evict": dict(n=1, free=13, used=18, cached=0, pool=31),
    "broadcast": dict(op="decode", wire_seq=5),
    "rebuild": dict(),
    "replica_eject": dict(replica="r1", why="stale_heartbeat", victims=2,
                          heartbeat_age_s=4.0, backoff_s=0.5),
    "replica_failover": dict(replica="r1", to_replica="r0",
                             replayed_tokens=3),
    "replica_drain": dict(replica="r0", inflight=2, timeout_s=30.0),
    "replica_join": dict(replica="r1", why="heal"),
    "tier_place": dict(tier="interactive", cls="vip", replica="r0",
                       overflow=None),
    "tier_overflow": dict(from_tier="interactive", to_tier="bulk",
                          why="burn", burn=14.4, queued=3, replica="r1"),
    "tier_regroup": dict(replica="r1", phase="done", from_tier="bulk",
                         to_tier="interactive", why="mix_shift", mix=0.8,
                         tp_from=1, tp_to=4),
    "migrate_export": dict(replica="r1", tokens=5, kv_len=21, pages=3,
                           bytes=4096),
    "migrate_import": dict(replica="r1", to_replica="r0", tokens=5,
                           pages=3, bytes=4096),
    "migrate_abort": dict(replica="r1", to_replica="r0",
                          why="transfer_failed"),
    "scale_up": dict(replica="a0", phase="done", tier="bulk", why="wake",
                     burn=0.0, queued=3, fleet=2, spawn_ms=412.0),
    "scale_down": dict(replica="r1", phase="start", tier="bulk",
                       why="idle", burn=0.0, queued=0, fleet=2,
                       inflight=1),
    "preempt_notice": dict(replica="r1", tier="bulk", notice_s=30.0,
                           why="fault_plan", inflight=1),
    "wal_admit": dict(fsync_ms=1.25, n_prompt=16),
    "recover_replay": dict(tokens=5, outcome="replayed", n_prompt=16,
                           wal_rid=3),
    "standby_sync": dict(seq=42, lag=0, records=14, epoch=2,
                         why="snapshot"),
    "router_takeover": dict(phase="done", why="primary_dead", epoch=3,
                            from_epoch=2, streams=2, migrated=0,
                            replayed=2, takeover_ms=812.5, lag=0),
    "epoch_fence": dict(epoch=3, stale_epoch=2, path="placement",
                        caller="router"),
    "compile": dict(site="ragged", key="('ragged', 256, 0)",
                    wall_ms=812.5, cache_size=3),
}


def test_every_kind_records_and_explains():
    assert set(_MINIMAL) == set(EVENTS)
    j = Journal(capacity=64)
    for kind, fields in _MINIMAL.items():
        rec = j.record(kind, req_id=3, user="bob", model="m", **fields)
        text = explain(rec)
        assert isinstance(text, str) and text
    assert j.seq == len(EVENTS)
    # The TUI line tracks the newest DECISION kind (the epoch fence is
    # the last one in the vocabulary walk above); page/broadcast/
    # rebuild bookkeeping must not displace it.
    assert "stale-epoch router call fenced" in j.last_summary()
    j.record("page_alloc", model="m", n=1, free=9, used=21, cached=1,
             pool=31)
    assert "stale-epoch router call fenced" in j.last_summary()


def test_tail_filters():
    j = Journal(capacity=128)
    for i in range(10):
        j.record("admit", req_id=i, user=f"u{i % 2}", queued=i)
    j.record("shed", user="u0", reason="queue_full", queued=9, limit=9)
    assert len(j.tail(n=3)) == 3
    assert all(r["user"] == "u1" for r in j.tail(None, user="u1"))
    assert len(j.tail(None, user="u1")) == 5
    assert [r["kind"] for r in j.tail(None, kind="shed")] == ["shed"]
    assert len(j.tail(None, req_id=7)) == 1


# ------------------------------------------------------------ file spill
def test_journal_file_rotation(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(capacity=32, path=path, rotate_bytes=4000, keep=2)
    for i in range(400):
        j.record("admit", req_id=i, user="u", queued=i)
    j.close()
    files = sorted(glob.glob(path + "*"))
    # Current file + at most `keep` rotated generations, each bounded.
    assert path in files
    assert len(files) <= 3
    assert any(f.endswith(".1") for f in files)
    import os

    for f in files:
        assert os.path.getsize(f) < 4000 + 500  # one record of slack
    # Every surviving file parses; the header meta line is skipped.
    meta, records = load_jsonl(path)
    assert records and all(r["kind"] == "admit" for r in records)
    # Rotated files carry a fresh meta header too.
    meta1, recs1 = load_jsonl(files[-1] if files[-1] != path else files[0])
    assert recs1


# ---------------------------------------------------- record/replay loop
def test_chaos_record_replays_deterministically(tmp_path):
    path = str(tmp_path / "chaos.jsonl")
    journal = record_chaos(path, seed=7, requests=32)
    kinds = {r["kind"] for r in journal.tail(None)}
    # The run must actually exercise degradation: sheds (bounded queue),
    # retries + poisons (injected step faults), and normal service.
    assert {"enqueue", "admit", "place", "install", "finish",
            "shed", "retry", "poison"} <= kinds
    # Every shed decision carries the inputs that justify it.
    for r in journal.tail(None, kind="shed"):
        assert r["queued"] >= r["limit"]
        assert "retry_after_s" in r
    # The recorded artifact is invariant-clean...
    assert check_invariants(journal.tail(None)) == []
    # ...and replays with an IDENTICAL decision sequence.
    ok, rec_sig, rep_sig, div = replay_journal(path)
    assert ok, f"diverged at {div}: {rec_sig[div:div+2]} vs {rep_sig[div:div+2]}"
    assert len(rec_sig) > 50


def test_replay_detects_tampered_recording(tmp_path):
    path = str(tmp_path / "chaos.jsonl")
    record_chaos(path, seed=3, requests=24)
    lines = open(path, encoding="utf-8").read().splitlines()
    # Flip one decision: the first finish becomes a different reason.
    import json as _json

    for i, line in enumerate(lines):
        obj = _json.loads(line)
        if obj.get("kind") == "finish":
            obj["reason"] = "cancelled" if obj["reason"] != "cancelled" \
                else "length"
            lines[i] = _json.dumps(obj)
            break
    open(path, "w", encoding="utf-8").write("\n".join(lines) + "\n")
    ok, _rec, _rep, div = replay_journal(path)
    assert not ok and div is not None


# ------------------------------------------------------------ invariants
def test_invariant_checker_catches_each_class():
    # 1. pages not conserved.
    bad = check_invariants([
        {"seq": 0, "kind": "page_alloc", "n": 2, "free": 5, "used": 5,
         "cached": 0, "pool": 31}])
    assert len(bad) == 1 and "not conserved" in bad[0]
    # 2. slot double-assignment.
    bad = check_invariants([
        {"seq": 0, "kind": "install", "model": "m", "slot": 1, "req_id": 1},
        {"seq": 1, "kind": "install", "model": "m", "slot": 1, "req_id": 2}])
    assert len(bad) == 1 and "double-assignment" in bad[0]
    # ...but a finish (or preempt) in between releases the slot.
    assert check_invariants([
        {"seq": 0, "kind": "install", "model": "m", "slot": 1, "req_id": 1},
        {"seq": 1, "kind": "finish", "model": "m", "slot": 1, "req_id": 1,
         "reason": "stop"},
        {"seq": 2, "kind": "install", "model": "m", "slot": 1,
         "req_id": 2}]) == []
    # 3. the VIP must never be the victim.
    bad = check_invariants([
        {"seq": 0, "kind": "preempt", "req_id": 4, "user": "alice",
         "slot": 0, "why": "kv_pressure", "vip": "alice"}])
    assert len(bad) == 1 and "VIP" in bad[0]
    assert check_invariants([
        {"seq": 0, "kind": "preempt", "req_id": 4, "user": "bob",
         "slot": 0, "why": "kv_pressure", "vip": "alice"}]) == []
    # 4. shed only when bounds exceeded.
    bad = check_invariants([
        {"seq": 0, "kind": "shed", "user": "u", "reason": "queue_full",
         "queued": 3, "limit": 8}])
    assert len(bad) == 1 and "below bound" in bad[0]
    # 5. starvation: admitted, then >= N batches with no progress.
    recs = [{"seq": 0, "kind": "admit", "req_id": 9, "queued": 1}]
    recs += [{"seq": 1 + i, "kind": "batch", "slots": [0], "bucket": 32,
              "batch_size": 1, "tokens": 8, "occupancy": 0.5}
             for i in range(60)]
    bad = check_invariants(recs)
    assert len(bad) == 1 and "starved" in bad[0]
    # Progress (install) clears it.
    recs.insert(30, {"seq": 99, "kind": "install", "req_id": 9, "slot": 0})
    assert check_invariants(recs) == []


PS = 8


def _overload_rt(**kw) -> ModelRuntime:
    defaults = dict(model="test-tiny", max_slots=3, num_pages=24,
                    page_size=PS, max_pages_per_seq=8,
                    prefill_buckets=(16, 32), max_new_tokens=8,
                    decode_steps_per_iter=2, preempt=True)
    defaults.update(kw)
    rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"],
                      EngineConfig(**defaults), dtype=jnp.float32)
    rt.tokenizer.eos_id = -1  # deterministic full-length streams
    return rt


@pytest.mark.parametrize("seed", [0, 1])
def test_invariant_fuzz_randomized_overload(seed):
    """Randomized overload traffic on a REAL runtime — arrival storms
    over an undersized page pool with injected allocation pressure, so
    preemptions, kv_stalls, page evictions, and stall-breaks all fire —
    and the journal must come out invariant-clean."""
    from ollamamq_tpu.engine.engine import drop_expired
    from ollamamq_tpu.testing.faults import FaultPlan

    rng = random.Random(seed)
    rt = _overload_rt()
    rt.fault_plan = FaultPlan([
        {"site": "extend", "kind": "alloc_fail", "every": 4},
    ], seed=seed)
    journal = Journal(capacity=8192)
    rt.journal = journal
    core = MQCore(None)

    def requeue(req):
        if req.expired():
            drop_expired(req, core, rt.name, journal=journal)
            return False
        rt.pending_prefill.appendleft(req)
        return True

    rt.on_preempt = requeue
    issued = 0
    reqs = []
    guard = 0
    while True:
        while issued < 14 and len(rt.pending_prefill) < 6 \
                and rng.random() < 0.7:
            n = rng.randrange(4, 40)
            req = Request(next(_IDS), f"u{issued % 4}", rt.name,
                          [rng.randrange(3, 400) for _ in range(n)],
                          SamplingParams(max_tokens=rng.randrange(2, 10)))
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            reqs.append(req)
            rt.pending_prefill.append(req)
            issued += 1
        rt.step_prefill(core)
        rt.step_chunk(core)
        if any(r is not None for r in rt.slot_req):
            rt.step_decode(core, k_steps=2)
        if issued >= 14 and all(r.stats.finished_at for r in reqs):
            break
        guard += 1
        assert guard < 20000, "overload fuzz wedged"
    recs = journal.tail(None)
    assert {"batch", "install", "finish", "page_alloc",
            "page_free"} <= {r["kind"] for r in recs}
    assert check_invariants(recs) == []
    # Batch stats are well-formed: padding waste is a real fraction.
    bs = batch_stats(recs)
    assert bs["batches"] > 0
    assert 0.0 <= bs["padding_waste"] < 1.0
    assert bs["real_tokens"] <= bs["padded_tokens"]


# ---------------------------------------------------------- HTTP surface
def _api(fn):
    def wrapper():
        from aiohttp.test_utils import TestClient, TestServer

        from ollamamq_tpu.server.app import Server

        async def main():
            with tempfile.TemporaryDirectory() as tmp:
                eng = FakeEngine(
                    EngineConfig(model="test-tiny", max_slots=8),
                    models={"test-tiny": None},
                    blocklist_path=f"{tmp}/blocked.json")
                eng.start()
                server = Server(eng, timeout_s=30)
                cl = TestClient(TestServer(server.build_app()))
                cl.engine = eng
                await cl.start_server()
                try:
                    await fn(cl)
                finally:
                    await cl.close()
                    eng.stop()

        asyncio.run(main())

    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


async def _gen(client, user="alice", prompt="hi"):
    r = await client.post("/api/generate", json={
        "model": "test-tiny", "prompt": prompt, "stream": False},
        headers={"X-User-ID": user})
    assert r.status == 200
    return r


@_api
async def test_debug_journal_filters(client):
    await _gen(client, user="alice")
    await _gen(client, user="bob")
    r = await client.get("/debug/journal")
    assert r.status == 200
    body = await r.json()
    assert body["capacity"] == 2048
    assert body["size"] == len(body["events"]) or body["size"] > 200
    kinds = {e["kind"] for e in body["events"]}
    assert {"enqueue", "admit", "place", "install", "finish"} <= kinds
    # kind filter.
    r = await client.get("/debug/journal?kind=enqueue")
    evs = (await r.json())["events"]
    assert evs and all(e["kind"] == "enqueue" for e in evs)
    # user filter.
    r = await client.get("/debug/journal?user=bob")
    evs = (await r.json())["events"]
    assert evs and all(e["user"] == "bob" for e in evs)
    # req_id filter follows one request through its lifecycle.
    rid = evs[0]["req_id"]
    r = await client.get(f"/debug/journal?req_id={rid}")
    evs = (await r.json())["events"]
    assert {"enqueue", "admit", "place"} <= {e["kind"] for e in evs}
    assert all(e["req_id"] == rid for e in evs)
    # n bounds the tail.
    r = await client.get("/debug/journal?n=2")
    assert len((await r.json())["events"]) == 2
    # Unknown kind is a client error naming the vocabulary, not [].
    r = await client.get("/debug/journal?kind=warp")
    assert r.status == 400
    assert "vocabulary" in (await r.json())["error"]
    # Junk n / req_id are client errors too.
    assert (await client.get("/debug/journal?n=x")).status == 400
    assert (await client.get("/debug/journal?req_id=x")).status == 400


@_api
async def test_request_timeline_includes_journal_slice(client):
    await _gen(client)
    r = await client.get("/debug/journal?kind=finish")
    rid = (await r.json())["events"][-1]["req_id"]
    r = await client.get(f"/debug/requests/{rid}")
    assert r.status == 200
    body = await r.json()
    assert "journal" in body
    assert all(e["req_id"] == rid for e in body["journal"])
    assert {"enqueue", "admit", "place", "install", "finish"} <= {
        e["kind"] for e in body["journal"]}


@_api
async def test_bundle_has_journal_section(client):
    await _gen(client)
    r = await client.get("/debug/bundle")
    assert r.status == 200
    body = await r.json()
    assert "journal" in body
    assert body["journal"]["capacity"] == 2048
    assert body["journal"]["events"]


# ------------------------------------------------------------- satellites
def test_retry_after_cold_start_is_clamped():
    eng = FakeEngine(EngineConfig(model="test-tiny"), blocklist_path=None)
    # No completions observed: whatever the queue depth claims, the
    # estimate stays in a small fixed window instead of extrapolating.
    eng.core.total_queued = lambda: 500
    assert 2.0 <= eng.retry_after_s() <= 10.0
    eng.core.total_queued = lambda: 0
    assert 2.0 <= eng.retry_after_s() <= 10.0


def test_health_monitor_raises_invariant_alert():
    from ollamamq_tpu.engine.health import HealthMonitor
    from ollamamq_tpu.telemetry.slo import AlertManager

    class Eng:
        alerts = AlertManager()
        journal = Journal(capacity=32)

    eng = Eng()
    mon = HealthMonitor.__new__(HealthMonitor)
    mon.engine = eng
    mon._check_journal_invariants()
    assert not any(a.name == "journal_invariant"
                   for a in eng.alerts.active())
    # A pages-conservation bug lands in the journal -> alert fires.
    eng.journal.record("page_alloc", model="m", n=1, free=1, used=1,
                       cached=1, pool=99)
    mon._check_journal_invariants()
    firing = [a for a in eng.alerts.active()
              if a.name == "journal_invariant"]
    assert firing and "not conserved" in firing[0].message
    # Violation ages out of the ring -> resolves.
    eng.journal = Journal(capacity=32)
    mon.engine = eng
    mon._check_journal_invariants()
    assert not any(a.name == "journal_invariant"
                   for a in eng.alerts.active())


def test_fair_share_audit_and_signature_shapes():
    path_free = {"free": 1, "used": 1, "cached": 0, "pool": 2}
    j = Journal(capacity=64)
    j.record("enqueue", req_id=1, user="a", n_prompt=3, queued=1)
    j.record("shed", user="b", reason="queue_full", queued=9, limit=9)
    j.record("page_alloc", model="m", n=1, **path_free)
    audit = fair_share_audit(j.tail(None))
    assert audit["a"]["enqueued"] == 1
    assert audit["b"]["shed"] == 1
    sig = decision_signature(j.tail(None))
    # Page events are not part of the replay-decision stream.
    assert [s[0] for s in sig] == ["enqueue", "shed"]
    assert all(s[0] in DECISION_KINDS for s in sig)
