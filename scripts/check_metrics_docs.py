#!/usr/bin/env python3
"""Doc/telemetry consistency gate, two surfaces:

  1. metrics — every metric the registry exports must be documented in
     README.md's Observability table, and every documented ollamamq_*
     name must still exist in the registry (no ghost docs);
  2. phases — every latency-attribution phase the engine can emit
     (telemetry/attribution.py PHASES) must appear in the README phase
     table (between the `<!-- phases:begin -->` / `<!-- phases:end -->`
     markers), and the table must not document phases that no longer
     exist;
  3. shed reasons — the closed `ollamamq_shed_total{reason}` label
     vocabulary (telemetry/schema.py SHED_REASONS) must match the README
     shed-reason table (between the `<!-- shed-reasons:begin -->` /
     `<!-- shed-reasons:end -->` markers) exactly;
  4. journal events — the decision-journal event vocabulary
     (telemetry/journal.py EVENTS) must match the README "Flight
     recorder" table (between the `<!-- journal-events:begin -->` /
     `<!-- journal-events:end -->` markers) exactly: an event kind the
     engine can record but the table doesn't document is a drift
     failure, and so is a documented kind the journal no longer emits;
  5. router spans — the fleet router's closed trace-span vocabulary
     (telemetry/tracing.py ROUTER_EVENTS: the event names the router
     drops into request traces, stitched fleet-wide at
     GET /debug/trace/{rid}) must match the README router-span table
     (between the `<!-- router-spans:begin -->` /
     `<!-- router-spans:end -->` markers) exactly — same pattern as
     phases;
  6. stepprof phases — the step profiler's closed dispatch-phase
     vocabulary (telemetry/stepprof.py PHASES: the `phase` label values
     of `ollamamq_step_phase_ms`) must match the README "Engine
     performance plane" phase table (between the
     `<!-- stepprof-phases:begin -->` / `<!-- stepprof-phases:end -->`
     markers) exactly.

Imports ONLY ollamamq_tpu.telemetry.schema/.attribution/.journal/
.tracing — the declaration sites — so the check runs without jax, a
device, or an engine. Wired into tier-1 via tests/test_metrics_docs.py.

Usage: python scripts/check_metrics_docs.py [README.md]
Exit 0 = consistent; 1 = drift (names printed); 2 = usage error.
"""

from __future__ import annotations

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PHASES_BEGIN = "<!-- phases:begin -->"
PHASES_END = "<!-- phases:end -->"
SHED_BEGIN = "<!-- shed-reasons:begin -->"
SHED_END = "<!-- shed-reasons:end -->"
JOURNAL_BEGIN = "<!-- journal-events:begin -->"
JOURNAL_END = "<!-- journal-events:end -->"
ROUTER_SPANS_BEGIN = "<!-- router-spans:begin -->"
ROUTER_SPANS_END = "<!-- router-spans:end -->"
STEPPROF_BEGIN = "<!-- stepprof-phases:begin -->"
STEPPROF_END = "<!-- stepprof-phases:end -->"


def documented_metric_names(readme_text: str) -> set:
    """ollamamq_* names that appear in backticks anywhere in the README
    (the Observability table is the intended home; being generous about
    WHERE keeps the check about coverage, not markdown layout)."""
    return set(re.findall(r"`(ollamamq_[a-z0-9_]+)`", readme_text))


def registered_metric_names() -> set:
    sys.path.insert(0, _REPO)
    from ollamamq_tpu.telemetry import schema  # noqa: F401  (declares all)
    from ollamamq_tpu.telemetry.metrics import REGISTRY

    return set(REGISTRY.names())


def documented_phase_names(readme_text: str) -> set:
    """Backticked names inside the marked phase-table region. Markers
    (not layout) scope the search, so `queue`-the-word elsewhere in the
    README can't satisfy the check by accident."""
    start = readme_text.find(PHASES_BEGIN)
    end = readme_text.find(PHASES_END)
    if start == -1 or end == -1 or end < start:
        return set()
    return set(re.findall(r"`([a-z_]+)`", readme_text[start:end]))


def registered_phase_names() -> set:
    sys.path.insert(0, _REPO)
    from ollamamq_tpu.telemetry.attribution import PHASES

    return set(PHASES)


def documented_shed_reasons(readme_text: str) -> set:
    """Backticked names inside the marked shed-reason region."""
    start = readme_text.find(SHED_BEGIN)
    end = readme_text.find(SHED_END)
    if start == -1 or end == -1 or end < start:
        return set()
    return set(re.findall(r"`([a-z_]+)`", readme_text[start:end]))


def registered_shed_reasons() -> set:
    sys.path.insert(0, _REPO)
    from ollamamq_tpu.telemetry.schema import SHED_REASONS

    return set(SHED_REASONS)


def documented_journal_events(readme_text: str) -> set:
    """Backticked names inside the marked journal-event region."""
    start = readme_text.find(JOURNAL_BEGIN)
    end = readme_text.find(JOURNAL_END)
    if start == -1 or end == -1 or end < start:
        return set()
    return set(re.findall(r"`([a-z_]+)`", readme_text[start:end]))


def registered_journal_events() -> set:
    sys.path.insert(0, _REPO)
    from ollamamq_tpu.telemetry.journal import EVENTS

    return set(EVENTS)


def documented_router_spans(readme_text: str) -> set:
    """Backticked names inside the marked router-span region."""
    start = readme_text.find(ROUTER_SPANS_BEGIN)
    end = readme_text.find(ROUTER_SPANS_END)
    if start == -1 or end == -1 or end < start:
        return set()
    return set(re.findall(r"`([a-z_]+)`", readme_text[start:end]))


def registered_router_spans() -> set:
    sys.path.insert(0, _REPO)
    from ollamamq_tpu.telemetry.tracing import ROUTER_EVENTS

    return set(ROUTER_EVENTS)


def documented_stepprof_phases(readme_text: str) -> set:
    """Backticked names inside the marked stepprof-phase region."""
    start = readme_text.find(STEPPROF_BEGIN)
    end = readme_text.find(STEPPROF_END)
    if start == -1 or end == -1 or end < start:
        return set()
    return set(re.findall(r"`([a-z_]+)`", readme_text[start:end]))


def registered_stepprof_phases() -> set:
    sys.path.insert(0, _REPO)
    from ollamamq_tpu.telemetry.stepprof import PHASES

    return set(PHASES)


def _diff(readme: str, what: str, registered: set, documented: set,
          missing_msg: str, ghost_msg: str) -> int:
    rc = 0
    missing = sorted(registered - documented)
    ghosts = sorted(documented - registered)
    if missing:
        rc = 1
        print(f"{readme}: {len(missing)} {missing_msg}:", file=sys.stderr)
        for name in missing:
            print(f"  - {name}", file=sys.stderr)
    if ghosts:
        rc = 1
        print(f"{readme}: {len(ghosts)} {ghost_msg}:", file=sys.stderr)
        for name in ghosts:
            print(f"  - {name}", file=sys.stderr)
    return rc


def main(argv) -> int:
    readme = argv[1] if len(argv) > 1 else os.path.join(_REPO, "README.md")
    try:
        with open(readme, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        print(f"cannot read {readme}: {e}", file=sys.stderr)
        return 2
    rc = _diff(
        readme, "metrics", registered_metric_names(),
        documented_metric_names(text),
        "registered metric(s) missing from the README metric table",
        "documented metric(s) no longer registered")
    rc |= _diff(
        readme, "phases", registered_phase_names(),
        documented_phase_names(text),
        "attribution phase(s) missing from the README phase table "
        f"(between {PHASES_BEGIN} / {PHASES_END})",
        "documented phase(s) the attribution layer no longer emits")
    rc |= _diff(
        readme, "shed reasons", registered_shed_reasons(),
        documented_shed_reasons(text),
        "shed reason(s) missing from the README shed-reason table "
        f"(between {SHED_BEGIN} / {SHED_END})",
        "documented shed reason(s) the engine no longer emits")
    rc |= _diff(
        readme, "journal events", registered_journal_events(),
        documented_journal_events(text),
        "journal event kind(s) missing from the README flight-recorder "
        f"table (between {JOURNAL_BEGIN} / {JOURNAL_END})",
        "documented journal event kind(s) the engine no longer records")
    rc |= _diff(
        readme, "router spans", registered_router_spans(),
        documented_router_spans(text),
        "router trace-span name(s) missing from the README router-span "
        f"table (between {ROUTER_SPANS_BEGIN} / {ROUTER_SPANS_END})",
        "documented router span(s) the router no longer emits")
    rc |= _diff(
        readme, "stepprof phases", registered_stepprof_phases(),
        documented_stepprof_phases(text),
        "step-profiler phase(s) missing from the README engine-"
        f"performance-plane table (between {STEPPROF_BEGIN} / "
        f"{STEPPROF_END})",
        "documented stepprof phase(s) the step profiler no longer emits")
    if rc == 0:
        print(f"ok: {len(registered_metric_names())} metrics, "
              f"{len(registered_phase_names())} phases, "
              f"{len(registered_shed_reasons())} shed reasons, "
              f"{len(registered_journal_events())} journal events, "
              f"{len(registered_router_spans())} router spans, and "
              f"{len(registered_stepprof_phases())} stepprof phases, "
              "all documented")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv))
