#!/usr/bin/env python3
"""Bench regression sentinel: diff the BENCH_r*.json trajectory.

The driver wraps every official bench round as
``{"n": int, "cmd": str, "rc": int, "tail": str, "parsed": dict|null}``
where ``parsed`` is the last JSON line bench.py printed (the structured
result record — success OR the ``_emit_error`` failure line). This tool
classifies each round and diffs the *comparable* ones:

- ``init-failed``  — the round never got a working device (nonzero rc
  with no parsed record, or a parsed error record from the init phase,
  e.g. "wedged TPU tunnel"). These are environment casualties, NOT
  performance regressions, and are excluded from all comparisons.
- ``failed``       — bench ran but died past init (parsed error record
  with a non-init phase). Excluded from comparisons, reported loudly.
- ``ok``           — a real measurement (rc == 0, value > 0).

Between consecutive ``ok`` rounds it checks:

- headline ``decode_tok_per_s_per_chip`` drop >= --threshold-pct
- per-mode step p99 (from the ``step_profile`` summary block, when both
  rounds carry one) increase >= --threshold-pct

Exit codes: 0 = no regression (including "nothing comparable"),
2 = regression detected, 1 = usage/load error. Stdlib-only on purpose —
it must run in the bare driver container, before any jax import works.

Usage:
    python scripts/bench_compare.py                  # BENCH_r*.json in cwd
    python scripts/bench_compare.py A.json B.json    # explicit trajectory
    python scripts/bench_compare.py --threshold-pct 10
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_round(path: str) -> dict:
    """One driver wrapper -> {"path", "n", "rc", "parsed", ...}."""
    with open(path, "r", encoding="utf-8") as f:
        rec = json.load(f)
    if not isinstance(rec, dict):
        raise ValueError(f"{path}: not a JSON object")
    rec.setdefault("rc", 0)
    rec.setdefault("parsed", None)
    rec["path"] = path
    # Round ordering key: the driver's round number when present, else
    # the filename (BENCH_r03.json sorts correctly either way).
    rec.setdefault("n", os.path.basename(path))
    return rec


def classify(rec: dict) -> str:
    """'init-failed' | 'failed' | 'ok' for one round wrapper."""
    parsed = rec.get("parsed")
    rc = rec.get("rc", 0)
    if parsed is None:
        # Crashed before bench.py could even print its structured line
        # (round 1 in history: jax backend init raised). Only an error
        # if rc says so; an rc-0 round with no record is also unusable.
        return "init-failed" if rc != 0 else "failed"
    if not isinstance(parsed, dict):
        return "failed"
    if parsed.get("error"):
        phase = parsed.get("phase", "")
        if phase == "init":
            return "init-failed"
        # No phase tag + zero value + nonzero rc: bench never measured
        # anything — treat as an init-class casualty, not a regression.
        if not phase and rc != 0 and not parsed.get("value"):
            return "init-failed"
        return "failed"
    if rc != 0:
        return "failed"
    return "ok"


def _step_p99s(parsed: dict) -> dict:
    """{mode: step p99 ms} from a record's step_profile block, if any."""
    sp = parsed.get("step_profile")
    if not isinstance(sp, dict):
        return {}
    out = {}
    for mode, phases in (sp.get("modes") or {}).items():
        step = (phases or {}).get("step") or {}
        p99 = step.get("p99_ms")
        if isinstance(p99, (int, float)) and p99 > 0:
            out[mode] = float(p99)
    return out


def compare(prev: dict, cur: dict, threshold_pct: float) -> list:
    """Regressions going prev -> cur, as human-readable strings."""
    regs = []
    pv = float(prev["parsed"].get("value") or 0.0)
    cv = float(cur["parsed"].get("value") or 0.0)
    if pv > 0:
        drop_pct = (pv - cv) / pv * 100.0
        if drop_pct >= threshold_pct:
            regs.append(
                f"tok/s regression: {pv:.1f} -> {cv:.1f} "
                f"(-{drop_pct:.1f}% >= {threshold_pct:g}%)")
    prev_p99 = _step_p99s(prev["parsed"])
    cur_p99 = _step_p99s(cur["parsed"])
    for mode in sorted(set(prev_p99) & set(cur_p99)):
        a, b = prev_p99[mode], cur_p99[mode]
        rise_pct = (b - a) / a * 100.0
        if rise_pct >= threshold_pct:
            regs.append(
                f"step p99 regression [{mode}]: {a:.2f}ms -> {b:.2f}ms "
                f"(+{rise_pct:.1f}% >= {threshold_pct:g}%)")
    return regs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff BENCH_r*.json rounds; exit 2 on regression")
    ap.add_argument("files", nargs="*",
                    help="round files in order (default: BENCH_r*.json "
                         "in the current directory, sorted)")
    ap.add_argument("--threshold-pct", type=float, default=20.0,
                    help="regression threshold in percent (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable report to stdout")
    args = ap.parse_args(argv)

    files = args.files or sorted(glob.glob("BENCH_r*.json"))
    if not files:
        print("bench_compare: no BENCH_r*.json files found", file=sys.stderr)
        return 1
    try:
        rounds = [load_round(p) for p in files]
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 1
    rounds.sort(key=lambda r: (str(r["n"]).zfill(8)
                               if not isinstance(r["n"], int)
                               else f"{r['n']:08d}"))

    report = {"rounds": [], "regressions": [], "threshold_pct":
              args.threshold_pct}
    comparable = []
    for rec in rounds:
        status = classify(rec)
        row = {"n": rec["n"], "path": rec["path"], "status": status}
        if status == "ok":
            row["tok_per_s"] = rec["parsed"].get("value")
            comparable.append(rec)
        elif isinstance(rec.get("parsed"), dict):
            row["error"] = rec["parsed"].get("error")
        report["rounds"].append(row)

    for prev, cur in zip(comparable, comparable[1:]):
        for msg in compare(prev, cur, args.threshold_pct):
            report["regressions"].append(
                {"from": prev["n"], "to": cur["n"], "what": msg})

    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for row in report["rounds"]:
            extra = ""
            if row["status"] == "ok":
                extra = f"  {row['tok_per_s']} tok/s/chip"
            elif row.get("error"):
                extra = f"  ({row['error']})"
            print(f"round {row['n']}: {row['status']}{extra}")
        if len(comparable) < 2:
            print(f"bench_compare: {len(comparable)} comparable round(s) — "
                  f"nothing to diff")
        for reg in report["regressions"]:
            print(f"REGRESSION r{reg['from']} -> r{reg['to']}: "
                  f"{reg['what']}")
        if not report["regressions"] and len(comparable) >= 2:
            print(f"bench_compare: {len(comparable)} comparable rounds, "
                  f"no regression >= {args.threshold_pct:g}%")
    return 2 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
