"""Pallas TPU kernel: ragged paged decode attention.

The jnp reference path (ops/attention.py:paged_decode_attention) gathers a
padded [B, max_pages*page_size, Hk, hd] context per step — materializing
the whole window in HBM traffic even for short sequences. This kernel
instead walks each sequence's ACTUAL pages: per batch element, double-
buffered DMA streams K/V pages HBM→VMEM while the previous page's partial
attention accumulates with an online (flash-style) softmax, so HBM reads
scale with true context length (ragged), not the padded maximum.

Layout contract (matches engine/kv_cache.py):
    k_cache, v_cache: [S, Hk, hd] flat slot pool; a page is `page_size`
    contiguous slots starting at page_id * page_size.
    page_table: [B, max_pages] int32 (trash page 0 padding)
    seq_lens:   [B] int32 — context length INCLUDING the current token

Grid: one program per batch element; page_table/seq_lens ride scalar
prefetch so the DMA offsets are known before the body runs
(PrefetchScalarGridSpec pattern from the Pallas TPU guide).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, max_pages] SMEM
    seq_lens_ref,  # [B] SMEM
    # inputs
    q_ref,  # [1, H, hd] VMEM (this program's query)
    k_hbm,  # [S, Hk, hd] HBM
    v_hbm,  # [S, Hk, hd] HBM
    # output
    o_ref,  # [1, H, hd] VMEM
    # scratch
    k_buf,  # [2, page_size, Hk, hd] VMEM
    v_buf,  # [2, page_size, Hk, hd] VMEM
    acc,  # [H, hd] f32 VMEM
    m_i,  # [H, 1] f32 VMEM running max
    l_i,  # [H, 1] f32 VMEM running denom
    sems,  # [2, 2] DMA semaphores (buffer, k/v)
    *,
    page_size: int,
    max_pages: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
):
    b = pl.program_id(0)
    seq_len = seq_lens_ref[b]
    # Clamp to the table width: a seq_len beyond capacity must not index
    # page_table out of bounds (the jnp reference implicitly truncates the
    # context the same way).
    num_pages = jnp.minimum(pl.cdiv(seq_len, page_size), max_pages)

    def page_dma(slot, page_idx):
        page_id = page_table_ref[b, page_idx]
        start = page_id * page_size
        k_dma = pltpu.make_async_copy(
            k_hbm.at[pl.ds(start, page_size)], k_buf.at[slot], sems.at[slot, 0]
        )
        v_dma = pltpu.make_async_copy(
            v_hbm.at[pl.ds(start, page_size)], v_buf.at[slot], sems.at[slot, 1]
        )
        return k_dma, v_dma

    # Warm up: first page in flight.
    k0, v0 = page_dma(0, 0)
    k0.start()
    v0.start()

    acc[...] = jnp.zeros_like(acc)
    m_i[...] = jnp.full_like(m_i, NEG_INF)
    l_i[...] = jnp.zeros_like(l_i)

    q = q_ref[0].astype(jnp.float32)  # [H, hd]
    scale = 1.0 / (head_dim ** 0.5)
    group = num_heads // num_kv_heads

    def body(p, _):
        slot = p % 2
        nxt = (p + 1) % 2

        @pl.when(p + 1 < num_pages)
        def _():
            kn, vn = page_dma(nxt, p + 1)
            kn.start()
            vn.start()

        kp, vp = page_dma(slot, p)
        kp.wait()
        vp.wait()

        k = k_buf[slot].astype(jnp.float32)  # [ps, Hk, hd]
        v = v_buf[slot].astype(jnp.float32)
        # GQA: broadcast kv heads over query-head groups.
        # scores[h, t] = q[h] . k[t, h // group]
        qr = q.reshape(num_kv_heads, group, head_dim)
        s = jax.lax.dot_general(
            qr, k,
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )  # [Hk, group, ps]
        s = s.reshape(num_heads, page_size) * scale

        # Mask positions beyond the sequence (final partial page).
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (num_heads, page_size), 1
        )
        s = jnp.where(pos < seq_len, s, NEG_INF)

        # Online softmax update.
        m_prev = m_i[...]  # [H, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p_ij = jnp.exp(s - m_new)  # [H, ps]
        l_i[...] = l_i[...] * alpha + jnp.sum(p_ij, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p_ij.reshape(num_kv_heads, group, page_size), v,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )  # [Hk, group, hd]
        acc[...] = acc[...] * alpha + pv.reshape(num_heads, head_dim)
        m_i[...] = m_new
        return ()

    jax.lax.fori_loop(0, num_pages, body, ())

    denom = jnp.maximum(l_i[...], 1e-20)
    o_ref[0] = (acc[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_decode_attention_pallas(
    q: jnp.ndarray,  # [B, H, hd]
    k_cache: jnp.ndarray,  # [S, Hk, hd]
    v_cache: jnp.ndarray,  # [S, Hk, hd]
    page_table: jnp.ndarray,  # [B, max_pages]
    seq_lens: jnp.ndarray,  # [B]
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    B, H, hd = q.shape
    _, Hk, _ = k_cache.shape
    max_pages = page_table.shape[1]

    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        max_pages=max_pages,
        num_heads=H,
        num_kv_heads=Hk,
        head_dim=hd,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, hd), lambda b, *_: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # k stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),  # v stays in HBM
        ],
        out_specs=pl.BlockSpec((1, H, hd), lambda b, *_: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, Hk, hd), k_cache.dtype),
            pltpu.VMEM((2, page_size, Hk, hd), v_cache.dtype),
            pltpu.VMEM((H, hd), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, hd), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q, k_cache, v_cache)
