"""Size-aware scheduling: the SchedulerPolicy seam (fcfs/srpt/edf), the
online output-length predictor, and the counterfactual promotion loop.

Load-bearing guarantees pinned here:
  - `fcfs` stays the default and is decision-for-decision identical to
    the pre-policy engine: a journal recorded under fcfs replays AND
    `simulate --scheduler fcfs` reproduces its decision_signature
    exactly;
  - `simulate` is deterministic — the same simulate twice yields an
    identical decision_signature — and srpt's counterfactual p99 TTFT
    on a bimodal trace does not lose to fcfs (and strictly wins on the
    pinned seed);
  - srpt anti-starvation aging: under a hostile stream of short
    requests a long request still finishes, the journal invariants
    (incl. the 50-batch starvation bound) stay clean, and
    `tools/journal.py check` exits 0;
  - single-request greedy streams are byte-identical across all three
    policies on a REAL runtime (ordering changes timing, never tokens);
  - predictor semantics: cold start predicts the max_tokens budget,
    EMAs converge toward observed lengths, accuracy is None before
    warmup ("acc n/a" in the TUI);
  - ordering semantics: srpt shortest-first, edf deadline-first, aging
    promotes a parked request to the queue front;
  - fail-fast validation: config.validate_scheduler, make_policy, and
    the CLI all reject an unknown policy loudly, pre-device;
  - observability: finish records carry predicted_tokens, `sched`
    records appear under srpt, scheduler_stats rides engine stats and
    the TUI brief.
"""

import collections
import itertools
import random

import jax.numpy as jnp
import pytest

from ollamamq_tpu.config import (MODEL_CONFIGS, SCHEDULERS, EngineConfig,
                                 validate_scheduler)
from ollamamq_tpu.core import MQCore
from ollamamq_tpu.engine.engine import ModelRuntime
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.engine.request import Request
from ollamamq_tpu.engine.scheduler import (AGING_TICKS, OutputLenPredictor,
                                           make_policy)
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry.journal import (Journal, check_invariants,
                                            decision_signature)
from ollamamq_tpu.tools.journal import (counterfactual_stats, drive_chaos,
                                        record_chaos, replay_journal,
                                        simulate_journal)
from ollamamq_tpu.tools.journal import main as journal_main

_IDS = itertools.count(1)


def _req(user="u", n_prompt=8, max_tokens=8, deadline_ms=0.0):
    return Request(next(_IDS), user, "test-tiny", [1] * n_prompt,
                   SamplingParams(max_tokens=max_tokens,
                                  deadline_ms=deadline_ms))


# ------------------------------------------------------------- validation
def test_scheduler_validation_fails_fast():
    for name in SCHEDULERS:
        assert validate_scheduler(name) is None
    err = validate_scheduler("sjf")
    assert err is not None and "sjf" in err and "fcfs" in err
    with pytest.raises(ValueError, match="sjf"):
        make_policy(EngineConfig(model="test-tiny", scheduler="sjf"))
    # Engines reject it at construction, pre-device.
    with pytest.raises(ValueError):
        FakeEngine(EngineConfig(model="test-tiny", scheduler="sjf"),
                   blocklist_path=None)


def test_cli_rejects_unknown_scheduler_pre_device():
    from ollamamq_tpu.cli import main

    # Dies at the config validator (exit 2), before any jax/device work.
    assert main(["--scheduler", "warp", "--no-tui"]) == 2


# -------------------------------------------------------------- predictor
def test_predictor_cold_start_and_learning():
    p = OutputLenPredictor()
    # Cold start: the request's own budget is the honest guess.
    assert p.predict("a", 10, 64) == 64
    for _ in range(12):
        pred = p.predict("a", 10, 64)
        p.observe("a", 10, 8, predicted=pred)
    # EMAs converge toward the observed short outputs.
    assert p.predict("a", 10, 64) <= 16
    # A new user blends from the global EMA, not the 64 ceiling.
    assert p.predict("newcomer", 10, 64) <= 32
    # Predictions clamp into [1, max_tokens].
    assert p.predict("a", 10, 2) <= 2
    assert p.predict("a", 0, 1) >= 1


def test_predictor_accuracy_warmup_then_reports():
    p = OutputLenPredictor()
    assert p.accuracy() is None  # "acc n/a" before warmup
    for _ in range(OutputLenPredictor.WARMUP):
        p.observe("u", 4, 8, predicted=8)
    acc = p.accuracy()
    assert acc is not None and acc == pytest.approx(1.0)


# --------------------------------------------------------------- ordering
def test_srpt_orders_shortest_predicted_first():
    pol = make_policy(EngineConfig(model="test-tiny", scheduler="srpt"))
    long = _req(user="batch", max_tokens=64)
    short = _req(user="chat", max_tokens=2)
    dq = collections.deque([long, short])
    pol.reorder_pending(dq)
    assert list(dq) == [short, long]
    assert pol.decisions == 1
    # pack_order and order_admission agree.
    assert pol.pack_order([long, short]) == [short, long]
    batch = [(1, "batch", "m", long), (2, "chat", "m", short)]
    assert [t[3] for t in pol.order_admission(batch)] == [short, long]
    # fcfs never reorders.
    fcfs = make_policy(EngineConfig(model="test-tiny"))
    dq2 = collections.deque([long, short])
    fcfs.reorder_pending(dq2)
    assert list(dq2) == [long, short] and fcfs.decisions == 0


def test_srpt_aging_promotes_parked_request():
    pol = make_policy(EngineConfig(model="test-tiny", scheduler="srpt"))
    long = _req(user="batch", max_tokens=64)
    dq = collections.deque([long])
    pol.reorder_pending(dq)  # stamps first-seen tick
    for _ in range(AGING_TICKS):
        pol.on_admit_tick()
    fresh_short = _req(user="chat", max_tokens=2)
    dq = collections.deque([fresh_short, long])
    pol.reorder_pending(dq)
    # Fully aged => score 0 beats any fresh score, however short.
    assert list(dq) == [long, fresh_short]


def test_edf_deadline_first_then_srpt_fallback():
    pol = make_policy(EngineConfig(model="test-tiny", scheduler="edf"))
    tight = _req(user="slo", max_tokens=64, deadline_ms=50.0)
    loose = _req(user="slo", max_tokens=64, deadline_ms=5000.0)
    free_short = _req(user="chat", max_tokens=2)
    free_long = _req(user="batch", max_tokens=64)
    dq = collections.deque([free_long, loose, free_short, tight])
    pol.reorder_pending(dq)
    # Deadlines first (earliest wins), deadline-less in srpt order.
    assert list(dq) == [tight, loose, free_short, free_long]


def test_victim_keys_per_policy():
    fcfs = make_policy(EngineConfig(model="test-tiny"))
    srpt = make_policy(EngineConfig(model="test-tiny", scheduler="srpt"))
    edf = make_policy(EngineConfig(model="test-tiny", scheduler="edf"))
    long = _req(user="batch", max_tokens=64)
    short = _req(user="chat", max_tokens=2)
    dl = _req(user="slo", max_tokens=64, deadline_ms=50.0)
    # fcfs: the legacy key, fair-share standing then age.
    assert fcfs.victim_key(long, 3) == (3, long.stats.enqueued_at)
    # srpt: the longest predicted remaining loses its slot first.
    assert srpt.victim_key(long, 0) > srpt.victim_key(short, 99)
    # edf: deadline-less victims before deadline-carrying ones.
    assert edf.victim_key(long, 0) > edf.victim_key(dl, 99)


# ------------------------------------------- fcfs identity + simulate
def test_fcfs_bimodal_record_replays_and_simulates_identically(tmp_path):
    path = str(tmp_path / "bimodal.jsonl")
    journal = record_chaos(path, seed=5, requests=40, trace="bimodal")
    recs = journal.tail(None)
    assert check_invariants(recs) == []
    # No faults in the bimodal trace: the stream is pure scheduling.
    assert not {"retry", "poison", "shed"} & {r["kind"] for r in recs}
    ok, _rec, _rep, div = replay_journal(path)
    assert ok, f"fcfs bimodal replay diverged at {div}"
    # simulate under fcfs IS a replay: identical decision stream.
    rec, sim = simulate_journal(path, "fcfs")
    assert decision_signature(rec) == decision_signature(sim)
    # finish records journal the prediction next to the outcome.
    fins = [r for r in recs if r["kind"] == "finish"]
    assert fins and all("predicted_tokens" in r for r in fins)


def test_simulate_srpt_deterministic_and_wins_p99_ttft(tmp_path):
    path = str(tmp_path / "bimodal.jsonl")
    record_chaos(path, seed=5, requests=40, trace="bimodal")
    rec, sim1 = simulate_journal(path, "srpt")
    _, sim2 = simulate_journal(path, "srpt")
    # Determinism: same simulate twice => identical decision signature.
    assert decision_signature(sim1) == decision_signature(sim2)
    assert check_invariants(sim1) == []
    base = counterfactual_stats(rec)
    cf = counterfactual_stats(sim1)
    # Same work served, counterfactually better tail latency (strict
    # win on this pinned seed; the acceptance gate is "does not lose").
    assert cf["served"] == base["served"] == 40
    assert cf["ttft_p99"] < base["ttft_p99"]
    assert cf["ttft_mean"] < base["ttft_mean"]
    # The policy's ordering decisions are explainable from the journal.
    scheds = [r for r in sim1 if r["kind"] == "sched"]
    assert scheds and all(r["policy"] == "srpt" for r in scheds)
    # edf on a deadline-less trace degrades to srpt order and stays
    # invariant-clean too.
    _, sime = simulate_journal(path, "edf")
    assert check_invariants(sime) == []
    assert counterfactual_stats(sime)["served"] == 40


def test_simulate_cli_reports_and_exits_clean(tmp_path, capsys):
    path = str(tmp_path / "bimodal.jsonl")
    record_chaos(path, seed=5, requests=32, trace="bimodal")
    assert journal_main(["simulate", path, "--scheduler", "srpt"]) == 0
    out = capsys.readouterr().out
    assert "ttft_p99" in out and "decision_signature" in out
    assert "invariant-clean" in out


def test_simulate_runs_over_live_spilled_journal(tmp_path):
    """A LIVE engine's --journal-file spill (no scenario meta, raw
    loop-iteration ticks with a big idle offset and dead gaps) is
    simulatable: arrivals are tick-normalized relative to the first one
    and the engine shape is read off the journal_meta header."""
    import json

    from ollamamq_tpu.tools.journal import (MAX_ARRIVAL_GAP_TICKS,
                                            normalize_arrival_ticks)

    # Tick normalization: rebase + gap cap, order/coincidence kept.
    arr = [{"tick": 100_000}, {"tick": 100_000}, {"tick": 100_007},
           {"tick": 190_000}]
    norm = normalize_arrival_ticks(arr)
    assert [a["tick"] for a in norm] == [0, 0, 7, 7 + MAX_ARRIVAL_GAP_TICKS]

    # A hand-rolled "live spill": journal_meta header (the live engine's
    # shape), no scenario block, enqueue ticks offset by ~1e5.
    path = str(tmp_path / "live.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"journal_meta": {
            "version": 1, "model": "test-tiny", "max_slots": 2,
            "num_pages": 64}}) + "\n")
        for i in range(6):
            f.write(json.dumps({
                "seq": i, "t": 0.0, "tick": 100_000 + i * 5_000,
                "kind": "enqueue", "req_id": i + 1, "user": f"u{i % 2}",
                "model": "test-tiny", "n_prompt": 4 + i,
                "max_tokens": 4, "queued": 1}) + "\n")
    rec, sim = simulate_journal(path, "srpt")
    stats = counterfactual_stats(sim)
    assert stats["served"] == 6  # every live arrival re-drove to finish
    assert check_invariants(sim) == []
    # Deterministic over live spills too.
    _, sim2 = simulate_journal(path, "srpt")
    assert decision_signature(sim) == decision_signature(sim2)
    # The CLI path exercises the same branch.
    assert journal_main(["simulate", path, "--scheduler", "fcfs"]) == 0


# --------------------------------------------------- starvation fairness
@pytest.mark.parametrize("seed", [0, 1])
def test_srpt_hostile_short_stream_never_starves_long(tmp_path, seed):
    """Fuzz: one long request enqueued first, then a relentless stream
    of short requests. Under srpt the long must still finish within the
    aging bound — the journal invariants (incl. no-starvation-past-50-
    batches) stay clean and `tools/journal.py check` exits 0."""
    rng = random.Random(seed)
    arrivals = [{"tick": 0, "user": "longy", "n_prompt": 30,
                 "max_tokens": 16}]
    for t in range(60):
        for _ in range(1 + (rng.random() < 0.5)):
            arrivals.append({"tick": t, "user": f"c{rng.randrange(4)}",
                             "n_prompt": rng.randrange(3, 10),
                             "max_tokens": 2})
    engine = {"max_slots": 2, "max_queued": 0, "max_queued_per_user": 0,
              "step_retries": 1, "scheduler": "srpt"}
    path = str(tmp_path / f"hostile{seed}.jsonl")
    journal = Journal(capacity=65536, path=path,
                      meta={"scenario": {"engine": engine}})
    drive_chaos(arrivals, {"seed": 0, "faults": []}, engine, journal)
    recs = journal.tail(None)
    long_rids = {r["req_id"] for r in recs
                 if r["kind"] == "enqueue" and r.get("max_tokens") == 16}
    assert len(long_rids) == 1
    fins = [r for r in recs if r["kind"] == "finish"
            and r["req_id"] in long_rids]
    assert fins and fins[-1]["tokens"] == 16, "long request starved"
    assert check_invariants(recs) == []
    assert journal_main(["check", path]) == 0


# ------------------------------------------------------- byte identity
def _drive_one(policy_name: str):
    """One greedy request through a REAL runtime under `policy_name`;
    returns its generated ids."""
    from ollamamq_tpu.engine.request import FinishReason  # noqa: F401

    ecfg = EngineConfig(model="test-tiny", max_slots=2, num_pages=64,
                        page_size=8, max_pages_per_seq=8,
                        decode_steps_per_iter=2, scheduler=policy_name)
    rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"], ecfg,
                      dtype=jnp.float32)
    rt.tokenizer.eos_id = -1
    rt.policy = make_policy(ecfg)
    core = MQCore(None)
    req = Request(77, "alice", "test-tiny", list(range(3, 20)),
                  SamplingParams(max_tokens=8))
    req._inc_decode = rt.tokenizer.make_incremental_decoder()
    rt.pending_prefill.append(req)
    guard = 0
    while not req.stats.finished_at:
        rt.policy.on_admit_tick()
        rt.step_ragged(core)
        if any(r is not None for r in rt.slot_req):
            rt.step_decode(core, k_steps=2)
        guard += 1
        assert guard < 500, f"single-request drive wedged ({policy_name})"
    return list(req.generated_ids)


def test_greedy_streams_byte_identical_across_policies():
    """Ordering must never change tokens — only timing. One greedy
    request produces the exact same ids under fcfs, srpt, and edf."""
    streams = {name: _drive_one(name) for name in SCHEDULERS}
    assert streams["fcfs"] == streams["srpt"] == streams["edf"]
    assert len(streams["fcfs"]) == 8


# ---------------------------------------------------------- observability
def test_engine_stats_and_tui_brief_carry_scheduler(tmp_path):
    from ollamamq_tpu.admin.tui import _engine_stats_brief

    eng = FakeEngine(EngineConfig(model="test-tiny", scheduler="srpt"),
                     models={"test-tiny": None}, blocklist_path=None)
    ss = eng.scheduler_stats()
    assert ss["policy"] == "srpt"
    assert ss["pred_accuracy"] is None  # "acc n/a" before warmup
    assert eng.stats()["scheduler"]["policy"] == "srpt"
    brief = _engine_stats_brief(eng)
    assert brief["sched"]["policy"] == "srpt"
    assert brief["sched"]["pred_accuracy"] is None
    # Default remains fcfs.
    eng2 = FakeEngine(EngineConfig(model="test-tiny"),
                      models={"test-tiny": None}, blocklist_path=None)
    assert eng2.stats()["scheduler"]["policy"] == "fcfs"


def test_predictor_warms_through_served_requests():
    """Serving real (fake) traffic feeds the predictor: finishes update
    observation counts and eventually the accuracy gauge."""
    eng = FakeEngine(EngineConfig(model="test-tiny", scheduler="srpt"),
                     models={"test-tiny": None}, blocklist_path=None)
    rt = eng.runtimes["test-tiny"]
    for i in range(10):
        req = eng.enqueue_request("warm", "", "test-tiny",
                                  prompt_tokens=[1] * 5,
                                  sampling=SamplingParams(max_tokens=4))
        guard = 0
        while not req.stats.finished_at:
            eng._admit()
            rt.step(eng.core)
            guard += 1
            assert guard < 100
    ss = eng.scheduler_stats()
    assert ss["pred_observed"] == 10
    assert ss["pred_accuracy"] is not None
