"""Concurrency chaos: admin mutations racing live traffic.

The reference's thread-safety story is Rust's compiler (SURVEY.md §5
"race detection: none beyond what the compiler enforces"); here the
equivalent assurance is exercised empirically: concurrent generate /
cancel / block / unblock / VIP-boost flips / model pull+delete / metrics
polls against one engine, then assert the system settled consistently —
no deadlock, queues drained, gauges zeroed, no thread deaths.
"""

import asyncio
import random
import tempfile

from aiohttp.test_utils import TestClient, TestServer

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.fake import FakeEngine
from ollamamq_tpu.server.app import Server


def test_admin_mutations_race_traffic():
    rng = random.Random(7)

    async def main():
        with tempfile.TemporaryDirectory() as tmp:
            eng = FakeEngine(
                EngineConfig(model="test-tiny", max_slots=8),
                models={"test-tiny": None},
                blocklist_path=f"{tmp}/blocked_items.json",
                token_latency_s=0.002,
            )
            eng.start()
            server = Server(eng, timeout_s=60)
            cl = TestClient(TestServer(server.build_app()))
            await cl.start_server()
            try:
                stop = asyncio.Event()

                async def traffic(user):
                    while not stop.is_set():
                        try:
                            async with cl.post("/api/generate", json={
                                "model": "test-tiny", "prompt": "x",
                                "stream": rng.random() < 0.5,
                                "options": {"num_predict": rng.randint(1, 6)},
                            }, headers={"X-User-ID": user}) as r:
                                await r.read()  # drive streams to completion
                        except Exception:
                            pass
                        await asyncio.sleep(0)

                async def admin():
                    core = eng.core
                    for _ in range(200):
                        action = rng.randint(0, 6)
                        user = f"chaos{rng.randint(0, 4)}"
                        if action == 0:
                            core.block_user(user)
                        elif action == 1:
                            core.unblock_user(user)
                        elif action == 2:
                            core.set_vip(user if rng.random() < 0.8 else None)
                        elif action == 3:
                            core.set_boost(user if rng.random() < 0.8 else None)
                        elif action == 4:
                            try:
                                await cl.post("/api/pull", json={
                                    "model": "test-tiny-qwen", "stream": False})
                            except Exception:
                                pass
                        elif action == 5:
                            try:
                                await cl.post("/api/delete", json={
                                    "model": "test-tiny-qwen"})
                            except Exception:
                                pass
                        else:
                            try:
                                async with cl.get("/metrics") as r:
                                    await r.read()
                            except Exception:
                                pass
                        await asyncio.sleep(0.002)
                    stop.set()

                users = [f"chaos{i}" for i in range(5)]
                await asyncio.gather(admin(), *(traffic(u) for u in users))

                # Unblock everyone, then the system must settle.
                for u in users:
                    eng.core.unblock_user(u)
                for _ in range(200):
                    if eng.core.total_queued() == 0 and not any(
                        rt.has_work() for rt in eng.runtimes.values()
                    ):
                        break
                    await asyncio.sleep(0.05)
                assert eng.core.total_queued() == 0
                snap = eng.core.snapshot()
                assert sum(u["processing"] for u in snap["users"].values()) == 0
                total = sum(u["processed"] + u["dropped"]
                            for u in snap["users"].values())
                assert total > 0
                # Engine thread is alive and still serves.
                r = await cl.post("/api/generate", json={
                    "model": "test-tiny", "prompt": "after-chaos",
                    "stream": False, "options": {"num_predict": 2}})
                assert r.status == 200
                assert (await r.json())["done"] is True
            finally:
                await cl.close()
                eng.stop()

    asyncio.run(main())


def test_runtime_recovers_after_step_failure():
    """Failure recovery beyond fail-everything (VERDICT r1 item 10), now
    with retry containment: a failing decode dispatch no longer errors
    the in-flight request — it is requeued (front, with its generated
    tokens folded in for replay), the engine rebuilds the runtime
    (weights reloaded), and BOTH the victim and a request enqueued while
    the runtime was down complete without a process restart."""
    import time

    from ollamamq_tpu.engine.engine import TPUEngine
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=4, num_pages=64, page_size=8,
                     max_pages_per_seq=16, prefill_buckets=(16, 32, 64),
                     max_new_tokens=8, decode_steps_per_iter=2),
        blocklist_path=None,
    )
    eng.recover_interval = 0.2
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        tok = rt.tokenizer

        def boom(*a, **kw):
            raise RuntimeError("injected device failure")

        rt._dispatch_decode = boom

        def start_req(user):
            rid = eng.core.enqueue(user, "", "test-tiny")
            req = Request(rid, user, "test-tiny", tok.encode("hello"),
                          SamplingParams(max_tokens=4))
            eng.submit(req)
            return req

        def finish(req):
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                item = req.stream.get(timeout=0.2)
                if item and item.kind in ("done", "error"):
                    return item
            raise TimeoutError(req.user)

        victim = start_req("victim")
        # The failed dispatch kills the runtime; the victim is retried,
        # not errored.
        deadline = time.monotonic() + 60
        while not rt._failed and time.monotonic() < deadline:
            time.sleep(0.05)
        assert rt._failed and not rt.has_capacity()
        assert victim.retries == 1

        # Enqueue while the runtime is STILL failed: the request must wait
        # in queue ("stuck in queue" semantics), not error.
        sreq = start_req("survivor")

        # The engine swaps in a fresh runtime on its recovery cadence,
        # then serves the retried victim AND the parked survivor.
        deadline = time.monotonic() + 60
        while eng.runtimes["test-tiny"] is rt and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng.runtimes["test-tiny"] is not rt, "runtime never rebuilt"

        item = finish(victim)
        assert item.kind == "done", getattr(item, "error", None)
        assert len(victim.generated_ids) == 4
        item = finish(sreq)
        assert item.kind == "done", getattr(item, "error", None)
        snap = eng.core.snapshot()
        assert snap["users"]["survivor"]["processed"] == 1
        assert snap["users"]["victim"]["processed"] == 1
        assert snap["users"]["victim"].get("dropped", 0) == 0
        assert sum(u["processing"] for u in snap["users"].values()) == 0
    finally:
        eng.stop()


def test_poisoned_request_errors_after_repeated_runtime_failure():
    """The flip side of retry containment: a request that fails its
    retried dispatch too is poisoned with an explicit error — one bad
    input cannot crash-loop the engine through endless rebuilds."""
    import time

    from ollamamq_tpu.engine.engine import ModelRuntime, TPUEngine
    from ollamamq_tpu.engine.request import Request
    from ollamamq_tpu.ops.sampling import SamplingParams

    eng = TPUEngine(
        EngineConfig(model="test-tiny", max_slots=4, num_pages=64, page_size=8,
                     max_pages_per_seq=16, prefill_buckets=(16, 32, 64),
                     max_new_tokens=8, decode_steps_per_iter=2),
        blocklist_path=None,
    )
    eng.recover_interval = 0.2
    eng.start()

    def boom(self, *a, **kw):
        raise RuntimeError("injected persistent device failure")

    # Patch the CLASS so every rebuilt runtime fails too.
    orig = ModelRuntime._dispatch_decode
    ModelRuntime._dispatch_decode = boom
    try:
        rt = eng.runtimes["test-tiny"]
        rid = eng.core.enqueue("victim", "", "test-tiny")
        req = Request(rid, "victim", "test-tiny", rt.tokenizer.encode("hi"),
                      SamplingParams(max_tokens=4))
        eng.submit(req)
        deadline = time.monotonic() + 120
        item = None
        while time.monotonic() < deadline:
            item = req.stream.get(timeout=0.2)
            if item and item.kind in ("done", "error"):
                break
        assert item is not None and item.kind == "error"
        assert "poisoned" in item.error
        assert req.retries == 1
    finally:
        ModelRuntime._dispatch_decode = orig
        eng.stop()
