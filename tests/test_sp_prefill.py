"""Sequence-parallel prefill in the SERVING path (VERDICT r1 item 5):
an sp=2 engine routes long prompts through forward_prefill_sp (ring
attention over the mesh seq axis, K/V scattered into pages) and produces
the same tokens as the sp=1 chunked-prefill engine."""

import time

import pytest

from ollamamq_tpu.config import EngineConfig
from ollamamq_tpu.engine.engine import TPUEngine
from ollamamq_tpu.engine.request import Request
from ollamamq_tpu.ops.sampling import SamplingParams
from testutil import collect


def cfg(sp):
    return EngineConfig(
        model="test-tiny", max_slots=2, num_pages=128, page_size=8,
        max_pages_per_seq=32, prefill_buckets=(16, 32, 64),
        max_new_tokens=8, decode_steps_per_iter=2, sp=sp,
    )


def run_long_prompt(eng, user):
    rt = next(iter(r for r in eng._step_targets()))
    tok = rt.tokenizer
    prompt = "long prompt " * 12  # 145 chars -> ~146 tokens > largest bucket 64
    rid = eng.core.enqueue(user, "", "test-tiny")
    req = Request(rid, user, "test-tiny", tok.encode(prompt),
                  SamplingParams(max_tokens=6))
    eng.submit(req)
    items = collect(req)
    assert items[-1].kind == "done", items[-1]
    return req.generated_ids


@pytest.mark.parametrize("sp", [2])
def test_sp_prefill_matches_chunked(sp):
    eng_sp = TPUEngine(cfg(sp), blocklist_path=None)
    eng_ref = TPUEngine(cfg(1), blocklist_path=None)
    eng_sp.start()
    eng_ref.start()
    try:
        rt_sp = eng_sp.runtimes["test-tiny"]
        assert rt_sp._sp, "sp engine did not enable sequence-parallel prefill"
        ids_sp = run_long_prompt(eng_sp, "sp-user")
        assert ("sp", 192) in rt_sp._prefill_jits or any(
            k[0] == "sp" for k in rt_sp._prefill_jits if isinstance(k, tuple)
        ), f"SP prefill jit never built: {list(rt_sp._prefill_jits)}"
        ids_ref = run_long_prompt(eng_ref, "ref-user")
        assert ids_sp == ids_ref, f"{ids_sp} != {ids_ref}"
    finally:
        eng_sp.stop()
        eng_ref.stop()


def test_full_mesh_dp_sp_tp_serving():
    """All three axes at once on the 8-device mesh: dp=2 replicas, each a
    [1, sp=2, tp=2] submesh — long prompts take the SP ring-attention
    prefill inside a TP-sharded replica, and outputs match the plain
    dp=sp=tp=1 engine token-for-token."""
    ecfg = EngineConfig(
        model="test-tiny-gqa", max_slots=2, num_pages=128, page_size=8,
        max_pages_per_seq=32, prefill_buckets=(16, 32, 64),
        max_new_tokens=8, decode_steps_per_iter=2, dp=2, sp=2, tp=2,
    )
    eng = TPUEngine(ecfg, blocklist_path=None)
    ref = TPUEngine(
        EngineConfig(model="test-tiny-gqa", max_slots=2, num_pages=128,
                     page_size=8, max_pages_per_seq=32,
                     prefill_buckets=(16, 32, 64), max_new_tokens=8,
                     decode_steps_per_iter=2),
        blocklist_path=None,
    )
    eng.start()
    ref.start()
    try:
        rs = eng.runtimes["test-tiny-gqa"]
        assert len(rs.replicas) == 2
        assert all(rt._sp for rt in rs.replicas)
        tok = rs.tokenizer
        prompt = tok.encode("full mesh " * 15)  # > largest bucket

        def run(e, user):
            rid = e.core.enqueue(user, "", "test-tiny-gqa")
            req = Request(rid, user, "test-tiny-gqa", prompt,
                          SamplingParams(max_tokens=5))
            e.submit(req)
            items = collect(req)
            assert items[-1].kind == "done", items[-1]
            return req.generated_ids

        ids_a = run(eng, "mesh-a")
        ids_b = run(eng, "mesh-b")  # second request: other replica
        ids_ref = run(ref, "mesh-ref")
        assert ids_a == ids_ref and ids_b == ids_ref
        # SP prefill genuinely ran inside a replica.
        assert any(
            isinstance(k, tuple) and k[0] == "sp"
            for rt in rs.replicas for k in rt._prefill_jits
        )
    finally:
        eng.stop()
        ref.stop()


def test_sp_decode_continues_after_sp_prefill():
    """After an SP prefill, decode reads the scattered K/V pages: the
    continuation must depend on the actual prompt (two different long
    prompts diverge)."""
    eng = TPUEngine(cfg(2), blocklist_path=None)
    eng.start()
    try:
        rt = eng.runtimes["test-tiny"]
        tok = rt.tokenizer
        outs = []
        for i, text in enumerate(("alpha " * 30, "omega " * 30)):
            rid = eng.core.enqueue(f"u{i}", "", "test-tiny")
            req = Request(rid, f"u{i}", "test-tiny", tok.encode(text),
                          SamplingParams(max_tokens=6))
            eng.submit(req)
            items = collect(req)
            assert items[-1].kind == "done"
            outs.append(req.generated_ids)
        assert outs[0] != outs[1], "decode ignored the prefilled context"
    finally:
        eng.stop()
