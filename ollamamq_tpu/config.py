"""Model and engine configuration.

Model architecture configs for the families the framework serves natively:
Llama 3.x (incl. llama3.2:1b and Llama-3-8B) and Qwen2.5 (attention bias),
plus a bidirectional encoder config for embedding models (nomic-embed-text
class). These are the model names the reference's stress test exercises
(/root/reference/test_dispatcher.sh:5-7) and BASELINE.json's configs list.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Decoder-only transformer architecture description (Llama/Qwen family)."""

    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 500_000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    # Qwen2-style attention projections carry a bias term; Llama's do not.
    attn_bias: bool = False
    # Qwen3-style per-head RMSNorm on q and k after projection (pre-RoPE).
    qk_norm: bool = False
    # Bidirectional attention + mean pooling => embedding encoder, not a LM.
    is_encoder: bool = False
    # Mixture-of-experts (Mixtral family): 0 = dense FFN. When > 0, each
    # layer's FFN becomes num_experts independent SwiGLU experts with
    # top-(num_experts_per_tok) routing (models/moe.py); experts shard
    # over the mesh "expert" axis.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Static per-expert token capacity = ceil(tokens * k / E) * factor;
    # overflow tokens fall through to the residual (their FFN delta is 0).
    moe_capacity_factor: float = 2.0

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def param_count(self) -> int:
        """Approximate parameter count (for HBM budgeting)."""
        d, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        mlp = 3 * d * f
        if self.num_experts:
            mlp = self.num_experts * 3 * d * f + d * self.num_experts
        per_layer = (
            d * self.q_dim + 2 * d * self.kv_dim + self.q_dim * d  # attn
            + mlp
            + 2 * d  # norms
        )
        embed = v * d * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + embed + d


# ---------------------------------------------------------------------------
# Architecture registry. Sizes follow the public architecture descriptions of
# each family; "test" configs are tiny and used by the unit-test suite.
# ---------------------------------------------------------------------------

MODEL_CONFIGS = {
    # Tiny config for tests — runs on CPU in milliseconds.
    "test-tiny": ModelConfig(
        name="test-tiny", vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        rope_theta=10_000.0, max_seq_len=512,
    ),
    # GQA variant with enough KV heads for tp=4 sharding tests (test-tiny's
    # 2 KV heads cap it at tp=2).
    "test-tiny-gqa": ModelConfig(
        name="test-tiny-gqa", vocab_size=512, hidden_size=128,
        intermediate_size=256, num_layers=2, num_heads=8, num_kv_heads=4,
        head_dim=16, rope_theta=10_000.0, max_seq_len=512,
    ),
    "test-tiny-qwen": ModelConfig(
        name="test-tiny-qwen", vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        rope_theta=10_000.0, max_seq_len=512, attn_bias=True,
    ),
    "llama3.2:1b": ModelConfig(
        name="llama3.2:1b", vocab_size=128_256, hidden_size=2048,
        intermediate_size=8192, num_layers=16, num_heads=32, num_kv_heads=8,
        head_dim=64, rope_theta=500_000.0, max_seq_len=131_072,
        tie_embeddings=True,
    ),
    "llama3.2:3b": ModelConfig(
        name="llama3.2:3b", vocab_size=128_256, hidden_size=3072,
        intermediate_size=8192, num_layers=28, num_heads=24, num_kv_heads=8,
        head_dim=128, rope_theta=500_000.0, max_seq_len=131_072,
        tie_embeddings=True,
    ),
    "llama3:8b": ModelConfig(
        name="llama3:8b", vocab_size=128_256, hidden_size=4096,
        intermediate_size=14_336, num_layers=32, num_heads=32, num_kv_heads=8,
        head_dim=128, rope_theta=500_000.0, max_seq_len=8192,
    ),
    "qwen2.5:7b": ModelConfig(
        name="qwen2.5:7b", vocab_size=152_064, hidden_size=3584,
        intermediate_size=18_944, num_layers=28, num_heads=28, num_kv_heads=4,
        head_dim=128, rope_theta=1_000_000.0, max_seq_len=32_768,
        attn_bias=True,
    ),
    "qwen2.5-7b-instruct": ModelConfig(  # LM-Studio style alias used in the
        name="qwen2.5-7b-instruct",      # reference stress test
        vocab_size=152_064, hidden_size=3584, intermediate_size=18_944,
        num_layers=28, num_heads=28, num_kv_heads=4, head_dim=128,
        rope_theta=1_000_000.0, max_seq_len=32_768, attn_bias=True,
    ),
    # Embedding encoder (nomic-embed-text class: 768-d encoder).
    "nomic-embed-text": ModelConfig(
        name="nomic-embed-text", vocab_size=30_528, hidden_size=768,
        intermediate_size=3072, num_layers=12, num_heads=12, num_kv_heads=12,
        head_dim=64, rope_theta=1000.0, max_seq_len=8192, tie_embeddings=True,
        is_encoder=True,
    ),
    "test-tiny-embed": ModelConfig(
        name="test-tiny-embed", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=4,
        head_dim=16, rope_theta=1000.0, max_seq_len=512, tie_embeddings=True,
        is_encoder=True,
    ),
    # Qwen3 family: per-head q/k RMSNorm, no attention bias.
    "qwen3:8b": ModelConfig(
        name="qwen3:8b", vocab_size=151_936, hidden_size=4096,
        intermediate_size=12_288, num_layers=36, num_heads=32,
        num_kv_heads=8, head_dim=128, rope_theta=1_000_000.0,
        max_seq_len=32_768, qk_norm=True,
    ),
    "test-tiny-qwen3": ModelConfig(
        name="test-tiny-qwen3", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, rope_theta=10_000.0, max_seq_len=512, qk_norm=True,
    ),
    # Mixture-of-experts family (Mixtral 8x7b architecture description).
    "mixtral:8x7b": ModelConfig(
        name="mixtral:8x7b", vocab_size=32_000, hidden_size=4096,
        intermediate_size=14_336, num_layers=32, num_heads=32,
        num_kv_heads=8, head_dim=128, rope_theta=1_000_000.0,
        max_seq_len=32_768, num_experts=8, num_experts_per_tok=2,
    ),
    "test-tiny-moe": ModelConfig(
        name="test-tiny-moe", vocab_size=512, hidden_size=64,
        intermediate_size=96, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, rope_theta=10_000.0, max_seq_len=512,
        num_experts=4, num_experts_per_tok=2,
    ),
}


def smart_match(name: str, candidates) -> Optional[str]:
    """Smart model matching: exact → lowercase → tag-stripped.

    Single Python implementation of the reference's `smart_model_match`
    (/root/reference/src/dispatcher.rs:231-252): `llama3` matches
    `llama3:8b`/`llama3:latest`, matching is case-insensitive. The native
    scheduler gate (cpp/mqcore.cpp) implements the same algorithm for its
    in-core eligibility check; tests/test_mqcore.py pins the two together.
    """
    candidates = list(candidates)
    if name in candidates:
        return name
    low = name.lower()
    by_lower = {c.lower(): c for c in candidates}
    if low in by_lower:
        return by_lower[low]
    base = low.split(":", 1)[0]
    for c in candidates:
        if c.lower().split(":", 1)[0] == base:
            return c
    return None


def get_model_config(name: str) -> Optional[ModelConfig]:
    """Resolve a requested model name to an architecture via smart_match."""
    key = smart_match(name, MODEL_CONFIGS.keys())
    return MODEL_CONFIGS[key] if key is not None else None


@dataclasses.dataclass
class EngineConfig:
    """Continuous-batching engine configuration."""

    model: str = "test-tiny"
    # Decode slots = max sequences generating concurrently in one batch.
    max_slots: int = 64
    # Paged KV cache: total pages in the pool and tokens per page.
    # page_size 32 measured faster than 16 on v5e (r3 unofficial best:
    # 1762 tok/s/chip greedy at 64 slots, page 32 > page 16) — larger
    # pages mean fewer, longer DMA bursts in the ragged decode kernel.
    # Pool bytes and max context unchanged vs the old 512x16 defaults.
    num_pages: int = 256
    page_size: int = 32
    # Max pages a single sequence may hold (=> max context length).
    max_pages_per_seq: int = 16
    # Prefill length buckets (padded; each bucket compiles once). Used by
    # the pipeline-parallel (pp > 1) prefill path and, in both modes, as
    # the chunk ceiling for the sequence-parallel prefill hand-off. The
    # legacy user-facing bucketed oracle (--attention=bucketed) was
    # removed one release after the ragged path shipped, as scheduled.
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024, 2048)
    # -- ragged mixed-batch attention ----------------------------------------
    # ONE token-budget dispatch packs any mix of variable-length prefill
    # spans and decode tokens into a flattened stream (Pallas ragged
    # kernel on TPU, jnp twin elsewhere) — no power-of-two bucket
    # padding. pp > 1 runtimes serve the stage-scheduled bucketed
    # prefill path instead (the ragged forward is single-stage).
    # Token budget of one ragged dispatch: decode rows (1 token per
    # active slot) plus as many prefill-tail tokens as fit. Clamped up
    # to max_slots + token_granule so a full decode batch always fits.
    max_batch_tokens: int = 512
    # The ONLY padding the ragged path pays: the stream's total token
    # count rounds up to this granule for shape stability (one compile
    # per padded total). Small => waste bounded by granule/batch_tokens.
    token_granule: int = 16
    # -- speculative multi-token decoding (ragged path) ----------------------
    # Propose up to spec_k draft tokens per greedy decode slot from an
    # n-gram prompt/history lookup (no second model), then verify them
    # all in ONE ragged dispatch as a (k+1)-token span: accepted drafts
    # emit together (the longest prefix where draft == argmax, plus the
    # model's own next token — byte-identical to non-speculative greedy),
    # rejected drafts' KV pages roll back. Greedy no-penalty requests
    # only; sampled/penalized rows stay 1-token decode rows.
    spec: bool = False
    spec_k: int = 4
    # Auto-throttle: once a user's observed accept rate over a warmup
    # sample falls below this, speculation is disabled for that user —
    # wasted verify FLOPs must pay for themselves. 0 = never throttle.
    spec_min_accept: float = 0.1
    # Max new tokens default when request doesn't specify.
    max_new_tokens: int = 256
    # Decode steps executed per host-loop iteration when no prefill pending
    # (amortizes dispatch overhead via lax.scan).
    decode_steps_per_iter: int = 8
    # Max batched-prefill forwards admitted per engine tick: TTFT-first,
    # but bounded so an arrival storm can't starve active decode streams
    # (the reference's analogue admits one task per loop pass). Chunked
    # prefills are separately bounded at one chunk per tick.
    prefill_batches_per_tick: int = 2
    # Repeat-penalty window: how many recent context tokens are penalized
    # (llama.cpp repeat_last_n; engine-wide static).
    repeat_last_n: int = 64
    # Automatic prefix caching: finished prompts' full KV pages merge into
    # a per-model radix tree (engine/prefix_cache.py); admissions sharing
    # a prefix pin those pages and prefill only the uncached tail.
    prefix_cache: bool = False
    # Minimum matched FULL pages before the cached-tail path is taken —
    # tiny hits aren't worth routing through the chunked prefill.
    prefix_cache_min_pages: int = 1
    # Mesh axis sizes; tp=-1 means "all remaining devices". The engine
    # builds its (data, pipe, seq, expert, tensor) mesh from these unless
    # an explicit mesh object is passed to TPUEngine.
    dp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    # GPipe microbatches per pp dispatch (None -> one per stage). The right
    # value is workload-dependent: prefill is compute-bound (more
    # microbatches shrink the (P-1)/(M+P-1) bubble) while decode is
    # weight-streaming-bound (each microbatch step re-streams the stage's
    # weights, so FEWER can win) — sweep on hardware.
    pp_microbatches: Optional[int] = None
    dtype: str = "bfloat16"
    # -- int8 quantization (serving density) ---------------------------------
    # weights_dtype="int8": per-channel symmetric int8 weights quantized
    # at load time (scales fp32, dequant fused into the matmuls, bf16
    # accumulation) — roughly halves weight HBM and the bytes every
    # weight-streaming-bound dispatch pays. kv_dtype="int8": int8 KV
    # pages with per-page-row fp32 scales stored alongside the pool —
    # every page shrinks ~2x, so ~2x concurrent requests fit the same
    # HBM. Invalid combinations (MoE weights, pp/sp KV) fail fast at
    # startup via validate_quant_config.
    weights_dtype: str = "bfloat16"
    kv_dtype: str = "bfloat16"
    seed: int = 0
    # Telemetry: finished request traces kept for GET /debug/trace
    # (Chrome trace-event export); in-flight traces are always exported.
    trace_ring: int = 512
    # Latency SLOs (telemetry/slo.py): 0/None = objective not configured.
    # slo_ttft_ms bounds enqueue -> first token; slo_tpot_ms bounds the
    # per-token decode step. slo_target is the good-fraction objective
    # (0.99 = 1% error budget); burn-rate alerts fire against it.
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    slo_target: float = 0.99
    # -- graceful degradation under load ------------------------------------
    # Preemption with recompute: decode-time KV-pool exhaustion preempts a
    # victim (never the VIP) back to the FRONT of its user's queue instead
    # of truncating; re-admission prefills prompt+generated through the
    # normal path (mostly cache hits with --prefix-cache). Off => explicit
    # kv_exhausted error, NEVER a silent LENGTH.
    preempt: bool = True
    # Anti-livelock budget: after this many preemptions a request holds
    # its reservation (slot + pages) and is never picked as a victim.
    preempt_max: int = 3
    # Bounded admission: total / per-user queued-request caps (0 = off).
    # Over-cap enqueues are shed with 503 / 429 + Retry-After instead of
    # growing the queue unboundedly.
    max_queued: int = 0
    max_queued_per_user: int = 0
    # Failure containment: requests implicated in a failed runtime step
    # are retried this many times (fresh dispatch, exponential backoff
    # from retry_backoff_s) before being poisoned with an explicit error.
    step_retries: int = 1
    retry_backoff_s: float = 0.2
    # Deterministic fault injection (testing/faults.py): path to a plan
    # file, or a FaultPlan instance (tests). None = no injection.
    fault_plan: Optional[object] = None
    # -- fleet router (fleet/router.py) --------------------------------------
    # Engine replicas behind the front-end router (1 = single engine, no
    # router). The router owns the fair-share queues and the bounded-
    # admission caps; members serve uncapped what the router placed.
    replicas: int = 1
    # Placement policy: "affinity" routes to the replica whose prefix-
    # cache radix tree already holds the prompt's prefix (falling back
    # to least-loaded); "least_loaded" skips the affinity probe.
    placement: str = "affinity"
    # POST /admin/drain/{replica}: in-flight streams get this long to
    # complete before the stragglers fail over to healthy replicas.
    drain_timeout_s: float = 30.0
    # KV page migration: failover/drain first tries to SHIP a victim
    # stream's KV pages + request state to a healthy member (resume from
    # shipped state, zero recomputed tokens), falling back to the
    # recompute replay when the source can't export or the transfer
    # fails; affinity misses may ship the cached prefix to the chosen
    # member. Off => every failover/drain uses recompute replay.
    migrate: bool = True
    # Per-transfer budget: a migration (export + ship + import ack) past
    # this aborts and falls back to recompute — a stalled transfer must
    # never hold a stream hostage longer than re-deriving it would.
    migrate_timeout_s: float = 10.0
    # Router-overhead bound: the always-on self-profiler times every
    # placement decision (ollamamq_router_overhead_ms{site="place"});
    # a windowed p99 above this budget fires the health monitor's
    # router_overhead alert and fails the bench fleet-chaos gate —
    # "router overhead measured and bounded". 0 disables the alert
    # (the timers stay on: measurement is not optional).
    router_overhead_budget_ms: float = 50.0
    # Metrics federation: re-export every HTTP member's series from the
    # router's /metrics with a `replica` label (scraped on the member
    # health heartbeat), so one Prometheus target sees the fleet.
    # LocalMembers share the router process's registry and are always
    # in the local exposition regardless.
    federate_metrics: bool = True
    # -- tiered fleet (fleet/tiering.py) -------------------------------------
    # Replica-tier spec: latency-sensitive traffic (VIP/boost users,
    # deadlined requests) places on the `interactive` tier, everything
    # else on `bulk`, with affinity/least-loaded preserved WITHIN a
    # tier and cross-tier placement only under journaled overflow
    # (per-tier SLO burn) or an empty tier. Syntax:
    #   "interactive=r0;bulk=r1,r2"          by member name
    #   "interactive@tp4=tp4;bulk@tp1=tp1"   by TP width (tpN matches
    #                                        every member at width N);
    # the optional @tpN suffix declares the tier's TARGET width — the
    # TierBalancer hot-restarts a retiered LocalMember at it. Members no
    # selector matches default to bulk. None = untiered fleet (every
    # member interchangeable, the pre-tiering behavior).
    tiers: Optional[str] = None
    # -- elastic fleet (fleet/autoscaler.py) ---------------------------------
    # SLO-burn-driven autoscaler: a per-tier control loop that watches
    # sustained burn + queue backlog and resizes the fleet one member at
    # a time through a MemberProvisioner. Scale-down is always drain ->
    # migrate-off -> retire (never a kill); the bulk tier may scale to
    # zero (queued bulk work parks at the router and wakes it), while
    # `interactive` keeps the min_replicas floor. Off = fixed fleet.
    autoscale: bool = False
    # Fleet-size bounds for the scaler: min_replicas is the floor for
    # the interactive tier (and untiered fleets); max_replicas caps the
    # whole fleet.
    min_replicas: int = 1
    max_replicas: int = 4
    # Hysteresis: after any scale event the scaler holds its fire this
    # long (TierBalancer discipline — the burn/idle signal must also be
    # SUSTAINED, with sustain windows derived from this knob). Waking a
    # scaled-to-zero tier with parked work bypasses the cooldown: parked
    # streams must never wait out a timer that exists to stop flapping.
    scale_cooldown_s: float = 30.0
    # Comma-separated member names flagged preemptible (spot-style
    # capacity): POST /admin/preempt/{replica} — or the fault plan's
    # "preempt_notice" site — serves them a termination notice that
    # triggers migrate-off-then-retire within the notice window.
    preemptible: Optional[str] = None
    # -- scheduling policy (engine/scheduler.py) -----------------------------
    # Admission / prefill-packing / preemption-victim ordering: "fcfs"
    # (default; bit-identical to the pre-policy-extraction engine),
    # "srpt" (shortest-predicted-remaining-first off the online
    # output-length predictor, with anti-starvation aging), "edf"
    # (earliest-deadline-first over Request.deadline; srpt order for
    # deadline-less requests). Policies reorder only within what the
    # fair-share core already released; promote a candidate via
    # `tools/journal simulate` counterfactual replay.
    scheduler: str = "fcfs"
    # -- flight recorder (telemetry/journal.py) ------------------------------
    # Decision-journal ring capacity (records retained for /debug/journal
    # and the health monitor's invariant sweep).
    journal_ring: int = 2048
    # Optional JSONL spill of every journal record (--journal-file);
    # rotated at journal_rotate_mb, keeping journal_keep rotated files —
    # bounded disk on soak runs.
    journal_file: Optional[str] = None
    journal_rotate_mb: float = 64.0
    journal_keep: int = 3
    # Probabilistic sampling of high-rate journal kinds (batch/chunk/
    # page_*/broadcast): 1.0 records everything (the default, and what
    # the deterministic record/replay harness requires); lower rates let
    # the ring and spill survive 100x event storms. Decision-critical
    # kinds (enqueue/admit/shed/preempt/finish/migrate_*/recover_*/...)
    # are ALWAYS retained regardless of the rate.
    journal_sample: float = 1.0
    # -- crash durability (durability/) --------------------------------------
    # Write-ahead request log directory: every accepted generation
    # request is durably recorded (batched fsync, --wal-fsync-ms window)
    # BEFORE the enqueue ACKs, emitted tokens are appended behind it,
    # and a restart replays unfinished requests token-exact — clients
    # reattach via GET /api/stream/{req_id}?from=N. None = no WAL (the
    # default; zero overhead). In fleet mode the ROUTER owns the WAL,
    # like the journal spill — member configs clear it.
    wal_dir: Optional[str] = None
    # Group-commit fsync window in ms: every admission waits at most
    # this long for the covering fsync; a crash loses at most this much
    # emitted-token progress (regenerated identically under greedy
    # decoding on recovery). 0 = fsync inline on every admission.
    wal_fsync_ms: float = 20.0
    # -- router HA (fleet/ha.py) ---------------------------------------------
    # Primary role: replicate WAL records + journal decision events to a
    # connected warm standby over GET /admin/ha/sync (batched, sequence-
    # numbered; the standby's poll position is the ack). Requires a WAL
    # (--wal-dir): the replicated WAL is what a takeover recovers from.
    ha: bool = False
    # Standby role: the primary router's base URL to tail. The process
    # builds the full fleet (same member URLs) but serves nothing until
    # the primary's heartbeat is lost past the takeover grace — then it
    # PROMOTES: epoch bump, member re-registration (stale-epoch callers
    # fenced), WAL-replica recovery re-admission. Mutually exclusive
    # with --ha.
    standby_of: Optional[str] = None
    # Heartbeat-loss window before the standby declares the primary dead
    # and promotes; also the sync poll cadence's upper bound (the
    # standby polls at grace/4, floor 50ms).
    takeover_grace_s: float = 3.0

    @property
    def max_context(self) -> int:
        return self.max_pages_per_seq * self.page_size


QUANT_DTYPES = ("bfloat16", "int8")

# Closed scheduling-policy vocabulary (engine/scheduler.py maps each
# name to its implementation and asserts the two stay in sync).
SCHEDULERS = ("fcfs", "srpt", "edf")


def validate_scheduler(name: str) -> Optional[str]:
    """Fail-fast --scheduler validation BEFORE any device work: returns
    an error string (None = valid). Shared by the CLI and the deploy
    plumbing so a typo'd SCHEDULER env kills the process at startup,
    not at the first admission pass."""
    if name not in SCHEDULERS:
        return f"--scheduler must be one of {SCHEDULERS}, got {name!r}"
    return None


def validate_autoscale(min_replicas: int, max_replicas: int,
                       scale_cooldown_s: float,
                       replicas: int) -> Optional[str]:
    """Fail-fast --autoscale validation BEFORE any device work: returns
    an error string (None = valid). Shared by the CLI and the deploy
    plumbing so a bad MIN_REPLICAS/MAX_REPLICAS env kills the process at
    startup, not at the scaler's first decision."""
    if min_replicas < 1:
        return (f"--min-replicas must be >= 1 (the interactive floor), "
                f"got {min_replicas}")
    if max_replicas < min_replicas:
        return (f"--max-replicas ({max_replicas}) must be >= "
                f"--min-replicas ({min_replicas})")
    if scale_cooldown_s <= 0:
        return (f"--scale-cooldown-s must be > 0, got {scale_cooldown_s}")
    if replicas > max_replicas:
        return (f"starting fleet size --replicas {replicas} exceeds "
                f"--max-replicas {max_replicas}")
    return None


def validate_ha(ha: bool, standby_of: Optional[str],
                takeover_grace_s: float, wal_dir: Optional[str],
                fleet: Optional[str]) -> Optional[str]:
    """Fail-fast --ha/--standby-of validation BEFORE any device work:
    returns an error string (None = valid). Shared by the CLI and the
    deploy plumbing so a bad HA/STANDBY_OF env kills the process at
    startup, not at the first (or worst: the promoting) heartbeat."""
    if not ha and not standby_of:
        return None
    if ha and standby_of:
        return ("--ha and --standby-of are mutually exclusive: a process "
                "is the primary or the standby, never both")
    if takeover_grace_s <= 0:
        return (f"--takeover-grace-s must be > 0, got {takeover_grace_s}")
    if not wal_dir:
        flag = "--ha" if ha else "--standby-of"
        return (f"{flag} requires --wal-dir: the replicated WAL is what "
                "a takeover recovers unfinished streams from")
    if standby_of:
        if not (standby_of.startswith("http://")
                or standby_of.startswith("https://")):
            return (f"--standby-of must be the primary router's http(s) "
                    f"base URL, got {standby_of!r}")
        if not fleet:
            return ("--standby-of requires --replica-urls with the SAME "
                    "member URLs the primary serves: promotion "
                    "re-registers those members under the new epoch")
    return None


# Closed tier vocabulary (fleet/tiering.py): `interactive` serves the
# latency-sensitive classes (VIP/boost users, deadlined requests), `bulk`
# everything else. The journal schema, metrics labels, and the TUI tiers
# line all read this tuple.
TIER_NAMES = ("interactive", "bulk")


class TiersError(ValueError):
    """Malformed --tiers spec / unresolvable tier assignment."""


def parse_tiers(spec: str) -> dict:
    """Parse a --tiers spec: `tier[@tpW]=sel[,sel...];tier=...` where a
    selector is a member name (`r0`, `h1`) or `tpN` (every member whose
    TP width is N). Returns {tier: {"tp": Optional[int],
    "selectors": [str, ...]}}; raises TiersError on syntax/vocabulary
    problems (assignment problems surface in assign_tiers, which knows
    the members)."""
    out: dict = {}
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise TiersError(
                f"tier entry {part!r} is not of the form "
                "tier[@tpN]=member[,member...]")
        head, sels = part.split("=", 1)
        head = head.strip()
        tp = None
        if "@" in head:
            head, width = head.split("@", 1)
            head = head.strip()
            width = width.strip()
            if not width.startswith("tp") or not width[2:].isdigit() \
                    or int(width[2:]) < 1:
                raise TiersError(
                    f"tier width {width!r} must be tpN with N >= 1")
            tp = int(width[2:])
        if head not in TIER_NAMES:
            raise TiersError(
                f"unknown tier name {head!r} (tiers: {TIER_NAMES})")
        if head in out:
            raise TiersError(f"tier {head!r} specified twice")
        selectors = [s.strip() for s in sels.split(",") if s.strip()]
        if not selectors:
            raise TiersError(f"tier {head!r} names no members")
        out[head] = {"tp": tp, "selectors": selectors}
    if not out:
        raise TiersError("--tiers spec is empty")
    return out


def assign_tiers(spec: str, members) -> tuple:
    """Resolve a --tiers spec against the fleet roster. `members` is a
    list of (name, tp_width_or_None) pairs. Returns (assignment, widths):
    assignment maps member name -> tier, widths maps tier -> declared
    target TP width (None = re-label only on regroup). Members no
    selector matches default to `bulk`. Raises TiersError when a
    selector names no member, a member lands in two tiers, or a tier
    ends up with no members — the fail-fast contract the CLI and the
    router share."""
    parsed = parse_tiers(spec)
    by_name = {name: tp for name, tp in members}
    assignment: dict = {}
    for tier, entry in parsed.items():
        for sel in entry["selectors"]:
            if sel.startswith("tp") and sel[2:].isdigit():
                width = int(sel[2:])
                matched = [n for n, tp in members if tp == width]
                if not matched:
                    raise TiersError(
                        f"tier {tier!r} selector {sel!r} matches no "
                        f"member (members: {sorted(by_name)})")
            elif sel in by_name:
                matched = [sel]
            else:
                raise TiersError(
                    f"tier {tier!r} selector {sel!r} names no member "
                    f"(members: {sorted(by_name)})")
            for name in matched:
                prev = assignment.get(name)
                if prev is not None and prev != tier:
                    raise TiersError(
                        f"member {name!r} assigned to both {prev!r} "
                        f"and {tier!r}")
                assignment[name] = tier
    for name in by_name:
        assignment.setdefault(name, "bulk")
    widths = {tier: entry["tp"] for tier, entry in parsed.items()}
    for tier in TIER_NAMES:
        widths.setdefault(tier, None)
        if not any(t == tier for t in assignment.values()):
            raise TiersError(
                f"tier {tier!r} has no members — a tiered fleet needs "
                f"at least one member per tier (assignment: {assignment})")
    return assignment, widths


def validate_tiers(spec: Optional[str], members) -> Optional[str]:
    """Fail-fast --tiers validation BEFORE any device work: returns an
    error string (None = valid). Shared by the CLI and the fleet router
    so a typo'd tier name or an empty tier kills the process at startup,
    not at the first placement."""
    if not spec:
        return None
    try:
        assign_tiers(spec, members)
    except TiersError as e:
        return str(e)
    return None


def validate_quant_config(weights_dtype: str, kv_dtype: str,
                          pp: int = 1, sp: int = 1,
                          model_names=()) -> Optional[str]:
    """Fail-fast validation of the quantization flags BEFORE any device
    work: returns an error string (None = valid). One definition shared
    by the CLI, the SPMD worker entry, and ModelRuntime so a typo'd or
    unsupported combination can never reach the first dispatch."""
    if weights_dtype not in QUANT_DTYPES:
        return (f"--weights-dtype must be one of {QUANT_DTYPES}, "
                f"got {weights_dtype!r}")
    if kv_dtype not in QUANT_DTYPES:
        return f"--kv-dtype must be one of {QUANT_DTYPES}, got {kv_dtype!r}"
    if kv_dtype == "int8" and pp > 1:
        return ("--kv-dtype=int8 needs the ragged attention path; pp > 1 "
                "runtimes serve the stage-scheduled bucketed prefill whose "
                "pipeline forwards read bf16 pages")
    if kv_dtype == "int8" and sp > 1:
        return ("--kv-dtype=int8 is unsupported with sequence-parallel "
                "prefill (its all-layer KV scatter bypasses the quantized "
                "page writer)")
    if weights_dtype == "int8":
        for name in model_names:
            cfg = get_model_config(name)
            if cfg is not None and cfg.num_experts:
                return (f"--weights-dtype=int8 does not cover MoE expert "
                        f"stacks (model {name}); load it in bfloat16")
    return None
