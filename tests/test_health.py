"""HealthMonitor: device probe, stall detection, recovery (the TPU-native
replacement for the reference's 10s backend poll, dispatcher.rs:261-387)."""

import time

from ollamamq_tpu.engine import health as health_mod
from ollamamq_tpu.engine.health import HealthMonitor


class _FakeCore:
    def __init__(self):
        self.queued = 1

    def total_queued(self):
        return self.queued


class _FakeRt:
    def __init__(self):
        self.tokens_generated = 0

    def has_work(self):
        return True


class _FakeEngine:
    def __init__(self):
        self.core = _FakeCore()
        self.runtimes = {"m": _FakeRt()}


def test_stall_detected_then_recovers(monkeypatch):
    monkeypatch.setattr(health_mod, "STALL_DEADLINE_S", 0.2)
    eng = _FakeEngine()
    hm = HealthMonitor(eng, period_s=0.05)
    hm.start()
    try:
        deadline = time.monotonic() + 10
        while not hm.engine_stalled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert hm.engine_stalled, "stall (work pending, no tokens) not flagged"
        # Progress resumes: tokens advance -> stall clears.
        eng.runtimes["m"].tokens_generated = 5
        deadline = time.monotonic() + 10
        while hm.engine_stalled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not hm.engine_stalled
        # Idle (no work) is never a stall.
        eng.core.queued = 0

        class _IdleRt(_FakeRt):
            def has_work(self):
                return False

        eng.runtimes["m"] = _IdleRt()
        time.sleep(0.5)
        assert not hm.engine_stalled
    finally:
        hm.stop()


def test_device_probe_online_and_status():
    eng = _FakeEngine()
    hm = HealthMonitor(eng, period_s=0.05)
    hm.start()
    try:
        deadline = time.monotonic() + 20
        while hm.last_device_check == 0.0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert hm.last_device_check > 0.0
        assert hm.device_online  # CPU backend answers the probe
        st = hm.status()
        assert set(st) == {"status", "device_online", "engine_stalled",
                           "last_device_check", "alerts"}
        assert st["status"] == "ok" and st["alerts"] == []
    finally:
        hm.stop()
