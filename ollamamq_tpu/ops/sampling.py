"""Token sampling under jit: greedy, temperature, top-k, top-p.

All branches are trace-friendly (no data-dependent Python control flow):
the sampling mode is encoded in per-sequence parameter vectors so one
compiled decode step serves heterogeneous per-request options — requests
with different temperatures share a batch, unlike the reference which
forwards options opaquely to Ollama.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class SamplingParams:
    """Host-side per-request sampling options (Ollama/OpenAI option names)."""

    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0
    repeat_penalty: float = 1.0  # 1.0 => off (Ollama's default is 1.1)
    seed: int = 0
    max_tokens: int = 256
    stop: tuple = ()

    @classmethod
    def from_ollama_options(cls, options: dict, max_tokens_default: int) -> "SamplingParams":
        options = options or {}
        return cls(
            temperature=float(options.get("temperature", 0.8) or 0.0),
            top_k=int(options.get("top_k", 0) or 0),
            top_p=float(options.get("top_p", 1.0) or 1.0),
            repeat_penalty=float(options.get("repeat_penalty", 1.1) or 1.0),
            seed=int(options.get("seed", 0) or 0),
            max_tokens=int(options.get("num_predict", max_tokens_default) or max_tokens_default),
            stop=tuple(options.get("stop", []) or []),
        )

    @classmethod
    def from_openai(cls, body: dict, max_tokens_default: int) -> "SamplingParams":
        stop = body.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        return cls(
            temperature=float(body.get("temperature", 1.0) or 0.0),
            top_k=0,
            top_p=float(body.get("top_p", 1.0) or 1.0),
            seed=int(body.get("seed", 0) or 0),
            max_tokens=int(
                body.get("max_tokens") or body.get("max_completion_tokens") or max_tokens_default
            ),
            stop=tuple(stop),
        )


def recent_token_mask(recent: jnp.ndarray, vocab: int) -> jnp.ndarray:
    """[B, W] ring of recent token ids (-1 = empty) -> [B, V] int8 mask."""
    B, _ = recent.shape
    valid = (recent >= 0).astype(jnp.int8)
    mask = jnp.zeros((B, vocab), jnp.int8)
    return mask.at[jnp.arange(B)[:, None], jnp.clip(recent, 0)].max(valid)


def apply_repeat_penalty(
    logits: jnp.ndarray,  # [B, V] float32
    recent: jnp.ndarray,  # [B, W] int32 — last-W context token ids (-1 pad)
    penalty: jnp.ndarray,  # [B] float (1.0 = off)
) -> jnp.ndarray:
    """llama.cpp-style repetition penalty over the recent-token window
    (repeat_last_n semantics): for tokens in the window, positive logits
    divide by the penalty and negative logits multiply by it."""
    mask = recent_token_mask(recent, logits.shape[1])
    p = penalty[:, None]
    penalized = jnp.where(logits > 0, logits / p, logits * p)
    return jnp.where((mask > 0) & (p != 1.0), penalized, logits)


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B]
) -> jnp.ndarray:
    """Vectorized per-sequence sampling. Greedy where temperature == 0."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    # top-k mask: keep the k largest (k==0 -> keep all).
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V] descending
    k_idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B,1]
    topk_mask = jnp.where((top_k > 0)[:, None], scaled >= kth, True)

    # top-p (nucleus) mask over the sorted distribution.
    probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens whose prob >= the threshold prob at the nucleus boundary
    cutoff_count = jnp.sum(cum - probs_sorted < top_p[:, None], axis=-1)  # >=1
    cut_idx = jnp.clip(cutoff_count - 1, 0, V - 1)
    p_kth = jnp.take_along_axis(sorted_desc, cut_idx[:, None], axis=-1)
    topp_mask = jnp.where((top_p < 1.0)[:, None], scaled >= p_kth, True)

    masked = jnp.where(topk_mask & topp_mask, scaled, -jnp.inf)
    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
