"""Automatic prefix caching: radix-tree KV reuse over the paged allocator.

Two requests sharing a system prompt used to recompute identical KV
pages ("Ragged Paged Attention" shows the TPU paged kernels already
tolerate per-sequence ragged prefixes, so sharing is purely a host-side
bookkeeping problem). This module is that bookkeeping:

  - The tree is keyed on token-id BLOCKS of `page_size`: each node is one
    fully-populated prompt page, its key the page's token ids, its value
    the physical page index in the KV pool. A node's path from the root
    spells the full token prefix, so equal paths imply bit-identical KV
    content (causal models: K/V at position p depend only on tokens
    [0, p]).
  - Admission walks the tree (ModelRuntime.step_prefill), pins the
    longest match (refcount++ on every node of the path — pinned sets
    are upward-closed), seeds the request's page table with the shared
    pages, and prefills only the uncached tail through the chunked path.
    The last partial prompt page is always private and decode writes
    start strictly after the full prompt pages, so shared pages are
    READ-ONLY on the hot path — no copy-on-write anywhere.
  - On completion (or post-install cancel) the request's full prompt
    pages are inserted: new blocks transfer page ownership to the tree,
    duplicate blocks (a concurrent identical prompt finished first) free
    the redundant page.
  - When the allocator runs dry, an LRU sweep evicts unreferenced leaf
    nodes back to the free list (leaves only: evicting an interior node
    would orphan descendants the walk could no longer reach).

Page accounting: every page is exactly one of free (allocator free
list), used (private to a slot), or cached (tree-owned) — the allocator
tracks the cached count so `free + used + cached == num_pages - 1` holds
at all times (tests/test_prefix_cache.py fuzzes this invariant).

Under SPMD the tree is PRIMARY-ONLY host state: it only decides which
page indices land in page-table rows, and those already travel on the
op wire, so worker hosts replay cache-hit steps with zero extra
machinery.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ollamamq_tpu.engine.kv_cache import PageAllocator
from ollamamq_tpu.telemetry import schema as tm


class PrefixNode:
    """One fully-populated prompt page: `block` is its page_size token
    ids, `page` the physical page index owned by the tree."""

    __slots__ = ("block", "page", "refcount", "children", "parent",
                 "last_used")

    def __init__(self, block: Optional[tuple], page: Optional[int],
                 parent: Optional["PrefixNode"] = None):
        self.block = block
        self.page = page
        self.refcount = 0
        self.children: dict = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """Per-runtime radix tree mapping token-block paths to refcounted
    physical KV pages. Single-threaded by design: every caller is the
    engine loop (admission, slot release, decode page growth), the same
    thread that owns the PageAllocator."""

    def __init__(self, page_size: int, alloc: PageAllocator, model: str = "",
                 min_pages: int = 1):
        self.page_size = page_size
        self.alloc = alloc
        self.min_pages = max(1, min_pages)
        self.root = PrefixNode(None, None)
        self._clock = 0  # logical LRU clock (no wall time on the hot path)
        self._nodes = 0
        self._pinned = 0  # nodes with refcount > 0
        # Counters mirrored into the registry (README metric table).
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.tokens_saved = 0
        self._tm_hits = tm.PREFIX_CACHE_HITS_TOTAL.labels(model=model)
        self._tm_misses = tm.PREFIX_CACHE_MISSES_TOTAL.labels(model=model)
        self._tm_evictions = tm.PREFIX_CACHE_EVICTIONS_TOTAL.labels(
            model=model)
        self._tm_ratio = tm.PREFIX_CACHE_HIT_RATIO.labels(model=model)
        self._tm_saved = tm.PREFIX_CACHE_TOKENS_SAVED.labels(model=model)
        self._tm_pages = tm.PREFIX_CACHE_PAGES.labels(model=model)
        self._tm_ratio.set(0.0)
        self._tm_pages.set(0)

    # -- bookkeeping -------------------------------------------------------
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @property
    def cached_pages(self) -> int:
        return self._nodes

    @property
    def evictable_pages(self) -> int:
        """Pages reclaimable by eviction. Pinned sets are upward-closed
        (pin() pins the whole path), so any unreferenced node's entire
        subtree is unreferenced too — every one of them is eventually
        evictable."""
        return self._nodes - self._pinned

    # -- lookup / pin ------------------------------------------------------
    def match(self, tokens: List[int],
              max_pages: Optional[int] = None) -> Tuple[list, List[int]]:
        """Longest cached prefix of `tokens` in full-page units. Returns
        (nodes, pages) root-to-leaf. Capped so at least one prompt token
        stays uncached (the tail forward must produce the first-token
        logits) and the request stays under the per-sequence page cap."""
        ps = self.page_size
        cap = (len(tokens) - 1) // ps
        cap = min(cap, self.alloc.max_pages_per_seq - 1)
        if max_pages is not None:
            cap = min(cap, max_pages)
        node = self.root
        nodes: list = []
        pages: List[int] = []
        for i in range(cap):
            child = node.children.get(tuple(tokens[i * ps:(i + 1) * ps]))
            if child is None:
                break
            nodes.append(child)
            pages.append(child.page)
            node = child
        return nodes, pages

    def pin(self, nodes: list) -> None:
        t = self._tick()
        for nd in nodes:
            if nd.refcount == 0:
                self._pinned += 1
            nd.refcount += 1
            nd.last_used = t

    def release(self, nodes: list) -> None:
        for nd in nodes:
            nd.refcount -= 1
            assert nd.refcount >= 0, "prefix-cache refcount underflow"
            if nd.refcount == 0:
                self._pinned -= 1

    def note_hit(self, tokens_saved: int) -> None:
        self.hits += 1
        self.tokens_saved += tokens_saved
        self._tm_hits.inc()
        self._tm_saved.inc(tokens_saved)
        self._set_ratio()

    def note_miss(self) -> None:
        self.misses += 1
        self._tm_misses.inc()
        self._set_ratio()

    def _set_ratio(self) -> None:
        total = self.hits + self.misses
        self._tm_ratio.set(self.hits / total if total else 0.0)

    # -- insert / evict ----------------------------------------------------
    def insert(self, tokens: List[int], pages: List[int]) -> int:
        """Merge a finished request's full prompt pages into the tree.
        `pages[i]` holds the KV of token block i. New blocks ADOPT their
        page (ownership moves from the slot to the tree); existing blocks
        keep the tree's copy and the caller's duplicate page is freed.
        Returns the number of pages adopted."""
        ps = self.page_size
        node = self.root
        t = self._tick()
        adopted = 0
        for i, page in enumerate(pages):
            block = tuple(tokens[i * ps:(i + 1) * ps])
            child = node.children.get(block)
            if child is None:
                child = PrefixNode(block, page, parent=node)
                node.children[block] = child
                self.alloc.adopt_cached()
                self._nodes += 1
                adopted += 1
            elif child.page != page:
                # A concurrent identical prompt finished first: its page
                # already holds this block's KV — ours is redundant.
                self.alloc.free([page])
            child.last_used = t
            node = child
        self._tm_pages.set(self._nodes)
        return adopted

    def evict(self, n_pages: int) -> int:
        """Reclaim up to n_pages from unreferenced LEAF nodes, oldest
        last_used first, back into the allocator free list. Returns pages
        actually freed (0 when everything is pinned)."""
        freed = 0
        while freed < n_pages:
            victim = self._lru_leaf()
            if victim is None:
                break
            del victim.parent.children[victim.block]
            self.alloc.reclaim_cached(victim.page)
            self._nodes -= 1
            freed += 1
            self.evictions += 1
            self._tm_evictions.inc()
        if freed:
            self._tm_pages.set(self._nodes)
        return freed

    def _lru_leaf(self) -> Optional[PrefixNode]:
        best = None
        stack = [self.root]
        while stack:
            nd = stack.pop()
            if nd is not self.root and not nd.children and nd.refcount == 0:
                if best is None or nd.last_used < best.last_used:
                    best = nd
            stack.extend(nd.children.values())
        return best

    def flush(self) -> int:
        """Evict every unreferenced node (POST /debug/prefix_cache).
        Pinned paths — prefixes live requests are decoding against —
        survive."""
        return self.evict(self._nodes)

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_ratio": round(self.hits / total, 4) if total else 0.0,
            "evictions": self.evictions,
            "tokens_saved": self.tokens_saved,
            "cached_pages": self._nodes,
            "evictable_pages": self.evictable_pages,
            "pinned_pages": self._pinned,
        }

    def pages(self) -> set:
        """Every physical page the tree owns (tests/invariants)."""
        out = set()
        stack = [self.root]
        while stack:
            nd = stack.pop()
            if nd is not self.root:
                out.add(nd.page)
            stack.extend(nd.children.values())
        return out

    def check(self) -> None:
        """Structural invariants (tests + fuzzing): refcounts ≥ 0,
        pinned sets upward-closed, node/page counts consistent with the
        allocator's cached accounting, no page owned twice."""
        seen = set()
        count = 0
        pinned = 0
        stack = [(self.root, True)]
        while stack:
            nd, parent_ok = stack.pop()
            if nd is not self.root:
                count += 1
                assert nd.refcount >= 0
                if nd.refcount > 0:
                    pinned += 1
                    # upward closure: a pinned node's parent is pinned
                    # (or the root).
                    assert parent_ok, "pinned node under unpinned parent"
                assert nd.page not in seen, "page owned by two nodes"
                seen.add(nd.page)
                assert nd.page not in self.alloc._free, \
                    "page both free and cached"
            ok = nd is self.root or nd.refcount > 0
            stack.extend((c, ok) for c in nd.children.values())
        assert count == self._nodes == self.alloc.cached_pages
        assert pinned == self._pinned
