"""Analytic MFU accounting: FLOPs per generated token over chip peak.

The FLOPs model is the standard decoder estimate (PaLM appendix B /
Chinchilla): matmul work is 2 x (active) parameters per token, plus
attention score+value work 4 x layers x context x q_dim per token. For
MoE models only routed-active experts count (a Mixtral 8x7b token pays
~13B, not 47B).

Peak FLOPs are the published bf16 dense peaks per chip; unknown
accelerators (CPU meshes in CI) yield None and the engine publishes
mfu=0 rather than a made-up number. OLLAMAMQ_PEAK_FLOPS overrides —
that is also how CPU tests get a deterministic nonzero MFU.

Stdlib only: the ModelConfig duck-types (num_layers, hidden_size, ...),
so the doc checker and tests can import this without jax.
"""

from __future__ import annotations

import os
from typing import Optional

# Published bf16 dense peak FLOP/s per chip, matched by substring against
# jax's device_kind (e.g. "TPU v5 lite", "TPU v4", "TPU v6e").
PEAK_FLOPS_BY_KIND = (
    ("v6 lite", 918e12),  # Trillium
    ("v6e", 918e12),
    ("v5 lite", 394e12),  # v5e
    ("v5e", 394e12),
    ("v5p", 459e12),
    ("v5", 459e12),  # bare "TPU v5" = v5p naming on some stacks
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops_per_chip(device_kind: str) -> Optional[float]:
    """Peak bf16 FLOP/s for one chip, or None if unknown (CPU, new HW)."""
    env = os.environ.get("OLLAMAMQ_PEAK_FLOPS", "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    kind = (device_kind or "").lower()
    for sub, peak in PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return None


def active_param_count(cfg) -> int:
    """Params touched per token: for MoE, the top-k routed experts plus
    router, not the full expert bank; dense models = param_count."""
    d, f, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    mlp = 3 * d * f
    if cfg.num_experts:
        mlp = cfg.num_experts_per_tok * 3 * d * f + d * cfg.num_experts
    per_layer = (
        d * cfg.q_dim + 2 * d * cfg.kv_dim + cfg.q_dim * d
        + mlp
        + 2 * d
    )
    embed = v * d * (1 if cfg.tie_embeddings else 2)
    return cfg.num_layers * per_layer + embed + d


def flops_per_token(cfg, context_len: float = 0.0) -> float:
    """Forward FLOPs to generate one token at the given KV context."""
    dense = 2.0 * active_param_count(cfg)
    # QK^T and attn x V: each 2 x ctx x q_dim MACs = 2 FLOPs, per layer.
    attn = 4.0 * cfg.num_layers * max(0.0, context_len) * cfg.q_dim
    return dense + attn


def mfu(cfg, tokens: float, seconds: float, peak_per_chip: Optional[float],
        n_chips: int = 1, context_len: float = 0.0) -> float:
    """Achieved FLOPs over peak, 0..1; 0.0 when unmeasurable."""
    if not peak_per_chip or seconds <= 0 or tokens <= 0 or n_chips < 1:
        return 0.0
    achieved = tokens * flops_per_token(cfg, context_len) / seconds
    return achieved / (peak_per_chip * n_chips)
