from ollamamq_tpu.fleet.members import HttpMember, LocalMember
from ollamamq_tpu.fleet.router import FleetRouter
from ollamamq_tpu.fleet.tiering import TierManager

__all__ = ["FleetRouter", "LocalMember", "HttpMember", "TierManager"]
