#!/usr/bin/env python3
"""Doc/metric consistency gate: every metric the registry exports must be
documented in README.md's Observability table, and every documented
ollamamq_* name must still exist in the registry (no ghost docs).

Imports ONLY ollamamq_tpu.telemetry.schema — the single declaration site
for the metric surface — so the check runs without jax, a device, or an
engine. Wired into tier-1 via tests/test_metrics_docs.py.

Usage: python scripts/check_metrics_docs.py [README.md]
Exit 0 = consistent; 1 = drift (names printed); 2 = usage error.
"""

from __future__ import annotations

import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def documented_metric_names(readme_text: str) -> set:
    """ollamamq_* names that appear in backticks anywhere in the README
    (the Observability table is the intended home; being generous about
    WHERE keeps the check about coverage, not markdown layout)."""
    return set(re.findall(r"`(ollamamq_[a-z0-9_]+)`", readme_text))


def registered_metric_names() -> set:
    sys.path.insert(0, _REPO)
    from ollamamq_tpu.telemetry import schema  # noqa: F401  (declares all)
    from ollamamq_tpu.telemetry.metrics import REGISTRY

    return set(REGISTRY.names())


def main(argv) -> int:
    readme = argv[1] if len(argv) > 1 else os.path.join(_REPO, "README.md")
    try:
        with open(readme, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        print(f"cannot read {readme}: {e}", file=sys.stderr)
        return 2
    documented = documented_metric_names(text)
    registered = registered_metric_names()
    missing = sorted(registered - documented)
    ghosts = sorted(documented - registered)
    rc = 0
    if missing:
        rc = 1
        print(f"{readme}: {len(missing)} registered metric(s) missing from "
              "the README metric table:", file=sys.stderr)
        for name in missing:
            print(f"  - {name}", file=sys.stderr)
    if ghosts:
        rc = 1
        print(f"{readme}: {len(ghosts)} documented metric(s) no longer "
              "registered:", file=sys.stderr)
        for name in ghosts:
            print(f"  - {name}", file=sys.stderr)
    if rc == 0:
        print(f"ok: {len(registered)} metrics, all documented")
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv))
