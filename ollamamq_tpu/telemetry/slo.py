"""SLO objectives, multi-window burn-rate alerting, and the alert table.

The operator declares latency objectives at startup (--slo-ttft-ms,
--slo-tpot-ms, --slo-target): "target fraction of requests get their
first token within N ms" and "target fraction of decode steps emit a
token within M ms". The engine hot path records each observation as
good/bad; this module turns those streams into *error-budget burn
rates* over sliding windows and fires alerts the multi-window way
(Google SRE workbook ch.5): an alert needs BOTH a long window over
threshold (sustained, not a blip) and a short window over threshold
(still happening, so resolved incidents clear fast).

    burn_rate(window) = (bad / total over window) / (1 - target)

burn 1.0 = exactly spending budget; 14.4 over 5m/1h = the classic
page-level burn. Defaults here are scaled to a serving engine's
time-horizon (requests arrive in ms, incidents minutes): a fast pair
(5m over 60s gate) at 14.4x pages, a slow pair (1h over 5m gate) at 6x
warns.

AlertManager is the one funnel for everything that can demand operator
attention — SLO burn, the stall watchdog (engine/health.py), device
loss — so /health, /metrics, /debug/bundle, and the TUI alerts panel
all read the same table.

Stdlib-only, thread-safe: the engine thread records, the health thread
evaluates, HTTP threads read.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ollamamq_tpu.telemetry import schema as tm

log = logging.getLogger("ollamamq.slo")

# (label, long_window_s, short_window_s, burn_factor, severity): fire
# when burn > factor over BOTH windows; resolve when either drops under.
DEFAULT_WINDOWS: Tuple[tuple, ...] = (
    ("fast", 300.0, 60.0, 14.4, "page"),
    ("slow", 3600.0, 300.0, 6.0, "warn"),
)

_SEVERITY_RANK = {"page": 0, "critical": 0, "error": 1, "warn": 2, "info": 3}


@dataclasses.dataclass
class Alert:
    name: str
    severity: str
    message: str
    since: float  # time.time(): operator-facing wall clock
    source: str = "slo"

    def to_dict(self) -> dict:
        return {"name": self.name, "severity": self.severity,
                "message": self.message, "since": self.since,
                "age_s": round(max(0.0, time.time() - self.since), 1),
                "source": self.source}


class AlertManager:
    """Active-alert table + bounded history of resolved alerts."""

    def __init__(self, history: int = 64):
        self._lock = threading.Lock()
        self._active: Dict[str, Alert] = {}
        self._history: collections.deque = collections.deque(maxlen=history)

    def fire(self, name: str, severity: str, message: str,
             source: str = "slo") -> bool:
        """Raise (or refresh the message of) an alert. Returns True only
        on the inactive->active transition, so callers can count/log
        firings without flapping on every evaluation tick."""
        with self._lock:
            cur = self._active.get(name)
            if cur is not None:
                cur.message = message
                cur.severity = severity
                return False
            self._active[name] = Alert(name, severity, message,
                                       since=time.time(), source=source)
        log.error("ALERT firing [%s/%s]: %s", severity, name, message)
        return True

    def resolve(self, name: str) -> bool:
        with self._lock:
            alert = self._active.pop(name, None)
            if alert is None:
                return False
            self._history.append(
                {**alert.to_dict(), "resolved_at": time.time()})
        log.warning("alert resolved [%s]", name)
        return True

    def active(self) -> List[Alert]:
        with self._lock:
            alerts = list(self._active.values())
        alerts.sort(key=lambda a: (_SEVERITY_RANK.get(a.severity, 9),
                                   a.since))
        return alerts

    def history(self) -> List[dict]:
        with self._lock:
            return list(self._history)

    def degraded(self) -> bool:
        with self._lock:
            return bool(self._active)

    def to_dict(self) -> dict:
        return {"active": [a.to_dict() for a in self.active()],
                "recently_resolved": self.history()}


class WindowedCounts:
    """Good/bad observation counts in one-second buckets over a bounded
    horizon; totals(window) sums the trailing window. O(1) record, O(60)
    worst-case trim per record, O(window) read — reads happen at the
    health-check cadence, not per token."""

    def __init__(self, horizon_s: float = 3600.0):
        self.horizon_s = float(horizon_s)
        self._lock = threading.Lock()
        self._buckets: collections.deque = collections.deque()  # [sec, good, bad]

    def record(self, good: int = 0, bad: int = 0,
               now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        sec = int(now)
        with self._lock:
            if self._buckets and self._buckets[-1][0] == sec:
                self._buckets[-1][1] += good
                self._buckets[-1][2] += bad
            else:
                self._buckets.append([sec, good, bad])
                horizon = sec - self.horizon_s
                while self._buckets and self._buckets[0][0] < horizon:
                    self._buckets.popleft()

    def totals(self, window_s: float,
               now: Optional[float] = None) -> Tuple[int, int]:
        now = time.monotonic() if now is None else now
        cutoff = now - window_s
        good = bad = 0
        with self._lock:
            for sec, g, b in reversed(self._buckets):
                if sec < cutoff:
                    break
                good += g
                bad += b
        return good, bad


class Objective:
    """One latency objective: observations over threshold_ms burn budget."""

    def __init__(self, name: str, threshold_ms: float, target: float,
                 horizon_s: float = 3600.0):
        if not (0.0 < target < 1.0):
            raise ValueError(f"slo target must be in (0, 1), got {target}")
        self.name = name
        self.threshold_ms = float(threshold_ms)
        self.target = float(target)
        self.counts = WindowedCounts(horizon_s)
        self._tm_violations = tm.SLO_VIOLATIONS_TOTAL.labels(objective=name)

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def record(self, latency_ms: float, n: int = 1,
               now: Optional[float] = None) -> None:
        if latency_ms > self.threshold_ms:
            self.counts.record(bad=n, now=now)
            self._tm_violations.inc(n)
        else:
            self.counts.record(good=n, now=now)

    def burn_rate(self, window_s: float, now: Optional[float] = None) -> float:
        good, bad = self.counts.totals(window_s, now=now)
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / self.budget


class SLOEngine:
    """Owns the configured objectives; evaluate() runs on the health
    thread, updating the ollamamq_slo_* gauges and raising/resolving
    burn-rate alerts through the shared AlertManager."""

    def __init__(self, alerts: AlertManager,
                 ttft_ms: Optional[float] = None,
                 tpot_ms: Optional[float] = None,
                 target: float = 0.99,
                 windows: Tuple[tuple, ...] = DEFAULT_WINDOWS):
        self.alerts = alerts
        self.windows = windows
        self.objectives: Dict[str, Objective] = {}
        horizon = max((w[1] for w in windows), default=3600.0)
        if ttft_ms:
            self.objectives["ttft"] = Objective("ttft", ttft_ms, target,
                                                horizon_s=horizon)
        if tpot_ms:
            self.objectives["tpot"] = Objective("tpot", tpot_ms, target,
                                                horizon_s=horizon)

    @property
    def enabled(self) -> bool:
        return bool(self.objectives)

    # -- hot path ----------------------------------------------------------
    def record(self, objective: str, latency_ms: float, n: int = 1) -> None:
        obj = self.objectives.get(objective)
        if obj is not None:
            obj.record(latency_ms, n=n)

    # -- health-thread cadence ---------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> dict:
        """Recompute burn rates, publish gauges, fire/resolve alerts.
        Returns the summary dict /health and /debug/bundle embed."""
        now = time.monotonic() if now is None else now
        summary: dict = {"enabled": self.enabled, "objectives": {}}
        for name, obj in self.objectives.items():
            rec = {"threshold_ms": obj.threshold_ms, "target": obj.target,
                   "windows": {}}
            for label, long_w, short_w, factor, severity in self.windows:
                burn_long = obj.burn_rate(long_w, now=now)
                burn_short = obj.burn_rate(short_w, now=now)
                tm.SLO_BURN_RATE.labels(objective=name, window=label).set(
                    burn_long)
                firing = burn_long > factor and burn_short > factor
                alert_name = f"slo_{name}_burn_{label}"
                if firing:
                    self.alerts.fire(
                        alert_name, severity,
                        f"{name} SLO burning {burn_long:.1f}x budget "
                        f"over {int(long_w)}s (threshold "
                        f"{obj.threshold_ms:g}ms, target {obj.target:g})")
                else:
                    self.alerts.resolve(alert_name)
                rec["windows"][label] = {
                    "burn_rate": round(burn_long, 3),
                    "burn_rate_short": round(burn_short, 3),
                    "factor": factor, "firing": firing,
                }
            summary["objectives"][name] = rec
        return summary

    def summary(self) -> dict:
        """Read-only snapshot (no alert transitions) for endpoints that
        must not race the health thread's evaluate cadence."""
        now = time.monotonic()
        out: dict = {"enabled": self.enabled, "objectives": {}}
        for name, obj in self.objectives.items():
            out["objectives"][name] = {
                "threshold_ms": obj.threshold_ms, "target": obj.target,
                "burn_rates": {
                    label: round(obj.burn_rate(long_w, now=now), 3)
                    for label, long_w, _s, _f, _sev in self.windows
                },
            }
        return out
