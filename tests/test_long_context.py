"""Long-context serving: a 2048-token prompt (32x the largest bucket)
streams through the engine's chunked prefill + blockwise paged attention
and generates the SAME greedy continuation as a one-shot full-sequence
forward — the long-context story end-to-end, not just per-op."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.engine import kv_cache as kvc
from ollamamq_tpu.engine.engine import TPUEngine
from ollamamq_tpu.engine.request import Request
from ollamamq_tpu.models import llama
from ollamamq_tpu.ops.sampling import SamplingParams
from testutil import collect

T_LONG = 2048
GEN = 8


def test_2k_prompt_chunked_serving_matches_oneshot():
    import dataclasses

    # test-tiny with the context ceiling lifted (max_seq_len gates prompt
    # length at admission); registered temporarily so the engine resolves
    # it by name.
    cfg = dataclasses.replace(MODEL_CONFIGS["test-tiny"],
                              name="test-tiny-long", max_seq_len=4096)
    rng = np.random.RandomState(11)
    prompt = rng.randint(3, cfg.vocab_size, size=T_LONG).tolist()

    # Engine path: largest bucket 64 => the prompt takes the chunked
    # route (blockwise online-softmax over real pages only).
    ps = 16
    ecfg = EngineConfig(
        model="test-tiny-long", max_slots=2, num_pages=192, page_size=ps,
        max_pages_per_seq=160, prefill_buckets=(16, 64), max_new_tokens=GEN,
        decode_steps_per_iter=4, dtype="float32",
    )
    eng = None
    MODEL_CONFIGS["test-tiny-long"] = cfg
    try:
        eng = TPUEngine(ecfg, blocklist_path=None)
        eng.start()
        rid = eng.core.enqueue("u", "127.0.0.1", "test-tiny-long")
        req = Request(rid, "u", "test-tiny-long", list(prompt),
                      SamplingParams(max_tokens=GEN))
        eng.submit(req)
        items = collect(req, timeout=300)
        assert items[-1].kind == "done", items[-1].error
        engine_ids = req.generated_ids
    finally:
        MODEL_CONFIGS.pop("test-tiny-long", None)
        if eng is not None:
            eng.stop()
    assert len(engine_ids) == GEN

    # Reference: one-shot full-sequence prefill + stepwise greedy decode
    # at the model level (no chunking anywhere).
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    # The engine seeds its weights identically (random-init path, seed 0).
    S = 192 * ps
    kc = jnp.zeros((cfg.num_layers, S, cfg.num_kv_heads, cfg.head_dim),
                   jnp.float32)
    vc = jnp.zeros_like(kc)
    alloc = kvc.PageAllocator(192, ps, 160)
    pages = alloc.alloc(T_LONG + GEN + 1)
    pt = jnp.asarray(np.stack([kvc.make_page_table_row(pages, 160)]))
    toks = jnp.asarray([prompt], jnp.int32)
    logits, kc, vc = llama.forward_prefill(
        params, cfg, toks, jnp.array([T_LONG]), kc, vc, pt, ps
    )
    out = []
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    pos = jnp.array([T_LONG], jnp.int32)
    for _ in range(GEN):
        out.append(int(tok[0]))
        logits, kc, vc = llama.forward_decode(
            params, cfg, tok, pos, kc, vc, pt, ps
        )
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        pos = pos + 1
    assert engine_ids == out, (engine_ids, out)
