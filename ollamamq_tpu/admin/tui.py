"""Admin TUI (placeholder — full curses dashboard lands with the admin
milestone). `run_tui` blocks until quit, mirroring the reference's
tui_loop on the main thread (main.rs:162-188)."""

from __future__ import annotations

import time


def run_tui(engine, registry) -> None:
    print("TUI not yet implemented; running headless. Ctrl-C to exit.")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
