"""Ragged token-budget batch composition vs the bucketed oracle.

The load-bearing guarantees pinned here:
  - ragged vs bucketed greedy token streams are BYTE-IDENTICAL across a
    randomized mix of prompt lengths straddling the old bucket
    boundaries, with the prefix cache off AND on, repeat-penalty
    requests included, and a request cancelled mid-prefill;
  - the journal's batch records on the ragged path report padding waste
    <= 0.10 under a synthetic overload (seed baseline on the bucketed
    path: 0.56) with occupancy above the 0.43 baseline — the regression
    gate for the padding tax this PR kills;
  - _bucket_for REFUSES oversize pieces instead of silently answering
    the largest bucket (satellite: the oracle path can't mask a packing
    bug);
  - a faulted ragged dispatch retries its implicated requests (prefill
    spans AND decode rows) and the streams still finish byte-identical.
"""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from ollamamq_tpu.config import MODEL_CONFIGS, EngineConfig
from ollamamq_tpu.core import MQCore
from ollamamq_tpu.engine.engine import ModelRuntime
from ollamamq_tpu.engine.request import Request
from ollamamq_tpu.ops.sampling import SamplingParams
from ollamamq_tpu.telemetry.journal import (Journal, batch_stats,
                                            check_invariants)
from ollamamq_tpu.testing.faults import FaultPlan

_IDS = itertools.count(1)

PS = 8
BUCKETS = (16, 64)  # boundaries the fuzz prompts straddle


def make_rt(mode, **kw):
    defaults = dict(
        model="test-tiny", max_slots=4, num_pages=96, page_size=PS,
        max_pages_per_seq=16, prefill_buckets=BUCKETS, max_new_tokens=8,
        decode_steps_per_iter=2, attention_mode=mode,
        max_batch_tokens=48, token_granule=8,
    )
    defaults.update(kw)
    rt = ModelRuntime("test-tiny", MODEL_CONFIGS["test-tiny"],
                      EngineConfig(**defaults), dtype=jnp.float32)
    rt.tokenizer.eos_id = -1  # deterministic full-length streams
    return rt


def tick(rt, core):
    """One engine-loop-shaped tick for either mode."""
    if rt.ragged:
        ran = rt.step_ragged(core)
        if not ran and any(r is not None for r in rt.slot_req):
            rt.step_decode(core, k_steps=1)
    else:
        rt.step_prefill(core)
        rt.step_chunk(core)
        if any(r is not None for r in rt.slot_req):
            rt.step_decode(core, k_steps=1)


def run_all(rt, prompts, max_tokens=6, repeat_penalty=1.0,
            cancel_mid_prefill=None, max_ticks=800):
    """Drive a batch of prompts to completion; returns each request's
    generated ids (None for a cancelled one). `cancel_mid_prefill`
    names a request index to cancel as soon as its prefill is
    partially done (0 < _chunk_pos < n in either mode)."""
    core = MQCore(None)
    reqs = []
    for p in prompts:
        req = Request(next(_IDS), f"u{len(reqs) % 3}", "test-tiny", list(p),
                      SamplingParams(max_tokens=max_tokens,
                                     repeat_penalty=repeat_penalty))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        reqs.append(req)
    victim = (reqs[cancel_mid_prefill]
              if cancel_mid_prefill is not None else None)
    for _ in range(max_ticks):
        if victim is not None and not victim.cancelled.is_set():
            pos = getattr(victim, "_chunk_pos", 0)
            if 0 < pos < len(victim.prompt_tokens):
                victim.cancelled.set()
        if all(r.stats.finished_at for r in reqs):
            break
        tick(rt, core)
    assert all(r.stats.finished_at for r in reqs), "requests wedged"
    return [None if r is victim else list(r.generated_ids) for r in reqs]


def _fuzz_prompts(rng, n):
    """Prompt lengths hugging/straddling the bucket boundaries plus a
    few randoms — the shapes the bucketed composer split into separate
    batches and the ragged composer must pack together."""
    straddle = [b + d for b in BUCKETS for d in (-1, 0, 1)]
    lens = [straddle[int(rng.integers(len(straddle)))]
            if rng.random() < 0.6 else int(rng.integers(2, 80))
            for _ in range(n)]
    return [rng.integers(3, 500, size=max(1, L)).tolist() for L in lens]


@pytest.mark.parametrize("repeat_penalty", [1.0, 1.1],
                         ids=["greedy", "repeat-penalty"])
def test_ragged_matches_bucketed_byte_identical(repeat_penalty):
    rng = np.random.default_rng(11)
    for round_ in range(3):
        prompts = _fuzz_prompts(rng, 6)
        a = run_all(make_rt("bucketed"), prompts,
                    repeat_penalty=repeat_penalty)
        b = run_all(make_rt("ragged"), prompts,
                    repeat_penalty=repeat_penalty)
        assert a == b, f"round {round_}: streams diverged"


@pytest.mark.parametrize("prefix_cache", [False, True],
                         ids=["cache-off", "cache-on"])
def test_ragged_matches_bucketed_with_prefix_cache(prefix_cache):
    rng = np.random.default_rng(7)
    shared = rng.integers(3, 500, size=3 * PS).tolist()
    prompts = [shared + rng.integers(3, 500, size=t).tolist()
               for t in (5, 17, 40)] + _fuzz_prompts(rng, 2)
    a = run_all(make_rt("bucketed", prefix_cache=prefix_cache), prompts)
    b = run_all(make_rt("ragged", prefix_cache=prefix_cache), prompts)
    assert a == b


def test_mid_prefill_cancel_leaves_survivors_identical():
    """Cancelling a long prompt mid-prefill (its spans already dispatched)
    must not perturb the other requests' streams in either mode, and the
    cancelled slot's pages must all return to the pool."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(3, 500, size=n).tolist()
               for n in (70, 15, 33)]  # 70 > largest bucket: chunks in both
    rts = {mode: make_rt(mode) for mode in ("bucketed", "ragged")}
    outs = {mode: run_all(rt, prompts, cancel_mid_prefill=0)
            for mode, rt in rts.items()}
    assert outs["ragged"] == outs["bucketed"]
    assert outs["ragged"][0] is None
    for rt in rts.values():
        assert rt.alloc.used_pages == 0
        assert not rt.reserved_slots and not rt.chunking


def test_bucket_for_refuses_oversize():
    rt = make_rt("bucketed")
    assert rt._bucket_for(16) == 16
    assert rt._bucket_for(17) == 64
    with pytest.raises(ValueError):
        rt._bucket_for(BUCKETS[-1] + 1)


def test_ragged_dispatch_fault_retries_and_streams_survive():
    """An injected exception in the mixed dispatch retries BOTH its
    prefill spans and its decode rows (replay semantics): every stream
    still completes, byte-identical to an unfaulted run."""
    rng = np.random.default_rng(9)
    prompts = [rng.integers(3, 500, size=n).tolist() for n in (20, 7, 35)]
    clean = run_all(make_rt("ragged"), prompts)
    # The 2nd mixed dispatch carries a prefill tail AND live decode rows,
    # so the containment path must replay both kinds.
    plan = FaultPlan([{"site": "ragged", "kind": "exception", "at": [2]}])
    rt = make_rt("ragged", retry_backoff_s=0.0)
    rt.fault_plan = plan
    faulted = run_all(rt, prompts)
    assert plan.injected == 1
    assert faulted == clean
    assert rt.retry_count >= 1


# ------------------------------------------------ padding-waste regression
def _overload_trace(mode, n_requests=24, seed=5):
    """Synthetic overload: arrivals outpace the drain so composition
    always has a backlog to pack; returns the journal's batch stats."""
    rng = np.random.default_rng(seed)
    rt = make_rt(mode, max_slots=4, num_pages=160,
                 max_batch_tokens=64, token_granule=8)
    journal = Journal(capacity=65536)
    rt.journal = journal
    core = MQCore(None)
    reqs = []
    issued = 0
    guard = 0
    while True:
        while issued < n_requests and len(rt.pending_prefill) < 6:
            n = int(rng.integers(5, 70))
            req = Request(next(_IDS), f"ov{issued % 4}", "test-tiny",
                          rng.integers(3, 500, size=n).tolist(),
                          SamplingParams(max_tokens=4))
            req._inc_decode = rt.tokenizer.make_incremental_decoder()
            rt.pending_prefill.append(req)
            reqs.append(req)
            issued += 1
        tick(rt, core)
        if issued >= n_requests and all(r.stats.finished_at for r in reqs):
            break
        guard += 1
        assert guard < 5000, "overload trace wedged"
    recs = journal.tail(None)
    assert not check_invariants(recs)
    return batch_stats(recs)


def test_padding_waste_gate_ragged():
    """CI gate: the ragged path's padding waste must stay <= 0.10 under
    overload (seed baseline on the bucketed path: 0.56), with batch
    occupancy strictly above the 0.43 baseline."""
    stats = _overload_trace("ragged")
    assert stats["batches"] > 0
    assert stats["padding_waste"] <= 0.10, stats
    assert stats["mean_occupancy"] > 0.43, stats


def test_padding_waste_bucketed_baseline_still_measured():
    """The oracle path keeps reporting its (worse) padding waste — the
    scoreboard both modes are judged on stays comparable."""
    stats = _overload_trace("bucketed")
    assert stats["batches"] > 0
    assert stats["padded_tokens"] >= stats["real_tokens"]
    assert stats["padding_waste"] > 0.10, stats  # the tax ragged kills


def test_ragged_batch_records_carry_the_split():
    """Every ragged batch record carries mode/padded_tokens and the
    prefill/decode row split the schema promises."""
    rng = np.random.default_rng(2)
    rt = make_rt("ragged")
    journal = Journal(capacity=4096)
    rt.journal = journal
    core = MQCore(None)
    run_all_rt(rt, core, rng)
    recs = journal.tail(None, kind="batch")
    assert recs, "no batch records journaled"
    for r in recs:
        assert r["mode"] == "ragged"
        assert r["padded_tokens"] >= r["tokens"]
        assert r["n_prefill"] + r["n_decode"] == r["batch_size"]
        assert r["padded_tokens"] % 8 == 0  # the granule


def run_all_rt(rt, core, rng):
    reqs = []
    for n in (20, 5, 33):
        req = Request(next(_IDS), "u", "test-tiny",
                      rng.integers(3, 500, size=n).tolist(),
                      SamplingParams(max_tokens=4))
        req._inc_decode = rt.tokenizer.make_incremental_decoder()
        rt.pending_prefill.append(req)
        reqs.append(req)
    for _ in range(400):
        if all(r.stats.finished_at for r in reqs):
            return
        tick(rt, core)
    raise AssertionError("requests wedged")
