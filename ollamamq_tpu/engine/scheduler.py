"""Scheduling policies: the decision seams extracted from the engine.

Three decision points, one interface (ROADMAP item 4; PAPERS.md "Optimal
Scheduling Algorithms for LLM Inference: Theory and Practice" for the
SRPT result, UELLM for prediction-driven scheduling):

  (a) admission order — the candidate window the fair-share core
      releases per tick (`MQCore.next_window`) plus each runtime's
      pending-prefill queue: which waiting request claims the next
      decode slot (`order_admission` / `reorder_pending`);
  (b) prefill-span packing — the order `step_ragged` spends its token
      budget across in-flight chunked prefills (`pack_order`);
  (c) preemption victim — which slot is evicted for recompute when the
      KV pool runs dry (`victim_key`).

Policies only REORDER within what fairness already allowed: the native
core's per-user fair-share / VIP / boost / blocklist semantics decide
WHICH requests are released; a policy decides in what order the
released set is served. `fcfs` is bit-identical to the pre-extraction
engine and stays the default. `srpt` serves shortest-predicted-
remaining-first off an online output-length predictor (per-user EMA of
actual output lengths blended with a prompt-shape feature — host-side
and stdlib-only, like `_propose_drafts`). `edf` serves earliest-
deadline-first over `Request.deadline`, falling back to srpt order for
deadline-less requests.

Anti-starvation: under srpt/edf a waiting request's effective score
decays linearly to 0 over AGING_TICKS batch ticks; aged requests are
FIFO among themselves and beat any fresh score, so the journal
invariant "no starvation past 50 batches" stays green even under a
hostile stream of short requests.

Promotion story: record a trace (`tools/journal record`), re-drive it
under each candidate (`tools/journal simulate FILE --scheduler X`),
ship the policy whose counterfactual p99 TTFT wins. Predictions and
outcomes are journaled (`finish.predicted_tokens`, `sched` records) and
exported live (`ollamamq_sched_pred_err`,
`ollamamq_sched_decisions_total`).
"""

from __future__ import annotations

import collections
from typing import Deque, List, Optional, Tuple

from ollamamq_tpu.config import SCHEDULERS
from ollamamq_tpu.telemetry import schema as tm

# Batch ticks for a waiting request's effective score to decay to 0 —
# well under the journal's 50-batch starvation bound, leaving slot-wait
# slack after the aged request reaches the front of the order.
AGING_TICKS = 32

# Prefill tokens ride many-per-dispatch in the ragged span path; weight
# the unprefilled prompt tail at one remaining "step" per this many
# tokens when scoring remaining work against decode tokens (one each).
PREFILL_TOKENS_PER_STEP = 16.0


class OutputLenPredictor:
    """Online output-length predictor: per-user EMA of actual output
    lengths blended with a global EMA and a prompt-shape feature (EMA
    of the output/prompt-length ratio). No ML dependencies. Cold start
    predicts the request's own max_tokens budget — the honest ceiling —
    so an unwarmed srpt degrades toward ordering by token budget."""

    WARMUP = 8  # (predicted, actual) pairs before accuracy() reports

    def __init__(self, alpha: float = 0.25):
        self.alpha = float(alpha)
        self._user: dict = {}                 # user -> output-length EMA
        self._global: Optional[float] = None  # fleet-wide output EMA
        self._ratio: Optional[float] = None   # output/prompt ratio EMA
        self._window: Deque[Tuple[int, int]] = collections.deque(maxlen=256)
        self.observed = 0

    def predict(self, user: str, n_prompt: int, max_tokens: int) -> int:
        cap = max(1, int(max_tokens))
        ue = self._user.get(user)
        if ue is not None and self._global is not None:
            base = 0.7 * ue + 0.3 * self._global
        elif ue is not None:
            base = ue
        elif self._global is not None:
            base = self._global
        else:
            return cap  # no observations yet: the budget is the guess
        if self._ratio is not None and n_prompt > 0:
            base = 0.75 * base + 0.25 * (self._ratio * n_prompt)
        return max(1, min(cap, int(round(base))))

    def observe(self, user: str, n_prompt: int, actual: int,
                predicted: Optional[int] = None) -> None:
        actual = max(0, int(actual))
        a = self.alpha
        ue = self._user.get(user)
        self._user[user] = actual if ue is None else (1 - a) * ue + a * actual
        self._global = actual if self._global is None \
            else (1 - a) * self._global + a * actual
        if n_prompt > 0:
            r = actual / n_prompt
            self._ratio = r if self._ratio is None \
                else (1 - a) * self._ratio + a * r
        if predicted is not None:
            self._window.append((int(predicted), actual))
        self.observed += 1

    def export_user(self, user: str) -> dict:
        """One user's predictor state for a KV migration blob: the
        target member's predictor shouldn't cold-start a user the fleet
        already learned."""
        return {"user_ema": self._user.get(user),
                "global_ema": self._global, "ratio_ema": self._ratio}

    def import_user(self, user: str, state: dict) -> None:
        """Merge a migrated user's predictor state: never clobber what
        this member already observed locally — migration fills gaps, it
        doesn't overwrite evidence."""
        ue = state.get("user_ema")
        if ue is not None and user not in self._user:
            self._user[user] = float(ue)
        if self._global is None and state.get("global_ema") is not None:
            self._global = float(state["global_ema"])
        if self._ratio is None and state.get("ratio_ema") is not None:
            self._ratio = float(state["ratio_ema"])

    def accuracy(self) -> Optional[float]:
        """Mean relative accuracy (1 - |pred - actual| / max(actual, 1))
        over the recent window, clamped to [0, 1]. None before warmup —
        the TUI renders that as "acc n/a"."""
        window = list(self._window)  # atomic snapshot: the TUI thread
        # reads accuracy at frame cadence while the engine appends.
        if len(window) < self.WARMUP:
            return None
        errs = [abs(p - a) / max(a, 1) for p, a in window]
        return max(0.0, 1.0 - sum(errs) / len(errs))


class SchedulerPolicy:
    """`fcfs`: first-come-first-served — bit-identical to the engine
    before the policy extraction (identity orderings; the legacy
    most-served-user/youngest-arrival victim key). Base class for the
    size-aware policies below. The predictor runs under every policy so
    its accuracy is observable live BEFORE promoting srpt/edf."""

    name = "fcfs"
    # Candidates popped from the fair-share core per admission window;
    # 1 = pop-and-place one at a time, exactly the legacy flow.
    admission_window = 1

    def __init__(self, ecfg=None):
        self.ecfg = ecfg
        self.predictor = OutputLenPredictor()
        self.decisions = 0  # reorders actually applied (stats/TUI)
        self._tick = 0
        self._seq = 0
        self._tm_dec = tm.SCHED_DECISIONS_TOTAL.labels(policy=self.name)

    # -- clock -------------------------------------------------------------
    def on_admit_tick(self) -> None:
        """One batch tick — the clock anti-starvation aging runs on.
        Called once per engine admission pass, in the live loop and the
        synchronous replay/simulate drivers alike."""
        self._tick += 1

    def _seen(self, req) -> Tuple[int, int]:
        """(first-seen tick, arrival sequence), stamped the first time
        this policy scores the request and preserved across preemption
        requeues — aging must survive req_id churn."""
        seen = getattr(req, "_sched_seen", None)
        if seen is None:
            self._seq += 1
            seen = (self._tick, self._seq)
            req._sched_seen = seen
        return seen

    def _note_decision(self) -> None:
        self.decisions += 1
        self._tm_dec.inc()

    # -- predictor ---------------------------------------------------------
    def predict(self, req) -> int:
        """Predicted output length, cached at first scoring so the
        finish record journals the prediction the scheduler acted on."""
        p = getattr(req, "_predicted_tokens", None)
        if p is None:
            p = self.predictor.predict(
                req.user, len(req.prompt_tokens),
                getattr(req.sampling, "max_tokens", 1))
            req._predicted_tokens = p
        return p

    def observe_finish(self, req, model: Optional[str] = None) -> None:
        """Fold a served request's actual output length back into the
        predictor and the prediction-error histogram."""
        predicted = self.predict(req)
        actual = len(req.generated_ids)
        self.predictor.observe(req.user, len(req.prompt_tokens), actual,
                               predicted=predicted)
        tm.SCHED_PRED_ERR.labels(model=model or req.model or "?").observe(
            abs(predicted - actual))

    # -- scoring -----------------------------------------------------------
    def remaining(self, req) -> float:
        """Predicted remaining work in decode-step units: predicted
        output still to emit plus the unprefilled prompt tail."""
        pred = max(0, self.predict(req) - len(req.generated_ids))
        left = max(0, len(req.prompt_tokens)
                   - int(getattr(req, "_chunk_pos", 0) or 0))
        return pred + left / PREFILL_TOKENS_PER_STEP

    def score(self, req) -> float:
        """Effective priority (lower serves first), with linear anti-
        starvation aging: decays to 0 over AGING_TICKS, after which aged
        requests are FIFO among themselves and beat any fresh score."""
        seen_tick, _seq = self._seen(req)
        age = self._tick - seen_tick
        if age >= AGING_TICKS:
            return 0.0
        return self.remaining(req) * (AGING_TICKS - age) / AGING_TICKS

    def _order_key(self, req):
        _tick, seq = self._seen(req)
        return (self.score(req), seq)

    # -- decision point (a): admission order -------------------------------
    def order_admission(self, batch: List[tuple]) -> List[tuple]:
        """Order a window of (rid, user, model, req) candidates the
        fair-share core released this pass. fcfs: pop order, untouched."""
        return batch

    def reorder_pending(self, dq) -> None:
        """Order a runtime's pending-prefill deque in place — the slot-
        admission order. fcfs: untouched."""

    # -- decision point (b): prefill-span packing --------------------------
    def pack_order(self, chunking) -> list:
        """Order the in-flight chunked prefills `step_ragged` spends its
        token budget on. fcfs: FIFO."""
        return list(chunking)

    # -- decision point (c): preemption victim -----------------------------
    def victim_key(self, req, served: int):
        """Victim preference key (max wins; VIP/budget eligibility stays
        in the engine). fcfs keeps the legacy heuristic: the most-served
        user's youngest request loses its slot."""
        return (served, req.stats.enqueued_at)


class SrptPolicy(SchedulerPolicy):
    """Shortest-predicted-remaining-first with anti-starvation aging."""

    name = "srpt"
    admission_window = 8

    def order_admission(self, batch: List[tuple]) -> List[tuple]:
        if len(batch) < 2:
            # Still stamp the age anchor: a lone candidate's aging
            # starts when the scheduler first sees it, not when
            # contention appears.
            for t in batch:
                self._seen(t[3])
            return batch
        ordered = sorted(batch, key=lambda t: self._order_key(t[3]))
        if ordered != batch:
            self._note_decision()
        return ordered

    def reorder_pending(self, dq) -> None:
        if len(dq) < 2:
            if dq:
                self._seen(dq[0])
            return
        ordered = sorted(dq, key=self._order_key)
        if list(dq) != ordered:
            dq.clear()
            dq.extend(ordered)
            self._note_decision()

    def pack_order(self, chunking) -> list:
        return sorted(chunking, key=self._order_key)

    def victim_key(self, req, served: int):
        # The longest predicted remaining loses its slot first (keep
        # shorts running — SRPT's dual); fair-share standing and age
        # break ties, i.e. the fcfs key demoted to tie-break.
        return (self.remaining(req), served, req.stats.enqueued_at)


class EdfPolicy(SrptPolicy):
    """Earliest-deadline-first over `Request.deadline`; deadline-less
    requests fall back to srpt order BEHIND any deadline-carrying one
    (a request that told us its latency budget outranks one that
    didn't)."""

    name = "edf"

    def _order_key(self, req):
        _tick, seq = self._seen(req)
        if req.deadline is not None:
            return (0.0, req.deadline, seq)
        return (1.0, self.score(req), seq)

    def victim_key(self, req, served: int):
        # Deadline-less victims first (nobody's SLO dies for pages),
        # then the farthest deadline, then the srpt preference.
        if req.deadline is None:
            return (1.0, 0.0, self.remaining(req), served,
                    req.stats.enqueued_at)
        return (0.0, req.deadline, self.remaining(req), served,
                req.stats.enqueued_at)


_POLICIES = {"fcfs": SchedulerPolicy, "srpt": SrptPolicy, "edf": EdfPolicy}
assert set(_POLICIES) == set(SCHEDULERS)


def make_policy(ecfg) -> SchedulerPolicy:
    """Build the configured policy; loud on an unknown --scheduler (the
    CLI validates pre-device via config.validate_scheduler, but tests
    and bench construct EngineConfig directly)."""
    name = getattr(ecfg, "scheduler", "fcfs") or "fcfs"
    cls = _POLICIES.get(name)
    if cls is None:
        raise ValueError(
            f"unknown --scheduler {name!r} (choose from {SCHEDULERS})")
    return cls(ecfg)
