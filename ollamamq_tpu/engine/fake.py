"""Deterministic fake engine for tests and API development.

The moral equivalent of the reference's test strategy of pointing the proxy
at real Ollama servers (SURVEY.md §4): an in-process engine with the same
interface as TPUEngine but no JAX — tokens are deterministic, latency is
configurable, cancellation works mid-stream. Lets the full HTTP surface be
conformance-tested without a TPU in the loop.
"""

from __future__ import annotations

import collections
import logging
import time
from typing import Dict, List, Optional

from ollamamq_tpu.config import EngineConfig, get_model_config
from ollamamq_tpu.engine.engine import TPUEngine
from ollamamq_tpu.engine.request import FinishReason, Request, StreamItem
from ollamamq_tpu.engine.tokenizer import ByteTokenizer
from ollamamq_tpu.telemetry import schema as tm
from ollamamq_tpu.telemetry import stepprof

log = logging.getLogger("ollamamq.fake")


class FakeRuntime:
    """Generates `word0 word1 ...` tokens, one per step, per active request."""

    slo = None  # attached by FakeEngine.load_model, like ModelRuntime
    fault_plan = None  # deterministic fault injection (testing/faults.py)
    on_preempt = None  # attached like ModelRuntime's (unused by fakes)
    journal = None  # decision journal, attached like ModelRuntime's
    # Scheduling policy (engine/scheduler.py), attached like the
    # journal — the deterministic seam the replay/simulate harness and
    # the policy tests drive without jax. None behaves exactly as fcfs.
    policy = None

    def __init__(self, name: str, engine_cfg: EngineConfig,
                 token_latency_s: float = 0.0, is_encoder: bool = False):
        # Kind gate (engine._place): encoder fakes are embedding-only, like
        # EncoderRuntime; generative fakes also implement embed in step(),
        # so they truthfully serve both kinds.
        self.SERVES = ("embed",) if is_encoder else ("generate", "embed")
        self.name = name
        self.ecfg = engine_cfg
        self.token_latency_s = token_latency_s
        self.is_encoder = is_encoder
        self.tokenizer = ByteTokenizer()
        self.pending_prefill: collections.deque = collections.deque()
        self.active: List[Request] = []
        self.tokens_generated = 0
        self.step_latency_ms = 0.0
        self.prefill_latency_ms = 0.0
        self.param_bytes = 0
        self.kv_bytes = 0
        # Same metric surface as ModelRuntime, so the exposition (and the
        # e2e telemetry tests) look identical under the fake engine.
        self._tm_ttft = tm.TTFT_MS.labels(model=name)
        self._tm_tpot = tm.TPOT_MS.labels(model=name)
        self._tm_tokens = tm.TOKENS_GENERATED_TOTAL.labels(model=name)
        self._tm_occupancy = tm.BATCH_OCCUPANCY.labels(model=name)
        self._tm_mfu = tm.MFU.labels(model=name)
        self._tm_occupancy.set(0.0)
        self._tm_mfu.set(0.0)

    def has_capacity(self, kind=None) -> bool:
        return len(self.active) + len(self.pending_prefill) < self.ecfg.max_slots

    def has_work(self) -> bool:
        return bool(self.pending_prefill) or bool(self.active)

    def active_count(self) -> int:
        return len(self.active)

    def submit(self, req: Request) -> bool:
        self.pending_prefill.append(req)
        return True

    def _jrec(self, kind, req=None, **fields) -> None:
        # Same journaling seam as ModelRuntime: the fake engine's decision
        # stream is what the deterministic replay harness re-drives.
        if self.journal is not None:
            self.journal.record(kind, req=req, model=self.name, **fields)

    def _finish_served(self, req: Request, core, reason: FinishReason) -> None:
        """Served-to-completion finish: journal the outcome next to the
        scheduler's prediction and feed the output-length predictor —
        same contract as ModelRuntime._finish_slot."""
        core.mark_done(req.user, tokens=len(req.generated_ids))
        req.stats.completion_tokens = len(req.generated_ids)
        pol = self.policy
        extra = ({"predicted_tokens": pol.predict(req)}
                 if pol is not None else {})
        self._jrec("finish", req, reason=reason.value,
                   tokens=len(req.generated_ids), **extra)
        if pol is not None:
            pol.observe_finish(req, model=self.name)
        req.finish(reason)

    def check_cancellations(self, core) -> None:
        for req in list(self.active):
            if req.cancelled.is_set():
                self.active.remove(req)
                core.mark_dropped(req.user)
                self._jrec("finish", req, reason="cancelled",
                           tokens=len(req.generated_ids))
                req.finish(FinishReason.CANCELLED)

    def step(self, core) -> None:
        # Fault seam: the fake analogue of ModelRuntime's dispatch hooks,
        # so shedding/retry/watchdog paths are testable without jax.
        if self.fault_plan is not None:
            self.fault_plan.check("step")
        # Step profiler, fake shape: admission is host_prep, the token-
        # latency sleep is the "device dispatch", the emit loop is detok
        # — so stepprof surfaces/tests run without jax. Idle ticks
        # abandon the timer (no zero-sample flood).
        _sp = stepprof.PROFILER.start("fake")
        _gen0 = self.tokens_generated
        # Admission: slot-bounded so scheduling-policy order actually
        # decides WHO enters a contended batch (pre-policy the pop gate
        # alone bounded concurrency, so this gate never binds for fcfs
        # traces — the decision stream is unchanged). Cancelled/expired
        # heads always drain regardless, and embeds hold no slot.
        # NOTE: core.mark_started already ran in TPUEngine._admit.
        if self.policy is not None:
            # Decision point (a): slot-admission order (fcfs: no-op).
            self.policy.reorder_pending(self.pending_prefill)
        admitted: List[Request] = []
        while self.pending_prefill:
            head = self.pending_prefill[0]
            if head._retry_at > time.monotonic():
                break  # head is backing off after a contained fault
            if (len(self.active) >= self.ecfg.max_slots
                    and not (self.is_encoder or head.kind == "embed")
                    and not head.cancelled.is_set()
                    and not head.expired()):
                break  # batch full: the policy order decides who's next
            req = self.pending_prefill.popleft()
            if req.cancelled.is_set():
                core.mark_dropped(req.user)
                self._jrec("finish", req, reason="cancelled", tokens=0)
                req.finish(FinishReason.CANCELLED)
                continue
            if req.expired():
                # Same deadline semantics as the real engine: expired
                # queued work drops before any "compute" is spent.
                from ollamamq_tpu.engine.engine import drop_expired

                drop_expired(req, core, self.name, journal=self.journal)
                continue
            if self.is_encoder or req.kind == "embed":
                req.trace_event("embed_batch", tokens=len(req.prompt_tokens))
                req.embedding = self._fake_embedding(req)
                req.stats.first_token_at = time.monotonic()
                core.mark_done(req.user, tokens=len(req.prompt_tokens))
                self._jrec("finish", req, reason="stop",
                           tokens=len(req.prompt_tokens))
                req.finish(FinishReason.STOP)
            else:
                req.trace_event("prefill", tokens=len(req.prompt_tokens))
                # Resume-aware: a retried request (engine containment
                # path) continues its word stream where it stopped rather
                # than restarting at word0 — mirrors the real engine's
                # replay-recompute continuity.
                done = len(req.generated_ids)
                req._fake_remaining = max(
                    1, min(req.sampling.max_tokens, 16) - done)
                req._fake_idx = done
                self._jrec("install", req, slot=-1,
                           n_prompt=len(req.prompt_tokens))
                self.active.append(req)
                admitted.append(req)
        real = sum(len(r.prompt_tokens) for r in admitted)
        if admitted:
            # Batch-compose record, fake shape: no padding (tokens are
            # words, not tensors), so real == padded — keeps the replay
            # harness's batch_stats/occupancy output meaningful.
            self._jrec("batch", slots=[-1] * len(admitted),
                       reqs=[r.req_id for r in admitted],
                       batch_size=len(admitted), tokens=real,
                       occupancy=round(len(self.active)
                                       / max(1, self.ecfg.max_slots), 4),
                       pending=len(self.pending_prefill),
                       mode="fake", padded_tokens=real)
        self._tm_occupancy.set(len(self.active) / max(1, self.ecfg.max_slots))
        _had_work = bool(admitted or self.active)
        _n_decode = len(self.active)
        _sp.mark("host_prep")
        if self.token_latency_s:
            time.sleep(self.token_latency_s)
        _sp.mark("dispatch")
        for req in list(self.active):
            if req.cancelled.is_set():
                self.active.remove(req)
                core.mark_dropped(req.user)
                self._jrec("finish", req, reason="cancelled",
                           tokens=len(req.generated_ids))
                req.finish(FinishReason.CANCELLED)
                continue
            # Speculative fake: with --spec the step emits 1 + k words at
            # once and journals the speculate/spec_verify decision pair —
            # the fake word stream is deterministic regardless of
            # stepping, so spec-on/off streams stay identical while the
            # journal vocabulary (and its invariants, /debug surfaces,
            # replay harness) exercise without jax. Fake drafts always
            # verify: the "model" IS the proposer here.
            emit_n = 1
            if (self.ecfg.spec and self.ecfg.spec_k > 0
                    and req._fake_remaining > 1):
                k = min(self.ecfg.spec_k, req._fake_remaining - 1)
                self._jrec("speculate", req, slot=-1, k=k, source="fake")
                self._jrec("spec_verify", req, slot=-1, proposed=k,
                           accepted=k, rolled_back=0)
                tm.SPEC_TOKENS_TOTAL.labels(
                    model=self.name, outcome="proposed").inc(k)
                tm.SPEC_TOKENS_TOTAL.labels(
                    model=self.name, outcome="accepted").inc(k)
                tm.SPEC_ACCEPT_RATE.labels(model=self.name).set(1.0)
                emit_n = 1 + k
            for _ in range(emit_n):
                word = f"word{req._fake_idx} "
                req._fake_idx += 1
                req._fake_remaining -= 1
                req.generated_ids.append(req._fake_idx)
                self.tokens_generated += 1
                self._tm_tokens.inc()
                if not req.stats.first_token_at:
                    req.stats.first_token_at = time.monotonic()
                    self._tm_ttft.observe(req.stats.ttft_ms)
                    self._tm_tpot.observe(self.token_latency_s * 1e3)
                    if self.slo is not None:
                        self.slo.record("ttft", req.stats.ttft_ms)
                    req.trace_event("first_token",
                                    ttft_ms=round(req.stats.ttft_ms, 3))
                elif self.slo is not None:
                    self.slo.record("tpot", self.token_latency_s * 1e3)
                chunk = req.emit_text(word)
                if chunk is None:
                    self.active.remove(req)
                    self._finish_served(req, core, FinishReason.STOP)
                    break
                if chunk:
                    req.stream.push(StreamItem("token", text=chunk,
                                               token_id=req._fake_idx))
                if req._fake_remaining <= 0:
                    self.active.remove(req)
                    tail = req.flush_text()
                    if tail:
                        req.stream.push(StreamItem("token", text=tail))
                    self._finish_served(req, core, FinishReason.LENGTH)
                    break
        if _had_work:
            _sp.mark("detok")
            _sp.finish(T_pad=0, k_cap=0, n_prefill=len(admitted),
                       n_decode=_n_decode,
                       tokens=real + (self.tokens_generated - _gen0),
                       padded_tokens=real + (self.tokens_generated - _gen0),
                       compiled=False)

    # -- KV page migration (fake shape: no pages, just the word cursor) ----
    def export_request(self, rid: int):
        """Same export contract as ModelRuntime, fake state: the word
        cursor IS the KV. Lets fleet drain/failover exercise the full
        two-phase migration path without jax."""
        from ollamamq_tpu.engine.engine import request_migration_state

        for req in self.active:
            if req.req_id == rid:
                break
        else:
            return None
        blob = {
            "version": 1, "kind": "fake", "model": self.name,
            "fake_idx": int(req._fake_idx),
            "fake_remaining": int(req._fake_remaining),
            "request": request_migration_state(req),
            "_inc_decode": req._inc_decode,
        }
        self.active.remove(req)
        return {"req": req}, blob

    def release_export(self, handle: dict) -> None:
        pass  # fakes hold no pages to free

    def import_request(self, blob: dict, req: Request) -> bool:
        if blob.get("kind") != "fake" \
                or len(self.active) >= self.ecfg.max_slots:
            return False
        req._fake_idx = int(blob["fake_idx"])
        req._fake_remaining = int(blob["fake_remaining"])
        self._jrec("install", req, slot=-1,
                   n_prompt=len(req.prompt_tokens))
        self.active.append(req)
        return True

    def _fake_embedding(self, req: Request) -> list:
        # Deterministic unit vector derived from the prompt bytes.
        dim = 64
        v = [0.0] * dim
        for i, t in enumerate(req.prompt_tokens):
            v[i % dim] += float((t % 13) + 1)
        norm = sum(x * x for x in v) ** 0.5 or 1.0
        return [x / norm for x in v]

    def stats(self) -> dict:
        return {
            "model": self.name,
            "active_slots": len(self.active),
            "max_slots": self.ecfg.max_slots,
            "pending_prefill": len(self.pending_prefill),
            "pages_used": 0,
            "pages_total": 0,
            "step_latency_ms": round(self.token_latency_s * 1e3, 3),
            "prefill_latency_ms": 0.0,
            "tokens_generated": self.tokens_generated,
            "preemptions": 0,  # fakes hold no KV pages to run out of
            "retries": 0,
            "stalled_slots": 0,
            "mfu": 0.0,
            "param_bytes": self.param_bytes,
            "kv_bytes": self.kv_bytes,
            "prefix_cache": None,  # fake tokens carry no KV to share
            "weights_dtype": "bfloat16",  # fake engine holds no weights
            "kv_dtype": "bfloat16",  # ...and no KV pool
            "spec": None,  # fake drafts never roll back
        }


class FakeEngine(TPUEngine):
    """TPUEngine with FakeRuntimes — identical scheduler/admission path."""

    def __init__(self, engine_cfg: Optional[EngineConfig] = None,
                 models: Optional[Dict[str, Optional[str]]] = None,
                 blocklist_path: Optional[str] = None,
                 token_latency_s: float = 0.0, **kw):
        self.token_latency_s = token_latency_s
        engine_cfg = engine_cfg or EngineConfig(model="test-tiny")
        super().__init__(engine_cfg, models=models,
                         blocklist_path=blocklist_path, mesh=None, **kw)

    def load_model(self, name: str, checkpoint_path: Optional[str] = None) -> None:
        if name in self.runtimes:
            return
        cfg = get_model_config(name)
        is_enc = bool(cfg and cfg.is_encoder)
        rt = FakeRuntime(
            name, self.ecfg, token_latency_s=self.token_latency_s, is_encoder=is_enc
        )
        rt.slo = self.slo
        rt.fault_plan = self.fault_plan
        rt.journal = self.journal
        rt.policy = self.policy
        self.runtimes[name] = rt
        self.notify()

    def _loop(self) -> None:
        while self._running:
            self.last_tick_at = time.monotonic()
            self.journal.tick += 1
            # Deferred engine-thread calls (the fleet's migration
            # export/import run through call_on_loop here too).
            self._drain_engine_calls()
            self._admit()
            did_work = False
            for rt in list(self.runtimes.values()):
                rt.check_cancellations(self.core)
                if rt.has_work():
                    try:
                        rt.step(self.core)
                    except Exception:
                        # Same containment contract as the real engine:
                        # retry-or-poison the implicated requests, keep
                        # the loop (and the fake runtime) alive.
                        log.exception("fake runtime %s step failed", rt.name)
                        self._fail_runtime(rt, "engine step failed")
                    did_work = True
            if not did_work:
                with self._cond:
                    self._cond.wait(timeout=0.02)
